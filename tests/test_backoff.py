"""Workqueue rate-limiting semantics (kube/controller.py).

The manager's retry path is controller-runtime's: per-item exponential
backoff with jitter + an overall token bucket, `forget()` on success,
retry budgets landing in Manager._errors on exhaustion — all deterministic
under the injected FakeClock (run_until_idle auto-advances over retry
backoffs; advance_clock=False exposes the pending delays for assertions).
"""

import pytest

from kubeflow_tpu.kube import (
    ApiServer,
    BucketRateLimiter,
    ItemExponentialBackoff,
    KubeObject,
    Manager,
    MaxOfRateLimiter,
    ObjectMeta,
    Result,
    retry_on_conflict,
)
from kubeflow_tpu.kube.errors import ConflictError
from kubeflow_tpu.utils.clock import FakeClock


def mk(kind: str, name: str, namespace: str = "default") -> KubeObject:
    return KubeObject(api_version="v1", kind=kind,
                      metadata=ObjectMeta(name=name, namespace=namespace))


class Failing:
    def __init__(self, fail_times: int = 10**9, clock=None):
        self.calls = 0
        self.fail_times = fail_times
        self.clock = clock
        self.call_times: list[float] = []

    def reconcile(self, req):
        self.calls += 1
        if self.clock is not None:
            self.call_times.append(self.clock.now())
        if self.calls <= self.fail_times:
            raise RuntimeError("boom")
        return Result()


class TestItemExponentialBackoff:
    def test_growth_jitter_bounds_and_cap(self):
        rl = ItemExponentialBackoff(base_s=0.01, cap_s=0.5, jitter=0.1,
                                    seed=7)
        item = ("c", "x")
        for n in range(12):
            delay = rl.when(item)
            pure = min(0.01 * (2 ** n), 0.5)
            assert pure <= delay <= pure * 1.1 + 1e-12, (n, delay)
        assert rl.num_failures(item) == 12

    def test_forget_resets(self):
        rl = ItemExponentialBackoff(base_s=0.01, jitter=0.0)
        item = ("c", "x")
        assert rl.when(item) == pytest.approx(0.01)
        assert rl.when(item) == pytest.approx(0.02)
        rl.forget(item)
        assert rl.when(item) == pytest.approx(0.01)

    def test_items_are_independent(self):
        rl = ItemExponentialBackoff(base_s=0.01, jitter=0.0)
        rl.when(("c", "x"))
        rl.when(("c", "x"))
        assert rl.when(("c", "y")) == pytest.approx(0.01)


class TestBucketRateLimiter:
    def test_burst_then_paced(self):
        clock = FakeClock()
        rl = BucketRateLimiter(qps=10.0, burst=3, clock=clock)
        assert [rl.when("i") for _ in range(3)] == [0.0, 0.0, 0.0]
        # bucket empty: reservations pace out at 1/qps
        assert rl.when("i") == pytest.approx(0.1)
        assert rl.when("i") == pytest.approx(0.2)
        clock.advance(0.2)  # tokens refill with (fake) time
        assert rl.when("i") == pytest.approx(0.1)

    def test_zero_qps_unlimited(self):
        rl = BucketRateLimiter(qps=0.0, burst=1, clock=FakeClock())
        assert all(rl.when("i") == 0.0 for _ in range(100))


class TestManagerBackoff:
    def _mgr(self, **kw):
        api = ApiServer()
        clock = FakeClock()
        mgr = Manager(api, clock=clock)
        return api, clock, mgr

    def test_failures_observe_monotonic_backoff_not_immediate(self):
        """Acceptance: 5 consecutive failures see monotonically increasing
        delays between attempts, asserted through the FakeClock."""
        api, clock, mgr = self._mgr()
        rec = Failing(clock=clock)
        mgr.register("nb", rec, for_kind="Notebook", max_retries=5)
        api.create(mk("Notebook", "nb1"))

        delays = []
        while True:
            mgr.run_until_idle(advance_clock=False)
            pending = mgr.pending_delayed()
            if not pending:
                break
            assert len(pending) == 1
            _, _, due = pending[0]
            gap = due - clock.now()
            assert gap > 0, "failed reconcile re-enqueued immediately"
            delays.append(gap)
            clock.advance(gap)

        assert rec.calls == 6  # initial + 5 retries
        assert len(delays) == 5
        assert all(b > a for a, b in zip(delays, delays[1:])), delays
        assert len(mgr.dropped_errors) == 1
        # the attempt timestamps themselves spread out on the fake clock
        gaps = [b - a for a, b in zip(rec.call_times, rec.call_times[1:])]
        assert gaps == pytest.approx(delays)

    def test_run_until_idle_auto_advances_fake_clock_over_backoff(self):
        api, clock, mgr = self._mgr()
        rec = Failing(fail_times=3, clock=clock)
        mgr.register("nb", rec, for_kind="Notebook", max_retries=5)
        t0 = clock.now()
        api.create(mk("Notebook", "nb1"))
        mgr.run_until_idle()
        assert rec.calls == 4  # 3 failures + success, drained in one call
        assert clock.now() > t0  # the backoff time actually passed
        assert not mgr.dropped_errors
        assert not mgr.pending_delayed()

    def test_forget_on_success_resets_item_backoff(self):
        api, clock, mgr = self._mgr()
        rec = Failing(fail_times=2, clock=clock)
        mgr.register("nb", rec, for_kind="Notebook", max_retries=5)
        api.create(mk("Notebook", "nb1"))
        mgr.run_until_idle()

        # fail twice more: delays restart from the base (5ms +10% jitter),
        # not from a carried-over failure count (which would start >= 20ms)
        rec.fail_times = rec.calls + 2
        obj = api.get("Notebook", "default", "nb1")
        obj.metadata.labels["touch"] = "1"
        api.update(obj)
        start = len(rec.call_times)
        mgr.run_until_idle()
        second_round = [b - a for a, b in zip(rec.call_times[start:],
                                              rec.call_times[start + 1:])]
        assert len(second_round) == 2
        assert 0.005 <= second_round[0] <= 0.0055
        assert 0.010 <= second_round[1] <= 0.011

    def test_unregister_mid_backoff_drops_delayed_retry(self):
        api, clock, mgr = self._mgr()
        rec = Failing(clock=clock)
        mgr.register("nb", rec, for_kind="Notebook", max_retries=5)
        api.create(mk("Notebook", "nb1"))
        mgr.run_until_idle(advance_clock=False)
        assert mgr.pending_delayed()
        mgr.unregister("nb")
        assert not mgr.pending_delayed()
        assert mgr.run_until_idle() == 0
        assert rec.calls == 1

    def test_exhaustion_lands_in_errors_with_budget_reset(self):
        api, clock, mgr = self._mgr()
        rec = Failing(clock=clock)
        mgr.register("nb", rec, for_kind="Notebook", max_retries=3)
        api.create(mk("Notebook", "nb1"))
        mgr.run_until_idle()
        assert rec.calls == 4
        assert len(mgr.dropped_errors) == 1
        name, req, err = mgr.dropped_errors[0]
        assert name == "nb" and req.name == "nb1"
        assert isinstance(err, RuntimeError)
        # a fresh event gets a fresh budget
        rec.fail_times = 0
        obj = api.get("Notebook", "default", "nb1")
        obj.metadata.labels["touch"] = "1"
        api.update(obj)
        mgr.run_until_idle()
        assert len(mgr.dropped_errors) == 1  # no new drop

    def test_requeue_true_is_rate_limited_not_hot(self):
        api, clock, mgr = self._mgr()

        class Requeuer:
            calls = 0

            def reconcile(self, req):
                Requeuer.calls += 1
                return Result(requeue=Requeuer.calls < 4)

        mgr.register("nb", Requeuer(), for_kind="Notebook")
        t0 = clock.now()
        api.create(mk("Notebook", "nb1"))
        mgr.run_until_idle()
        assert Requeuer.calls == 4
        assert clock.now() > t0  # requeues waited out backoff, not hot-loop

    def test_requeue_after_not_auto_advanced(self):
        api, clock, mgr = self._mgr()

        class Scheduler:
            calls = 0

            def reconcile(self, req):
                Scheduler.calls += 1
                return Result(requeue_after=60.0) if Scheduler.calls == 1 \
                    else Result()

        mgr.register("nb", Scheduler(), for_kind="Notebook")
        api.create(mk("Notebook", "nb1"))
        mgr.run_until_idle()
        assert Scheduler.calls == 1  # scheduled work stays scheduled
        assert mgr.pending_delayed()
        mgr.advance(61)
        assert Scheduler.calls == 2

    def test_queue_stats_and_metrics_export(self):
        from kubeflow_tpu.core.metrics import NotebookMetrics

        api, clock, mgr = self._mgr()
        rec = Failing(clock=clock)
        mgr.register("nb", rec, for_kind="Notebook", max_retries=2)
        api.create(mk("Notebook", "nb1"))
        mgr.run_until_idle()
        stats = mgr.queue_stats()
        assert stats["retries_total"]["nb"] == 2
        assert stats["errors_total"]["nb"] == 1
        assert stats["last_backoff_s"]["nb"] > 0
        metrics = NotebookMetrics(api, manager=mgr)
        text = metrics.scrape()
        assert 'workqueue_retries_total{controller="nb"} 2' in text
        assert 'reconcile_errors_total{controller="nb"} 1' in text

    def test_backoff_delays_land_in_queue_duration_histogram(self):
        """A request that backs off twice shows those delays in the
        workqueue_queue_duration_seconds buckets — timed entirely off the
        FakeClock (enqueue-timestamp -> pop), no wall-clock reads."""
        api, clock, mgr = self._mgr()
        rec = Failing(fail_times=2, clock=clock)
        mgr.register("nb", rec, for_kind="Notebook", max_retries=5)
        api.create(mk("Notebook", "nb1"))
        mgr.run_until_idle()

        hist = mgr.queue_duration
        assert hist.count_value("nb") == 3  # initial + 2 backoff requeues
        buckets = hist.bucket_counts("nb")
        # initial enqueue popped with no clock movement: <= 5ms bucket
        assert buckets[0.005] == 1
        # first backoff: 5ms base * [1, 1.1) jitter -> (5, 5.5]ms
        assert buckets[0.01] == 2
        # second backoff: 10-11ms
        assert buckets[0.025] == 3
        assert buckets[float("inf")] == 3
        # the sum is exactly the two backoff delays (initial wait was 0)
        assert 0.015 <= hist.sum_value("nb") <= 0.0165
        # work/reconcile histograms saw every attempt
        assert mgr.work_duration.count_value("nb") == 3
        assert mgr.reconcile_time.count_value("nb") == 3

    def test_requeue_after_wait_is_not_queue_time(self):
        """requeue_after is a timer, not queueing: the scheduled wait must
        NOT inflate workqueue_queue_duration_seconds."""
        api, clock, mgr = self._mgr()

        class Scheduler:
            calls = 0

            def reconcile(self, req):
                Scheduler.calls += 1
                return Result(requeue_after=60.0) if Scheduler.calls == 1 \
                    else Result()

        mgr.register("nb", Scheduler(), for_kind="Notebook")
        api.create(mk("Notebook", "nb1"))
        mgr.run_until_idle()
        mgr.advance(61)
        assert Scheduler.calls == 2
        hist = mgr.queue_duration
        assert hist.count_value("nb") == 2
        # both pops saw ~0 queue time; the 60s timer never entered the queue
        assert hist.bucket_counts("nb")[0.005] == 2
        assert hist.sum_value("nb") <= 1.0 + 1e-9

    def test_max_of_rate_limiter_takes_worst(self):
        clock = FakeClock()
        rl = MaxOfRateLimiter(
            ItemExponentialBackoff(base_s=0.5, jitter=0.0),
            BucketRateLimiter(qps=10.0, burst=100, clock=clock),
        )
        assert rl.when("i") == pytest.approx(0.5)


class TestRetryOnConflictBackoff:
    def test_backoff_grows_capped_between_conflicts(self):
        sleeps: list[float] = []
        calls = [0]

        def always_conflict():
            calls[0] += 1
            raise ConflictError("nope")

        with pytest.raises(ConflictError):
            retry_on_conflict(always_conflict, steps=5,
                              initial_backoff_s=0.01, factor=2.0,
                              max_backoff_s=0.03, jitter=0.0,
                              sleep_fn=sleeps.append)
        assert calls[0] == 5
        # capped exponential: 10ms, 20ms, then pinned at the 30ms cap;
        # no sleep after the final attempt
        assert sleeps == pytest.approx([0.01, 0.02, 0.03, 0.03])

    def test_jitter_bounds(self):
        sleeps: list[float] = []

        def always_conflict():
            raise ConflictError("nope")

        with pytest.raises(ConflictError):
            retry_on_conflict(always_conflict, steps=3,
                              initial_backoff_s=0.01, factor=2.0,
                              max_backoff_s=1.0, jitter=0.5,
                              sleep_fn=sleeps.append)
        assert 0.01 <= sleeps[0] <= 0.015
        assert 0.02 <= sleeps[1] <= 0.03

    def test_success_after_conflict_returns_value(self):
        state = {"n": 0}

        def flaky():
            state["n"] += 1
            if state["n"] < 3:
                raise ConflictError("racing")
            return "ok"

        assert retry_on_conflict(flaky, sleep_fn=lambda s: None) == "ok"
