"""Causal diagnosis engine (utils/diagnosis.py): level-latch change-point
math on synthetic series, the online engine over a real TSDB, the
per-notebook explainer's deterministic ranking, the /debug/alerts
annotation contract, the lifecycle excursion ring it reads, and offline
reconstruction from diagnose bundles.

Everything runs off the FakeClock — the detector consumes injected TSDB
sample timestamps, never a wall clock, so every boundary here is exact."""

from __future__ import annotations

import json

import pytest

from kubeflow_tpu.utils import tracing
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.diagnosis import (
    CAUSE_FAULT_INJECTION,
    CAUSE_NOMINAL,
    CAUSE_PRIMARY_FAILOVER,
    DiagnosisEngine,
    changepoints_from_bundle,
    correlate_events,
    detect_level_shifts,
    matched_kind,
    merge_timelines,
    register_diagnosis_metrics,
    watched_series,
)
from kubeflow_tpu.utils.flightrecorder import FlightRecorder
from kubeflow_tpu.utils.lifecycle import LifecycleLedger
from kubeflow_tpu.utils.metrics import Registry
from kubeflow_tpu.utils.tracing import get_tracer
from kubeflow_tpu.utils.tsdb import TimeSeriesStore


@pytest.fixture()
def clock():
    c = FakeClock()
    tracing.set_clock(c)
    yield c
    tracing.set_clock(None)


def series(values, t0=0.0, dt=60.0):
    """[[t, v], ...] with evenly spaced injected timestamps."""
    return [[t0 + i * dt, float(v)] for i, v in enumerate(values)]


class TestLevelShiftMath:
    """detect_level_shifts on synthetic step/ramp/noise: a step fires
    exactly once, stationary noise never fires, a ramp fires at least
    once — the detector's falsifiable contract."""

    def test_step_fires_exactly_once(self):
        hits = detect_level_shifts(series([1] * 8 + [9] * 12))
        assert len(hits) == 1
        assert hits[0]["direction"] == "up"
        # the firing tail window straddles the transition at t=8*60
        assert hits[0]["t_start"] <= 8 * 60.0 <= hits[0]["t_end"]

    def test_down_step_fires_down(self):
        hits = detect_level_shifts(series([9] * 8 + [1] * 12))
        assert [h["direction"] for h in hits] == ["down"]

    def test_flat_never_fires(self):
        assert detect_level_shifts(series([4] * 30)) == []

    def test_stationary_noise_never_fires(self):
        # deterministic bounded noise around level 10: the latched spread
        # covers the oscillation amplitude
        noise = [10 + ((i * 7) % 5 - 2) * 0.3 for i in range(40)]
        assert detect_level_shifts(series(noise)) == []

    def test_ramp_fires_at_least_once(self):
        hits = detect_level_shifts(series([i * 2.0 for i in range(30)]))
        assert len(hits) >= 1
        assert all(h["direction"] == "up" for h in hits)

    def test_step_up_then_down_is_two_findings(self):
        hits = detect_level_shifts(
            series([1] * 10 + [9] * 10 + [1] * 10))
        assert [h["direction"] for h in hits] == ["up", "down"]

    def test_relative_threshold_scales_with_level(self):
        # 10% shift on a high flat level stays quiet (rel_factor 0.25);
        # a 4x shift fires
        assert detect_level_shifts(series([100] * 10 + [110] * 10)) == []
        hits = detect_level_shifts(series([100] * 10 + [400] * 10))
        assert len(hits) == 1

    def test_short_series_never_fires(self):
        # fewer points than window+1: baseline never challenged
        assert detect_level_shifts(series([1, 9, 1, 9])) == []

    def test_correlation_window_and_kind_priority(self):
        events = [
            {"t": 100.0, "kind": "recovery", "detail": "", "object": ""},
            {"t": 110.0, "kind": "fault", "detail": "", "object": ""},
            {"t": 500.0, "kind": "promotion", "detail": "", "object": ""},
        ]
        matched = correlate_events(events, 120.0, 240.0, lookback_s=120.0)
        assert {e["kind"] for e in matched} == {"recovery", "fault"}
        # fault is the most causally-specific kind present
        assert matched_kind(matched) == "fault"
        assert matched_kind([]) == "none"

    def test_watched_series_vocabulary(self):
        assert watched_series("ready_p99_s")
        assert watched_series("stage_p99.schedule_cold")
        assert not watched_series("tenant_cs.user1")


class TestEngineDetection:
    """The online engine over a real TimeSeriesStore: incremental
    consumption, counter labels, event correlation, and equivalence with
    the offline batch detector."""

    def _engine(self, clock):
        tsdb = TimeSeriesStore()
        reg = Registry()
        eng = DiagnosisEngine(clock, registry=reg, tsdb=tsdb)
        return eng, tsdb, reg

    def _tick(self, clock, tsdb, eng, value, name="workqueue_depth"):
        clock.advance(60.0)
        tsdb.sample(clock.now(), {name: float(value)})
        return eng.evaluate()

    def test_step_emits_single_finding_and_counter(self, clock):
        eng, tsdb, reg = self._engine(clock)
        found = []
        for v in [0] * 8 + [12] * 10:
            found.extend(self._tick(clock, tsdb, eng, v))
        assert len(found) == 1
        f = found[0]
        assert f["series"] == "workqueue_depth"
        assert f["direction"] == "up"
        assert f["matched"] == "none"
        counts = reg.get("notebook_changepoints_total").collect()
        assert counts == {("workqueue_depth", "none"): 1.0}
        snap = eng.snapshot()
        assert snap["enabled"] and snap["evaluations"] == 18
        assert snap["changepoints"] == [f]

    def test_evaluate_without_new_samples_is_idempotent(self, clock):
        eng, tsdb, reg = self._engine(clock)
        for v in [0] * 8 + [12] * 10:
            self._tick(clock, tsdb, eng, v)
        before = len(eng.findings())
        for _ in range(5):
            eng.evaluate()  # no new points: nothing to consume
        assert len(eng.findings()) == before

    def test_fault_event_correlates_shift(self, clock):
        eng, tsdb, reg = self._engine(clock)
        recorder = FlightRecorder()
        tracer = get_tracer("diag-test")
        for v in [0] * 8:
            self._tick(clock, tsdb, eng, v)
        # a faulted attempt lands just before the shift
        with tracer.start_span("reconcile", {
                "controller": "notebook", "namespace": "u1",
                "name": "nb"}) as root:
            root.add_event("fault.injected", {"fault.rule": "api-degrade"})
            root.set_attribute("reconcile.result", "error")
        eng.observe_attempt(recorder.record(root))
        found = []
        for v in [12] * 10:
            found.extend(self._tick(clock, tsdb, eng, v))
        assert len(found) == 1
        assert found[0]["matched"] == "fault"
        assert any(e["detail"] == "api-degrade" for e in found[0]["events"])
        counts = reg.get("notebook_changepoints_total").collect()
        assert counts == {("workqueue_depth", "fault"): 1.0}

    def test_unwatched_series_ignored(self, clock):
        eng, tsdb, reg = self._engine(clock)
        for v in [0] * 8 + [50] * 10:
            self._tick(clock, tsdb, eng, v, name="tenant_cs.user1")
        assert eng.findings() == []

    def test_incremental_matches_offline_batch(self, clock):
        eng, tsdb, reg = self._engine(clock)
        found = []
        for v in [2] * 8 + [20] * 8 + [2] * 8:
            found.extend(self._tick(clock, tsdb, eng, v))
        raw = tsdb.query("workqueue_depth", tier="raw")["points"]
        offline = detect_level_shifts(raw)
        assert [(h["t_start"], h["direction"]) for h in offline] == \
            [(h["t_start"], h["direction"]) for h in found]


class _Harness:
    """Feeds recorder + ledger the way the Manager does (one finished
    root span per attempt), with the diagnosis engine attached."""

    def __init__(self, clock):
        self.clock = clock
        self.tracer = get_tracer("diag-explain-test")
        self.recorder = FlightRecorder()
        self.ledger = LifecycleLedger()
        self.engine = DiagnosisEngine(clock, recorder=self.recorder,
                                      lifecycle=self.ledger)

    def attempt(self, *, ns="u1", name="nb", gen=1, cause_ts=None,
                result="success", body=None):
        attrs = {"controller": "notebook", "namespace": ns, "name": name,
                 "generation": gen}
        if cause_ts is not None:
            attrs["cause_ts"] = cause_ts
        with self.tracer.start_span("reconcile", attrs) as root:
            if body is not None:
                body(root)
            root.set_attribute("reconcile.result", result)
        rec = self.recorder.record(root)
        self.ledger.observe_attempt(rec, root, "")
        self.engine.observe_attempt(rec)
        return rec

    def phase(self, phase, seconds):
        with self.tracer.start_span(phase, {"phase": phase}):
            self.clock.advance(seconds)

    def ready(self, *, ns="u1", name="nb", gen=1, cold_s=5.0):
        cause = self.clock.now()
        self.clock.advance(1.0)
        return self.attempt(
            ns=ns, name=name, gen=gen, cause_ts=cause,
            body=lambda root: (self.phase("schedule", cold_s),
                               root.add_event("notebook.ready", {})))


class TestExplainer:
    def test_fault_injection_outranks_stage_share(self, clock):
        h = _Harness(clock)
        h.ready(cold_s=30.0)

        def faulted(root):
            root.add_event("fault.injected", {"fault.rule": "api-window"})
            h.phase("apply", 0.5)

        h.attempt(result="error", body=faulted)
        out = h.engine.explain("u1", "nb")
        assert out["cause"] == CAUSE_FAULT_INJECTION
        causes = [c["cause"] for c in out["candidates"]]
        # direct evidence outranks every stage-share inference
        assert causes[0] == CAUSE_FAULT_INJECTION
        assert causes[-1] == CAUSE_NOMINAL
        scores = [c["score"] for c in out["candidates"]]
        assert scores == sorted(scores, reverse=True)
        assert "fault plan" in out["verdict"]
        assert all(link["claim"] for link in out["chain"])

    def test_ranking_is_deterministic(self, clock):
        h = _Harness(clock)
        h.ready(cold_s=30.0)
        h.attempt(result="error", body=lambda root: root.add_event(
            "fault.injected", {"fault.rule": "api-window"}))
        first = h.engine.explain("u1", "nb")
        second = h.engine.explain("u1", "nb")
        assert first == second

    def test_promote_excursion_names_primary_failover(self, clock):
        h = _Harness(clock)
        h.ready()
        h.attempt(body=lambda root: h.phase("promote", 2.0))
        out = h.engine.explain("u1", "nb")
        assert out["cause"] == CAUSE_PRIMARY_FAILOVER
        ex = out["evidence"]["excursions"]
        assert ex and ex[-1]["stage"] == "promote"
        assert ex[-1]["duration_s"] == pytest.approx(2.0)

    def test_unknown_object_is_verdictless_not_an_error(self, clock):
        h = _Harness(clock)
        out = h.engine.explain("ghost", "nb")
        assert out["verdict"] == "" and out["cause"] == ""
        assert out["error"]

    def test_nominal_floor_when_healthy(self, clock):
        h = _Harness(clock)
        # all wall time in apply (not a candidate stage): no queue wait,
        # no cold schedule, no faults — nothing beats the nominal floor
        h.attempt(cause_ts=clock.now(),
                  body=lambda root: (h.phase("apply", 5.0),
                                     root.add_event("notebook.ready", {})))
        out = h.engine.explain("u1", "nb")
        assert out["cause"] == CAUSE_NOMINAL
        assert out["verdict"]

    def test_one_line_cause_and_alert_annotation(self, clock):
        h = _Harness(clock)
        h.ready()
        rec = h.attempt(result="error", body=lambda root: root.add_event(
            "fault.injected", {"fault.rule": "api-window"}))
        line = h.engine.one_line_cause(rec.trace_id)
        assert "fault plan" in line
        snap = h.engine.annotate_alerts(
            {"firing": [{"objective": "reconcile_errors",
                         "trace_id": rec.trace_id}]})
        assert snap["firing"][0]["diagnosis"] == line
        # unknown trace and malformed entries degrade to "" — never raise
        assert h.engine.one_line_cause("no-such-trace") == ""
        snap = h.engine.annotate_alerts({"firing": [{}]})
        assert snap["firing"][0]["diagnosis"] == ""

    def test_register_twice_returns_same_family(self):
        reg = Registry()
        a = register_diagnosis_metrics(reg)["changepoints"]
        b = register_diagnosis_metrics(reg)["changepoints"]
        assert a is b


class TestExcursionRing:
    def test_ring_records_stage_duration_trace(self, clock):
        h = _Harness(clock)
        h.ready()
        rec = h.attempt(body=lambda root: h.phase("recover", 2.5))
        ring = h.ledger.excursions("u1", "nb")
        assert len(ring) == 1
        x = ring[0]
        assert x["stage"] == "recover"
        assert x["duration_s"] == pytest.approx(2.5)
        assert x["trace_id"] == rec.trace_id
        assert h.ledger.snapshot()["excursion_objects"] == 1

    def test_ring_is_bounded(self, clock):
        h = _Harness(clock)
        h.ledger.excursions_per_notebook = 4
        h.ready()
        for _ in range(10):
            h.attempt(body=lambda root: h.phase("recover", 1.0))
        assert len(h.ledger.excursions("u1", "nb")) == 4

    def test_latest_entry_tracks_newest_generation(self, clock):
        h = _Harness(clock)
        h.ready(gen=1)
        h.ready(gen=2, cold_s=9.0)
        entry = h.ledger.latest_entry("u1", "nb")
        assert entry is not None and entry["generation"] == 2
        assert h.ledger.latest_entry("u1", "ghost") is None

    def test_clear_resets_ring(self, clock):
        h = _Harness(clock)
        h.ready()
        h.attempt(body=lambda root: h.phase("recover", 1.0))
        h.ledger.clear()
        assert h.ledger.excursions("u1", "nb") == []


class TestOfflineBundles:
    def _bundle(self, source, values, t0=0.0):
        return {
            "source": source,
            "timeline": {"series": {
                "workqueue_depth": {"raw": series(values, t0=t0)}}},
            "diagnosis": {"timeline": [
                {"t": t0 + 7 * 60.0, "kind": "fault",
                 "detail": "api-window", "object": "u1/nb"}]},
        }

    def test_changepoints_from_bundle_correlates(self):
        bundle = self._bundle("m-0", [0] * 8 + [12] * 10)
        # survives a JSON round trip (the ops/diagnose artifact path)
        bundle = json.loads(json.dumps(bundle))
        hits = changepoints_from_bundle(bundle)
        assert len(hits) == 1
        assert hits[0]["series"] == "workqueue_depth"
        assert hits[0]["matched"] == "fault"

    def test_merge_timelines_sorts_and_tags(self):
        merged = merge_timelines([
            self._bundle("m-0", [1, 2, 3], t0=0.0),
            self._bundle("m-1", [4, 5, 6], t0=30.0),
        ])
        assert merged["sources"] == ["m-0", "m-1"]
        pts = merged["series"]["workqueue_depth"]
        assert [p["t"] for p in pts] == sorted(p["t"] for p in pts)
        assert {p["source"] for p in pts} == {"m-0", "m-1"}
        assert merged["points_total"] == 6

    def test_merge_handles_missing_series(self):
        merged = merge_timelines([{"source": "empty"}])
        assert merged["series"] == {} and merged["points_total"] == 0
