"""`python -m kubeflow_tpu.deploy [profile]` -> multi-doc YAML on stdout
(the `kustomize build config/overlays/{profile}` analog)."""

import sys

from .manifests import PROFILES, render_yaml

profile = sys.argv[1] if len(sys.argv) > 1 else "standalone"
if profile not in PROFILES:
    sys.exit(f"unknown profile {profile!r}; choose from {PROFILES}")
sys.stdout.write(render_yaml(profile))
