"""Deployment-plane tests: manifest rendering (kustomize analog), the
single-manager entrypoint's HTTP surface, and the chaos/CI-style validation
(reference ci/kustomize.sh + config/ tree)."""

import json
import urllib.request

import yaml

from kubeflow_tpu.deploy import PROFILES, render_profile, render_yaml, validate_docs
from kubeflow_tpu.main import build_manager, serve_http


class TestManifests:
    def test_all_profiles_render_and_validate(self):
        for profile in PROFILES:
            docs = render_profile(profile)
            validate_docs(docs)
            # YAML round-trips
            parsed = list(yaml.safe_load_all(render_yaml(profile)))
            assert len(parsed) == len(docs)

    def test_crd_has_three_versions_v1_storage(self):
        crd = render_profile("openshift")[0]
        assert crd["kind"] == "CustomResourceDefinition"
        versions = {v["name"]: v for v in crd["spec"]["versions"]}
        assert set(versions) == {"v1alpha1", "v1beta1", "v1"}
        assert versions["v1"]["storage"] is True
        assert crd["spec"]["conversion"]["strategy"] == "Webhook"
        tpu = versions["v1"]["schema"]["openAPIV3Schema"]["properties"]["spec"][
            "properties"]["tpu"]
        assert set(tpu["properties"]) == {"accelerator", "topology", "slices"}

    def test_standalone_profile_has_no_webhook_configs(self):
        kinds = {d["kind"] for d in render_profile("standalone")}
        assert "MutatingWebhookConfiguration" not in kinds
        kinds_os = {d["kind"] for d in render_profile("openshift")}
        assert {"MutatingWebhookConfiguration",
                "ValidatingWebhookConfiguration"} <= kinds_os

    def test_rbac_covers_managed_kinds(self):
        role = next(
            d for d in render_profile("openshift") if d["kind"] == "ClusterRole"
        )
        resources = {r for rule in role["rules"] for r in rule["resources"]}
        for needed in ("notebooks", "statefulsets", "services", "httproutes",
                       "referencegrants", "networkpolicies", "rolebindings"):
            assert needed in resources, f"RBAC missing {needed}"


class TestManagerHTTP:
    def test_health_metrics_state_endpoints(self):
        mgr, api, cluster, metrics = build_manager()
        cluster.add_node("n1")
        server = serve_http(0, mgr, metrics, expose_state=True)
        port = server.server_address[1]
        try:
            def get(path):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5
                ) as resp:
                    return resp.status, resp.read().decode()

            assert get("/healthz")[0] == 200
            # liveness and readiness are split: the process is alive but
            # the manager has not started reconciling yet
            try:
                get("/readyz")
                assert False, "/readyz must fail before mgr.start()"
            except urllib.error.HTTPError as e:
                assert e.code == 503
            mgr.start()
            assert get("/readyz")[0] == 200
            status, body = get("/metrics")
            assert status == 200
            assert "notebook_create_total" in body or "# TYPE" in body
            status, body = get("/state")
            assert status == 200
            assert "Node" in json.loads(body)
            assert get("/nope")[0:1] != (200,)
        except urllib.error.HTTPError as e:
            assert e.code == 404  # /nope
        finally:
            mgr.stop()
            server.shutdown()
