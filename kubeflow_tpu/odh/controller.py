"""ODH extension reconciler: routing, auth, integrations, lock protocol.

Port of OpenshiftNotebookReconciler (odh notebook_controller.go:190-526):
finalizer lifecycle for the cross-namespace / cluster-scoped objects
(HTTPRoute, ReferenceGrant, kube-rbac-proxy CRB, legacy OAuthClient), the CA
bundle ConfigMap, NetworkPolicies, pipeline integrations, the auth/non-auth
routing branch, MLflow, and removal of the reconciliation lock the mutating
webhook stamped on create.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..api.types import Notebook
from ..kube import (
    ApiServer,
    EventRecorder,
    KubeObject,
    Manager,
    NotFoundError,
    Request,
    Result,
    WatchSpec,
    retry_on_conflict,
    suppress_status_only,
)
from ..utils import tracing
from ..utils.config import OdhConfig
from . import auth, ca_bundle, constants as C, network, oauth, rbac, routing
from .dspa import sync_elyra_runtime_config_secret
from .mlflow import reconcile_mlflow_integration
from .runtime_images import sync_runtime_images_configmap
from .webhook import NotebookMutatingWebhook, NotebookValidatingWebhook

logger = logging.getLogger("kubeflow_tpu.odh")

# phase child spans (cert_trust/auth/routing) parent onto the manager's
# per-attempt reconcile root span via the shared context stack
_TRACER = tracing.get_tracer("kubeflow_tpu.odh.controller")

LOCK_PULL_SECRET_MAX_ATTEMPTS = 3


def reconciliation_lock_is_enabled(nb: Notebook) -> bool:
    """ReconciliationLockIsEnabled (notebook_controller.go:145-151)."""
    return (
        nb.metadata.annotations.get(C.STOP_ANNOTATION) == C.RECONCILIATION_LOCK_VALUE
    )


class OpenshiftNotebookReconciler:
    def __init__(
        self,
        api: ApiServer,
        cfg: OdhConfig,
        recorder: Optional[EventRecorder] = None,
    ):
        self.api = api
        self.cfg = cfg
        self.recorder = recorder or EventRecorder(api, "odh-notebook-controller")
        # per-notebook attempts waiting for the SA pull secret before the
        # lock is removed anyway (best-effort wait, reference retry.OnError
        # Steps:3, notebook_controller.go:158-181)
        self._lock_wait_attempts: dict[tuple[str, str], int] = {}

    # -- main loop -------------------------------------------------------------
    def reconcile(self, req: Request) -> Result:
        obj = self.api.try_get("Notebook", req.namespace, req.name)
        if obj is None:
            return Result()
        nb = Notebook(obj)

        if obj.metadata.deletion_timestamp is not None:
            return self._handle_deletion(nb)

        # finalizers first; adding them requeues (notebook_controller.go:335-381)
        if self._ensure_finalizers(nb):
            return Result(requeue=True)

        with _TRACER.start_span("cert_trust",
                                {"phase": "cert_trust"}) as ct_span:
            ca_bundle.create_notebook_cert_configmap(self.api, nb)
            if ca_bundle.is_configmap_deleted(self.api, nb):
                ct_span.add_event("cert_trust.source_configmap_deleted")
                ca_bundle.unset_notebook_cert_config(self.api, nb)

        network.reconcile_all_network_policies(
            self.api, nb, self.cfg.controller_namespace
        )
        sync_runtime_images_configmap(
            self.api, nb.namespace, self.cfg.controller_namespace
        )
        if self.cfg.set_pipeline_rbac:
            rbac.reconcile_role_bindings(self.api, nb)
        if self.cfg.set_pipeline_secret:
            try:
                sync_elyra_runtime_config_secret(self.api, nb, self.cfg)
            except Exception as err:
                logger.warning("elyra secret reconcile failed: %s", err)

        with _TRACER.start_span("routing",
                                {"phase": "routing"}) as routing_span:
            auth_mode = self._auth_enabled(nb)
            routing_span.set_attribute("auth_enabled", auth_mode)
            # ReferenceGrant before HTTPRoutes (notebook_controller.go:427-433)
            routing.reconcile_reference_grant(
                self.api, nb, self.cfg.controller_namespace)

            if auth_mode:
                routing.ensure_conflicting_httproute_absent(
                    self.api, nb, self.cfg.controller_namespace,
                    is_auth_mode=True
                )
                with _TRACER.start_span("auth", {"phase": "auth"}):
                    auth.reconcile_auth_resources(self.api, nb)
                routing.reconcile_httproute(
                    self.api,
                    nb,
                    self.cfg.controller_namespace,
                    self.cfg.gateway_name,
                    self.cfg.gateway_namespace,
                    new_route=routing.new_kube_rbac_proxy_httproute,
                )
            else:
                routing.ensure_conflicting_httproute_absent(
                    self.api, nb, self.cfg.controller_namespace,
                    is_auth_mode=False
                )
                with _TRACER.start_span("auth", {"phase": "auth"}):
                    auth.cleanup_cluster_role_binding(self.api, nb)
                routing.reconcile_httproute(
                    self.api,
                    nb,
                    self.cfg.controller_namespace,
                    self.cfg.gateway_name,
                    self.cfg.gateway_namespace,
                )

        if self.cfg.mlflow_enabled:
            delay = reconcile_mlflow_integration(self.api, nb, self.recorder)
            if delay is not None:
                return Result(requeue_after=delay)

        if reconciliation_lock_is_enabled(nb):
            return self._remove_reconciliation_lock(nb)
        return Result()

    # -- helpers ---------------------------------------------------------------
    def _auth_enabled(self, nb: Notebook) -> bool:
        return nb.metadata.annotations.get(C.ANNOTATION_INJECT_AUTH) == "true"

    def _ensure_finalizers(self, nb: Notebook) -> bool:
        """Add missing finalizers; True when a write happened (and the
        reconcile should requeue)."""
        want = [C.HTTPROUTE_FINALIZER, C.REFERENCEGRANT_FINALIZER]
        if self._auth_enabled(nb):
            want.append(C.KUBE_RBAC_PROXY_FINALIZER)
        if C.OAUTH_CLIENT_FINALIZER not in nb.metadata.finalizers \
                and self.api.try_get(
                    "OAuthClient", "",
                    oauth.oauth_client_name(nb)) is not None:
            # a legacy RHOAI 2.x client exists for this notebook: gate its
            # deletion-time cleanup (without this the _handle_deletion
            # branch at OAUTH_CLIENT_FINALIZER is unreachable).  The
            # already-present check keeps the cluster-scoped lookup off
            # the steady-state reconcile path
            want.append(C.OAUTH_CLIENT_FINALIZER)
        missing = [f for f in want if f not in nb.metadata.finalizers]
        if not missing:
            return False

        def add() -> None:
            live = self.api.get("Notebook", nb.namespace, nb.name)
            changed = False
            for f in missing:
                if f not in live.metadata.finalizers:
                    live.metadata.finalizers.append(f)
                    changed = True
            if changed:
                self.api.update(live)

        retry_on_conflict(add)
        return True

    def _handle_deletion(self, nb: Notebook) -> Result:
        """Finalizer-gated cleanup of cross-namespace / cluster-scoped
        objects (notebook_controller.go:206-333)."""
        finalizers = list(nb.metadata.finalizers)
        to_remove: list[str] = []
        if C.OAUTH_CLIENT_FINALIZER in finalizers:
            oauth.delete_oauth_client(self.api, nb)
            to_remove.append(C.OAUTH_CLIENT_FINALIZER)
        if C.HTTPROUTE_FINALIZER in finalizers:
            routing.delete_httproutes_for_notebook(
                self.api, nb, self.cfg.controller_namespace
            )
            to_remove.append(C.HTTPROUTE_FINALIZER)
        if C.REFERENCEGRANT_FINALIZER in finalizers:
            routing.delete_reference_grant_if_last_notebook(self.api, nb)
            to_remove.append(C.REFERENCEGRANT_FINALIZER)
        if C.KUBE_RBAC_PROXY_FINALIZER in finalizers:
            auth.cleanup_cluster_role_binding(self.api, nb)
            to_remove.append(C.KUBE_RBAC_PROXY_FINALIZER)
        if not to_remove:
            return Result()

        def strip() -> None:
            try:
                live = self.api.get("Notebook", nb.namespace, nb.name)
            except NotFoundError:
                return
            live.metadata.finalizers = [
                f for f in live.metadata.finalizers if f not in to_remove
            ]
            self.api.update(live)

        retry_on_conflict(strip)
        self._lock_wait_attempts.pop((nb.namespace, nb.name), None)
        return Result()

    def _remove_reconciliation_lock(self, nb: Notebook) -> Result:
        """Wait (bounded, best-effort) for the notebook SA's pull secret,
        then merge-patch the lock annotation away
        (RemoveReconciliationLock, notebook_controller.go:155-186)."""
        key = (nb.namespace, nb.name)
        sa = self.api.try_get("ServiceAccount", nb.namespace, nb.name)
        pull_secrets = (sa.body.get("imagePullSecrets") if sa else None) or []
        if sa is not None and not pull_secrets:
            attempts = self._lock_wait_attempts.get(key, 0)
            if attempts < LOCK_PULL_SECRET_MAX_ATTEMPTS:
                self._lock_wait_attempts[key] = attempts + 1
                return Result(requeue_after=1.0 * (5**attempts))
        self._lock_wait_attempts.pop(key, None)
        self.api.merge_patch(
            "Notebook",
            nb.namespace,
            nb.name,
            {"metadata": {"annotations": {C.STOP_ANNOTATION: None}}},
        )
        return Result()


def setup_odh_controllers(
    mgr: Manager, cfg: Optional[OdhConfig] = None
) -> OpenshiftNotebookReconciler:
    """Register the ODH reconciler and both webhooks (odh main.go:141-347).
    Watch wiring mirrors SetupWithManager (:736-884): Owns the namespaced
    objects; Watches central-ns HTTPRoutes and CA-bundle ConfigMaps with
    label/name fan-out mappers."""
    cfg = cfg or OdhConfig.from_env()
    api = mgr.api
    rec = OpenshiftNotebookReconciler(api, cfg)

    api.register_admission(NotebookMutatingWebhook(api, cfg).hook())
    api.register_admission(NotebookValidatingWebhook(api, cfg).hook())

    # fleet sweeps in the fan-out mappers below read the informer cache's
    # namespace index instead of live-listing every Notebook per event
    cache = mgr.cache
    if cache is not None:
        cache.add_namespace_index("Notebook")

    def list_notebooks(namespace: str) -> list[KubeObject]:
        if cache is not None:
            return cache.list("Notebook", namespace=namespace)
        return api.list("Notebook", namespace=namespace)

    def httproute_to_request(route: KubeObject) -> list[Request]:
        name = route.metadata.labels.get(C.NOTEBOOK_NAME_LABEL)
        namespace = route.metadata.labels.get(C.NOTEBOOK_NAMESPACE_LABEL)
        if name and namespace:
            return [Request(namespace, name)]
        return []

    def configmap_to_requests(cm: KubeObject) -> list[Request]:
        # owned ConfigMaps (kube-rbac-proxy config) map to their notebook;
        # CA-bundle source ConfigMaps fan out to every notebook in the
        # namespace (odh SetupWithManager ConfigMap watch, :812-860)
        ref = cm.metadata.controller_owner()
        if ref is not None and ref.kind == "Notebook":
            return [Request(cm.namespace, ref.name)]
        if cm.name not in (
            C.ODH_TRUSTED_CA_BUNDLE_CONFIGMAP,
            C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP,
            C.KUBE_ROOT_CA_CONFIGMAP,
            C.OPENSHIFT_SERVICE_CA_CONFIGMAP,
        ):
            return []
        return [
            Request(n.namespace, n.name)
            for n in list_notebooks(cm.namespace)
        ]

    def referencegrant_to_requests(grant: KubeObject) -> list[Request]:
        if grant.name != C.REFERENCEGRANT_NAME:
            return []
        notebooks = list_notebooks(grant.namespace)
        return [Request(n.namespace, n.name) for n in notebooks[:1]]

    mgr.register(
        "odh-notebook",
        rec,
        for_kind="Notebook",
        owns=[
            "ServiceAccount",
            "Service",
            "Secret",
            "NetworkPolicy",
            "RoleBinding",
        ],
        watches=[
            WatchSpec(kind="HTTPRoute", mapper=httproute_to_request),
            WatchSpec(kind="ReferenceGrant", mapper=referencegrant_to_requests),
            WatchSpec(kind="ConfigMap", mapper=configmap_to_requests),
        ],
        # the odh reconciler never reads Notebook status; the core
        # controller's status writes must not re-run the routing/auth pass
        for_predicate=suppress_status_only,
    )
    return rec
