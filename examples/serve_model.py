"""End-to-end in-notebook SERVING workflow: train -> quantize -> decode.

The serving half of the compute-plane surface (train_llm.py covers
training) — what a workbench user runs to serve a model they just
trained:

  1. train a tiny decoder a few steps (stand-in for a real checkpoint);
  2. plain bf16 KV-cache decode (`generate`: fused projections, staged
     KV writes, layout-native cache — models/generate.py defaults);
  3. int8 weight-streaming decode (`fuse_decode_params` then
     `quantize_params` — fuse BEFORE quantize so scales stay
     per-projection), logits cross-checked against bf16;
  4. greedy speculative decoding with a self-draft (exactness asserted);
  5. temperature sampling via the rejection-sampling speculative mode.

Runs anywhere (CPU mesh or a real chip).  Prints RESULT: OK when every
stage behaves.
"""

from __future__ import annotations

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

# some images re-register the hardware plugin from a site hook AFTER env
# processing; pin the requested platform explicitly (tests/conftest.py
# does the same)
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from kubeflow_tpu.models.configs import TINY  # noqa: E402
from kubeflow_tpu.models.generate import (  # noqa: E402
    decode_config,
    fuse_decode_params,
    generate,
    unroll_params,
)
from kubeflow_tpu.models.quant import quantize_params  # noqa: E402
from kubeflow_tpu.models.speculative import (  # noqa: E402
    speculative_generate,
    speculative_sample,
)
from kubeflow_tpu.models.train import (  # noqa: E402
    default_optimizer,
    setup_training,
)
from kubeflow_tpu.parallel.mesh import MeshConfig, make_mesh  # noqa: E402


def main() -> int:
    cfg = TINY
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    setup = setup_training(cfg, mesh, batch_shape=(4, 64),
                           optimizer=default_optimizer(learning_rate=1e-3))
    key = jax.random.PRNGKey(0)
    data = {"inputs": jax.random.randint(key, (4, 64), 0, cfg.vocab_size)}
    data["targets"] = jnp.roll(data["inputs"], -1, axis=1)
    state = setup.state
    for _ in range(5):
        state, metrics = setup.train_step(state, data)
    print(f"trained 5 steps: loss {float(metrics['loss']):.3f}")
    params = state.params

    prompt = data["inputs"][:, :16]
    out = generate(cfg, params, prompt, max_new_tokens=12)
    assert out.shape == (4, 28)
    print("bf16 decode:", np.asarray(out[0, 16:]).tolist())

    # int8: fuse FIRST (per-projection scales), then quantize
    dcfg = decode_config(cfg)
    fused = fuse_decode_params(unroll_params(params, cfg.num_layers), dcfg)
    qparams = quantize_params(fused)
    qout = generate(dcfg.with_(weight_dtype="int8"), qparams, prompt,
                    max_new_tokens=12)
    agree = float(np.mean(np.asarray(out) == np.asarray(qout)))
    print(f"int8 decode: token agreement vs bf16 = {agree:.2f}")
    assert agree > 0.8, agree

    # speculative runs its decode with staged_kv=False (rewind path), so
    # the bitwise-exactness reference must be the SAME numerics: an
    # unstaged generate run.  Comparing against the staged default can
    # flip near-tie argmaxes (softmax reassociation — the staged-vs-
    # unstaged gate in tests/test_generate.py is >=0.95 agreement, not
    # equality).
    ref_unstaged = generate(decode_config(cfg).with_(staged_kv=False),
                            params, prompt, max_new_tokens=12)
    spec_out, rounds = speculative_generate(
        cfg, params, cfg, params, prompt, 12, gamma=4)
    assert (np.asarray(spec_out) == np.asarray(ref_unstaged)).all(), \
        "speculative output must equal unstaged plain greedy"
    print(f"speculative (self-draft): exact in {int(rounds)} rounds")

    samp, steps, rate = speculative_sample(
        cfg, params, cfg, params, prompt, 12, gamma=4,
        temperature=0.8, rng=jax.random.PRNGKey(7))
    assert samp.shape == (4, 28)
    print(f"sampled decode: accept_rate {float(rate):.2f} "
          f"in {int(steps)} rounds")

    print("RESULT: OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
