"""Hot-path scan ban: reconciler bodies read the cache, not api.list.

PR 5 moved every reconcile hot path onto the InformerCache so one event
costs O(its objects), not O(cluster); PR 8's 10k-notebook gate depends
on it.  This analyzer flags `api.list(...)` / `api.list_with_rv(...)` /
`api.select(...)` calls (receiver chain ending in `.api`) inside methods
of reconciler-shaped classes (name ending in Reconciler / Controller /
Manager / Scheduler) UNLESS the call sits under an `if`/ternary whose
test mentions the cache — the sanctioned cache-less fallback pattern:

    if self.cache is not None:
        return self.cache.select(...)
    return self.api.list(...)

Anything else is either a real regression (fix it) or a justified
exception (allowlist it with the reason).
"""

from __future__ import annotations

import ast

from . import Module, Violation, dotted

CHECK = "hotpath"

_CLASS_SUFFIXES = ("Reconciler", "Controller", "Manager", "Scheduler")
_SCAN_METHODS = {"list", "list_with_rv", "select"}


def _mentions_cache(test) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and "cache" in node.id.lower():
            return True
        if isinstance(node, ast.Attribute) and "cache" in node.attr.lower():
            return True
    return False


def analyze(mod: Module) -> list[Violation]:
    if not mod.rel.startswith("kubeflow_tpu/"):
        return []
    out = []

    def scan_class(cls: ast.ClassDef, prefix: str):
        qn = f"{prefix}.{cls.name}" if prefix else cls.name
        # parent chain per node so we can look for cache-guarded ancestors
        parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(cls):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        # early-return guard regions: after `if <cache...>: ... return`,
        # the rest of the block IS the cache-less fallback
        guarded_lines: set[int] = set()
        for parent in ast.walk(cls):
            body = getattr(parent, "body", None)
            for block in (body, getattr(parent, "orelse", None)):
                if not isinstance(block, list):
                    continue
                for i, stmt in enumerate(block):
                    if isinstance(stmt, ast.If) \
                            and _mentions_cache(stmt.test) \
                            and stmt.body \
                            and isinstance(stmt.body[-1],
                                           (ast.Return, ast.Raise)):
                        for later in block[i + 1:]:
                            end = getattr(later, "end_lineno", later.lineno)
                            guarded_lines.update(
                                range(later.lineno, end + 1))
        for node in ast.walk(cls):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SCAN_METHODS):
                continue
            recv = dotted(node.func.value)
            if not recv or recv.split(".")[-1] != "api":
                continue
            guarded = node.lineno in guarded_lines
            cur = node
            while not guarded and cur in parents:
                cur = parents[cur]
                if isinstance(cur, (ast.If, ast.IfExp)) and \
                        _mentions_cache(cur.test):
                    guarded = True
                    break
                if isinstance(cur, ast.ClassDef):
                    break
            if guarded:
                continue
            out.append(Violation(
                CHECK, mod.rel, node.lineno, mod.qualname_at(node.lineno),
                f"{recv}.{node.func.attr}() inside {cls.name} — hot paths "
                "read the InformerCache (cache.list/select/by_index); "
                "guard an intentional fallback on cache availability or "
                "allowlist with a reason"))

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                if child.name.endswith(_CLASS_SUFFIXES):
                    scan_class(child, prefix)
                walk(child, f"{prefix}.{child.name}" if prefix
                     else child.name)
            else:
                walk(child, prefix)

    walk(mod.tree, "")
    return out
