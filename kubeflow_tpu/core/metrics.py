"""Notebook controller metrics, mirroring pkg/metrics/metrics.go:13-99:
counters for creations/failures/cullings plus a scraper-style gauge that
counts running notebooks by listing workload StatefulSets with the
notebook-name label, extended with TPU slice/chip gauges."""

from __future__ import annotations

import copy
import json
from typing import Optional

from ..api.types import CONDITION_RECOVERY_EXHAUSTED, TPUSpec
from ..kube import ApiServer, parse_quantity
from ..utils.diagnosis import register_diagnosis_metrics
from ..utils.lifecycle import register_lifecycle_metrics
from ..utils.metering import (BUCKET_IDLE, BUCKET_READY, BUCKET_RECOVERING,
                              BUCKET_SCHEDULING, register_metering_metrics)
from ..utils.metrics import Registry, register_cardinality_metrics
from ..utils.profiler import register_profiler_metrics
from ..utils.slo import register_slo_metrics
from . import constants as C
from .telemetry import register_dataplane_metrics

# /debug/fleet health states, derived per Notebook by fleet_state(); a
# bounded set so the rollup is O(namespaces x states), never O(fleet)
FLEET_STATES = ("ready", "degraded", "recovering", "exhausted",
                "scheduling", "stopped", "pending")


def fleet_state(nb) -> str:
    """One health bucket per Notebook for the fleet rollup.  The terminal
    RecoveryExhausted condition wins (an exhausted slice reads Degraded in
    sliceHealth but has stopped consuming restarts — the operator signal);
    an active recovery budget (status.sliceRecovery with attempts) turns a
    broken slice "recovering" rather than plain "degraded"; CPU notebooks
    (no sliceHealth) bucket off readyReplicas."""
    status = nb.body.get("status") or {}
    for cond in (status.get("conditions") or []):
        if cond.get("type") == CONDITION_RECOVERY_EXHAUSTED and \
                cond.get("status") == "True":
            return "exhausted"
    health = status.get("sliceHealth")
    if health in ("Healthy",):
        return "ready"
    if health in ("Stopped", "Stopping"):
        return "stopped"
    if health in ("Scheduling", "Queued"):
        # quota/fair-share-queued gangs roll up with scheduling: both are
        # "wants chips, has none"; per-tenant queue depth lives in the
        # tenancy section of /debug/fleet, not a new fleet state
        return "scheduling"
    if health in ("Degraded", "Unhealthy"):
        recovery = status.get("sliceRecovery") or {}
        if any(e.get("attempts") for e in recovery.values()
               if isinstance(e, dict)):
            return "recovering"
        return "degraded"
    # CPU notebook (or no status yet)
    return "ready" if status.get("readyReplicas") else "pending"


# (accelerator, topology, slices) -> total chips; topology resolution is
# pure, so the cache never invalidates
_CHIP_CACHE: dict[tuple[str, str, int], float] = {}


def placement_chips(nb) -> float:
    """Total TPU chips a placed notebook's gang occupies (0.0 for CPU
    notebooks or an unresolvable shape — it still meters wall time)."""
    tpu = nb.spec.get("tpu") or {}
    if not tpu.get("accelerator"):
        return 0.0
    key = (str(tpu.get("accelerator", "")), str(tpu.get("topology", "")),
           int(tpu.get("slices", 1) or 1))
    chips = _CHIP_CACHE.get(key)
    if chips is None:
        try:
            shape = TPUSpec.from_dict(tpu).validate()
            chips = float(shape.chips * max(key[2], 1))
        except Exception:  # noqa: BLE001 — an invalid spec must not
            chips = 0.0    # break the metering census
        _CHIP_CACHE[key] = chips
    return chips


def metering_bucket(nb) -> str:
    """The chip-second bucket a placed notebook is currently accruing
    into: stop-annotated (culled or user-stopped) counts as idle — chips
    held past the cull decision; otherwise sliceHealth partitions placed
    time into ready / scheduling / recovering."""
    if C.STOP_ANNOTATION in (nb.metadata.annotations or {}):
        return BUCKET_IDLE
    health = (nb.body.get("status") or {}).get("sliceHealth")
    if health == "Healthy":
        return BUCKET_READY
    if health in ("Unhealthy", "Degraded"):
        return BUCKET_RECOVERING
    if health in ("Stopping", "Stopped"):
        return BUCKET_IDLE
    # Scheduling, or placed before the first health write
    return BUCKET_SCHEDULING


def histogram_quantile(hist, q: float) -> float:
    """Prometheus-style quantile estimate over ALL label sets of one
    histogram: cumulative bucket counts summed across series, then linear
    interpolation inside the target bucket (the +Inf bucket clamps to the
    largest finite bound).  Feeds the TSDB's p99-vs-time series without
    needing raw samples retained anywhere."""
    totals: dict[float, float] = {}
    for key in hist.collect():
        for bound, c in hist.bucket_counts(*key).items():
            totals[bound] = totals.get(bound, 0.0) + c
    if not totals:
        return 0.0
    count = totals.get(float("inf"), 0.0)
    if count <= 0:
        return 0.0
    rank = q * count
    bounds = sorted(b for b in totals if b != float("inf"))
    prev_bound, prev_cum = 0.0, 0.0
    for b in bounds:
        cum = totals[b]
        if cum >= rank:
            if cum == prev_cum:
                return b
            return prev_bound + (b - prev_bound) * \
                (rank - prev_cum) / (cum - prev_cum)
        prev_bound, prev_cum = b, cum
    return bounds[-1] if bounds else 0.0


class NotebookMetrics:
    def __init__(self, api: ApiServer, registry: Optional[Registry] = None,
                 manager=None):
        self.api = api
        self.registry = registry or Registry()
        self.manager = manager  # kube.Manager: workqueue gauges source
        self.running = self.registry.gauge(
            "notebook_running",
            "Current running notebooks in the cluster",
            labels=("namespace",),
        )
        self.creation = self.registry.counter(
            "notebook_create_total",
            "Total times of creating notebooks",
            labels=("namespace",),
        )
        self.fail_creation = self.registry.counter(
            "notebook_create_failed_total",
            "Total failure times of creating notebooks",
            labels=("namespace",),
        )
        self.culling = self.registry.counter(
            "notebook_culling_total",
            "Total times of culling notebooks",
            labels=("namespace", "name"),
        )
        self.last_culling_timestamp = self.registry.gauge(
            "last_notebook_culling_timestamp_seconds",
            "Timestamp of the last notebook culling in seconds",
            labels=("namespace", "name"),
        )
        # TPU extensions
        self.tpu_chips_requested = self.registry.gauge(
            "notebook_tpu_chips_requested",
            "TPU chips requested by running notebook slices",
            labels=("namespace",),
        )
        # first-readiness latency distribution, observed once per notebook
        # by the NotebookReconciler off the injected clock (the reference
        # has no such metric; NotebookOS-style schedulers want it)
        self.notebook_ready_seconds = self.registry.histogram(
            "notebook_to_ready_seconds",
            "Latency from Notebook creation to all workers Ready",
            labels=("namespace",),
            buckets=(1.0, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
                     1800.0, 3600.0),
        )
        # self-healing (core/selfheal.py): slice-atomic restarts performed
        # by the recovery engine, labeled by the disruption classification
        # (a bounded set — see selfheal.REASON_*), and the
        # disruption-detected -> slice-Healthy-again latency distribution
        self.slice_restarts = self.registry.counter(
            "notebook_slice_restarts_total",
            "Slice-atomic worker restarts performed by the self-healing "
            "engine",
            labels=("namespace", "reason"),
        )
        self.disruption_recovery_seconds = self.registry.histogram(
            "notebook_disruption_recovery_seconds",
            "Latency from disruption detection to the slice reading "
            "Healthy again",
            labels=("namespace",),
            buckets=(1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0, 600.0,
                     1800.0),
        )
        # session-state tier (core/sessionstate.py + selfheal migrate verb):
        # snapshots the control plane recorded/confirmed (trigger: final |
        # cull), the checkpoint age observed at each migrate decision, and
        # the migrate-verb outcomes.  trigger/result are bounded sets —
        # selfheal.MIGRATE_* constants.
        self.checkpoint_snapshots = self.registry.counter(
            "notebook_checkpoint_snapshots_total",
            "Session checkpoints recorded or confirmed by the controllers",
            labels=("namespace", "trigger"),
        )
        self.checkpoint_age_seconds = self.registry.histogram(
            "notebook_checkpoint_age_seconds",
            "Age of the freshest session checkpoint at migrate-decision "
            "time",
            labels=("namespace",),
            buckets=(1.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0, 1800.0,
                     3600.0),
        )
        self.migrations = self.registry.counter(
            "notebook_migrations_total",
            "Checkpoint/migrate recoveries by trigger and outcome",
            labels=("trigger", "result"),
        )
        # replicated-kernel tier (spec.replication + selfheal promote
        # verb): promotion outcomes (result is the bounded selfheal
        # PROMOTE_RESULT_* set), the primary-failure -> follower-promoted
        # latency (sub-second buckets — the tier's reason to exist), and
        # session-store writes rejected by the replication epoch fence
        # (a demoted/zombie primary tried to ack state after demotion)
        self.promotions = self.registry.counter(
            "notebook_promotions_total",
            "Primary promotions attempted by the self-healing engine, by "
            "outcome",
            labels=("namespace", "result"),
        )
        self.promotion_duration_seconds = self.registry.histogram(
            "notebook_promotion_duration_seconds",
            "Latency from primary disruption detection to a follower "
            "promoted (epoch fenced, primary pointer flipped)",
            labels=("namespace",),
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0),
        )
        self.replication_fenced_writes = self.registry.counter(
            "notebook_replication_fenced_writes_total",
            "Session-store writes rejected by the replication epoch fence "
            "(zombie primary writing under lost authority)",
            labels=("namespace",),
        )
        # slice scheduler + warm pool (core/scheduler.py): per-reconcile
        # scheduling outcomes (result is the bounded scheduler.SCHEDULE_*
        # set), per-claim warm-pool outcomes (hit | miss | bypass), and the
        # per-shape pool census recomputed at scrape time from the
        # TPUWarmPool objects (state: Provisioning | Ready | Claimed)
        self.schedule_attempts = self.registry.counter(
            "notebook_schedule_attempts_total",
            "Slice-scheduler placement attempts by outcome",
            labels=("result",),
        )
        self.warmpool_hits = self.registry.counter(
            "notebook_warmpool_hits_total",
            "Warm-pool claim outcomes (hit=pre-provisioned slice claimed, "
            "miss=cold provision, bypass=pre-existing capacity)",
            labels=("result",),
        )
        self.warmpool_size = self.registry.gauge(
            "notebook_warmpool_size",
            "Warm-pool slices per accelerator-topology shape and state",
            labels=("shape", "state"),
        )
        # tenancy layer (core/scheduler.py admission gate +
        # core/preemption.py): preemption outcomes (result is the bounded
        # preemption.PREEMPT_* set, priority the victim's class — or the
        # beneficiary's for result="no-victims"), and the quota/fair-share
        # queue wait from first queuing to placement-intent written
        # (observed as 0 for gangs that never queued, so the distribution
        # is over ALL placements and its p99 is the time-to-placement SLO)
        self.preemptions = self.registry.counter(
            "notebook_preemptions_total",
            "Checkpoint-then-preempt evictions by outcome and priority "
            "class",
            labels=("result", "priority"),
        )
        self.queue_wait_seconds = self.registry.histogram(
            "notebook_queue_wait_seconds",
            "Time a gang spent queued behind quota/fair share before its "
            "placement intent was written, by priority class",
            labels=("priority",),
            buckets=(0.5, 1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0,
                     600.0, 1800.0),
        )
        # watch-dispatch audit (kube/store.py filtered fan-out): delivered
        # = callbacks actually invoked per event kind; skipped = callbacks
        # an unfiltered broadcast would have made but the per-kind
        # subscriber index spared.  skipped >> delivered on churn-heavy
        # kinds is the fleet-scale fan-out reduction, proven in numbers.
        self.watch_dispatch = self.registry.counter(
            "apiserver_watch_dispatch_total",
            "Watch dispatch outcomes per event kind on the in-memory "
            "apiserver (delivered = interested watchers invoked, skipped = "
            "watchers the filtered index never touched)",
            labels=("kind", "result"),
        )
        # workqueue / retry observability (controller-runtime exports the
        # same family: workqueue_depth, workqueue_retries_total) — scraped
        # from Manager.queue_stats() when a manager is attached.  The
        # *_total families are monotonic counters fed by deltas from the
        # scrape-state snapshot (a gauge set() from scrape state would
        # break Prometheus rate()/increase() on counter-suffixed names)
        self.workqueue_depth = self.registry.gauge(
            "workqueue_depth",
            "Current reconcile requests queued per controller",
            labels=("controller",),
        )
        self.workqueue_backoff_pending = self.registry.gauge(
            "workqueue_backoff_pending",
            "Reconcile requests waiting out a retry backoff",
            labels=("controller",),
        )
        self.workqueue_retries_total = self.registry.counter(
            "workqueue_retries_total",
            "Total rate-limited requeues scheduled per controller",
            labels=("controller",),
        )
        self.workqueue_last_backoff_seconds = self.registry.gauge(
            "workqueue_last_backoff_seconds",
            "Most recent backoff delay handed out per controller",
            labels=("controller",),
        )
        self.workqueue_longest_running = self.registry.gauge(
            "workqueue_longest_running_processor_seconds",
            "Age of the oldest reconcile currently being processed per "
            "controller (0 when idle)",
            labels=("controller",),
        )
        self.reconcile_errors_total = self.registry.counter(
            "reconcile_errors_total",
            "Reconcile requests dropped after exhausting their retry budget",
            labels=("controller",),
        )
        # fleet SLO engine families (utils/slo.py) + continuous-profiler
        # self-measurement (utils/profiler.py): registered here so the
        # metric inventory is identical whether or not an engine/profiler
        # is attached (ci/metrics_families.golden stability); the engine
        # and profiler re-register identically and feed the same objects
        self.slo_burn_rate, self.slo_budget_remaining, self.slo_firing = \
            register_slo_metrics(self.registry)
        self.profiler_overhead, self.profiler_samples = \
            register_profiler_metrics(self.registry)
        # lifecycle critical-path family (utils/lifecycle.py): registered
        # here for inventory stability; an attached LifecycleLedger
        # re-registers identically and feeds the same histogram
        self.stage_duration = register_lifecycle_metrics(self.registry)
        # data-plane rollup families (core/telemetry.py): registered here
        # so the inventory is identical whether or not a
        # WorkerTelemetryAggregator is attached; the aggregator
        # re-registers identically and feeds the same objects
        register_dataplane_metrics(self.registry)
        # tenant metering families (utils/metering.py): registered here
        # for inventory stability; an attached TenantMeteringLedger
        # re-registers identically and feeds the same counters
        register_metering_metrics(self.registry)
        # diagnosis family (utils/diagnosis.py): registered here for
        # inventory stability; an attached DiagnosisEngine re-registers
        # identically and feeds the same counter
        register_diagnosis_metrics(self.registry)
        # cardinality-guard visibility (utils/metrics.py): ONE exported
        # family fed at scrape time from every scraped registry's
        # labelsets_dropped() — per-registry auto-registration would emit
        # duplicate TYPE lines in the combined exposition
        self.labelsets_dropped = register_cardinality_metrics(self.registry)
        # active-active sharding families (kube/shard.py): registered
        # unconditionally for inventory stability; fed from an attached
        # ShardedFleet's per-replica snapshots at every scrape
        self.shard_keys_owned = self.registry.gauge(
            "notebook_shard_keys_owned",
            "Notebook keys owned by each control-plane shard replica "
            "(off its filtered informer cache)",
            labels=("shard",),
        )
        self.shard_epoch = self.registry.gauge(
            "notebook_shard_epoch",
            "Shard-map epoch as last observed by each replica (replicas "
            "disagreeing for long means a stuck membership view)",
            labels=("shard",),
        )
        self.shard_fenced_writes = self.registry.counter(
            "notebook_shard_fenced_writes_total",
            "Writes rejected by epoch fencing per shard replica (a "
            "deposed/zombie holder tried to write under lost authority)",
            labels=("shard",),
        )
        self.shard_handoff_duration = self.registry.histogram(
            "notebook_shard_handoff_duration_seconds",
            "Shard-map handoff duration, membership commit to the ack "
            "that completed it (drains + adoptions)",
            buckets=(0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0),
        )
        # ShardedFleet attached via attach_shard(); per-shard handoff
        # durations already fed into the histogram (indexed per shard)
        self.shards = None
        self._handoff_fed: dict[str, int] = {}
        # SLOEngine attached via attach_slo(): evaluated at every scrape
        # so burn rates/alerts advance at scrape resolution
        self.slo = None
        # WorkerTelemetryAggregator attached via attach_dataplane():
        # evaluated at every scrape, BEFORE the SLO engine so its verdict
        # counters are fresh when the burn rates read them
        self.dataplane = None
        # LifecycleLedger attached via attach_lifecycle(): fleet_snapshot
        # grows the per-namespace stage-latency rollup and the TSDB feed
        # samples its stage p99s
        self.lifecycle = None
        # TenantMeteringLedger attached via attach_metering(): every
        # scrape() feeds it the placement census + apiserver tenant verb
        # counts and runs the noisy-neighbor evaluation; fleet_snapshot
        # grows a `tenants` section
        self.metering = None
        # TimeSeriesStore attached via attach_tsdb(): every scrape()
        # appends one sample per selected series (the /debug/timeline and
        # diagnostics-bundle history)
        self.tsdb = None
        self._tsdb_clock = None
        # DiagnosisEngine attached via attach_diagnosis(): every scrape()
        # runs one change-point evaluation AFTER the TSDB sample lands
        # (the detector consumes the raw tier this scrape just extended);
        # fleet_snapshot grows a `diagnosis` section
        self.diagnosis = None
        # previous-scrape values behind the TSDB's *_delta series (a
        # cumulative counter can't feed a level-shift detector)
        self._tsdb_prev: dict[str, float] = {}
        # last snapshot of the manager's cumulative totals, so each scrape
        # feeds the counters exactly the delta since the previous scrape
        self._counter_snapshots: dict[tuple, float] = {}
        # shape labels emitted by the last warm-pool census — a deleted
        # pool's series must be driven to 0, not left at its last value
        self._warmpool_shapes: set[str] = set()
        # whether the cache-side census aggregates registered successfully
        # (None = not yet attempted; False = fell back to list scans, e.g.
        # a real-cluster backend without the TPUWarmPool CRD)
        self._census_ready: Optional[bool] = None

    def attach_manager(self, manager) -> None:
        self.manager = manager

    def attach_slo(self, engine) -> None:
        """Attach a fleet SLOEngine; every scrape() evaluates it (burn
        rates, budget gauges, alert transitions) so the SLO verdict
        advances exactly as often as anyone looks at the fleet."""
        self.slo = engine

    def attach_dataplane(self, aggregator) -> None:
        """Attach a WorkerTelemetryAggregator; every scrape() rolls the
        per-worker telemetry annotations into the notebook_dataplane_*
        series and runs straggler detection."""
        self.dataplane = aggregator

    def attach_shard(self, fleet) -> None:
        """Attach a ShardedFleet (kube/shard.py); every scrape() feeds
        the notebook_shard_* families from its replicas' snapshots and
        fleet_snapshot() grows a `shards` section."""
        self.shards = fleet

    def attach_lifecycle(self, ledger) -> None:
        """Attach a LifecycleLedger (utils/lifecycle.py); fleet_snapshot()
        grows the per-namespace stage-latency rollup and the TSDB feed
        samples the ledger's stage p99s each scrape."""
        self.lifecycle = ledger

    def attach_metering(self, ledger) -> None:
        """Attach a TenantMeteringLedger (utils/metering.py); every
        scrape() accrues chip-seconds off the placement census, folds the
        apiserver tenant verb counts, and evaluates the noisy-neighbor
        detector (before the SLO engine, whose tenant_fairness objective
        reads the verdict counter this feeds)."""
        self.metering = ledger

    def attach_tsdb(self, store, clock=None) -> None:
        """Attach a TimeSeriesStore (utils/tsdb.py); every scrape()
        appends one sample per selected series, timestamped off `clock`
        (falls back to the attached manager's clock) so the history is
        FakeClock-deterministic in tests."""
        self.tsdb = store
        self._tsdb_clock = clock

    def attach_diagnosis(self, engine) -> None:
        """Attach a DiagnosisEngine (utils/diagnosis.py); every scrape()
        runs one change-point evaluation over the TSDB's fresh raw
        points and diffs the discrete evidence surfaces, and
        fleet_snapshot() grows a `diagnosis` section."""
        self.diagnosis = engine

    def _feed_counter(self, counter, label, total: float) -> None:
        """Advance a monotonic counter to `total` using deltas against the
        previous scrape; a source reset (new manager) re-counts from zero.
        `label` is one label value or a tuple of them."""
        labels = label if isinstance(label, tuple) else (label,)
        key = (counter.name,) + labels
        prev = self._counter_snapshots.get(key, 0.0)
        if total > prev:
            counter.labels(*labels).inc(total - prev)
        elif total < prev:
            counter.labels(*labels).inc(total)
        self._counter_snapshots[key] = float(total)

    # -- census aggregates (InformerCache.add_aggregate) ----------------------
    # Group keys are SEP-joined so one aggregate carries several gauge
    # families; contributions are small exact counts.  The cache maintains
    # the sums incrementally on its watch stream, so a scrape reads
    # O(label series), never O(objects) — and never touches the apiserver.
    _SEP = "\x1f"

    @classmethod
    def _sts_census(cls, sts) -> dict:
        nb_name = (
            sts.spec.get("template", {})
            .get("metadata", {})
            .get("labels", {})
            .get(C.NOTEBOOK_NAME_LABEL)
        )
        if nb_name is None:
            return {}
        out: dict[str, float] = {}
        replicas = int(sts.spec.get("replicas", 0) or 0)
        if replicas > 0:
            # one key per (ns, notebook): a multi-slice notebook renders
            # one STS per slice but is still one running notebook — the
            # scrape counts distinct keys, not their values
            out[cls._SEP.join(("run", sts.namespace, nb_name))] = 1.0
        chips = 0.0
        for c in sts.spec.get("template", {}).get("spec", {}).get(
                "containers", []):
            q = (c.get("resources", {}).get("requests") or {}).get(
                C.TPU_RESOURCE)
            if q:
                chips += parse_quantity(q) * replicas
        if chips:
            out[cls._SEP.join(("chips", sts.namespace))] = chips
        return out

    @classmethod
    def _warmpool_census(cls, pool) -> dict:
        shape = "%s-%s" % (pool.spec.get("accelerator", ""),
                           pool.spec.get("topology", ""))
        # shape presence rides along so an empty pool still zero-fills its
        # state series each scrape
        out: dict[str, float] = {cls._SEP.join(("shape", shape)): 1.0}
        for e in (pool.body.get("status", {}).get("slices") or {}).values():
            if e.get("external"):
                continue  # bypass claims are not pool capacity
            state = e.get("state", "")
            if state in C.WARMSLICE_STATES:
                key = cls._SEP.join(("state", shape, state))
                out[key] = out.get(key, 0.0) + 1.0
        return out

    @classmethod
    def _fleet_census(cls, nb) -> dict:
        """Per-Notebook contribution to the /debug/fleet rollup: one count
        under its namespace and (for TPU notebooks) its accelerator-
        topology shape, keyed by health state.  Maintained incrementally
        by InformerCache.add_aggregate — O(changed) per watch event — so
        a /debug/fleet request is O(series), never O(objects)."""
        state = fleet_state(nb)
        out = {cls._SEP.join(("ns", nb.namespace, state)): 1.0}
        tpu = nb.spec.get("tpu") or {}
        if tpu.get("accelerator"):
            shape = "%s-%s" % (tpu.get("accelerator", ""),
                               tpu.get("topology", ""))
            out[cls._SEP.join(("shape", shape, state))] = 1.0
        return out

    @classmethod
    def _metering_census(cls, nb) -> dict:
        """Per-Notebook contribution to the tenant metering census: placed
        notebooks (placement annotation written by the scheduler) appear
        under (namespace, name, bucket) with their chip count; release
        removes the key, and the ledger closes the interval.  Incremental
        via add_aggregate, so placement/release and sliceHealth
        transitions maintain it on the watch stream."""
        if C.ANNOTATION_PLACEMENT not in (nb.metadata.annotations or {}):
            return {}
        key = cls._SEP.join((nb.namespace, nb.name, metering_bucket(nb)))
        return {key: placement_chips(nb)}

    def _ensure_census(self, cache) -> bool:
        if self._census_ready is not None:
            return self._census_ready
        try:
            cache.add_aggregate("StatefulSet", "nb-census", self._sts_census)
            cache.add_aggregate(C.WARMPOOL_KIND, "warmpool-census",
                                self._warmpool_census)
            cache.add_aggregate("Notebook", "fleet-census",
                                self._fleet_census)
            cache.add_aggregate("Notebook", "tenant-metering",
                                self._metering_census)
            self._census_ready = True
        except Exception:  # noqa: BLE001 — a backend that cannot list a
            # kind (real cluster without the CRD) falls back to scans
            self._census_ready = False
        return self._census_ready

    def scrape(self, openmetrics: bool = False) -> str:
        """Scrape-time gauge recomputation.  With an informer cache the
        census gauges read the cache's incremental aggregates — O(changed)
        per event, O(series) per scrape, zero API calls — replacing the
        per-scrape rescans of metrics.go:82-99 that fall over at fleet
        scale.  Without a cache (direct-construction unit tests, degraded
        backends) the original list-based scan still runs."""
        cache = getattr(self.manager, "cache", None)
        if cache is not None and self._ensure_census(cache):
            self._scrape_census_from_cache(cache)
        else:
            self._scrape_census_from_lists()
        # filtered watch fan-out audit (in-memory apiserver only)
        dispatch = getattr(self.api, "watch_dispatch_counts", None)
        if dispatch is not None:
            for (kind, result), total in sorted(dispatch().items()):
                self._feed_counter(self.watch_dispatch, (kind, result),
                                   total)
        if self.manager is not None:
            stats = self.manager.queue_stats()
            for name in stats["controllers"]:
                self.workqueue_depth.labels(name).set(
                    stats["depth"].get(name, 0))
                self.workqueue_backoff_pending.labels(name).set(
                    stats["backoff_pending"].get(name, 0))
                self._feed_counter(self.workqueue_retries_total, name,
                                   stats["retries_total"].get(name, 0))
                self.workqueue_last_backoff_seconds.labels(name).set(
                    stats["last_backoff_s"].get(name, 0.0))
                self.workqueue_longest_running.labels(name).set(
                    stats.get("longest_running_s", {}).get(name, 0.0))
                self._feed_counter(self.reconcile_errors_total, name,
                                   stats["errors_total"].get(name, 0))
        if self.shards is not None:
            # before the SLO engine: the handoff-stall objective reads
            # the histogram this feeds
            self._scrape_shards()
        if self.dataplane is not None:
            # data-plane rollup first: the SLO engine's straggler/MFU
            # objectives read the verdict counters this evaluation feeds
            self.dataplane.evaluate()
        if self.metering is not None:
            # metering round before the SLO engine: the tenant_fairness
            # objective reads the verdict counter this evaluation feeds
            self._feed_metering()
        # cardinality-guard visibility: fold per-family drop counts from
        # every scraped registry into the one exported counter
        self._feed_labelsets_dropped()
        if self.slo is not None:
            # burn rates / budget gauges / alert lifecycle advance at
            # scrape resolution, exactly like a Prometheus-side burn rule
            self.slo.evaluate()
        if self.tsdb is not None:
            # last, so the sample reads this scrape's fresh evaluations
            self._feed_tsdb()
        if self.diagnosis is not None:
            # after the TSDB feed: the change-point detector consumes the
            # raw point this scrape just appended
            self.diagnosis.evaluate()
        return self.render(openmetrics=openmetrics)

    def _feed_metering(self) -> None:
        """One metering round: decode the placement census (cache
        aggregate, list-scan fallback), snapshot the apiserver's tenant
        verb counts, and run the ledger's accrual + noisy-neighbor
        evaluation."""
        census: dict[tuple[str, str], tuple[str, float]] = {}
        cache = getattr(self.manager, "cache", None)
        if cache is not None and self._ensure_census(cache):
            sums = cache.aggregate("Notebook", "tenant-metering").items()
        else:
            sums_d: dict[str, float] = {}
            for nb in self.api.list("Notebook"):
                for key, v in self._metering_census(nb).items():
                    sums_d[key] = v
            sums = sums_d.items()
        for key, chips in sums:
            parts = key.split(self._SEP)
            census[(parts[0], parts[1])] = (parts[2], chips)
        verbs = getattr(self.api, "tenant_verb_counts", None)
        self.metering.evaluate(
            census=census,
            verb_counts=verbs() if verbs is not None else None)

    def _feed_labelsets_dropped(self) -> None:
        """Advance metrics_labelsets_dropped_total to the summed per-family
        drop counts of every registry this exposition scrapes."""
        regs = [self.registry]
        mgr_registry = getattr(self.manager, "metrics_registry", None)
        if mgr_registry is not None:
            regs.append(mgr_registry)
        merged: dict[str, float] = {}
        for reg in regs:
            dropped = getattr(reg, "labelsets_dropped", None)
            if dropped is None:
                continue
            for family, n in dropped().items():
                merged[family] = merged.get(family, 0.0) + n
        for family, total in sorted(merged.items()):
            self._feed_counter(self.labelsets_dropped, family, total)

    def _tsdb_delta(self, key: str, total: float) -> float:
        """Per-scrape delta of a cumulative total (floored at 0 across
        source resets) for the TSDB's *_delta series."""
        prev = self._tsdb_prev.get(key, 0.0)
        self._tsdb_prev[key] = total
        return max(total - prev, 0.0)

    def _feed_tsdb(self) -> None:
        """One TSDB sample per scrape: the handful of series whose curves
        answer 'where does it bend' — ready/reaction p99s, queue state,
        fleet size, and the lifecycle stage p99s."""
        clock = self._tsdb_clock or getattr(self.manager, "clock", None)
        if clock is None:
            return
        values: dict[str, float] = {
            "ready_p99_s": histogram_quantile(
                self.notebook_ready_seconds, 0.99),
            "event_to_reconcile_p99_s": 0.0,
            "notebooks_running": sum(
                self.running.collect().values()),
        }
        mgr_registry = getattr(self.manager, "metrics_registry", None)
        if mgr_registry is not None:
            e2r = mgr_registry.get("notebook_event_to_reconcile_seconds")
            if e2r is not None:
                values["event_to_reconcile_p99_s"] = \
                    histogram_quantile(e2r, 0.99)
            rt = mgr_registry.get("controller_runtime_reconcile_total")
            if rt is not None:
                counts = rt.collect()
                values["reconciles_total"] = sum(counts.values())
                # errored ATTEMPTS (not retry-budget drops): the rate a
                # fault-plan window actually moves
                values["reconcile_errors_delta"] = self._tsdb_delta(
                    "reconcile_errors",
                    float(sum(v for k, v in counts.items()
                              if "error" in k)))
        if self.manager is not None:
            stats = self.manager.queue_stats()
            values["workqueue_depth"] = float(
                sum(stats["depth"].values()))
            values["workqueue_backoff_pending"] = float(
                sum(stats["backoff_pending"].values()))
        # level-shift-friendly shapes for the diagnosis engine: active
        # straggler count plus per-scrape deltas of the promotion counter
        # (cumulative totals ramp forever; only their rate level-shifts)
        straggler = self.registry.get("notebook_dataplane_straggler")
        if straggler is not None:
            values["dataplane_stragglers"] = float(
                sum(straggler.collect().values()))
        promotions = self.registry.get("notebook_promotions_total")
        if promotions is not None:
            values["promotions_delta"] = self._tsdb_delta(
                "promotions", float(sum(promotions.collect().values())))
        if self.lifecycle is not None:
            for stage, p99 in self.lifecycle.stage_p99s().items():
                values["stage_p99.%s" % stage] = p99
            cons = self.lifecycle.conservation()
            values["criticalpath_finalized"] = float(cons["finalized"])
            values["criticalpath_violations"] = float(cons["violations"])
        if self.metering is not None:
            # top-K tenant chip-second curves + the conservation gate's
            # violation count over time (/debug/timeline)
            for tenant, chips in self.metering.tenant_chip_series().items():
                values["tenant_chip_seconds.%s" % tenant] = chips
            mcons = self.metering.conservation()
            values["metering_violations"] = float(mcons["violations"])
        self.tsdb.sample(clock.now(), values)

    def _scrape_shards(self) -> None:
        """Feed the notebook_shard_* families from the attached fleet:
        per-replica gauges, fenced-rejection counter deltas, and any
        handoff durations completed since the previous scrape."""
        snap = self.shards.shard_snapshot()
        for sid, rep in snap["replicas"].items():
            self.shard_keys_owned.labels(sid).set(rep["keys_owned"])
            self.shard_epoch.labels(sid).set(rep["epoch"])
            self._feed_counter(self.shard_fenced_writes, sid,
                               rep["fenced_rejections"])
        for sid, replica in self.shards.replicas.items():
            durations = replica.handoff_durations
            fed = self._handoff_fed.get(sid, 0)
            for d in durations[fed:]:
                self.shard_handoff_duration.observe(d)
            self._handoff_fed[sid] = len(durations)

    # -- fleet rollup (/debug/fleet) ------------------------------------------
    def fleet_snapshot(self) -> dict:
        """Per-namespace / per-shape health rollup from the cache's
        incremental fleet-census sums (list-scan fallback without a
        cache), plus the SLO engine's last verdicts when attached."""
        per_ns: dict[str, dict[str, int]] = {}
        per_shape: dict[str, dict[str, int]] = {}
        totals: dict[str, int] = {s: 0 for s in FLEET_STATES}
        cache = getattr(self.manager, "cache", None)
        if cache is not None and self._ensure_census(cache):
            sums = cache.aggregate("Notebook", "fleet-census").items()
        else:
            sums_d: dict[str, float] = {}
            for nb in self.api.list("Notebook"):
                for key, v in self._fleet_census(nb).items():
                    sums_d[key] = sums_d.get(key, 0.0) + v
            sums = sums_d.items()
        for key, v in sums:
            parts = key.split(self._SEP)
            n = int(v)
            if n <= 0:
                continue  # drained series linger at 0 in the aggregate
            if parts[0] == "ns":
                per_ns.setdefault(parts[1], {})[parts[2]] = n
                totals[parts[2]] = totals.get(parts[2], 0) + n
            elif parts[0] == "shape":
                per_shape.setdefault(parts[1], {})[parts[2]] = n
        out = {
            "states": list(FLEET_STATES),
            "notebooks": sum(totals.values()),
            "totals": totals,
            "namespaces": {ns: dict(sorted(states.items()))
                           for ns, states in sorted(per_ns.items())},
            "shapes": {sh: dict(sorted(states.items()))
                       for sh, states in sorted(per_shape.items())},
        }
        if self.slo is not None:
            snap = self.slo.snapshot()
            out["slo"] = {
                "objectives": snap["objectives"],
                "firing": snap["firing"],
            }
        if self.dataplane is not None:
            out["dataplane"] = self.dataplane.snapshot()
        if self.shards is not None:
            out["shards"] = self.shards.shard_snapshot()
        if self.lifecycle is not None:
            # the tenants view: ready-time and stage-latency by namespace
            # (the seed signal for fairness/starvation gates), plus the
            # fleet critical path so /debug/fleet alone answers "which
            # stage dominates and for whom"
            out["stage_latency"] = self.lifecycle.namespace_rollup()
            out["criticalpath"] = {
                "ranking": self.lifecycle.ranking(),
                "conservation": self.lifecycle.conservation(),
            }
        if self.metering is not None:
            # the tenant accounting view: per-tenant usage, top-K
            # consumers, fairness verdicts, and the chip-second
            # conservation gate — /debug/fleet alone reconstructs a
            # noisy-neighbor incident
            out["tenants"] = self.metering.snapshot()
        # the tenancy view (always present, zeros when the scheduler /
        # quota layer is off): per-tenant queue depth, placed chip usage,
        # configured quota/weights, and recent preemptions
        out["tenancy"] = self.tenancy_snapshot()
        if self.diagnosis is not None:
            # the causal view: change-point counts and the most recent
            # annotated findings (full detail at /debug/changepoints,
            # per-object verdicts at /debug/explain)
            out["diagnosis"] = self.diagnosis.fleet_summary()
        return out

    def tenancy_snapshot(self) -> dict:
        """Per-tenant tenancy view for /debug/fleet and /debug/tenants:
        queue depth + oldest queued-since per namespace (off the queued
        annotations the admission gate stamps), placed chip usage, the
        TenantQuota policy when one exists, and the write-ahead
        preemption bookkeeping (pending records + recent completions)."""
        reader = getattr(self.manager, "cache", None) or self.api
        queued: dict[str, dict] = {}
        usage: dict[str, float] = {}
        try:
            notebooks = reader.list("Notebook")
        except Exception:  # noqa: BLE001 — degraded backends must not
            notebooks = []  # break the debug surface
        for nb in notebooks:
            ann = nb.metadata.annotations or {}
            if C.ANNOTATION_PLACEMENT in ann:
                usage[nb.namespace] = \
                    usage.get(nb.namespace, 0.0) + placement_chips(nb)
            raw = ann.get(C.ANNOTATION_QUEUED)
            if raw:
                try:
                    info = json.loads(raw)
                except ValueError:
                    info = {}
                ent = queued.setdefault(
                    nb.namespace, {"depth": 0, "oldest_since": None})
                ent["depth"] += 1
                since = info.get("since")
                if isinstance(since, (int, float)) and (
                        ent["oldest_since"] is None
                        or since < ent["oldest_since"]):
                    ent["oldest_since"] = since
        out: dict = {
            "queued": {ns: dict(v) for ns, v in sorted(queued.items())},
            "usage_chips": dict(sorted(usage.items())),
            "quota": {},
            "pending_preemptions": 0,
            "recent_preemptions": [],
        }
        try:
            qobj = self.api.try_get(C.TENANTQUOTA_KIND, "",
                                    C.TENANTQUOTA_NAME)
        except Exception:  # noqa: BLE001
            qobj = None
        if qobj is not None:
            out["quota"] = copy.deepcopy(qobj.spec.get("tenants") or {})
            st = qobj.body.get("status") or {}
            out["pending_preemptions"] = len(st.get("preemptions") or {})
            out["recent_preemptions"] = copy.deepcopy(
                list(st.get("recentPreemptions") or [])[-8:])
        return out

    def _scrape_census_from_cache(self, cache) -> None:
        """Census gauges off the cache's incremental aggregates."""
        running: dict[str, int] = {}
        for key, v in cache.aggregate("StatefulSet", "nb-census").items():
            parts = key.split(self._SEP)
            if parts[0] == "run":
                running[parts[1]] = running.get(parts[1], 0) + 1
            elif parts[0] == "chips":
                self.tpu_chips_requested.labels(parts[1]).set(v)
        for ns, n in running.items():
            self.running.labels(ns).set(n)
        seen_shapes: set[str] = set()
        per_state: dict[tuple[str, str], float] = {}
        for key, v in cache.aggregate(C.WARMPOOL_KIND,
                                      "warmpool-census").items():
            parts = key.split(self._SEP)
            if parts[0] == "shape":
                seen_shapes.add(parts[1])
            elif parts[0] == "state":
                per_state[(parts[1], parts[2])] = v
        # every shape x state combination is set each scrape (zeros
        # included) so a drained state reads 0, not stale
        for shape in seen_shapes:
            for state in C.WARMSLICE_STATES:
                self.warmpool_size.labels(shape, state).set(
                    per_state.get((shape, state), 0.0))
        # a TPUWarmPool deleted between scrapes would otherwise leave its
        # shape's series frozen at the last census — drive them to 0
        for shape in self._warmpool_shapes - seen_shapes:
            for state in C.WARMSLICE_STATES:
                self.warmpool_size.labels(shape, state).set(0)
        self._warmpool_shapes = seen_shapes

    def _scrape_census_from_lists(self) -> None:
        """Legacy list-based census (metrics.go:82-99): the no-cache
        fallback path; O(objects) per scrape."""
        running_notebooks: dict[str, set[str]] = {}  # ns -> notebook names
        per_ns_chips: dict[str, float] = {}
        for sts in self.api.list("StatefulSet"):
            contrib = self._sts_census(sts)
            for key, v in contrib.items():
                parts = key.split(self._SEP)
                if parts[0] == "run":
                    running_notebooks.setdefault(parts[1], set()).add(
                        parts[2])
                elif parts[0] == "chips":
                    per_ns_chips[parts[1]] = \
                        per_ns_chips.get(parts[1], 0.0) + v
        for ns, names in running_notebooks.items():
            self.running.labels(ns).set(len(names))
        for ns, n in per_ns_chips.items():
            self.tpu_chips_requested.labels(ns).set(n)
        try:
            pools = self.api.list(C.WARMPOOL_KIND)
        except Exception:  # noqa: BLE001 — a real-cluster backend without
            pools = []     # the CRD must not break the scrape
        seen_shapes: set[str] = set()
        for pool in pools:
            counts: dict[tuple[str, str], float] = {}
            shape = ""
            for key, v in self._warmpool_census(pool).items():
                parts = key.split(self._SEP)
                if parts[0] == "shape":
                    shape = parts[1]
                    seen_shapes.add(shape)
                elif parts[0] == "state":
                    counts[(parts[1], parts[2])] = v
            if shape:
                for state in C.WARMSLICE_STATES:
                    self.warmpool_size.labels(shape, state).set(
                        counts.get((shape, state), 0.0))
        for shape in self._warmpool_shapes - seen_shapes:
            for state in C.WARMSLICE_STATES:
                self.warmpool_size.labels(shape, state).set(0)
        self._warmpool_shapes = seen_shapes

    def render(self, openmetrics: bool = False) -> str:
        """Full exposition: this registry plus the attached manager's
        reconcile/workqueue registry (controller_runtime_reconcile_*,
        workqueue_*_duration_seconds) as one scrape body.  Families are
        disjoint between the two registries, so the combined text stays a
        valid single exposition.  The OpenMetrics variant carries bucket
        exemplars and ends with the spec-required `# EOF` terminator."""
        text = self.registry.render(openmetrics=openmetrics)
        mgr_registry = getattr(self.manager, "metrics_registry", None)
        if mgr_registry is not None:
            text += mgr_registry.render(openmetrics=openmetrics)
        if openmetrics:
            text += "# EOF\n"
        return text

    def families(self) -> list[tuple[str, str]]:
        """(name, kind) inventory across both registries — what
        ci/metrics_drift_check.sh freezes in its golden list."""
        fams = self.registry.families()
        mgr_registry = getattr(self.manager, "metrics_registry", None)
        if mgr_registry is not None:
            fams += mgr_registry.families()
        return fams
