"""Fault-injection substrate (kube/faults.py + the ApiServer gate).

Covers the injection surface the chaos soak is built on: per-verb/per-kind
errors with match counts and a complete fault log, seeded determinism,
latency against the FakeClock, stale reads, watch-stream drops with
resume, and history resets forcing the 410 Gone -> relist path.
"""

import pytest

from kubeflow_tpu.kube import (
    ApiServer,
    ConflictError,
    FakeCluster,
    FaultPlan,
    FaultRule,
    KubeObject,
    Manager,
    ObjectMeta,
    Result,
    ServerError,
    random_fault_plan,
)
from kubeflow_tpu.utils.clock import FakeClock


def mk(kind: str, name: str, namespace: str = "default",
       labels=None) -> KubeObject:
    return KubeObject(api_version="v1", kind=kind,
                      metadata=ObjectMeta(name=name, namespace=namespace,
                                          labels=dict(labels or {})))


class TestFaultRules:
    def test_error_injection_per_verb_and_kind_with_match_count(self):
        api = ApiServer()
        api.create(mk("ConfigMap", "cm"))
        plan = FaultPlan([FaultRule(verbs=("get",), kinds=("ConfigMap",),
                                    error="server", max_matches=2)])
        api.install_fault_plan(plan)
        for _ in range(2):
            with pytest.raises(ServerError):
                api.get("ConfigMap", "default", "cm")
        # exhausted: the third call goes through
        assert api.get("ConfigMap", "default", "cm").name == "cm"
        assert plan.exhausted()
        # other verbs/kinds were never gated
        api.create(mk("Secret", "s"))
        assert api.list("ConfigMap")
        assert [r.action for r in plan.log] == ["error:server"] * 2
        assert all(r.verb == "get" and r.kind == "ConfigMap"
                   for r in plan.log)

    def test_conflict_injection_is_a_409(self):
        api = ApiServer()
        obj = api.create(mk("ConfigMap", "cm"))
        api.install_fault_plan(FaultPlan(
            [FaultRule(verbs=("update",), error="conflict")]))
        with pytest.raises(ConflictError):
            api.update(obj)
        assert api.update(obj).metadata.resource_version  # second try lands

    def test_after_skips_first_matches(self):
        api = ApiServer()
        api.create(mk("ConfigMap", "cm"))
        plan = FaultPlan([FaultRule(verbs=("get",), error="server",
                                    after=2, max_matches=1)])
        api.install_fault_plan(plan)
        api.get("ConfigMap", "default", "cm")
        api.get("ConfigMap", "default", "cm")
        with pytest.raises(ServerError):
            api.get("ConfigMap", "default", "cm")

    def test_seeded_probability_is_deterministic(self):
        def run(seed):
            api = ApiServer()
            api.create(mk("ConfigMap", "cm"))
            plan = FaultPlan([FaultRule(verbs=("get",), error="server",
                                        probability=0.5, max_matches=100)],
                             seed=seed)
            api.install_fault_plan(plan)
            outcomes = []
            for _ in range(20):
                try:
                    api.get("ConfigMap", "default", "cm")
                    outcomes.append(0)
                except ServerError:
                    outcomes.append(1)
            return outcomes

        assert run(42) == run(42)
        assert run(42) != run(43)  # different seed, different draw
        assert 0 < sum(run(42)) < 20

    def test_latency_advances_fake_clock_and_logs(self):
        api = ApiServer()
        clock = FakeClock()
        api.create(mk("ConfigMap", "cm"))
        plan = FaultPlan([FaultRule(verbs=("get",), latency_s=2.5)],
                         clock=clock)
        api.install_fault_plan(plan)
        t0 = clock.now()
        api.get("ConfigMap", "default", "cm")
        assert clock.now() - t0 == pytest.approx(2.5)
        assert plan.log[0].action == "latency"

    def test_stale_read_serves_previous_version_once(self):
        api = ApiServer()
        cm = api.create(mk("ConfigMap", "cm"))
        cm.body["data"] = {"v": "2"}
        api.update(cm)
        api.install_fault_plan(FaultPlan(
            [FaultRule(verbs=("get",), stale_read=True, max_matches=1)]))
        stale = api.get("ConfigMap", "default", "cm")
        assert stale.body.get("data", {}).get("v") is None  # pre-update view
        fresh = api.get("ConfigMap", "default", "cm")
        assert fresh.body["data"]["v"] == "2"

    def test_internal_reentry_and_exemption_are_not_gated(self):
        api = ApiServer()
        api.create(mk("ConfigMap", "cm"))
        api.install_fault_plan(FaultPlan(
            [FaultRule(verbs=("get", "update"), error="server",
                       max_matches=100)]))
        with api.fault_exempt():
            assert api.get("ConfigMap", "default", "cm").name == "cm"
        # merge_patch re-enters get/update internally: only the top-level
        # "patch" verb is gated, and this plan does not target it
        api.merge_patch("ConfigMap", "default", "cm",
                        {"data": {"k": "v"}})

    def test_unknown_error_kind_rejected(self):
        with pytest.raises(ValueError):
            FaultRule(error="teapot")

    def test_random_plan_reproducible_and_bounded(self):
        kinds = ("Notebook", "StatefulSet", "Pod")
        a = random_fault_plan(99, kinds)
        b = random_fault_plan(99, kinds)
        assert [(r.verbs, r.kinds, r.error, r.max_matches, r.probability)
                for r in a.rules] == \
               [(r.verbs, r.kinds, r.error, r.max_matches, r.probability)
                for r in b.rules]
        assert all(r.max_matches >= 1 for r in a.rules)


class TestWatchDrops:
    def _stack(self):
        api = ApiServer()
        clock = FakeClock()
        mgr = Manager(api, clock=clock)
        seen: list[str] = []

        class Rec:
            def reconcile(self, req):
                seen.append(req.name)
                return Result()

        mgr.register("nb", Rec(), for_kind="Notebook")
        return api, mgr, seen

    def test_drop_resumes_from_last_rv_without_loss(self):
        api, mgr, seen = self._stack()
        api.create(mk("Notebook", "n1"))
        mgr.run_until_idle()
        api.install_fault_plan(FaultPlan(
            [FaultRule(verbs=("create",), kinds=("ConfigMap",),
                       drop_watch=True)]))
        # the drop fires on this create; the manager's session resumes via
        # subscribe(since_rv) and still sees the Notebook event that lands
        # inside the same call graph
        api.create(mk("ConfigMap", "noise"))
        api.clear_fault_plan()
        api.create(mk("Notebook", "n2"))
        mgr.run_until_idle()
        assert "n2" in seen
        assert mgr._watch_session.drops == 1
        assert mgr._watch_session.relists == 0

    def test_drop_with_history_reset_forces_relist(self):
        api, mgr, seen = self._stack()
        api.create(mk("Notebook", "n1"))
        mgr.run_until_idle()
        seen.clear()
        # the classic dead-resourceVersion sequence: the stream drops,
        # events land while the client is away, and etcd compaction then
        # evicts exactly the window the client would resume from
        api.install_fault_plan(FaultPlan([
            FaultRule(verbs=("create",), kinds=("ConfigMap",),
                      drop_watch=True),
            FaultRule(verbs=("create",), kinds=("Secret",),
                      reset_watch_history=True),
        ]))
        api.create(mk("ConfigMap", "noise"))   # drop fires; commit missed
        api.create(mk("Secret", "compaction"))  # evicts the resume window
        api.clear_fault_plan()
        mgr.run_until_idle()
        # resume rv predates the compacted window -> 410 Gone -> live
        # re-subscribe + relist, which re-enqueues every primary
        assert mgr._watch_session.drops == 1
        assert mgr._watch_session.relists == 1
        assert "n1" in seen
        # and the session is live again for future events
        api.create(mk("Notebook", "n2"))
        mgr.run_until_idle()
        assert "n2" in seen

    def test_plain_watchers_survive_drops(self):
        api, mgr, _ = self._stack()
        cluster = FakeCluster(api)  # plain callback watcher (data plane)
        cluster.add_node("n1")
        api.install_fault_plan(FaultPlan(
            [FaultRule(drop_watch=True, max_matches=1)]))
        sts = mk("StatefulSet", "web")
        sts.body["spec"] = {
            "replicas": 1,
            "template": {"metadata": {"labels": {"app": "web"}},
                         "spec": {"containers": [{"name": "c",
                                                  "image": "i"}]}},
        }
        sts.api_version = "apps/v1"
        api.create(sts)  # fires the drop; kubelet must still realize pods
        api.clear_fault_plan()
        assert api.try_get("Pod", "default", "web-0") is not None
