"""Fake TPU device plugin: protocol-certified against a kubelet harness.

The harness plays the kubelet's two roles over real unix-domain sockets:
a `v1beta1.Registration` gRPC server that receives the plugin's
`Register` handshake, and a `v1beta1.DevicePlugin` CLIENT that drives
`GetDevicePluginOptions` / `ListAndWatch` / `Allocate` against the
plugin's socket — the exact call pattern kubelet uses, so a kind node
with this plugin in a DaemonSet gets `google.com/tpu` allocatable
(SURVEY.md §4.5's named gap).  The apiserver-side fallback
(`label_tpu_node`) is certified against the in-memory ApiServer.
"""

from __future__ import annotations

import threading
from concurrent import futures

import pytest

grpc = pytest.importorskip("grpc")

from kubeflow_tpu.tpu.device_plugin import (  # noqa: E402
    API_VERSION,
    DEFAULT_RESOURCE,
    HEALTHY,
    UNHEALTHY,
    FakeTpuDevicePlugin,
    label_tpu_node,
    messages,
)


class KubeletHarness:
    """The kubelet side of the handshake: Registration server + plugin
    client helpers."""

    def __init__(self, socket_dir: str):
        self.socket_dir = socket_dir
        self.register_requests: list = []
        self.registered = threading.Event()
        M = messages()
        handlers = {
            "Register": grpc.unary_unary_rpc_method_handler(
                self._register,
                request_deserializer=M["RegisterRequest"].FromString,
                response_serializer=lambda m: m.SerializeToString()),
        }
        self.server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        self.server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(
                f"{API_VERSION}.Registration", handlers),
        ))
        self.server.add_insecure_port(
            f"unix://{socket_dir}/kubelet.sock")
        self.server.start()

    def _register(self, request, context):
        self.register_requests.append(request)
        self.registered.set()
        return messages()["Empty"]()

    def plugin_channel(self, endpoint: str):
        return grpc.insecure_channel(f"unix://{self.socket_dir}/{endpoint}")

    def stop(self):
        self.server.stop(grace=0.2)


@pytest.fixture
def socket_dir(tmp_path):
    return str(tmp_path)


@pytest.fixture
def harness(socket_dir):
    h = KubeletHarness(socket_dir)
    yield h
    h.stop()


@pytest.fixture
def plugin(socket_dir, harness):
    p = FakeTpuDevicePlugin(socket_dir, chips=4)
    p.start()
    yield p
    p.stop()


def _stub(chan, method, req_cls, resp_cls, stream=False):
    kind = chan.unary_stream if stream else chan.unary_unary
    return kind(
        f"/{API_VERSION}.DevicePlugin/{method}",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=resp_cls.FromString)


class TestRegistration:
    def test_plugin_registers_with_kubelet(self, plugin, harness):
        assert harness.registered.wait(timeout=5)
        (req,) = harness.register_requests
        assert req.version == API_VERSION
        assert req.resource_name == DEFAULT_RESOURCE
        assert req.endpoint == plugin.endpoint


class TestDevicePluginService:
    def test_options(self, plugin, harness):
        M = messages()
        with harness.plugin_channel(plugin.endpoint) as chan:
            opts = _stub(chan, "GetDevicePluginOptions", M["Empty"],
                         M["DevicePluginOptions"])(M["Empty"](), timeout=5)
        assert not opts.pre_start_required

    def test_list_and_watch_streams_devices_and_health(self, plugin,
                                                       harness):
        M = messages()
        with harness.plugin_channel(plugin.endpoint) as chan:
            stream = _stub(chan, "ListAndWatch", M["Empty"],
                           M["ListAndWatchResponse"], stream=True)(
                M["Empty"](), timeout=10)
            first = next(stream)
            assert [d.ID for d in first.devices] == [
                "tpu-0", "tpu-1", "tpu-2", "tpu-3"]
            assert all(d.health == HEALTHY for d in first.devices)

            # a dead chip re-streams the list with the device Unhealthy
            plugin.set_health("tpu-2", healthy=False)
            second = next(stream)
            by_id = {d.ID: d.health for d in second.devices}
            assert by_id["tpu-2"] == UNHEALTHY
            assert by_id["tpu-0"] == HEALTHY

    def test_allocate_returns_device_specs_and_env(self, plugin, harness):
        M = messages()
        req = M["AllocateRequest"]()
        creq = req.container_requests.add()
        creq.devicesIDs.extend(["tpu-0", "tpu-3"])
        with harness.plugin_channel(plugin.endpoint) as chan:
            resp = _stub(chan, "Allocate", M["AllocateRequest"],
                         M["AllocateResponse"])(req, timeout=5)
        (cresp,) = resp.container_responses
        assert [d.host_path for d in cresp.devices] == [
            "/dev/accel0", "/dev/accel3"]
        assert all(d.permissions == "rw" for d in cresp.devices)
        assert cresp.envs["TPU_FAKE_DEVICE_IDS"] == "tpu-0,tpu-3"
        assert cresp.envs["TPU_CHIPS_ALLOCATED"] == "2"

    def test_set_health_unknown_device(self, socket_dir):
        p = FakeTpuDevicePlugin(socket_dir, chips=1)
        with pytest.raises(KeyError):
            p.set_health("tpu-9", healthy=False)


class TestNodeLabelFallback:
    def test_label_tpu_node_patches_capacity_and_labels(self):
        from kubeflow_tpu.kube.meta import KubeObject, ObjectMeta
        from kubeflow_tpu.kube.store import ApiServer
        from kubeflow_tpu.tpu.device_plugin import (
            LABEL_ACCELERATOR,
            LABEL_TOPOLOGY,
        )

        api = ApiServer()
        api.create(KubeObject("v1", "Node", ObjectMeta(name="worker-0")))

        class DirectClient:
            def get(self, kind, namespace, name):
                return api.get(kind, namespace, name)

            def update(self, obj):
                return api.update(obj)

            def update_status(self, obj):
                return api.update(obj, subresource="status")

        node = label_tpu_node(DirectClient(), "worker-0", chips=8,
                              topology="2x4")
        assert node.metadata.labels[LABEL_ACCELERATOR] == \
            "tpu-v5-lite-podslice"
        assert node.metadata.labels[LABEL_TOPOLOGY] == "2x4"
        stored = api.get("Node", "", "worker-0")
        assert stored.status["capacity"]["google.com/tpu"] == "8"
        assert stored.status["allocatable"]["google.com/tpu"] == "8"
