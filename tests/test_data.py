"""Input pipeline: host sharding, determinism, prefetch, train-step feed."""

from __future__ import annotations

import numpy as np
import pytest

from kubeflow_tpu.models.configs import TINY
from kubeflow_tpu.models.train import setup_training
from kubeflow_tpu.parallel.mesh import MeshConfig, make_mesh
from kubeflow_tpu.runtime.data import (
    DevicePrefetcher,
    ShardedBatcher,
    TokenBatches,
    input_pipeline,
)

TOKENS = np.arange(10_000) % 251


class TestTokenBatches:
    def test_shapes_targets_and_determinism(self):
        a = list(TokenBatches(TOKENS, global_batch=8, seq_len=32, seed=3,
                              num_epochs=1))
        b = list(TokenBatches(TOKENS, global_batch=8, seq_len=32, seed=3,
                              num_epochs=1))
        assert len(a) > 0
        for ba, bb in zip(a, b):
            assert ba["inputs"].shape == (8, 32)
            np.testing.assert_array_equal(ba["inputs"], bb["inputs"])
            # targets are inputs shifted by one over the raw stream
            np.testing.assert_array_equal(ba["inputs"][:, 1:],
                                          ba["targets"][:, :-1])
        c = list(TokenBatches(TOKENS, 8, 32, seed=4, num_epochs=1))
        assert not np.array_equal(a[0]["inputs"], c[0]["inputs"])

    def test_host_shards_partition_the_global_batch(self):
        """Two simulated hosts must see disjoint halves whose union is the
        single-host global batch, in order."""
        full = next(iter(TokenBatches(TOKENS, 8, 16, seed=1,
                                      process_index=0, process_count=1)))
        h0 = next(iter(TokenBatches(TOKENS, 8, 16, seed=1,
                                    process_index=0, process_count=2)))
        h1 = next(iter(TokenBatches(TOKENS, 8, 16, seed=1,
                                    process_index=1, process_count=2)))
        assert h0["inputs"].shape == (4, 16)
        np.testing.assert_array_equal(
            np.concatenate([h0["inputs"], h1["inputs"]]), full["inputs"])

    def test_validation(self):
        with pytest.raises(ValueError, match="not divisible"):
            TokenBatches(TOKENS, 9, 32, process_count=2)
        with pytest.raises(ValueError, match="windows"):
            TokenBatches(TOKENS[:100], 64, 32)


class TestShardingAndPrefetch:
    def test_batches_land_sharded_on_the_mesh(self):
        mesh = make_mesh(MeshConfig(data=4, fsdp=2))
        pipe = ShardedBatcher(
            TokenBatches(TOKENS, 8, 32, num_epochs=1), mesh)
        batch = next(iter(pipe))
        arr = batch["inputs"]
        assert arr.shape == (8, 32)
        assert arr.sharding.spec == \
            __import__("jax").sharding.PartitionSpec(("data", "fsdp"), None)

    def test_prefetcher_preserves_order_and_terminates(self):
        src = ({"i": np.full((2,), n)} for n in range(7))
        pf = DevicePrefetcher(src, depth=3)
        seen = [int(b["i"][0]) for b in pf]
        assert seen == list(range(7))

    def test_prefetcher_propagates_loader_errors(self):
        def bad():
            yield {"i": np.zeros(1)}
            raise RuntimeError("disk on fire")

        pf = DevicePrefetcher(bad(), depth=2)
        next(pf)
        with pytest.raises(RuntimeError, match="disk on fire"):
            next(pf)

    def test_close_unblocks_producer(self):
        src = ({"i": np.full((1,), n)} for n in range(1000))
        pf = DevicePrefetcher(src, depth=1)
        next(pf)
        pf.close()  # must not hang on the full queue

    def test_end_to_end_feeds_a_sharded_train_step(self):
        mesh = make_mesh(MeshConfig(data=4, fsdp=2))
        setup = setup_training(TINY, mesh, batch_shape=(8, 32))
        pipe = input_pipeline(TOKENS, global_batch=8, seq_len=32, mesh=mesh,
                              num_epochs=1, prefetch=2)
        state, losses = setup.state, []
        for i, batch in enumerate(pipe):
            state, metrics = setup.train_step(state, batch)
            losses.append(float(metrics["loss"]))
            if i >= 3:
                pipe.close()
                break
        assert len(losses) >= 3 and all(0 < l < 20 for l in losses)
