"""In-notebook performance metrics: MFU, throughput, HBM.

The north-star metrics from BASELINE.md are measured here (the control-plane
Prometheus metrics live in core/metrics.py; this is the data-plane side,
exported through the same `utils.metrics.Registry` so both planes share one
exposition format, HELP/TYPE metadata, the ci/lint.py naming rule, and the
ci/metrics_drift_check.sh family inventory).

The StepTimer is now a SHIM over `runtime.telemetry.TelemetryAgent` (the
deprecated direct path — new code should construct an agent): `observe()`
forwards to the agent's step boundary and every derived stat reads the
agent's rolling window, so `notebook_training_step_duration_seconds` and
the agent's samples are one stream by construction and can never
disagree.  MFU comes from `runtime.roofline` — the same single definition
bench.py reports.

`jax` is imported lazily (hbm_usage_bytes) so the family inventory and the
timing logic are usable from control-plane tooling — the drift check
registers the families without touching an accelerator, and tests drive
the timer off an injected monotonic clock instead of time.perf_counter.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Callable, Optional

from ..utils.metrics import Registry

if TYPE_CHECKING:  # pragma: no cover - typing only
    from .telemetry import TelemetryAgent

    from ..models.configs import TransformerConfig


def hbm_usage_bytes() -> dict[str, int]:
    """Per-device HBM in use (0s on backends without memory_stats)."""
    import jax

    usage = {}
    for dev in jax.local_devices():
        stats = getattr(dev, "memory_stats", lambda: None)() or {}
        usage[str(dev)] = int(stats.get("bytes_in_use", 0))
    return usage


# train steps span ~ms (tiny models, microbatches) to minutes (large-model
# accumulation); DefaultBuckets tops out at 10s, too short for the tail
STEP_TIME_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def register_step_metrics(registry: Registry) -> dict:
    """Register the data-plane training families on `registry` and return
    them by short name.  Idempotent (the Registry returns the existing
    family on identical re-registration); ci/metrics_drift_check.sh calls
    this to fold the data-plane inventory into the golden list."""
    return {
        "step_duration": registry.histogram(
            "notebook_training_step_duration_seconds",
            "Distribution of synced train-step wall time",
            buckets=STEP_TIME_BUCKETS),
        "tokens_per_second": registry.gauge(
            "notebook_training_tokens_per_second",
            "Rolling training throughput over the step window"),
        "mfu_ratio": registry.gauge(
            "notebook_training_mfu_ratio",
            "Rolling model FLOPs utilization (0-1) over the step window"),
        "hbm_bytes_in_use": registry.gauge(
            "notebook_training_hbm_bytes_in_use",
            "HBM bytes in use across local devices"),
    }


@dataclass
class StepTimer:
    """Rolling train-step telemetry; call `observe()` once per synced step.

    DEPRECATED SHIM: everything routes through a TelemetryAgent
    (`runtime.telemetry`) — the agent observes the step histogram,
    computes MFU through `runtime.roofline`, and keeps the rolling
    window this class's properties read, so the two paths cannot drift.
    Kept for the workbench-image API (`report()`/`prometheus_text()`);
    new loops should construct the agent directly for phase scopes,
    the sample ring, and annotation publishing."""

    config: "TransformerConfig"
    batch: int
    seq_len: int
    num_chips: int
    accelerator: str = "v5e"
    window: int = 20
    registry: Optional[Registry] = None
    time_fn: Callable[[], float] = time.perf_counter

    def __post_init__(self) -> None:
        from .telemetry import TelemetryAgent

        if self.registry is None:
            self.registry = Registry()
        self.agent: "TelemetryAgent" = TelemetryAgent(
            config=self.config, batch=self.batch, seq_len=self.seq_len,
            num_chips=self.num_chips, accelerator=self.accelerator,
            window=self.window, registry=self.registry,
            time_fn=self.time_fn)

    def observe(self) -> None:
        self.agent.step_boundary()

    # the rolling window lives in the agent; tests historically poked
    # `_times` directly, so the shim aliases it read/write
    @property
    def _times(self) -> list[float]:
        return list(self.agent._durations)

    @_times.setter
    def _times(self, values: list[float]) -> None:
        self.agent._durations.clear()
        self.agent._durations.extend(values)

    @property
    def step_time_s(self) -> float:
        return self.agent.step_time_s

    @property
    def tokens_per_s(self) -> float:
        return self.agent.tokens_per_s

    @property
    def mfu(self) -> float:
        return self.agent.mfu

    def report(self) -> dict:
        return {
            "step_time_s": self.step_time_s,
            "tokens_per_s": self.tokens_per_s,
            "mfu": self.mfu,
            "hbm_bytes_in_use": self.agent.hbm_bytes_in_use(),
        }

    def prometheus_text(self) -> str:
        """Prometheus exposition the workbench image can serve on /metrics
        — full HELP/TYPE metadata from the shared Registry."""
        return self.registry.render()
