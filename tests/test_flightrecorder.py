"""Flight recorder, tail-sampled export, and the /debug + /readyz surface
(the PR-3 tentpole): bounded attempt history assembled from span trees,
TailSampler policy (errors/slow always exported, fast successes dropped),
the loopback-gated /debug endpoints over real HTTP, content-negotiated
/metrics, and the liveness/readiness split."""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.kube import ApiServer, KubeObject, Manager, ObjectMeta, Result
from kubeflow_tpu.main import (
    HealthAndMetricsHandler,
    negotiate_metrics_format,
    serve_http,
)
from kubeflow_tpu.utils import tracing
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.flightrecorder import FlightRecorder
from kubeflow_tpu.utils.tracing import InMemorySpanExporter, TailSampler, get_tracer


@pytest.fixture()
def clock():
    c = FakeClock()
    tracing.set_clock(c)
    yield c
    tracing.set_clock(None)


def mk(kind: str, name: str, namespace: str = "default") -> KubeObject:
    return KubeObject(api_version="v1", kind=kind,
                      metadata=ObjectMeta(name=name, namespace=namespace))


def attempt_span(tracer, clock, controller="nb", namespace="ns", name="x",
                 attempt=1, result="success", phases=(), error=None,
                 trace_id=""):
    """Build one finished reconcile root span tree, deterministically."""
    with tracer.start_span("reconcile", {
        "controller": controller, "namespace": namespace, "name": name,
        "attempt": attempt,
    }, trace_id=trace_id) as root:
        for phase, seconds in phases:
            with tracer.start_span(phase, {"phase": phase}):
                clock.advance(seconds)
        if error is not None:
            root.set_attribute("error", True)
            root.add_event("reconcile.error", {
                "exception.type": type(error).__name__,
                "exception.message": str(error)})
        root.set_attribute("reconcile.result", result)
    return root


class TestFlightRecorder:
    def test_attempt_summarized_from_span_tree(self, clock):
        tracer = get_tracer("t")
        rec = FlightRecorder()
        root = attempt_span(tracer, clock, phases=[("render", 0.1),
                                                   ("apply", 0.3),
                                                   ("status", 0.05)])
        a = rec.record(root)
        assert a.object_key == "ns/x"
        assert a.controller == "nb"
        assert a.result == "success"
        assert a.duration_s == pytest.approx(0.45)
        assert a.phases == {"render": pytest.approx(0.1),
                            "apply": pytest.approx(0.3),
                            "status": pytest.approx(0.05)}
        assert a.trace_id == root.trace_id and a.span_id == root.span_id
        # spans record with NO exporter installed: the recorder is the
        # in-process consumer the standalone pod relies on
        assert tracing._exporter is None

    def test_nested_phase_and_plain_grandchild(self, clock):
        """A grandchild WITH a phase attribute (odh auth inside routing)
        counts as its own phase; one without (webhook re-entered inside
        apply) stays inside its enclosing phase."""
        tracer = get_tracer("t")
        rec = FlightRecorder()
        with tracer.start_span("reconcile", {
            "controller": "odh", "namespace": "ns", "name": "x",
            "attempt": 1,
        }) as root:
            with tracer.start_span("routing", {"phase": "routing"}):
                clock.advance(0.1)
                with tracer.start_span("auth", {"phase": "auth"}):
                    clock.advance(0.2)
            with tracer.start_span("apply", {"phase": "apply"}):
                with tracer.start_span("webhook"):
                    clock.advance(0.4)
            root.set_attribute("reconcile.result", "success")
        a = rec.record(root)
        assert a.phases["routing"] == pytest.approx(0.3)  # includes auth
        assert a.phases["auth"] == pytest.approx(0.2)
        assert a.phases["apply"] == pytest.approx(0.4)
        assert "webhook" not in a.phases

    def test_error_text_and_fault_attribution(self, clock):
        tracer = get_tracer("t")
        rec = FlightRecorder()
        with tracer.start_span("reconcile", {
            "controller": "nb", "namespace": "ns", "name": "x", "attempt": 2,
        }) as root:
            root.add_event("fault.injected", {"fault.rule": "drill",
                                              "fault.seq": 7})
            root.set_attribute("error", True)
            root.add_event("reconcile.error", {
                "exception.type": "ServerError",
                "exception.message": "injected: internal error"})
            root.set_attribute("reconcile.result", "error")
        a = rec.record(root)
        assert a.result == "error"
        assert a.error == "ServerError: injected: internal error"
        assert a.faults == [{"fault.rule": "drill", "fault.seq": 7}]
        assert rec.errored()[-1] is a

    def test_ring_and_per_object_bounds(self, clock):
        tracer = get_tracer("t")
        rec = FlightRecorder(capacity=4, per_object=2)
        for i in range(6):
            rec.record(attempt_span(tracer, clock, name="a", attempt=i + 1))
        assert len(rec.attempts()) == 4          # ring evicted the oldest
        history = rec.attempts("ns/a")
        assert [r.attempt for r in history] == [5, 6]  # per-object cap
        assert rec.attempts("ns/missing") == []

    def test_slowest_and_errored_survive_ring_eviction(self, clock):
        tracer = get_tracer("t")
        rec = FlightRecorder(capacity=2, keep_slowest=2, keep_errored=2)
        rec.record(attempt_span(tracer, clock, name="slow",
                                phases=[("apply", 5.0)]))
        rec.record(attempt_span(tracer, clock, name="bad", result="error",
                                error=RuntimeError("boom")))
        for i in range(4):
            rec.record(attempt_span(tracer, clock, name=f"fast{i}"))
        ring_objects = {r.object_key for r in rec.attempts()}
        assert "ns/slow" not in ring_objects  # evicted from the ring...
        assert rec.slowest()[0].object_key == "ns/slow"  # ...but retained
        assert rec.errored()[0].object_key == "ns/bad"

    def test_trace_store_resolves_and_evicts(self, clock):
        tracer = get_tracer("t")
        rec = FlightRecorder(keep_traces=1)
        first = attempt_span(tracer, clock, name="a",
                             phases=[("render", 0.1)])
        rec.record(first)
        got = rec.trace(first.trace_id)
        assert got is not None and got["attempts"] == 1
        assert got["spans"][0]["children"][0]["name"] == "render"
        second = attempt_span(tracer, clock, name="b")
        rec.record(second)
        assert rec.trace(first.trace_id) is None  # LRU-evicted
        assert rec.trace(second.trace_id) is not None

    def test_retry_chain_groups_attempts_under_one_trace(self, clock):
        tracer = get_tracer("t")
        rec = FlightRecorder()
        first = attempt_span(tracer, clock, attempt=1, result="error",
                             error=RuntimeError("boom"))
        rec.record(first)
        rec.record(attempt_span(tracer, clock, attempt=2,
                                trace_id=first.trace_id))
        got = rec.trace(first.trace_id)
        assert got["attempts"] == 2
        assert [s["attributes"]["attempt"] for s in got["spans"]] == [1, 2]


class TestTailSampler:
    @pytest.fixture()
    def sampled(self, clock):
        inner = InMemorySpanExporter()
        sampler = TailSampler(inner, slow_threshold_s=1.0, sample_rate=0.0)
        tracing.set_exporter(sampler)
        yield inner, sampler
        tracing.set_exporter(None)

    def test_fast_success_dropped_children_included(self, clock, sampled):
        inner, sampler = sampled
        tracer = get_tracer("t")
        attempt_span(tracer, clock, phases=[("apply", 0.1)])
        assert inner.spans == []
        assert sampler.dropped_total == 2  # root + child
        assert sampler.stats()["buffered_traces"] == 0

    def test_errored_attempt_always_exported(self, clock, sampled):
        inner, sampler = sampled
        tracer = get_tracer("t")
        root = attempt_span(tracer, clock, result="error",
                            error=RuntimeError("boom"),
                            phases=[("apply", 0.1)])
        names = [s.name for s in inner.spans]
        assert names == ["apply", "reconcile"]  # whole tree, child first
        assert root.attributes["sampling.decision"] == "error"
        assert sampler.exported_total == 2

    def test_slow_attempt_always_exported(self, clock, sampled):
        inner, _ = sampled
        tracer = get_tracer("t")
        root = attempt_span(tracer, clock, phases=[("apply", 2.0)])
        assert [s.name for s in inner.spans] == ["apply", "reconcile"]
        assert root.attributes["sampling.decision"] == "slow"

    def test_probabilistic_keep_is_seeded(self, clock):
        tracer = get_tracer("t")
        inner = InMemorySpanExporter()
        sampler = TailSampler(inner, slow_threshold_s=100.0, sample_rate=0.5,
                              seed=42)
        tracing.set_exporter(sampler)
        try:
            for _ in range(40):
                attempt_span(tracer, clock)
        finally:
            tracing.set_exporter(None)
        kept = len(inner.find("reconcile"))
        assert 0 < kept < 40  # sampled, not all-or-nothing
        assert sampler.stats()["decisions"] == {"probabilistic": kept}

    def test_buffer_bound_evicts_oldest(self, clock):
        inner = InMemorySpanExporter()
        sampler = TailSampler(inner, max_buffered_traces=2)
        tracer = get_tracer("t")
        # three distinct traces whose roots never reach the sampler: the
        # oldest trace's buffered spans are evicted as dropped
        children = []
        for i in range(3):
            with tracer.start_span(f"root{i}"):
                with tracer.start_span("child") as c:
                    children.append(c)
        for c in children:
            sampler.export(c)
        assert sampler.stats()["buffered_traces"] == 2
        assert sampler.dropped_total == 1

    def test_flush_exports_leftovers(self, clock):
        inner = InMemorySpanExporter()
        sampler = TailSampler(inner)
        tracer = get_tracer("t")
        with tracer.start_span("orphan-parent"):
            with tracer.start_span("child") as c:
                pass
        sampler.export(c)  # child buffered, root never arrives
        assert inner.spans == []
        sampler.flush()
        assert [s.name for s in inner.spans] == ["child"]


class TestContentNegotiation:
    def test_negotiation_matrix(self):
        nego = negotiate_metrics_format
        assert nego("application/openmetrics-text") is True
        assert nego("application/openmetrics-text; version=1.0.0; q=0.9,"
                    "text/plain;version=0.0.4;q=0.5,*/*;q=0.1") is True
        assert nego("") is False
        assert nego("*/*") is False
        assert nego("text/plain") is False
        assert nego("application/openmetrics-text;q=0") is False
        # the scraper explicitly prefers classic text: honor it
        assert nego("text/plain;q=0.9,"
                    "application/openmetrics-text;q=0.5") is False


class ScriptedReconciler:
    """error, error, then success PER OBJECT — deterministic retry chains
    even when several objects interleave on the queue."""

    def __init__(self, failures: int = 2):
        self.failures = failures
        self.calls: dict[str, int] = {}

    def reconcile(self, req):
        n = self.calls.get(req.name, 0) + 1
        self.calls[req.name] = n
        if n <= self.failures:
            raise RuntimeError("boom")
        return Result()


class TestDebugEndpoints:
    @pytest.fixture()
    def stack(self, clock):
        from kubeflow_tpu.core.metrics import NotebookMetrics

        api = ApiServer()
        mgr = Manager(api, clock=clock)
        metrics = NotebookMetrics(api, manager=mgr)
        server = serve_http(0, mgr, metrics)
        port = server.server_address[1]
        yield api, mgr, port
        server.shutdown()

    @staticmethod
    def get(port, path, headers=None):
        req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                     headers=headers or {})
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.status, resp.headers.get("Content-Type", ""), \
                resp.read().decode()

    def test_reconciles_global_and_filtered(self, stack):
        api, mgr, port = stack
        mgr.register("nb", ScriptedReconciler(), for_kind="Notebook",
                     max_retries=5)
        api.create(mk("Notebook", "nb1"))
        api.create(mk("Notebook", "nb2"))
        mgr.run_until_idle()

        _, ctype, body = self.get(port, "/debug/reconciles")
        assert ctype == "application/json"
        snap = json.loads(body)
        assert snap["recorded_total"] == 6  # 3 attempts per object
        assert {a["object"] for a in snap["attempts"]} == \
            {"default/nb1", "default/nb2"}
        assert len(snap["errored"]) == 4

        _, _, body = self.get(port,
                              "/debug/reconciles?object=default/nb1")
        per = json.loads(body)
        assert [a["attempt"] for a in per["attempts"]] == [1, 2, 3]
        assert [a["result"] for a in per["attempts"]] == \
            ["error", "error", "success"]
        assert all(a["duration_s"] >= 0.0 for a in per["attempts"])

    def test_trace_endpoint_resolves_recorded_trace(self, stack):
        api, mgr, port = stack
        mgr.register("nb", ScriptedReconciler(), for_kind="Notebook",
                     max_retries=5)
        api.create(mk("Notebook", "nb1"))
        mgr.run_until_idle()
        _, _, body = self.get(port, "/debug/reconciles?object=default/nb1")
        tid = json.loads(body)["attempts"][0]["trace_id"]
        status, _, body = self.get(port, f"/debug/traces/{tid}")
        trace = json.loads(body)
        assert status == 200 and trace["attempts"] == 3
        with pytest.raises(urllib.error.HTTPError) as err:
            self.get(port, "/debug/traces/ffffffffffffffff")
        assert err.value.code == 404

    def test_workqueue_debug_shows_backoff_deadlines(self, stack):
        api, mgr, port = stack

        class AlwaysFails:
            def reconcile(self, req):
                raise RuntimeError("nope")

        mgr.register("nb", AlwaysFails(), for_kind="Notebook", max_retries=5)
        api.create(mk("Notebook", "nb1"))
        # one attempt, no clock advance: the retry sits in backoff
        mgr.run_until_idle(max_iterations=10_000, advance_clock=False)
        _, _, body = self.get(port, "/debug/workqueue")
        wq = json.loads(body)
        assert wq["backoff_pending"] == 1
        (delayed,) = wq["delayed"]
        assert delayed["retry"] is True
        assert delayed["object"] == "default/nb1"
        assert delayed["due_at"] > wq["now"]
        assert wq["retries"] == [
            {"controller": "nb", "object": "default/nb1", "count": 1}]

    def test_debug_endpoints_are_loopback_only(self, stack, monkeypatch):
        api, mgr, port = stack
        monkeypatch.setattr(HealthAndMetricsHandler, "_loopback_only",
                            lambda self: False)
        for path in ("/debug/reconciles", "/debug/workqueue",
                     "/debug/traces/abc"):
            with pytest.raises(urllib.error.HTTPError) as err:
                self.get(port, path)
            assert err.value.code == 403, path

    def test_metrics_negotiation_over_http(self, stack):
        api, mgr, port = stack
        mgr.register("nb", ScriptedReconciler(), for_kind="Notebook",
                     max_retries=5)
        api.create(mk("Notebook", "nb1"))
        mgr.run_until_idle()
        status, ctype, body = self.get(
            port, "/metrics",
            headers={"Accept": "application/openmetrics-text"})
        assert status == 200
        assert ctype.startswith("application/openmetrics-text")
        assert body.rstrip().endswith("# EOF")
        # exemplars on the reconcile-time buckets resolve to recorded traces
        import re

        tids = set(re.findall(r'# \{trace_id="([0-9a-f]+)"\}', body))
        assert tids
        for tid in tids:
            assert mgr.flight_recorder.trace(tid) is not None, tid
        # OpenMetrics counters drop the _total suffix from the family decl
        assert "# TYPE controller_runtime_reconcile counter" in body
        assert 'controller_runtime_reconcile_total{' in body

        status, ctype, body = self.get(port, "/metrics")
        assert ctype == "text/plain; version=0.0.4"
        assert "# EOF" not in body and "# {" not in body
        assert "# TYPE controller_runtime_reconcile_total counter" in body


class TestReadinessSplit:
    @staticmethod
    def get_code(port, path):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
                return resp.status
        except urllib.error.HTTPError as err:
            return err.code

    def test_caches_synced_tracks_watch_connection(self):
        api = ApiServer()
        mgr = Manager(api, clock=FakeClock())
        assert mgr.caches_synced()
        mgr._watch_session.on_watch_dropped()
        assert not mgr.caches_synced()
        mgr.run_until_idle()  # lazy reconnect happens at the next step
        assert mgr.caches_synced()

    def test_readyz_transitions(self):
        from kubeflow_tpu.core.metrics import NotebookMetrics

        class StubElector:
            is_leader = False

        api = ApiServer()
        mgr = Manager(api, clock=FakeClock())
        metrics = NotebookMetrics(api, manager=mgr)
        elector = StubElector()
        server = serve_http(0, mgr, metrics, elector=elector)
        port = server.server_address[1]
        try:
            # alive but not ready: the manager never started
            assert self.get_code(port, "/healthz") == 200
            assert self.get_code(port, "/readyz") == 503
            mgr.start()
            # started but a follower: still not ready
            assert self.get_code(port, "/readyz") == 503
            elector.is_leader = True
            assert self.get_code(port, "/readyz") == 200
            # losing the lease flips readiness without killing liveness
            elector.is_leader = False
            assert self.get_code(port, "/readyz") == 503
            assert self.get_code(port, "/healthz") == 200
            # a stopped manager fails BOTH (restart the pod)
            elector.is_leader = True
            mgr.stop()
            assert self.get_code(port, "/readyz") == 503
            assert self.get_code(port, "/healthz") == 503
        finally:
            mgr.stop()
            server.shutdown()
