"""Active-active sharded control plane: fenced shard map + handoff.

One Manager converges 10k notebooks (loadtest/convergence.py), but the
fleet story needs N managers that are all *working* — Podracer
(arXiv:2104.06272) style sharded workers over read-optimized shared
state — and that survive any replica dying mid-churn.  This module
shards the Notebook keyspace across N in-process manager replicas:

  - **ControlPlaneShardMap** — one cluster-scoped object (same
    optimistic-concurrency, all-state-in-status pattern as TPUWarmPool)
    holding the authoritative membership: an epoch counter, per-shard
    member leases (each stamped with the epoch of its last (re)join —
    its *incarnation*), and the pending handoff records.  The
    consistent-hash ring is DERIVED from the member list
    deterministically (`HashRing`), never stored key-by-key.
  - **Namespace-affine placement** — a key's ring position hashes ONLY
    its namespace, so every key of one tenant namespace lands on one
    shard: that tenant's churn hits one cache and one workqueue instead
    of spraying every ring (the 100k sweep's first binding lever; the
    Kubeflow deployment model is a namespace per user profile).
  - **Fenced writes** — every replica's controllers write through a
    `FencedApi` proxy that calls the authority's `verify()` before each
    write verb: a deposed, evicted, or rejoined-elsewhere incarnation
    holds a stale epoch and gets `StaleEpochError` (counted), so a
    zombie of a killed replica can never clobber the new owner's state.
    The authority protocol is shared with `kube/leader.py`: a
    LeaderElector (fencing epoch = leaseTransitions) and a ShardMember
    (fencing epoch = member incarnation) are interchangeable behind
    `verify()`.
  - **Write-ahead handoff, one record per change** — every membership
    change commits, in the SAME map RMW as the epoch bump, its OWN
    handoff record (appended to `status.handoffs`) naming the shards
    that gain keys (`adopters`) and the surviving shards that lose keys
    (`drains`).  Losers observe the commit (the in-process watch fires
    synchronously at commit), stop dispatching moved keys immediately,
    finish in-flight ones, and RMW-ack out of every record's `drains`
    in one commit (a drain resync against the CURRENT ring covers all
    pending movements at once); adopters enqueue their gained keys ONLY
    once every record granting them has drained, then ack out of
    `adopters` — each record whose lists empty stamps
    `status.lastHandoff` with its measured duration.  Per-change
    records mean N simultaneous joins complete independently instead of
    convoying through one merged record.  The commit is strictly
    write-ahead of adoption (`ShardedReplica.join_fleet`; pinned by
    ci/analyzers/write_ahead.py and model-checked by
    tests/test_interleave.py — including two SIMULTANEOUS joins), so no
    key is ever reconciled by two shards in the same epoch and a crash
    mid-handoff leaves committed records any survivor completes.

Per-shard resource isolation rides the PR 8 substrate: each replica runs
its own Manager worker pool and its own `InformerCache` with a
`key_filter` that admits only owned keys of the sharded kinds, so cache
memory and watch fan-out scale per-shard.
"""

from __future__ import annotations

import bisect
import copy
import hashlib
import logging
import threading
from typing import Callable, Iterable, Optional

from ..utils import invariants
from ..utils.clock import Clock, parse_iso
from ..utils.flightrecorder import FlightRecorder
from ..utils.metrics import Registry
from .cache import InformerCache
from .controller import Manager
from .errors import (ApiError, ConflictError, is_already_exists,
                     retry_on_conflict)
from .leader import FencingToken, StaleEpochError, _iso
from .meta import KubeObject, ObjectMeta

logger = logging.getLogger("kubeflow_tpu.kube.shard")

SHARD_MAP_KIND = "ControlPlaneShardMap"
SHARD_MAP_API_VERSION = "kubeflow.org/v1"
DEFAULT_MAP_NAME = "control-plane"
DEFAULT_LEASE_DURATION_S = 15.0
#: virtual nodes per member on the ring — enough that a join moves
#: roughly 1/N of the keyspace instead of a contiguous half
VNODES = 32
#: the kinds whose keyspace is sharded; owned objects (StatefulSet, Pod,
#: Service, ...) hash to unrelated ring points and MUST stay visible to
#: whichever shard owns their notebook, so they are never filtered
DEFAULT_SHARDED_KINDS = ("Notebook",)


def _hash64(s: str) -> int:
    return int.from_bytes(hashlib.sha1(s.encode()).digest()[:8], "big")


def new_shard_map(name: str = DEFAULT_MAP_NAME) -> KubeObject:
    """A fresh cluster-scoped shard map (all state lives in status)."""
    return KubeObject(
        api_version=SHARD_MAP_API_VERSION,
        kind=SHARD_MAP_KIND,
        metadata=ObjectMeta(name=name),
        body={"spec": {}},
    )


class HashRing:
    """Consistent-hash ring derived deterministically from a member-id
    list: every replica that observes the same member set computes the
    same ownership, so the ring itself never needs to be persisted or
    coordinated beyond the membership.

    Placement is **namespace-affine**: the ring position hashes ONLY
    `namespace`, never the object name, so all keys of one namespace
    share one owner — one tenant's churn stays on one shard's cache and
    workqueue.  Ownership lookups memoize per namespace (a ring is
    immutable once built; membership changes build a new ring), which
    turns the hot dispatch-filter path from sha1+bisect per call into a
    dict hit."""

    __slots__ = ("members", "_points", "_keys", "_owner_cache")

    def __init__(self, members: Iterable[str], vnodes: int = VNODES) -> None:
        self.members: tuple[str, ...] = tuple(sorted(members))
        pts = []
        for sid in self.members:
            for i in range(vnodes):
                pts.append((_hash64(f"{sid}#{i}"), sid))
        pts.sort()
        self._points = pts
        self._keys = [p for p, _ in pts]
        # benign CPython race: concurrent misses compute the same value
        self._owner_cache: dict[str, str] = {}

    def owner_of(self, namespace: str, name: str) -> Optional[str]:
        if not self._points:
            return None
        owner = self._owner_cache.get(namespace)
        if owner is None:
            h = _hash64(namespace)
            idx = bisect.bisect_right(self._keys, h) % len(self._points)
            owner = self._points[idx][1]
            self._owner_cache[namespace] = owner
        return owner


def _lease_expired(member: dict, now: float) -> bool:
    renew = parse_iso(member["renewTime"]) if member.get("renewTime") \
        else 0.0
    duration = float(member.get("leaseDurationSeconds",
                                DEFAULT_LEASE_DURATION_S))
    return renew + duration < now


def _append_handoff(status: dict, now: float, adopters: set,
                    drains: set) -> None:
    """Commit a membership change's key movement as its OWN write-ahead
    record, appended to the epoch-ordered `status.handoffs` list.
    Per-change records let overlapping changes complete independently —
    N simultaneous joins each carry their own adopter/drain lists
    instead of convoying through one merged record.  Departed members
    are pruned from every pending record (a dead shard cannot ack); a
    record pruned empty simply disappears — its movement became moot
    before anyone had to act on it."""
    members = set(status.get("members") or {})
    records = []
    for h in status.get("handoffs") or ():
        a = sorted(set(h.get("adopters") or ()) & members)
        d = sorted(set(h.get("drains") or ()) & members)
        if a or d:
            records.append({"epoch": h.get("epoch"),
                            "startedAt": h.get("startedAt"),
                            "adopters": a, "drains": d})
    adopters = set(adopters) & members
    drains = set(drains) & members
    if adopters or drains:
        records.append({
            "epoch": int(status.get("epoch") or 0),
            "startedAt": _iso(now),
            "adopters": sorted(adopters),
            "drains": sorted(drains),
        })
    if records:
        status["handoffs"] = records
    else:
        status.pop("handoffs", None)


class ShardMember:
    """One replica's handle on the shard map: membership RMWs (join /
    renew / leave / handoff acks, all `retry_on_conflict` over
    update_status, the TPUWarmPool idiom) plus the fencing authority
    (`verify()`) its FencedApi writes are checked against.

    The fencing epoch is the member's **incarnation**: the map epoch at
    its last (re)join.  Renewals do not change it, so survivors stay
    valid across other members' joins; any (re)join bumps it, so the
    token held by a killed-and-evicted — or killed-and-rejoined —
    process's threads is stale the instant the change commits."""

    def __init__(self, api, shard_id: str, *,
                 map_name: str = DEFAULT_MAP_NAME,
                 lease_duration_s: float = DEFAULT_LEASE_DURATION_S,
                 clock: Optional[Clock] = None) -> None:
        self.api = api
        self.shard_id = shard_id
        self.map_name = map_name
        self.lease_duration_s = lease_duration_s
        self.clock = clock or Clock()
        self.token = FencingToken()
        #: shard-map RMW optimistic-concurrency losses (409s retried by
        #: _mutate_map) — the loadtest sweeps record this per point as
        #: the membership-contention trend
        self.rmw_conflicts = 0
        #: resourceVersion of this member's last committed map RMW.
        #: Written only by the protocol thread (join/renew/ack/leave run
        #: single-threaded per replica), so callers may read it right
        #: after an RMW returns to order the view they were handed.
        self.last_commit_rv = 0
        self._last_renew: Optional[float] = None

    # -- map access -----------------------------------------------------------
    def _exempt_get(self) -> Optional[KubeObject]:
        """Read the map fault-exempt (membership observation is protocol
        machinery, not client traffic under chaos test)."""
        exempt = getattr(self.api, "fault_exempt", None)
        if exempt is not None:
            with exempt():
                return self.api.try_get(SHARD_MAP_KIND, "", self.map_name)
        return self.api.try_get(SHARD_MAP_KIND, "", self.map_name)

    def _load(self) -> KubeObject:
        obj = self.api.try_get(SHARD_MAP_KIND, "", self.map_name)
        if obj is None:
            try:
                self.api.create(new_shard_map(self.map_name))
            except ApiError as err:
                if not is_already_exists(err):
                    raise
            obj = self.api.get(SHARD_MAP_KIND, "", self.map_name)
        return obj

    def _mutate_map(self, mutate: Callable[[dict], None]) -> dict:
        """One committed RMW of the map status; returns the committed
        view.  Conflicts re-run `mutate` on a fresh read — concurrent
        membership changes serialize into distinct epochs — with capped
        exponential backoff on the INJECTED clock: a FakeClock-driven
        run backs off in logical time (deterministic, no wall sleeps),
        and membership churn under load spreads out instead of
        hot-looping on 409s.  Every conflict is counted."""
        def attempt() -> dict:
            obj = self._load()
            status = copy.deepcopy(obj.body.get("status") or {})
            mutate(status)
            obj.body["status"] = status
            try:
                committed = self.api.update_status(obj)
            except ConflictError:
                self.rmw_conflicts += 1
                raise
            self.last_commit_rv = committed.metadata.resource_version
            return status
        return retry_on_conflict(attempt, jitter=0.0,
                                 sleep_fn=self.clock.sleep)

    def read_status(self) -> dict:
        """The committed map status (read-only view; fault-exempt so
        membership observation cannot be chaos-injected away)."""
        return self.read_status_rv()[0]

    def read_status_rv(self) -> tuple[dict, int]:
        """`read_status` plus the resourceVersion it was read at, so the
        caller can order the view against watch-delivered ones (map
        commits fan out to watchers outside the store lock, so two
        writers' events can arrive out of commit order)."""
        obj = self._exempt_get()
        if obj is None:
            return {}, 0
        return (obj.body.get("status") or {}), \
            obj.metadata.resource_version

    # -- membership mutations -------------------------------------------------
    def _join_mutation(self, status: dict, now: float) -> None:
        members = status.setdefault("members", {})
        expired = [sid for sid, m in members.items()
                   if sid != self.shard_id and _lease_expired(m, now)]
        for sid in expired:
            members.pop(sid)
        survivors = set(members) - {self.shard_id}
        epoch = int(status.get("epoch") or 0) + 1
        status["epoch"] = epoch
        members[self.shard_id] = {
            "epoch": epoch,
            "renewTime": _iso(now),
            "leaseDurationSeconds": int(self.lease_duration_s),
        }
        # the joiner gains keys from every survivor; an eviction in the
        # same commit hands the dead member's keys to ALL survivors
        adopters = {self.shard_id} | (survivors if expired else set())
        _append_handoff(status, now, adopters, survivors)

    def join(self) -> dict:
        """Commit this member into the map — epoch bump, fresh
        incarnation, expired-member eviction, and the write-ahead
        handoff record, all in ONE status commit.  The fencing token
        activates only from the committed view, never from local
        intent."""
        now = self.clock.now()
        view = self._mutate_map(lambda status:
                                self._join_mutation(status, now))
        self.token.renew(int(view["members"][self.shard_id]["epoch"]))
        self._last_renew = now
        return view

    def preview_join(self) -> dict:
        """The status view `join()` would commit, computed locally
        WITHOUT writing — a planning helper for ops tooling (how much of
        the keyspace would move?).  Adopting from a preview instead of
        the commit is exactly the write-ahead violation the seeded
        mutant in tests/test_interleave.py exercises."""
        obj = self._exempt_get()
        status = copy.deepcopy(obj.body.get("status") or {}) \
            if obj is not None else {}
        self._join_mutation(status, self.clock.now())
        return status

    def renew_due(self) -> bool:
        """Whether the lease wants renewing: a third of the lease
        duration since the last committed renewal (client-go's
        renewDeadline idiom).  A fresh or fenced member is always due.
        The fleet's settle/maintain loops use this to COALESCE renewals
        — without it every settle round is a map RMW per replica, and N
        replicas' heartbeats contend for 409s they don't need."""
        if self._last_renew is None or not self.token.valid:
            return True
        return (self.clock.now() - self._last_renew) >= \
            self.lease_duration_s / 3.0

    def renew(self) -> bool:
        """Renew this member's lease (incarnation unchanged) and evict
        any member whose lease expired — eviction bumps the epoch and
        appends a handoff record in the same commit.  Returns False
        (token invalidated FIRST) if this member was itself evicted."""
        now = self.clock.now()

        def mutate(status: dict) -> None:
            members = status.setdefault("members", {})
            me = members.get(self.shard_id)
            if me is None or int(me.get("epoch", -1)) != self.token.epoch:
                raise StaleEpochError(
                    f"shard {self.shard_id}: evicted from the map "
                    f"(incarnation {self.token.epoch} gone)")
            me = dict(me)
            me["renewTime"] = _iso(now)
            members[self.shard_id] = me
            expired = [sid for sid, m in members.items()
                       if sid != self.shard_id and _lease_expired(m, now)]
            if expired:
                for sid in expired:
                    members.pop(sid)
                status["epoch"] = int(status.get("epoch") or 0) + 1
                _append_handoff(status, now, set(members), set())
            else:
                # prune departed members out of pending records even on
                # a quiet renew (their ack will never come)
                if status.get("handoffs"):
                    _append_handoff(status, now, set(), set())

        try:
            self._mutate_map(mutate)
            self._last_renew = now
            return True
        except StaleEpochError:
            self.token.invalidate()
            return False
        except ApiError as err:
            logger.warning("shard %s: lease renew failed: %s",
                           self.shard_id, err)
            return False

    def leave(self) -> dict:
        """Graceful departure.  The token dies FIRST — a successor may
        own our keys the instant the removal commits, so any of our
        writes racing past this point must already be fenced — then the
        removal commits with the survivors as adopters (and no drain:
        the caller drained us before asking)."""
        self.token.invalidate()
        now = self.clock.now()

        def mutate(status: dict) -> None:
            members = status.setdefault("members", {})
            if members.pop(self.shard_id, None) is None:
                _append_handoff(status, now, set(), set())
                return
            status["epoch"] = int(status.get("epoch") or 0) + 1
            _append_handoff(status, now, set(members), set())

        return self._mutate_map(mutate)

    # -- handoff acks ---------------------------------------------------------
    def _ack(self, status: dict, now: float, field: str,
             completed: list) -> None:
        """Remove this member from `field` of EVERY pending record in
        one commit: a drain resync runs against the CURRENT ring, so it
        covers all pending movements at once, and an adopter only acks
        when every record granting it keys has drained — N concurrent
        handoffs cost one ack RMW here, not N.  Each record whose lists
        both empty completes; completions land in epoch order, so the
        highest-epoch completion wins the `lastHandoff` stamp."""
        completed[0] = None
        records = status.get("handoffs") or []
        remaining: list = []
        done: list = []
        changed = False
        for h in records:
            if self.shard_id in (h.get(field) or ()):
                h = dict(h)
                h[field] = [s for s in h[field] if s != self.shard_id]
                changed = True
            if not h.get("adopters") and not h.get("drains"):
                done.append(h)
            else:
                remaining.append(h)
        if not changed and not done:
            return
        if remaining:
            status["handoffs"] = remaining
        else:
            status.pop("handoffs", None)
        for h in done:
            started = parse_iso(h["startedAt"]) if h.get("startedAt") \
                else now
            duration = max(now - started, 0.0)
            status["lastHandoff"] = {
                "epoch": h.get("epoch"),
                "completedAt": _iso(now),
                "durationSeconds": duration,
            }
            completed[0] = duration

    def ack_drain(self) -> dict:
        """This member finished draining keys it no longer owns."""
        now = self.clock.now()
        completed: list = [None]
        return self._mutate_map(
            lambda status: self._ack(status, now, "drains", completed))

    def ack_adopt(self) -> tuple[dict, Optional[float]]:
        """This member adopted its gained keys; returns the committed
        view plus the completed handoff's duration when THIS ack
        finished one (the handoff-duration observation point — the last
        record this ack completed, when it completed several)."""
        now = self.clock.now()
        completed: list = [None]
        view = self._mutate_map(
            lambda status: self._ack(status, now, "adopters", completed))
        return view, completed[0]

    # -- fencing authority (shared protocol with LeaderElector.verify) --------
    def verify(self) -> int:
        """Raises StaleEpochError unless the token is valid AND the
        committed map still carries this member at the token's
        incarnation epoch.  Called by FencedApi before every write."""
        tok = self.token
        if not tok.valid:
            raise StaleEpochError(
                f"shard {self.shard_id}: fencing token invalidated")
        me = (self.read_status().get("members") or {}).get(self.shard_id)
        if me is None or int(me.get("epoch", -1)) != tok.epoch:
            tok.invalidate()
            raise StaleEpochError(
                f"shard {self.shard_id}: incarnation {tok.epoch} deposed "
                f"(map now has {me or 'no such member'})")
        return tok.epoch


#: every ApiServer/KubeClient verb that commits state — each one is
#: fenced; reads, watches and introspection delegate untouched
WRITE_VERBS = ("create", "update", "update_status", "delete",
               "merge_patch", "strategic_merge_patch", "json_patch",
               "apply")


class FencedApi:
    """Write-fencing proxy: every write verb first asks the authority
    (`ShardMember` or `LeaderElector`) to `verify()` its fencing epoch
    against the committed lease, so a deposed holder's late write raises
    `StaleEpochError` (counted in `rejected_total`) instead of landing.
    Everything else — reads, watch/subscribe plumbing, `fault_exempt`,
    capability probes — delegates to the wrapped api, so Manager and
    InformerCache run on a FencedApi unchanged."""

    def __init__(self, api, authority,
                 on_rejected: Optional[Callable[[], None]] = None) -> None:
        self._api = api
        self._authority = authority
        self._on_rejected = on_rejected
        self.rejected_total = 0

    def _fence(self) -> int:
        try:
            return self._authority.verify()
        except StaleEpochError:
            self.rejected_total += 1
            if self._on_rejected is not None:
                self._on_rejected()
            raise

    def __getattr__(self, name):
        return getattr(self._api, name)


def _fenced_verb(verb: str):
    def call(self, *args, **kwargs):
        self._fence()
        return getattr(self._api, verb)(*args, **kwargs)
    call.__name__ = verb
    call.__qualname__ = f"FencedApi.{verb}"
    call.__doc__ = f"Fenced `{verb}`: verify() the epoch, then delegate."
    return call


for _verb in WRITE_VERBS:
    setattr(FencedApi, _verb, _fenced_verb(_verb))
del _verb


class ShardedReplica:
    """One control-plane replica: a ShardMember (map RMWs on the raw
    api), a FencedApi, a key-filtered InformerCache and a Manager worker
    pool whose dispatch admits only owned keys.  The replica observes
    every map commit synchronously (in-process watch), so the instant a
    membership change lands its ring view — and therefore its dispatch
    filter — is current: a key moved away stops dispatching here before
    the commit's caller even returns."""

    def __init__(self, api, shard_id: str, *,
                 clock: Optional[Clock] = None,
                 map_name: str = DEFAULT_MAP_NAME,
                 lease_duration_s: float = DEFAULT_LEASE_DURATION_S,
                 sharded_kinds: tuple = DEFAULT_SHARDED_KINDS,
                 workers: Optional[int] = None,
                 flight_recorder: Optional[FlightRecorder] = None,
                 vnodes: int = VNODES) -> None:
        self.api = api
        self.shard_id = shard_id
        self.clock = clock or Clock()
        self.sharded_kinds = tuple(sharded_kinds)
        self.alive = False
        self._vnodes = vnodes
        self._lock = invariants.tracked(
            threading.Lock(), "ShardedReplica._lock")
        self._ring = HashRing((), vnodes=vnodes)
        #: ring at the last NO-pending-handoff state: the dispatch gate
        #: for keys gained by a still-draining change.  A single
        #: previous-ring snapshot is wrong under overlapping changes
        #: (the ring one change ago is not the last stable ownership);
        #: this only advances when every record has acked out.
        self._stable_ring = self._ring
        self._epoch = 0
        self._pending_handoffs: list[dict] = []
        #: resourceVersion of the installed view — map commits fan out
        #: to watchers outside the store lock, so two writers' events
        #: can be DELIVERED out of commit order; installing by rv keeps
        #: the ring/gate view from regressing to an older commit
        self._installed_rv = 0
        #: completed-handoff durations observed by THIS replica's acks
        self.handoff_durations: list[float] = []
        self.member = ShardMember(api, shard_id, map_name=map_name,
                                  lease_duration_s=lease_duration_s,
                                  clock=self.clock)
        self.fenced = FencedApi(api, self.member)
        self.flight_recorder = flight_recorder if flight_recorder \
            is not None else FlightRecorder()
        registry = Registry()
        self.cache = InformerCache(self.fenced, registry=registry,
                                   key_filter=self._cache_filter)
        self.manager = Manager(self.fenced, clock=self.clock,
                               registry=registry, workers=workers,
                               flight_recorder=self.flight_recorder,
                               cache=self.cache, key_filter=self.owns_key)
        if hasattr(api, "watch"):
            api.watch(self._on_map_event, kinds=[SHARD_MAP_KIND])

    # -- ownership view -------------------------------------------------------
    def _on_map_event(self, ev) -> None:
        if ev.obj.kind != SHARD_MAP_KIND or \
                ev.obj.name != self.member.map_name:
            return
        self._install_status(ev.obj.body.get("status") or {},
                             rv=ev.obj.metadata.resource_version)

    def _install_status(self, status: dict,
                        rv: Optional[int] = None) -> None:
        with self._lock:
            if rv is not None:
                if rv <= self._installed_rv:
                    return    # stale delivery: a newer commit installed
                self._installed_rv = rv
            members = tuple(sorted(status.get("members") or {}))
            if members != self._ring.members:
                self._ring = HashRing(members, vnodes=self._vnodes)
            self._epoch = int(status.get("epoch") or 0)
            records = [dict(h) for h in status.get("handoffs") or ()]
            self._pending_handoffs = records
            if not records:
                # every movement acked out: current ownership is stable
                self._stable_ring = self._ring

    @property
    def epoch(self) -> int:
        return self._epoch

    def owns_key(self, namespace: str, name: str) -> bool:
        """Dispatch filter: the ring must assign the key here — and a
        key GAINED in a still-draining handoff is not dispatchable yet
        (the previous owner may have it in flight); it arrives via the
        batched adopt-enqueue at adoption time.  "Gained" is judged
        against the last STABLE ring — the ownership when no handoff was
        pending — so the gate stays correct when two changes overlap."""
        with self._lock:
            ring, stable, records = self._ring, self._stable_ring, \
                self._pending_handoffs
        if self.shard_id not in ring.members or \
                ring.owner_of(namespace, name) != self.shard_id:
            return False
        gated = any(self.shard_id in (h.get("adopters") or ())
                    and (h.get("drains") or ())
                    for h in records)
        if gated:
            if not stable.members or \
                    stable.owner_of(namespace, name) != self.shard_id:
                return False
        return True

    def _cache_filter(self, kind: str, namespace: str, name: str) -> bool:
        if kind not in self.sharded_kinds:
            return True
        with self._lock:
            ring = self._ring
        return self.shard_id in ring.members and \
            ring.owner_of(namespace, name) == self.shard_id

    # -- handoff protocol -----------------------------------------------------
    def join_fleet(self) -> None:
        """Join (or re-join) the fleet.  The map commit inside
        `member.join` is strictly WRITE-AHEAD of adoption: only after
        the RMW lands — epoch bump, fresh incarnation, handoff record
        naming this shard an adopter — does the replica install the
        committed view and start draining/adopting.  A crash between
        the two leaves a committed record any survivor completes; the
        reverse order would reconcile keys nobody committed to us
        (ci/analyzers/write_ahead.py pins this order statically,
        tests/test_interleave.py model-checks it)."""
        view = self.member.join()
        self._install_status(view, rv=self.member.last_commit_rv)
        self._drain_and_adopt(view)
        self.alive = True

    def sync(self) -> None:
        """One handoff-protocol step off the committed map: refresh the
        ownership view, ack pending drains once nothing foreign is in
        flight, adopt once every record granting us keys has drained."""
        status, rv = self.member.read_status_rv()
        self._install_status(status, rv=rv)
        self._drain_and_adopt(status)

    def maintain(self) -> bool:
        """Periodic housekeeping: renew the member lease when a renewal
        is actually due (evicting expired peers), then run one handoff
        step.  Returns False when this replica found itself evicted
        (token already invalidated)."""
        if self.member.renew_due():
            if not self.member.renew():
                return False
        self.sync()
        return True

    def _drain_and_adopt(self, status: dict) -> None:
        records = status.get("handoffs") or ()
        if not records:
            return
        added: Optional[dict] = None
        if any(self.shard_id in (h.get("drains") or ()) for h in records) \
                and not self._holding_foreign_keys():
            # draining = dropping the moved keys: evict them from the
            # filtered cache before the ack tells adopters to proceed.
            # One resync against the CURRENT ring covers every pending
            # record's movement, so the ack clears all our drains.
            added = self._resync_sharded()
            status = self.member.ack_drain()
            self._install_status(status, rv=self.member.last_commit_rv)
            records = status.get("handoffs") or ()
        mine = [h for h in records
                if self.shard_id in (h.get("adopters") or ())]
        if mine and not any(h.get("drains") for h in mine):
            self._adopt(added)

    def _resync_sharded(self) -> dict:
        """Realign the filtered cache for every sharded kind; returns
        the keys the sweep newly admitted, per kind."""
        added: dict = {}
        for kind in self.sharded_kinds:
            try:
                added[kind] = set(self.cache.resync(kind))
            except ApiError as err:
                added[kind] = set()
                logger.warning("shard %s: resync of %s failed: %s",
                               self.shard_id, kind, err)
        return added

    def _adopt(self, added: Optional[dict]) -> None:
        """Adopt the keys this shard gained: realign the filtered cache
        with current ownership (unless the drain step just did), then
        enqueue the GAINED keys in one batched pass per kind — gained =
        newly admitted by the sweep plus anything the stable ring did
        not already assign here (keys that arrived by watch while the
        drain gate held) — and ack.  Runs strictly after the map commit
        that granted the keys (see join_fleet) and strictly after every
        drain ack.  The batched pass replaces a full enqueue_all walk:
        adoption cost scales with the keys that MOVED, not the keys the
        shard holds."""
        if added is None:
            added = self._resync_sharded()
        with self._lock:
            stable = self._stable_ring
        for kind in self.sharded_kinds:
            gained = set(added.get(kind) or ())
            for ns, name in self.cache.keys(kind):
                if not stable.members or \
                        stable.owner_of(ns, name) != self.shard_id:
                    gained.add((ns, name))
            self.manager.enqueue_keys(kind, sorted(gained))
        # non-sharded primary kinds (Event, TenantQuota, WarmPool, ...)
        # keep the full resync sweep: their keyspaces are small and the
        # dispatch filter still applies per namespace
        self.manager.enqueue_all(exclude_kinds=self.sharded_kinds)
        view, duration = self.member.ack_adopt()
        self._install_status(view, rv=self.member.last_commit_rv)
        if duration is not None:
            self.handoff_durations.append(duration)

    def _holding_foreign_keys(self) -> bool:
        for _reg, req in self.manager.inflight_requests():
            if not self.owns_key(req.namespace, req.name):
                return True
        return False

    # -- lifecycle ------------------------------------------------------------
    def kill(self) -> None:
        """Simulate the process dying mid-churn: workers stop (joined —
        no reconcile survives in this address space), but NO map write
        happens and the token is left as-is: the lease must expire and a
        survivor must evict us, and any zombie thread still holding the
        old FencedApi must be fenced, not trusted."""
        self.manager.stop()
        self.alive = False

    def leave_fleet(self) -> None:
        """Graceful departure: stop dispatch, drain in-flight work, then
        commit the removal (survivors adopt; nothing to drain)."""
        self.manager.stop()
        self.alive = False
        self.member.leave()

    def keys_owned(self) -> int:
        """Owned keys of the primary sharded kind, straight off the
        filtered cache (O(keys of this shard), never O(fleet))."""
        if not self.alive:
            return 0
        with self._lock:
            if self.shard_id not in self._ring.members:
                return 0  # evicted: stale cache entries are not ownership
        try:
            return len(self.cache.keys(self.sharded_kinds[0]))
        except ApiError:
            return 0

    def snapshot(self) -> dict:
        """Per-shard health for /debug/fleet and the metrics scrape."""
        return {
            "shard": self.shard_id,
            "alive": self.alive,
            "epoch": self._epoch,
            "incarnation": self.member.token.epoch,
            "token_valid": self.member.token.valid,
            "keys_owned": self.keys_owned(),
            "fenced_rejections": self.fenced.rejected_total,
            "handoffs_completed": len(self.handoff_durations),
            "rmw_conflicts": self.member.rmw_conflicts,
        }


class ShardedFleet:
    """N ShardedReplicas over one shared ApiServer — the test/loadtest/
    soak harness for the active-active control plane.  The
    `controller_factory(replica)` callback registers each replica's
    controllers (against `replica.fenced` — that is what
    `replica.manager` hands reconcilers) before the replica joins."""

    def __init__(self, api, count: int = 3, *,
                 clock: Optional[Clock] = None,
                 controller_factory: Optional[Callable] = None,
                 workers: Optional[int] = None,
                 sharded_kinds: tuple = DEFAULT_SHARDED_KINDS,
                 lease_duration_s: float = DEFAULT_LEASE_DURATION_S,
                 map_name: str = DEFAULT_MAP_NAME) -> None:
        self.api = api
        self.clock = clock or Clock()
        self.map_name = map_name
        self.lease_duration_s = lease_duration_s
        self.sharded_kinds = tuple(sharded_kinds)
        self.workers = workers
        self._factory = controller_factory
        self.replicas: dict[str, ShardedReplica] = {}
        for i in range(count):
            self.add_replica(f"shard-{i}")

    def add_replica(self, shard_id: str) -> ShardedReplica:
        r = ShardedReplica(
            self.api, shard_id, clock=self.clock, map_name=self.map_name,
            lease_duration_s=self.lease_duration_s,
            sharded_kinds=self.sharded_kinds, workers=self.workers)
        self.replicas[shard_id] = r
        if self._factory is not None:
            self._factory(r)
        r.join_fleet()
        return r

    def kill(self, shard_id: str) -> None:
        self.replicas[shard_id].kill()

    def rejoin(self, shard_id: str) -> None:
        """Bring a killed replica back: a fresh incarnation through the
        same join path every replica uses."""
        self.replicas[shard_id].join_fleet()

    def alive_replicas(self) -> list[ShardedReplica]:
        return [r for r in self.replicas.values() if r.alive]

    def map_status(self) -> dict:
        for r in self.replicas.values():
            return r.member.read_status()
        return {}

    def pending_handoffs(self) -> list:
        """Every pending handoff record off the committed map."""
        return [dict(h) for h in self.map_status().get("handoffs") or ()]

    def rmw_conflicts(self) -> int:
        """Total shard-map RMW 409 retries across the fleet's members —
        the contention figure the sweep artifact records per point."""
        return sum(r.member.rmw_conflicts for r in self.replicas.values())

    def owner_of(self, namespace: str, name: str) -> Optional[str]:
        ring = HashRing(sorted(self.map_status().get("members") or {}))
        return ring.owner_of(namespace, name)

    def settle(self, max_rounds: int = 500,
               advance_clock: bool = True) -> int:
        """Round-robin the live replicas — renew, handoff step, drain
        workqueues — until a full pass does nothing and no handoff is
        pending.  Structurally idle replicas are SKIPPED: a replica with
        nothing queued, parked, or delayed, no pending record naming it,
        and a fresh lease has no step to run, so a pass costs O(active
        shards) instead of walking every replica's maintain + workqueue
        (at 10k+ notebooks the idle walks dominated handoff-stall wall
        time).  When a handoff stalls on a dead member's lease, the
        FakeClock jumps past the lease duration so survivors evict it
        (exactly what wall time does in production).  Returns total
        reconciles executed."""
        total = 0
        adv = getattr(self.clock, "advance", None) if advance_clock \
            else None
        last_status: Optional[dict] = None
        for _ in range(max_rounds):
            did = 0
            involved: set = set()
            for h in self.map_status().get("handoffs") or ():
                involved.update(h.get("adopters") or ())
                involved.update(h.get("drains") or ())
            for r in self.alive_replicas():
                busy = r.manager.has_pending_work()
                if not busy and r.shard_id not in involved and \
                        not r.member.renew_due():
                    continue
                r.maintain()
                if busy or r.manager.has_pending_work():
                    # livelock cap scaled to the shard's outstanding
                    # work: a 100k-notebook fleet legitimately drains
                    # tens of thousands of reconciles per round, so a
                    # flat cap misreads initial convergence as livelock
                    did += r.manager.run_until_idle(
                        max_iterations=max(
                            10_000, 8 * r.manager.pending_count()),
                        advance_clock=advance_clock)
            total += did
            status = self.map_status()
            changed = status != last_status
            last_status = status
            if did == 0 and not changed:
                # a full pass moved neither work nor the protocol
                if not status.get("handoffs"):
                    return total
                # a handoff waits on a member that will never ack (it
                # died): step time in sub-lease increments — survivors
                # renew each round, so only the dead lease ages past the
                # duration and gets evicted
                if adv is not None:
                    adv(self.lease_duration_s * 0.6)
                else:
                    raise RuntimeError(
                        "sharded fleet: handoff pending but no replica "
                        "made progress and the clock is not advanceable")
        raise RuntimeError("sharded fleet did not settle: handoff "
                           f"stalled after {max_rounds} rounds "
                           f"({self.pending_handoffs()})")

    def merged_records(self) -> list:
        """Every replica's flight-recorder history merged — the
        cross-process stream `flightrecorder.sweep_overlaps` (and
        ops/diagnose --merge) runs over."""
        out = []
        for r in self.replicas.values():
            out.extend(r.flight_recorder.attempts())
        return out

    def cross_process_overlaps(self) -> list:
        """Per-key serialization violations ACROSS replicas: two shards
        reconciling one key in the same wall-clock window.  Empty is the
        single-owner proof the kill/rejoin soak asserts."""
        from ..utils.flightrecorder import sweep_overlaps
        return sweep_overlaps(self.merged_records())

    def shard_snapshot(self) -> dict:
        """Fleet-wide shard health: the committed map plus each
        replica's local view — the `shards` section of /debug/fleet and
        the source the notebook_shard_* metric families scrape.  The
        `handoff` key stays the one-record rollup older dashboards read
        (None when nothing is pending); `handoffs` is the full
        per-change list."""
        status = self.map_status()
        records = [dict(h) for h in status.get("handoffs") or ()]
        merged = None
        if records:
            merged = {
                "epoch": records[-1].get("epoch"),
                "startedAt": records[0].get("startedAt"),
                "adopters": sorted({s for h in records
                                    for s in h.get("adopters") or ()}),
                "drains": sorted({s for h in records
                                  for s in h.get("drains") or ()}),
            }
        return {
            "epoch": int(status.get("epoch") or 0),
            "members": sorted(status.get("members") or {}),
            "handoff": merged,
            "handoffs": records,
            "lastHandoff": dict(status["lastHandoff"])
            if status.get("lastHandoff") else None,
            "replicas": {sid: r.snapshot()
                         for sid, r in sorted(self.replicas.items())},
        }


__all__ = [
    "DEFAULT_LEASE_DURATION_S", "DEFAULT_MAP_NAME", "DEFAULT_SHARDED_KINDS",
    "FencedApi", "HashRing", "SHARD_MAP_API_VERSION", "SHARD_MAP_KIND",
    "ShardMember", "ShardedFleet", "ShardedReplica", "StaleEpochError",
    "VNODES", "WRITE_VERBS", "new_shard_map",
]
