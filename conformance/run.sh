#!/usr/bin/env bash
# Notebook conformance profile (reference conformance/1.7/Makefile analog,
# retargeted at the notebook subsystem): the e2e phase harness IS the
# conformance suite — CRD lifecycle, routing, auth, culling semantics.
set -euo pipefail
cd "$(dirname "$0")/.."
python -m pytest tests/test_e2e.py tests/test_odh_routing.py tests/test_culling.py -q
echo "notebook conformance: PASS"
