"""The real Jupyter HTTP probe path, exercised against a live server.

Round 1 only ever drove culling through FakeJupyterState; here a local HTTP
server speaks the actual kernels/terminals REST shapes
(culling_controller.go:244-336, KernelStatus :63-85) and
`HttpJupyterClient` probes it over a real socket — including the culling
end-to-end: probe -> idle -> stop annotation -> STS to 0 (the flow the
reference verifies on a live cluster, odh e2e/notebook_creation_test.go:31-83).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubeflow_tpu.api.types import Notebook
from kubeflow_tpu.core import constants as C
from kubeflow_tpu.core.culling_controller import setup_culling
from kubeflow_tpu.core.jupyter import HttpJupyterClient
from kubeflow_tpu.core.notebook_controller import setup_core_controllers
from kubeflow_tpu.kube import ApiServer, FakeCluster, Manager
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.config import CoreConfig


def iso(t: float) -> str:
    import time as _time

    return _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime(t))


class JupyterServer:
    """Speaks GET /notebook/{ns}/{name}/api/{kernels|terminals}."""

    def __init__(self):
        self.kernels: dict[tuple[str, str], object] = {}
        self.terminals: dict[tuple[str, str], object] = {}
        self.status_code = 200
        self.raw_body: bytes | None = None
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):  # noqa: N802
                parts = [p for p in self.path.split("/") if p]
                # notebook/{ns}/{name}/api/{resource}
                if len(parts) != 5 or parts[0] != "notebook" or parts[3] != "api":
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                ns, name, resource = parts[1], parts[2], parts[4]
                store = outer.kernels if resource == "kernels" else outer.terminals
                body = (outer.raw_body if outer.raw_body is not None
                        else json.dumps(store.get((ns, name), [])).encode())
                self.send_response(outer.status_code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture()
def server():
    srv = JupyterServer()
    yield srv
    srv.stop()


class TestHttpJupyterClient:
    def test_parses_kernels_over_http(self, server):
        server.kernels[("user1", "wb")] = [{
            "id": "k1", "name": "python3",
            "last_activity": "2026-07-29T10:00:00.533016Z",
            "execution_state": "idle", "connections": 1,
        }]
        client = HttpJupyterClient(base_url=server.url)
        kernels = client.get_kernels("wb", "user1")
        assert kernels is not None and kernels[0]["execution_state"] == "idle"
        assert client.get_terminals("wb", "user1") == []

    def test_non_200_returns_none(self, server):
        server.status_code = 503
        client = HttpJupyterClient(base_url=server.url)
        assert client.get_kernels("wb", "user1") is None

    def test_malformed_json_returns_none(self, server):
        server.raw_body = b"{not json"
        client = HttpJupyterClient(base_url=server.url)
        assert client.get_kernels("wb", "user1") is None

    def test_non_list_json_returns_none(self, server):
        server.raw_body = b'{"message": "forbidden"}'
        client = HttpJupyterClient(base_url=server.url)
        assert client.get_kernels("wb", "user1") is None

    def test_unreachable_server_returns_none(self):
        client = HttpJupyterClient(base_url="http://127.0.0.1:1")
        assert client.get_kernels("wb", "user1") is None


class TestCullingOverHttp:
    """probe -> idle -> stop annotation -> STS 0, with the production HTTP
    transport end to end."""

    @pytest.fixture()
    def env(self, server):
        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("n1", allocatable={"cpu": "32", "memory": "64Gi"})
        clock = FakeClock()
        mgr = Manager(api, clock=clock)
        cfg = CoreConfig(enable_culling=True, cull_idle_time_min=60,
                         idleness_check_period_min=1)
        setup_core_controllers(mgr, cfg)
        jupyter = HttpJupyterClient(base_url=server.url)
        setup_culling(mgr, cfg, jupyter=jupyter)
        return api, mgr, clock

    def test_active_notebook_not_culled(self, server, env):
        api, mgr, clock = env
        server.kernels[("user1", "wb")] = [{
            "id": "k1", "name": "python3",
            "last_activity": iso(clock.now()),
            "execution_state": "busy", "connections": 1,
        }]
        api.create(Notebook.new("wb", "user1").obj)
        mgr.run_until_idle()
        clock.advance(120)
        # keep the kernel's activity fresh as time advances
        server.kernels[("user1", "wb")][0]["last_activity"] = iso(clock.now())
        mgr.run_until_idle()
        nb = api.get("Notebook", "user1", "wb")
        assert C.STOP_ANNOTATION not in nb.annotations
        assert api.get("StatefulSet", "user1", "wb").spec["replicas"] == 1

    def test_idle_notebook_culled_to_zero(self, server, env):
        api, mgr, clock = env
        t0 = clock.now()
        server.kernels[("user1", "wb")] = [{
            "id": "k1", "name": "python3",
            "last_activity": iso(t0),
            "execution_state": "idle", "connections": 0,
        }]
        api.create(Notebook.new("wb", "user1").obj)
        mgr.run_until_idle()
        assert api.get("StatefulSet", "user1", "wb").spec["replicas"] == 1
        # idle past CULL_IDLE_TIME (60 min), probed each check period
        for _ in range(65):
            mgr.advance(60)
        nb = api.get("Notebook", "user1", "wb")
        assert C.STOP_ANNOTATION in nb.annotations, "idle notebook not culled"
        mgr.run_until_idle()
        assert api.get("StatefulSet", "user1", "wb").spec["replicas"] == 0

    def test_probe_failure_leaves_activity_stale_then_culls(self, server, env):
        """Reference parity: a failed probe does NOT refresh last-activity
        (updateTimestampFromKernelsActivity returns early on empty/nil,
        culling_controller.go:382-385), so a notebook whose Jupyter API is
        unreachable for longer than CULL_IDLE_TIME is culled — but not
        before the idle window expires."""
        api, mgr, clock = env
        server.status_code = 500  # jupyter unreachable
        api.create(Notebook.new("wb", "user1").obj)
        mgr.run_until_idle()
        # within the window: still running
        for _ in range(30):
            mgr.advance(60)
        assert api.get("StatefulSet", "user1", "wb").spec["replicas"] == 1
        # past CULL_IDLE_TIME with no successful probe: culled
        for _ in range(35):
            mgr.advance(60)
        nb = api.get("Notebook", "user1", "wb")
        assert C.STOP_ANNOTATION in nb.annotations
        mgr.run_until_idle()
        assert api.get("StatefulSet", "user1", "wb").spec["replicas"] == 0
