"""Model zoo for the BASELINE workload matrix: MNIST MLP, ViT, and the
Llama/Gemma decoder family with sharded training (models.train)."""

from .configs import GEMMA_7B, LLAMA2_7B, LLAMA2_350M, PRESETS, TINY, TransformerConfig
from .mlp import MLP
from .transformer import Transformer
from .vit import VIT_B16, VIT_TINY, ViT, ViTConfig

__all__ = [
    "GEMMA_7B", "LLAMA2_7B", "LLAMA2_350M", "MLP", "PRESETS", "TINY",
    "Transformer", "TransformerConfig", "VIT_B16", "VIT_TINY", "ViT", "ViTConfig",
]
