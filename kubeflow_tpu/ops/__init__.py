"""TPU compute ops: flash/XLA attention and ring attention
(sequence-parallel exact attention over the ICI ring)."""

from .attention import attention, flash_attention, xla_attention
from .ring_attention import ring_attention

__all__ = ["attention", "flash_attention", "ring_attention", "xla_attention"]
