"""`python -m kubeflow_tpu.deploy [profile] [--image IMG]` -> multi-doc
YAML on stdout (the `kustomize build config/overlays/{profile}` analog)."""

import argparse

from .manifests import PROFILES, render_yaml

parser = argparse.ArgumentParser(prog="kubeflow_tpu.deploy")
parser.add_argument("profile", nargs="?", default="standalone",
                    choices=sorted(PROFILES))
parser.add_argument("--image", default="kubeflow-tpu-controller:latest",
                    help="manager container image")
args = parser.parse_args()
print(render_yaml(args.profile, image=args.image), end="")
