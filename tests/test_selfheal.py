"""Slice-atomic self-healing tests (core/selfheal.py): disruption
classification, budgeted slice-atomic recovery, crash-safe restart
bookkeeping, and the terminal RecoveryExhausted escalation.

The suite leans on the ApiServer audit log: a recovery restart must show
up as a CONTIGUOUS group of pod-delete attempts covering every ordinal of
the slice — anything else is a partial-slice restart, the state
slice-atomicity forbids (JAX collectives cannot survive partial
membership)."""

import pytest

from kubeflow_tpu.api.types import (
    CONDITION_RECOVERY_EXHAUSTED,
    Notebook,
    ReplicationSpec,
    TPUSpec,
)
from kubeflow_tpu.core import constants as C
from kubeflow_tpu.core.metrics import NotebookMetrics
from kubeflow_tpu.core.notebook_controller import setup_core_controllers
from kubeflow_tpu.core.selfheal import (
    EVENT_PRIMARY_PROMOTED,
    MIGRATE_RESULT_FALLBACK,
    MIGRATE_RESULT_MIGRATED,
    MIGRATE_RESULT_RESTORED,
    MIGRATE_RESULT_SKIPPED,
    MIGRATE_TRIGGER_DRAIN,
    MIGRATE_TRIGGER_FAILURE,
    MIGRATE_TRIGGER_NODE_DRAIN,
    PENDING,
    PROMOTE_RESULT_LOST_RACE,
    PROMOTE_RESULT_NO_CANDIDATE,
    PROMOTE_RESULT_PROMOTED,
    REASON_CRASH_LOOP,
    REASON_MIGRATE,
    REASON_NODE_GONE,
    REASON_PENDING_TIMEOUT,
    REASON_POD_FAILED,
    classify_worker,
)
from kubeflow_tpu.core.sessionstate import InMemorySessionStore, StaleWriterError
from kubeflow_tpu.kube import (
    ApiServer,
    FakeCluster,
    FaultPlan,
    FaultRule,
    KubeObject,
    Manager,
    ObjectMeta,
)
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.config import CoreConfig

HOSTS = 4  # v5e 4x4 single slice


# -- harness -------------------------------------------------------------------
def make_env(cfg=None, tpu_nodes=HOSTS):
    api = ApiServer()
    cluster = FakeCluster(api)
    cluster.add_node("cpu-node", allocatable={"cpu": "64", "memory": "256Gi"})
    if tpu_nodes:
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4",
                                    tpu_nodes, 4)
    clock = FakeClock()
    mgr = Manager(api, clock=clock)
    metrics = NotebookMetrics(api)
    cfg = cfg or CoreConfig()
    setup_core_controllers(mgr, cfg, metrics)
    return api, cluster, mgr, clock, metrics


def create_tpu_nb(api, mgr, name="heal", ns="u1"):
    nb = Notebook.new(name, ns, tpu=TPUSpec("v5e", "4x4"))
    api.create(nb.obj)
    mgr.run_until_idle()
    return nb


def pod_delete_groups(api, name, hosts=HOSTS):
    """Partition the audited worker-pod delete ATTEMPTS (ok or not) into
    consecutive groups; assert every group covers the full ordinal set —
    i.e. the controller only ever issued whole-slice restarts — and
    return the group count."""
    recs = [r for r in api.audit_log(verb="delete", kind="Pod")
            if r.name.startswith(name + "-")]
    expected = {f"{name}-{i}" for i in range(hosts)}
    groups = 0
    for i in range(0, len(recs), hosts):
        chunk = {r.name for r in recs[i:i + hosts]}
        assert chunk == expected, (
            "partial-slice pod deletion observed in the audit log",
            [(r.name, r.ok) for r in recs])
        groups += 1
    return groups


def recovery_state(api, ns="u1", name="heal", slice_id="0"):
    status = api.get("Notebook", ns, name).body.get("status", {})
    return (status.get("sliceRecovery") or {}).get(slice_id)


def exhausted_condition(api, ns="u1", name="heal"):
    status = api.get("Notebook", ns, name).body.get("status", {})
    return next((c for c in status.get("conditions", [])
                 if c.get("type") == CONDITION_RECOVERY_EXHAUSTED), None)


def event_reasons(api, ns="u1"):
    return [e.body.get("reason") for e in api.list("Event", namespace=ns)]


# -- disruption classification -------------------------------------------------
def _mk_pod(api, phase="Running", ready=True, waiting_reason=None,
            node=None):
    status = {
        "phase": phase,
        "conditions": [
            {"type": "Ready", "status": "True" if ready else "False"},
        ],
        "containerStatuses": [{
            "name": "main",
            "ready": ready,
            "state": ({"waiting": {"reason": waiting_reason}}
                      if waiting_reason else
                      {"running": {"startedAt": "2023-01-01T00:00:00Z"}}),
        }],
    }
    spec = {"containers": [{"name": "main"}]}
    if node:
        spec["nodeName"] = node
    return KubeObject("v1", "Pod", ObjectMeta(name="w-0", namespace="u1"),
                      body={"spec": spec, "status": status})


class TestDisruptionClassification:
    """Table-driven: the disruptions that MUST trigger recovery, and the
    healthy/transient states that must NOT."""

    @pytest.mark.parametrize("label,pod_kwargs,node_ready,want", [
        ("pod-failed", dict(phase="Failed", ready=False), True,
         REASON_POD_FAILED),
        ("crash-loop", dict(ready=False,
                            waiting_reason="CrashLoopBackOff"), True,
         REASON_CRASH_LOOP),
        ("node-deleted", dict(node="ghost-node"), True, REASON_NODE_GONE),
        ("node-unready", dict(node="sick-node"), False, REASON_NODE_GONE),
        ("pending-unscheduled", dict(phase="Pending", ready=False), True,
         PENDING),
        ("image-pull-backoff", dict(phase="Pending", ready=False,
                                    waiting_reason="ImagePullBackOff"),
         True, PENDING),
        ("container-creating", dict(phase="Pending", ready=False,
                                    waiting_reason="ContainerCreating",
                                    node="ok-node"), True, PENDING),
        ("healthy", dict(node="ok-node"), True, None),
        ("running-not-ready", dict(ready=False, node="ok-node"), True,
         None),
    ])
    def test_classification(self, label, pod_kwargs, node_ready, want):
        api = ApiServer()
        for name in ("ok-node", "sick-node"):
            api.create(KubeObject(
                "v1", "Node", ObjectMeta(name=name),
                body={"status": {"conditions": [
                    {"type": "Ready",
                     "status": "True" if (node_ready
                                          or name == "ok-node") else
                     "False"},
                ]}}))
        pod = _mk_pod(api, **pod_kwargs)
        assert classify_worker(pod, api) == want, label

    def test_crashloop_beats_pending(self):
        """A scheduled pod crash-looping reads crash-loop, not pending —
        restarting it can actually help, so no deadline wait applies."""
        api = ApiServer()
        pod = _mk_pod(api, phase="Running", ready=False,
                      waiting_reason="CrashLoopBackOff")
        assert classify_worker(pod, api) == REASON_CRASH_LOOP


# -- the recovery engine -------------------------------------------------------
class TestSliceRecovery:
    def test_failed_worker_restarts_whole_slice(self):
        api, cluster, mgr, clock, metrics = make_env()
        create_tpu_nb(api, mgr)
        uids_before = {p.name: p.metadata.uid
                       for p in api.list("Pod", namespace="u1")}
        cluster.fail_pod("u1", "heal-1")
        mgr.run_until_idle()
        status = api.get("Notebook", "u1", "heal").body["status"]
        assert status["sliceHealth"] == "Healthy"
        # slice-atomic: ALL four workers were replaced, not just heal-1
        uids_after = {p.name: p.metadata.uid
                      for p in api.list("Pod", namespace="u1")}
        assert set(uids_after) == set(uids_before)
        assert all(uids_after[n] != uids_before[n] for n in uids_before)
        assert pod_delete_groups(api, "heal") == 1
        assert metrics.slice_restarts.value("u1", REASON_POD_FAILED) == 1
        assert "SliceRecovery" in event_reasons(api)
        # bookkeeping persisted on the CR: one attempt, backoff armed
        state = recovery_state(api)
        assert len(state["attempts"]) == 1
        assert "backoffUntil" in state
        # disruption fully healed: transient fields cleared, latency
        # observed into the recovery histogram
        assert "disruptedAt" not in state
        assert metrics.disruption_recovery_seconds.count_value("u1") == 1

    def test_crashloop_worker_recovers(self):
        api, cluster, mgr, clock, metrics = make_env()
        create_tpu_nb(api, mgr)
        cluster.crashloop_pod("u1", "heal-2")
        mgr.run_until_idle()
        status = api.get("Notebook", "u1", "heal").body["status"]
        assert status["sliceHealth"] == "Healthy"
        assert pod_delete_groups(api, "heal") == 1
        assert metrics.slice_restarts.value("u1", REASON_CRASH_LOOP) == 1

    def test_node_deletion_recovers_on_spare_capacity(self):
        # one spare TPU node: after the preempted node vanishes the
        # restarted slice can land fully on the survivors
        api, cluster, mgr, clock, metrics = make_env(tpu_nodes=HOSTS + 1)
        create_tpu_nb(api, mgr)
        victim = api.get("Pod", "u1", "heal-2").spec["nodeName"]
        cluster.delete_node(victim)
        # the manager watches Nodes: the deletion alone re-enqueues the
        # notebook — no pod event or resync needed
        mgr.run_until_idle()
        status = api.get("Notebook", "u1", "heal").body["status"]
        assert status["sliceHealth"] == "Healthy"
        assert pod_delete_groups(api, "heal") == 1
        assert metrics.slice_restarts.value("u1", REASON_NODE_GONE) == 1
        for pod in api.list("Pod", namespace="u1"):
            assert pod.spec["nodeName"] != victim

    def test_pending_within_deadline_is_not_disruption(self):
        # no TPU nodes at all: every worker parks in Pending
        api, cluster, mgr, clock, metrics = make_env(tpu_nodes=0)
        create_tpu_nb(api, mgr)
        state = recovery_state(api)
        assert "pendingSince" in state and "attempts" not in state
        mgr.advance(100)  # well inside the 300s default deadline
        assert pod_delete_groups(api, "heal") == 0
        assert exhausted_condition(api) is None

    def test_pending_past_deadline_restarts_then_exhausts(self):
        cfg = CoreConfig(recovery_backoff_base_s=10.0,
                         recovery_backoff_max_s=300.0,
                         recovery_max_attempts=3,
                         recovery_window_s=100000.0,
                         recovery_pending_deadline_s=60.0)
        api, cluster, mgr, clock, metrics = make_env(cfg, tpu_nodes=0)
        create_tpu_nb(api, mgr)
        # ride the requeue-after schedule to the deadline and through
        # every backoff until the budget is spent
        for _ in range(12):
            mgr.advance(120)
        assert pod_delete_groups(api, "heal") == 3  # exactly the cap
        assert metrics.slice_restarts.value(
            "u1", REASON_PENDING_TIMEOUT) == 3
        cond = exhausted_condition(api)
        assert cond is not None and cond["status"] == "True"
        assert "RecoveryExhausted" in event_reasons(api)
        assert recovery_state(api)["exhausted"] is True
        # terminal: no further churn, ever
        mgr.advance(10000)
        assert pod_delete_groups(api, "heal") == 3

    def test_budget_survives_manager_failover(self):
        """Crash-safe bookkeeping: a new manager (leader failover /
        crash-restart) resumes the persisted budget — the attempt cap
        holds EXACTLY across the handoff, and the in-flight backoff
        deadline is honored, not reset."""
        cfg = CoreConfig(recovery_backoff_base_s=10.0,
                         recovery_backoff_max_s=300.0,
                         recovery_max_attempts=4,
                         recovery_window_s=100000.0)
        api, cluster, mgr_a, clock, metrics_a = make_env(cfg)
        create_tpu_nb(api, mgr_a)
        cluster.poison_statefulset("u1", "heal")  # permanently broken
        mgr_a.enqueue_all()
        mgr_a.run_until_idle()    # attempt 1 (immediate)
        mgr_a.advance(10)         # attempt 2 after base backoff
        assert len(recovery_state(api)["attempts"]) == 2
        assert pod_delete_groups(api, "heal") == 2

        # leader failover mid-recovery: fresh manager, fresh metrics,
        # fresh everything EXCEPT the CR — same cluster clock
        mgr_b = Manager(api, clock=clock)
        metrics_b = NotebookMetrics(api)
        setup_core_controllers(mgr_b, cfg, metrics_b)
        mgr_b.enqueue_all()
        mgr_b.run_until_idle()
        # B must honor A's backoff deadline: no immediate third restart
        assert pod_delete_groups(api, "heal") == 2
        mgr_b.advance(20)    # attempt 3
        mgr_b.advance(40)    # attempt 4 == cap
        mgr_b.advance(300)   # next detection -> exhausted
        assert pod_delete_groups(api, "heal") == cfg.recovery_max_attempts
        cond = exhausted_condition(api)
        assert cond is not None and cond["status"] == "True"
        mgr_b.advance(10000)  # budget NOT reset by the failover
        assert pod_delete_groups(api, "heal") == cfg.recovery_max_attempts

    def test_operator_fix_after_exhaustion_resets_budget(self):
        cfg = CoreConfig(recovery_backoff_base_s=5.0,
                         recovery_max_attempts=2,
                         recovery_window_s=100000.0)
        api, cluster, mgr, clock, metrics = make_env(cfg)
        create_tpu_nb(api, mgr)
        cluster.poison_statefulset("u1", "heal")
        mgr.enqueue_all()
        mgr.run_until_idle()
        for _ in range(4):
            mgr.advance(50)
        assert recovery_state(api)["exhausted"] is True
        assert pod_delete_groups(api, "heal") == 2

        # the operator replaces the hardware and requests a restart
        cluster.heal_statefulset("u1", "heal")
        live = api.get("Notebook", "u1", "heal")
        live.metadata.annotations[
            "notebooks.opendatahub.io/notebook-restart"] = "true"
        api.update(live)
        mgr.run_until_idle()
        status = api.get("Notebook", "u1", "heal").body["status"]
        assert status["sliceHealth"] == "Healthy"
        # exhaustion cleared, bookkeeping dropped, budget fresh
        assert exhausted_condition(api) is None
        assert recovery_state(api) is None
        assert "RecoveryRestored" in event_reasons(api)
        before = pod_delete_groups(api, "heal")
        cluster.fail_pod("u1", "heal-0")
        mgr.run_until_idle()
        assert pod_delete_groups(api, "heal") == before + 1
        assert api.get("Notebook", "u1",
                       "heal").body["status"]["sliceHealth"] == "Healthy"

    def test_transient_not_ready_never_triggers_recovery(self):
        api, cluster, mgr, clock, metrics = make_env()
        create_tpu_nb(api, mgr)
        api.clear_audit_log()
        # a worker flaps not-Ready while Running (kubelet probe blip):
        # Degraded status, but NOT a disruption — no restart
        with api.fault_exempt():
            pod = api.get("Pod", "u1", "heal-3")
            for cond in pod.body["status"]["conditions"]:
                if cond["type"] == "Ready":
                    cond["status"] = "False"
            api.update_status(pod)
        mgr.run_until_idle()
        assert api.audit_log(verb="delete", kind="Pod") == []
        status = api.get("Notebook", "u1", "heal").body["status"]
        assert "sliceRecovery" not in status

    def test_disabled_by_config(self):
        api, cluster, mgr, clock, metrics = make_env(
            CoreConfig(enable_self_healing=False))
        create_tpu_nb(api, mgr)
        cluster.fail_pod("u1", "heal-1")
        mgr.run_until_idle()
        status = api.get("Notebook", "u1", "heal").body["status"]
        assert status["sliceHealth"] == "Degraded"
        assert api.audit_log(verb="delete", kind="Pod") == []


class TestRestartAggregation:
    """Satellite regression: _restart_pods must attempt EVERY pod of the
    slice even when a delete errors mid-loop, and must not report the
    restart done (annotation cleared) until the whole slice went."""

    def test_error_mid_slice_still_attempts_all_then_retries(self):
        api, cluster, mgr, clock, metrics = make_env()
        create_tpu_nb(api, mgr)
        api.clear_audit_log()
        # first pod delete 503s; the sweep must still attempt the rest
        plan = FaultPlan([FaultRule(verbs=("delete",), kinds=("Pod",),
                                    error="unavailable", max_matches=1,
                                    name="first-delete")], clock=clock)
        api.install_fault_plan(plan)
        with api.fault_exempt():
            live = api.get("Notebook", "u1", "heal")
            live.metadata.annotations[
                "notebooks.opendatahub.io/notebook-restart"] = "true"
            api.update(live)
        mgr.run_until_idle()
        api.clear_fault_plan()
        assert plan.exhausted()
        # the faulted sweep covered the whole slice: 4 attempts, exactly
        # one of them failed — never a short-circuited partial loop
        recs = [r for r in api.audit_log(verb="delete", kind="Pod")
                if r.name.startswith("heal-")]
        first_sweep = recs[:HOSTS]
        assert {r.name for r in first_sweep} == \
            {f"heal-{i}" for i in range(HOSTS)}
        assert [r.ok for r in first_sweep].count(False) == 1
        # the retry finished the job: annotation cleared, slice healthy
        live = api.get("Notebook", "u1", "heal")
        assert "notebooks.opendatahub.io/notebook-restart" not in \
            live.metadata.annotations
        assert live.body["status"]["sliceHealth"] == "Healthy"

    def test_new_metric_families_registered(self):
        _, _, _, _, metrics = make_env()
        fams = dict(metrics.families())
        assert fams["notebook_slice_restarts_total"] == "counter"
        assert fams["notebook_disruption_recovery_seconds"] == "histogram"
        assert fams["notebook_checkpoint_snapshots_total"] == "counter"
        assert fams["notebook_checkpoint_age_seconds"] == "histogram"
        assert fams["notebook_migrations_total"] == "counter"


# -- the migrate verb ----------------------------------------------------------
def make_migrate_env(cfg=None, tpu_nodes=HOSTS):
    """make_env plus a wired session-state store: the cluster answers
    final-snapshot requests and stamps restores, the engine prefers the
    migrate verb."""
    api = ApiServer()
    cluster = FakeCluster(api)
    cluster.add_node("cpu-node", allocatable={"cpu": "64", "memory": "256Gi"})
    if tpu_nodes:
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4",
                                    tpu_nodes, 4)
    clock = FakeClock()
    mgr = Manager(api, clock=clock)
    store = InMemorySessionStore(clock=clock)
    cluster.attach_session_store(store)
    metrics = NotebookMetrics(api)
    cfg = cfg or CoreConfig(checkpoint_store_uri="mem://session-state")
    setup_core_controllers(mgr, cfg, metrics, session=store)
    return api, cluster, mgr, clock, metrics, store


def restored_stamps(api, ns="u1"):
    """(generation, digest) restore stamps per pod name — the fake
    kubelet's record of what the runtime restored at boot."""
    from kubeflow_tpu.core import constants as C

    return {
        p.name: (p.metadata.annotations.get(
            C.ANNOTATION_RESTORED_GENERATION),
            p.metadata.annotations.get(C.ANNOTATION_RESTORED_DIGEST))
        for p in api.list("Pod", namespace=ns)
    }


def session_entry(api, ns="u1", name="heal", slice_id="0"):
    status = api.get("Notebook", ns, name).body.get("status", {})
    return (status.get("sessionState") or {}).get(slice_id)


class TestMigrateVerb:
    def test_fresh_checkpoint_prefers_migrate_over_restart(self):
        api, cluster, mgr, clock, metrics, store = make_migrate_env()
        create_tpu_nb(api, mgr)
        cluster.set_session_payload("u1", "heal", b"kernel-state-A")
        (snap,) = cluster.snapshot_sessions("u1", "heal")
        cluster.fail_pod("u1", "heal-1")
        mgr.run_until_idle()
        status = api.get("Notebook", "u1", "heal").body["status"]
        assert status["sliceHealth"] == "Healthy"
        assert pod_delete_groups(api, "heal") == 1  # still slice-atomic
        # restarts counted under the migrate reason, not the disruption
        assert metrics.slice_restarts.value("u1", REASON_MIGRATE) == 1
        assert metrics.slice_restarts.value("u1", REASON_POD_FAILED) == 0
        assert metrics.migrations.value(
            MIGRATE_TRIGGER_FAILURE, MIGRATE_RESULT_MIGRATED) == 1
        assert metrics.migrations.value(
            MIGRATE_TRIGGER_FAILURE, MIGRATE_RESULT_RESTORED) == 1
        # write-ahead record reached its terminal phase
        entry = session_entry(api)
        assert entry["phase"] == "restored"
        assert entry["restoreGeneration"] == snap.generation
        # restored-state equivalence: every recreated worker restored the
        # pre-disruption snapshot, byte-for-byte (digest)
        for name, (gen, digest) in restored_stamps(api).items():
            assert gen == str(snap.generation), name
            assert digest == snap.digest, name
        assert "SliceMigration" in event_reasons(api)
        assert "MigrationComplete" in event_reasons(api)

    def test_stale_checkpoint_falls_back_to_bare_restart(self):
        cfg = CoreConfig(checkpoint_store_uri="mem://session-state",
                         checkpoint_max_age_s=300.0)
        api, cluster, mgr, clock, metrics, store = make_migrate_env(cfg)
        create_tpu_nb(api, mgr)
        cluster.snapshot_sessions("u1", "heal")
        clock.advance(3600)  # checkpoint is now ancient
        mgr.run_until_idle()
        cluster.fail_pod("u1", "heal-2")
        mgr.run_until_idle()
        status = api.get("Notebook", "u1", "heal").body["status"]
        assert status["sliceHealth"] == "Healthy"
        assert metrics.slice_restarts.value("u1", REASON_POD_FAILED) == 1
        assert metrics.slice_restarts.value("u1", REASON_MIGRATE) == 0
        assert metrics.migrations.value(
            MIGRATE_TRIGGER_FAILURE, MIGRATE_RESULT_FALLBACK) == 1
        # no restore instructions were stamped: the session started cold
        assert all(gen is None for gen, _ in restored_stamps(api).values())
        assert session_entry(api) is None

    def test_voluntary_drain_annotation_migrates_and_clears(self):
        api, cluster, mgr, clock, metrics, store = make_migrate_env()
        create_tpu_nb(api, mgr)
        cluster.set_session_payload("u1", "heal", b"drained-state")
        live = api.get("Notebook", "u1", "heal")
        live.metadata.annotations[
            "notebooks.kubeflow.org/migrate"] = "drain"
        api.update(live)
        mgr.run_until_idle()
        status = api.get("Notebook", "u1", "heal").body["status"]
        assert status["sliceHealth"] == "Healthy"
        # a healthy slice CAN flush: the store got a final snapshot and
        # the restored state is exactly that flush
        assert metrics.checkpoint_snapshots.value("u1", "final") == 1
        snap = store.latest("u1", "heal", 0)
        assert snap.trigger == "final"
        for gen, digest in restored_stamps(api).values():
            assert gen == str(snap.generation) and digest == snap.digest
        assert metrics.migrations.value(
            MIGRATE_TRIGGER_DRAIN, MIGRATE_RESULT_MIGRATED) == 1
        # request consumed; budget charged (shared with recovery)
        live = api.get("Notebook", "u1", "heal")
        assert "notebooks.kubeflow.org/migrate" not in \
            live.metadata.annotations
        assert len(recovery_state(api)["attempts"]) == 1

    def test_cordoned_node_triggers_node_drain_migration(self):
        api, cluster, mgr, clock, metrics, store = make_migrate_env(
            tpu_nodes=HOSTS + 4)
        create_tpu_nb(api, mgr)
        cluster.set_session_payload("u1", "heal", b"on-cordoned-node")
        victim = api.get("Pod", "u1", "heal-2").spec["nodeName"]
        cluster.cordon_node(victim)
        mgr.run_until_idle()
        status = api.get("Notebook", "u1", "heal").body["status"]
        assert status["sliceHealth"] == "Healthy"
        assert metrics.migrations.value(
            MIGRATE_TRIGGER_NODE_DRAIN, MIGRATE_RESULT_MIGRATED) == 1
        # the migrated slice left the cordoned node entirely
        for pod in api.list("Pod", namespace="u1"):
            assert pod.spec["nodeName"] != victim
        assert session_entry(api)["phase"] == "restored"

    def test_voluntary_without_checkpoint_is_skipped(self):
        """A healthy session is never torn down without its state in hand:
        no store wired to the cluster -> final snapshot unanswered, no
        stored checkpoint -> the voluntary request is consumed without a
        restart."""
        api, cluster, mgr, clock, metrics, store = make_migrate_env()
        cluster._session_store.set_final_snapshot_handler(None)  # unreachable
        create_tpu_nb(api, mgr)
        api.clear_audit_log()
        live = api.get("Notebook", "u1", "heal")
        live.metadata.annotations[
            "notebooks.kubeflow.org/migrate"] = "defrag"
        api.update(live)
        mgr.run_until_idle()
        assert api.audit_log(verb="delete", kind="Pod") == []
        assert metrics.migrations.value(
            "defrag", MIGRATE_RESULT_SKIPPED) == 1
        assert "MigrationSkipped" in event_reasons(api)
        live = api.get("Notebook", "u1", "heal")
        assert "notebooks.kubeflow.org/migrate" not in \
            live.metadata.annotations
        # nothing charged against the shared budget
        assert recovery_state(api) is None

    def test_migrate_and_restart_share_one_budget(self):
        """The satellite acceptance: attempts spent by the migrate verb and
        by bare restarts draw from ONE budget, and exhaustion still yields
        RecoveryExhausted.  A poisoned slice (pods always come back
        Failed) with a checkpoint that goes stale mid-recovery migrates
        first, bare-restarts after, and exhausts at exactly the cap."""
        cfg = CoreConfig(checkpoint_store_uri="mem://session-state",
                         checkpoint_max_age_s=25.0,
                         recovery_backoff_base_s=10.0,
                         recovery_backoff_max_s=40.0,
                         recovery_max_attempts=3,
                         recovery_window_s=100000.0)
        api, cluster, mgr, clock, metrics, store = make_migrate_env(cfg)
        create_tpu_nb(api, mgr)
        cluster.snapshot_sessions("u1", "heal")  # fresh at t0
        cluster.poison_statefulset("u1", "heal")
        mgr.enqueue_all()
        mgr.run_until_idle()      # attempt 1: ckpt fresh -> migrate
        assert metrics.slice_restarts.value("u1", REASON_MIGRATE) == 1
        for _ in range(8):
            mgr.advance(50)       # ckpt now stale -> bare restarts
        assert pod_delete_groups(api, "heal") == cfg.recovery_max_attempts
        assert metrics.slice_restarts.value("u1", REASON_MIGRATE) == 1
        assert metrics.slice_restarts.value(
            "u1", REASON_POD_FAILED) == cfg.recovery_max_attempts - 1
        cond = exhausted_condition(api)
        assert cond is not None and cond["status"] == "True"
        assert recovery_state(api)["exhausted"] is True
        # terminal: no further churn of either verb
        mgr.advance(10000)
        assert pod_delete_groups(api, "heal") == cfg.recovery_max_attempts


# -- the promote verb (replicated-kernel tier) ---------------------------------
def make_replicated_env(cfg=None):
    """make_migrate_env with two slice pools (2 gangs x 4 hosts) and a
    replicated notebook: one primary gang plus one follower gang kept warm
    from the checkpoint-delta stream."""
    api, cluster, mgr, clock, metrics, store = make_migrate_env(
        cfg, tpu_nodes=2 * HOSTS)
    nb = Notebook.new("rep", "u1", tpu=TPUSpec("v5e", "4x4"),
                      replication=ReplicationSpec(replicas=2))
    api.create(nb.obj)
    mgr.run_until_idle()
    return api, cluster, mgr, clock, metrics, store


def replication_record(api, ns="u1", name="rep"):
    status = api.get("Notebook", ns, name).body.get("status") or {}
    return status.get("replication") or {}


def warm_follower(cluster, store, deltas=2, lag=0):
    """Prime the delta chain and stamp the follower gang's catch-up
    freshness onto its pods, `lag` deltas behind the head."""
    cluster.set_session_payload("u1", "rep", b"kernel-A")
    cluster.snapshot_sessions("u1", "rep")
    for i in range(deltas):
        cluster.stream_session_delta("u1", "rep", b"+cell%d" % i,
                                     writer_epoch=1)
    return cluster.sync_followers("u1", "rep", lag=lag)


class TestPromoteVerb:
    def test_primary_failure_promotes_caught_up_follower(self):
        api, cluster, mgr, clock, metrics, store = make_replicated_env()
        status = api.get("Notebook", "u1", "rep").body["status"]
        assert status["sliceHealth"] == "Healthy"
        rep = replication_record(api)
        assert (rep["epoch"], rep["primary"]) == (1, 0)
        # the service fronts the primary gang's worker 0
        svc = api.get("Service", "u1", "rep")
        assert svc.spec["selector"][C.STATEFULSET_LABEL] == "rep"

        # every replica-labeled pod gets a freshness stamp (both gangs)
        assert warm_follower(cluster, store) == 2 * HOSTS
        mgr.enqueue_all()
        mgr.run_until_idle()
        head_gen, head_seq, head_digest = store.chain_head("u1", "rep", 0)
        rep = replication_record(api)
        follower = rep["followers"]["1"]
        assert follower["ready"] is True
        assert follower["slices"]["0"] == {
            "generation": head_gen, "seq": head_seq, "digest": head_digest}

        cluster.fail_pod("u1", "rep-0")
        mgr.enqueue_all()
        mgr.run_until_idle()
        rep = replication_record(api)
        assert (rep["epoch"], rep["primary"]) == (2, 1)
        promo = rep["promotion"]
        assert promo["phase"] == "promoted"
        assert (promo["from"], promo["to"]) == (0, 1)
        assert promo["reason"] == REASON_POD_FAILED
        assert store.fence_epoch("u1", "rep") == 2
        assert metrics.promotions.value("u1", PROMOTE_RESULT_PROMOTED) == 1
        assert metrics.promotions.value("u1", PROMOTE_RESULT_LOST_RACE) == 0
        assert metrics.promotion_duration_seconds.count_value("u1") == 1
        assert EVENT_PRIMARY_PROMOTED in event_reasons(api)
        # promotion replaced the primary restart: the follower gang was
        # NEVER churned (its warm state is the whole point)
        assert not [r for r in api.audit_log(verb="delete", kind="Pod")
                    if r.name.startswith("rep-r1-")]
        # the demoted zombie cannot ack a session write with its old epoch
        with pytest.raises(StaleWriterError):
            store.append_delta("u1", "rep", 0, b"+zombie", writer_epoch=1)
        assert metrics.replication_fenced_writes.value("u1") == 1
        # the demoted gang heals and rejoins as a follower; the next
        # reconcile repoints the service selector — user traffic follows
        # the flip with no pod restarts behind the service
        for _ in range(4):
            mgr.advance(30)
        svc = api.get("Service", "u1", "rep")
        assert svc.spec["selector"][C.STATEFULSET_LABEL] == "rep-r1"
        status = api.get("Notebook", "u1", "rep").body["status"]
        assert status["sliceHealth"] == "Healthy"
        rep = replication_record(api)
        assert (rep["epoch"], rep["primary"]) == (2, 1)
        assert "0" in rep["followers"]
        # the new primary's writes land at the new epoch
        store.append_delta("u1", "rep", 0, b"+post", writer_epoch=2)

    def test_lagging_follower_is_not_electable(self):
        """Election needs positive catch-up evidence: a follower trailing
        the chain head beyond REPLICATION_MAX_LAG is skipped and the
        ordinary slice-atomic restart heals the primary in place."""
        api, cluster, mgr, clock, metrics, store = make_replicated_env()
        cfg_lag = CoreConfig().replication_max_lag
        warm_follower(cluster, store, deltas=cfg_lag + 2, lag=cfg_lag + 1)
        mgr.enqueue_all()
        mgr.run_until_idle()
        cluster.fail_pod("u1", "rep-0")
        mgr.enqueue_all()
        mgr.run_until_idle()
        for _ in range(4):
            mgr.advance(30)
        rep = replication_record(api)
        assert (rep["epoch"], rep["primary"]) == (1, 0)
        assert "promotion" not in rep
        assert metrics.promotions.value(
            "u1", PROMOTE_RESULT_NO_CANDIDATE) >= 1
        assert metrics.promotions.value("u1", PROMOTE_RESULT_PROMOTED) == 0
        assert metrics.slice_restarts.value("u1", REASON_POD_FAILED) == 1
        assert api.get("Notebook", "u1", "rep") \
            .body["status"]["sliceHealth"] == "Healthy"
        # the primary was never demoted: epoch-1 writes still pass
        store.append_delta("u1", "rep", 0, b"+still-primary", writer_epoch=1)

    def test_promotion_commits_through_control_plane_partition(self):
        """Promotion under an apiserver brown-out: injected 503s on the
        Notebook status commits delay the flip but can never split it —
        the write-ahead record resumes the promotion, the epoch bumps
        exactly once, and the zombie stays fenced throughout."""
        api, cluster, mgr, clock, metrics, store = make_replicated_env()
        warm_follower(cluster, store)
        mgr.enqueue_all()
        mgr.run_until_idle()
        plan = FaultPlan([FaultRule(verbs=("update",), kinds=("Notebook",),
                                    error="unavailable", max_matches=3,
                                    name="status-brownout")], clock=clock)
        api.install_fault_plan(plan)
        with api.fault_exempt():
            cluster.fail_pod("u1", "rep-0")
        mgr.run_until_idle()
        for _ in range(6):
            mgr.advance(30)
        api.clear_fault_plan()
        assert plan.exhausted()
        for _ in range(4):
            mgr.advance(30)
        rep = replication_record(api)
        assert (rep["epoch"], rep["primary"]) == (2, 1)
        assert rep["promotion"]["phase"] == "promoted"
        assert store.fence_epoch("u1", "rep") == 2
        # retried commits never double-bump: one promotion, one epoch
        assert metrics.promotions.value("u1", PROMOTE_RESULT_PROMOTED) >= 1
        with pytest.raises(StaleWriterError):
            store.append_delta("u1", "rep", 0, b"+zombie", writer_epoch=1)
        assert api.get("Notebook", "u1", "rep") \
            .body["status"]["sliceHealth"] == "Healthy"

    def test_promotion_metric_families_registered(self):
        _, _, _, _, metrics = make_env()
        fams = dict(metrics.families())
        assert fams["notebook_promotions_total"] == "counter"
        assert fams["notebook_promotion_duration_seconds"] == "histogram"
        assert fams["notebook_replication_fenced_writes_total"] == "counter"


class TestConfigParsing:
    def test_recovery_knobs_parse_sub_second_floats(self):
        """Satellite regression: RECOVERY_* duration knobs went through
        _int, so RECOVERY_BACKOFF_BASE_S=0.5 (fast soak configs) silently
        truncated to the default."""
        cfg = CoreConfig.from_env({
            "RECOVERY_BACKOFF_BASE_S": "0.5",
            "RECOVERY_BACKOFF_MAX_S": "2.5",
            "RECOVERY_WINDOW_S": "90.5",
            "RECOVERY_PENDING_DEADLINE_S": "1.25",
            "CHECKPOINT_INTERVAL_S": "0.75",
            "CHECKPOINT_MAX_AGE_S": "1.5",
        })
        assert cfg.recovery_backoff_base_s == 0.5
        assert cfg.recovery_backoff_max_s == 2.5
        assert cfg.recovery_window_s == 90.5
        assert cfg.recovery_pending_deadline_s == 1.25
        assert cfg.checkpoint_interval_s == 0.75
        assert cfg.checkpoint_max_age_s == 1.5

    def test_checkpoint_knob_defaults_and_uri(self):
        cfg = CoreConfig.from_env({})
        assert cfg.checkpoint_store_uri == ""
        assert cfg.checkpoint_interval_s == 300.0
        assert cfg.checkpoint_max_age_s == 600.0
        cfg = CoreConfig.from_env({
            "CHECKPOINT_STORE_URI": "file:///var/ckpt",
            "CHECKPOINT_SIGNAL_ROOT": "/var/signals",
        })
        assert cfg.checkpoint_store_uri == "file:///var/ckpt"
        assert cfg.checkpoint_signal_root == "/var/signals"

    def test_garbage_floats_keep_defaults(self):
        cfg = CoreConfig.from_env({"RECOVERY_BACKOFF_BASE_S": "soon"})
        assert cfg.recovery_backoff_base_s == 10.0
