"""Istio VirtualService path under USE_ISTIO — the analog of the
reference's istio integration surface
(notebook_controller.go:558-699: generateVirtualService +
reconcileVirtualService with CopyVirtualService drift repair).

Covers: rendering (prefix match, rewrite default + annotation override,
destination host/port, gateway/host config, headers annotation incl. the
malformed-JSON tolerance), reconcile wiring (created only when
use_istio, owner reference, whole-spec drift copy), and the env surface
(USE_ISTIO / ISTIO_GATEWAY / ISTIO_HOST / CLUSTER_DOMAIN)."""

import pytest

from kubeflow_tpu.api.types import Notebook
from kubeflow_tpu.core import constants as C
from kubeflow_tpu.core.metrics import NotebookMetrics
from kubeflow_tpu.core.notebook_controller import setup_core_controllers
from kubeflow_tpu.core.workload import generate_virtual_service
from kubeflow_tpu.kube import ApiServer, FakeCluster, Manager
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.config import CoreConfig


class TestRendering:
    def _nb(self, annotations=None):
        return Notebook.new("my-nb", "user1", annotations=annotations)

    def test_shape_matches_reference(self):
        vs = generate_virtual_service(self._nb(), CoreConfig())
        assert vs.api_version == "networking.istio.io/v1alpha3"
        assert vs.kind == "VirtualService"
        # virtualServiceName(name, ns) = notebook-{ns}-{name}
        # (notebook_controller.go:555)
        assert vs.name == "notebook-user1-my-nb"
        assert vs.namespace == "user1"
        spec = vs.body["spec"]
        assert spec["hosts"] == ["*"]
        assert spec["gateways"] == ["kubeflow/kubeflow-gateway"]
        (route,) = spec["http"]
        assert route["match"] == [
            {"uri": {"prefix": "/notebook/user1/my-nb/"}}]
        # default rewrite falls back to the prefix itself
        assert route["rewrite"] == {"uri": "/notebook/user1/my-nb/"}
        (dest,) = route["route"]
        assert dest["destination"]["host"] == \
            "my-nb.user1.svc.cluster.local"
        assert dest["destination"]["port"] == {"number": 80}

    def test_config_overrides(self):
        cfg = CoreConfig(istio_gateway="ns/gw", istio_host="nb.example.com",
                         cluster_domain="corp.local")
        spec = generate_virtual_service(self._nb(), cfg).body["spec"]
        assert spec["hosts"] == ["nb.example.com"]
        assert spec["gateways"] == ["ns/gw"]
        assert spec["http"][0]["route"][0]["destination"]["host"] == \
            "my-nb.user1.svc.corp.local"

    def test_env_surface(self, monkeypatch):
        monkeypatch.setenv("USE_ISTIO", "true")
        monkeypatch.setenv("ISTIO_GATEWAY", "g/w")
        monkeypatch.setenv("ISTIO_HOST", "h.example.com")
        monkeypatch.setenv("CLUSTER_DOMAIN", "env.local")
        cfg = CoreConfig.from_env()
        assert cfg.use_istio and cfg.istio_gateway == "g/w"
        spec = generate_virtual_service(self._nb(), cfg).body["spec"]
        assert spec["hosts"] == ["h.example.com"]
        assert spec["http"][0]["route"][0]["destination"]["host"].endswith(
            "svc.env.local")

    def test_rewrite_annotation_override(self):
        nb = self._nb({C.ANNOTATION_REWRITE_URI: "/custom/path/"})
        route = generate_virtual_service(nb, CoreConfig()).body["spec"]["http"][0]
        assert route["rewrite"] == {"uri": "/custom/path/"}
        # empty/whitespace annotation falls back to the prefix
        # (reference: len check, notebook_controller.go:572-574)
        nb = self._nb({C.ANNOTATION_REWRITE_URI: "  "})
        route = generate_virtual_service(nb, CoreConfig()).body["spec"]["http"][0]
        assert route["rewrite"] == {"uri": "/notebook/user1/my-nb/"}

    def test_headers_annotation(self):
        nb = self._nb({C.ANNOTATION_HEADERS_REQUEST_SET:
                       '{"X-Forwarded-Prefix": "/notebook/user1/my-nb"}'})
        route = generate_virtual_service(nb, CoreConfig()).body["spec"]["http"][0]
        assert route["headers"] == {
            "request": {"set": {"X-Forwarded-Prefix": "/notebook/user1/my-nb"}}}

    def test_malformed_headers_annotation_tolerated(self):
        # reference decodes into an empty map on bad JSON
        # (notebook_controller.go:609-613); here the headers section is
        # simply omitted — the same no-op VirtualService semantics
        nb = self._nb({C.ANNOTATION_HEADERS_REQUEST_SET: "{not json"})
        route = generate_virtual_service(nb, CoreConfig()).body["spec"]["http"][0]
        assert "headers" not in route


@pytest.fixture()
def istio_env():
    api = ApiServer()
    cluster = FakeCluster(api)
    cluster.add_node("cpu-node", allocatable={"cpu": "64", "memory": "256Gi"})
    mgr = Manager(api, clock=FakeClock())
    setup_core_controllers(mgr, CoreConfig(use_istio=True),
                           NotebookMetrics(api))
    return api, cluster, mgr


class TestReconcile:
    def _create(self, api, mgr, name="test-nb", ns="user1", annotations=None):
        nb = Notebook.new(name, ns, annotations=annotations)
        api.create(nb.obj)
        mgr.run_until_idle()
        return nb

    def test_created_with_owner_reference(self, istio_env):
        api, _, mgr = istio_env
        self._create(api, mgr)
        vs = api.get("VirtualService", "user1", "notebook-user1-test-nb")
        (owner,) = vs.metadata.owner_references
        assert owner.kind == "Notebook" and owner.name == "test-nb"
        assert owner.controller is True

    def test_not_created_without_flag(self):
        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("n", allocatable={"cpu": "64", "memory": "256Gi"})
        mgr = Manager(api, clock=FakeClock())
        setup_core_controllers(mgr, CoreConfig(use_istio=False),
                               NotebookMetrics(api))
        api.create(Notebook.new("test-nb", "user1").obj)
        mgr.run_until_idle()
        assert api.try_get("VirtualService", "user1",
                           "notebook-user1-test-nb") is None

    def test_drift_reverted_whole_spec(self, istio_env):
        # CopyVirtualService copies the whole desired spec over the found
        # one (util.go:199-219 via reconcilehelper.copy_spec)
        api, _, mgr = istio_env
        self._create(api, mgr)
        vs = api.get("VirtualService", "user1", "notebook-user1-test-nb")
        vs.body["spec"]["gateways"] = ["intruder/gateway"]
        vs.body["spec"]["http"][0]["timeout"] = "1s"
        api.update(vs)
        mgr.run_until_idle()
        spec = api.get("VirtualService", "user1",
                       "notebook-user1-test-nb").body["spec"]
        assert spec["gateways"] == ["kubeflow/kubeflow-gateway"]
        assert spec["http"][0]["timeout"] == "300s"

    def test_annotation_change_propagates(self, istio_env):
        api, _, mgr = istio_env
        self._create(api, mgr)
        nb = api.get("Notebook", "user1", "test-nb")
        nb.metadata.annotations[C.ANNOTATION_REWRITE_URI] = "/new/"
        api.update(nb)
        mgr.run_until_idle()
        route = api.get("VirtualService", "user1",
                        "notebook-user1-test-nb").body["spec"]["http"][0]
        assert route["rewrite"] == {"uri": "/new/"}

    def test_deleted_with_notebook(self, istio_env):
        api, _, mgr = istio_env
        self._create(api, mgr)
        api.delete("Notebook", "user1", "test-nb")
        mgr.run_until_idle()
        assert api.try_get("VirtualService", "user1",
                           "notebook-user1-test-nb") is None
