"""First-difference reporting for the restart-blocking webhook path.

Port of FirstDifferenceReporter + getStructDiff
(notebook_mutating_webhook.go:601-646): compare two nested structures and
render only the FIRST difference as a one-line human-readable string — enough
for the `update-pending` annotation without dumping the whole diff.
"""

from __future__ import annotations

from typing import Any, Optional


def _fmt(value: Any) -> str:
    if value is _MISSING:
        return "<absent>"
    return repr(value)


class _Missing:
    def __repr__(self) -> str:  # pragma: no cover
        return "<absent>"


_MISSING = _Missing()


def _walk(a: Any, b: Any, path: str) -> Optional[str]:
    if a is _MISSING or b is _MISSING or type(a) is not type(b):
        if a == b:
            return None
        return f"{path or '.'}: {_fmt(a)} != {_fmt(b)}"
    if isinstance(a, dict):
        for key in sorted(set(a) | set(b), key=str):
            found = _walk(
                a.get(key, _MISSING), b.get(key, _MISSING), f"{path}.{key}"
            )
            if found:
                return found
        return None
    if isinstance(a, list):
        for i in range(max(len(a), len(b))):
            found = _walk(
                a[i] if i < len(a) else _MISSING,
                b[i] if i < len(b) else _MISSING,
                f"{path}[{i}]",
            )
            if found:
                return found
        return None
    if a != b:
        return f"{path or '.'}: {_fmt(a)} != {_fmt(b)}"
    return None


def first_difference(a: Any, b: Any) -> str:
    """One-line description of the first difference, or the reference's
    fallback string when the walk fails (getStructDiff :632-646)."""
    try:
        found = _walk(a, b, "")
        return found or ""
    except Exception:
        return "failed to compute the reason for why there is a pending restart"
