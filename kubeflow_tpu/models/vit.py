"""ViT — the BASELINE "v5e-8 single host" fine-tune workload.

Encoder-only transformer over patch embeddings with the same logical-axis
sharding vocabulary as the decoder (parallel.sharding): dp/fsdp shard the
batch and parameters, tensor parallelism shards heads/MLP.  Attention is
bidirectional (causal=False) through the same ops.attention dispatch.
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax.numpy as jnp

from ..ops.attention import attention
from .transformer import RMSNorm, _dense, _dtype


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    num_layers: int = 12
    embed_dim: int = 768
    num_heads: int = 12
    mlp_dim: int = 3072
    dtype: str = "bfloat16"


VIT_B16 = ViTConfig()
VIT_TINY = ViTConfig(
    image_size=32, patch_size=8, num_classes=10, num_layers=2,
    embed_dim=64, num_heads=4, mlp_dim=128, dtype="float32",
)


def vit_flops_per_image(cfg: ViTConfig) -> float:
    """Training (fwd+bwd) matmul FLOPs per image — the same 6x-activated-
    params convention as the decoder's MFU accounting (configs.py
    flops_per_token), with the BIDIRECTIONAL attention term 12*L*S*D (no
    causal halving)."""
    tokens = (cfg.image_size // cfg.patch_size) ** 2
    d = cfg.embed_dim
    per_layer = 4 * d * d + 2 * d * cfg.mlp_dim
    matmul_params = (cfg.num_layers * per_layer
                     + cfg.patch_size * cfg.patch_size * 3 * d)
    attn = 12 * cfg.num_layers * tokens * d
    # The classifier head runs ONCE per image (after global average pooling,
    # ViT.__call__ below) — it must not be multiplied by the token count.
    head = 6.0 * d * cfg.num_classes
    return (6.0 * matmul_params + attn) * tokens + head


class ViTBlock(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dtype = _dtype(cfg.dtype)
        head_dim = cfg.embed_dim // cfg.num_heads
        h = RMSNorm(dtype=dtype, name="attn_norm")(x)
        q = _dense((cfg.num_heads, head_dim), ("embed", "heads", "kv"), "q", dtype)(h)
        k = _dense((cfg.num_heads, head_dim), ("embed", "heads", "kv"), "k", dtype)(h)
        v = _dense((cfg.num_heads, head_dim), ("embed", "heads", "kv"), "v", dtype)(h)
        out = attention(q, k, v, causal=False)
        x = x + _dense(
            cfg.embed_dim, ("heads", "kv", "embed"), "out", dtype,
            contract_axes=(-2, -1),
        )(out)
        h = RMSNorm(dtype=dtype, name="mlp_norm")(x)
        h = _dense(cfg.mlp_dim, ("embed", "mlp"), "up", dtype)(h)
        h = nn.gelu(h)
        return x + _dense(cfg.embed_dim, ("mlp", "embed"), "down", dtype)(h)


class ViT(nn.Module):
    """images [B, H, W, C] -> logits [B, num_classes]."""

    cfg: ViTConfig

    @nn.compact
    def __call__(self, images):
        cfg = self.cfg
        dtype = _dtype(cfg.dtype)
        x = nn.Conv(
            cfg.embed_dim,
            kernel_size=(cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            dtype=dtype,
            name="patch_embed",
        )(images)
        x = x.reshape(x.shape[0], -1, cfg.embed_dim)  # [B, tokens, D]
        num_tokens = x.shape[1]
        pos = self.param(
            "pos_embed",
            nn.with_logical_partitioning(
                nn.initializers.normal(0.02), (None, "seq", "embed")
            ),
            (1, num_tokens, cfg.embed_dim),
            jnp.float32,
        )
        x = (x + pos.astype(dtype)).astype(dtype)
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        for i in range(cfg.num_layers):
            x = ViTBlock(cfg, name=f"block_{i}")(x)
        x = RMSNorm(dtype=dtype, name="final_norm")(x)
        x = jnp.mean(x, axis=1)  # global average pool
        return nn.Dense(
            cfg.num_classes, dtype=jnp.float32, name="head"
        )(x.astype(jnp.float32))
