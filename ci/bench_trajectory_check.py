"""Bench-trajectory gate: the headline MFU may never silently regress.

The repo's bench history lives in BENCH_r*.json — one record per round,
written by the driver as {"n": <round>, "rc": <exit>, "parsed": <the
bench.py JSON line, or null when the run crashed before printing one>}.
The roofline chase stalled once already because round 5 crashed on an
unavailable TPU backend and NOTHING noticed until a human read the file:
rc 1, parsed null, headline target unmeasured for two PRs.  This gate
makes that class of silence a CI failure:

  - the newest MEASURED run of the headline metric (train_mfu_v5e) must
    not regress sustained MFU more than --max-regression (default 10%)
    below the best run so far; the comparison is like-for-like: records
    measured on the CPU-smoke fallback (detail.backend == "cpu", the PR 5
    path) prove the bench pipeline is alive end-to-end but their MFU is
    against the v5e peak and so is ~0 by construction — they are reported
    and satisfy "newest run is measured", but only accelerator-measured
    runs gate the floor;
  - the newest record must not be a silent skip: a {"skipped": true}
    result without a "reason" field fails (bench.py emits the reason on
    every fallback path — its absence means an unknown writer);
  - unparseable records (parsed null — a crash predating the bench
    fallback, like r05) are surfaced as warnings: they carry no
    measurement, so they cannot gate, but the newest one being a crash
    is printed loudly so the next bench round re-measures.

Pure stdlib; wired into ci/run_tests.sh.  Exit 0 = trajectory healthy.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re

HEADLINE_METRIC = "train_mfu_v5e"


def load_records(paths: list[str]) -> list[dict]:
    """Normalize each BENCH file to {"n", "rc", "result"} where result is
    the bench.py JSON object or None.  Accepts both the driver envelope
    ({"n":..,"parsed":..}) and a bare bench.py line (local runs)."""
    records = []
    for path in sorted(paths):
        with open(path) as f:
            raw = json.load(f)
        if "parsed" in raw or "rc" in raw:
            n = raw.get("n")
            if n is None:
                m = re.search(r"r(\d+)", os.path.basename(path))
                n = int(m.group(1)) if m else len(records) + 1
            records.append({"path": path, "n": int(n),
                            "rc": raw.get("rc", 0),
                            "result": raw.get("parsed")})
        else:
            m = re.search(r"r(\d+)", os.path.basename(path))
            records.append({"path": path,
                            "n": int(m.group(1)) if m else len(records) + 1,
                            "rc": 0, "result": raw})
    records.sort(key=lambda r: r["n"])
    return records


def check(records: list[dict], max_regression: float = 0.10,
          metric: str = HEADLINE_METRIC) -> tuple[bool, list[str]]:
    """Returns (ok, messages).  Gating rules in the module docstring."""
    msgs: list[str] = []
    if not records:
        return True, ["no bench records found — nothing to gate"]
    measured = []
    smoke = []
    for rec in records:
        res = rec["result"]
        if res is None:
            msgs.append(
                f"WARN r{rec['n']:02d}: no parseable bench result "
                f"(rc {rec['rc']}) — crashed before the JSON line; "
                "carries no measurement")
            continue
        if res.get("skipped"):
            if not res.get("reason"):
                if rec is records[-1]:
                    msgs.append(
                        f"FAIL r{rec['n']:02d}: skipped without a "
                        "'reason' field — silent skips are exactly the "
                        "regression this gate exists to catch")
                    return False, msgs
                msgs.append(f"WARN r{rec['n']:02d}: silent skip "
                            "(no reason) in history")
            else:
                msgs.append(f"note r{rec['n']:02d}: skipped "
                            f"({res['reason'][:80]})")
            continue
        if res.get("metric") != metric:
            continue
        if (res.get("detail") or {}).get("backend") == "cpu":
            smoke.append((rec["n"], float(res["value"]), res))
            continue
        measured.append((rec["n"], float(res["value"]), res))
    for n, v, _ in smoke:
        msgs.append(f"note r{n:02d}: cpu-smoke measurement ({v:.4f}) — "
                    "bench fallback path alive; excluded from the "
                    "accelerator floor")
    if not measured:
        msgs.append(f"WARN: no accelerator-measured {metric} runs in "
                    "history — gate passes vacuously, but the target is "
                    "unmeasured")
        return True, msgs
    best_n, best = max(((n, v) for n, v, _ in measured),
                       key=lambda t: t[1])
    newest_n, newest, newest_res = measured[-1]
    floor = best * (1.0 - max_regression)
    msgs.append(
        f"trajectory: {len(measured)} measured runs, best {best:.4f} "
        f"(r{best_n:02d}), newest {newest:.4f} (r{newest_n:02d}), "
        f"floor {floor:.4f}")
    if records[-1]["result"] is None:
        msgs.append(
            f"WARN: newest record r{records[-1]['n']:02d} is a crash — "
            f"gating on the newest measured run r{newest_n:02d} instead; "
            "re-measure the headline next bench round")
    for key in ("roofline_fraction", "bound"):
        if key in newest_res:
            msgs.append(f"  newest {key}: {newest_res[key]}")
    if newest < floor:
        msgs.append(
            f"FAIL: newest measured MFU {newest:.4f} regresses more than "
            f"{max_regression:.0%} below the best-so-far {best:.4f}")
        return False, msgs
    return True, msgs


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="gate CI on the BENCH_r*.json MFU trajectory")
    parser.add_argument("--glob", default="BENCH_r*.json",
                        help="bench-history files (default %(default)s, "
                             "relative to --root)")
    parser.add_argument("--root",
                        default=os.path.dirname(os.path.dirname(
                            os.path.abspath(__file__))),
                        help="repo root holding the bench history")
    parser.add_argument("--max-regression", type=float, default=0.10,
                        help="allowed fraction below best-so-far "
                             "(default %(default)s)")
    parser.add_argument("--metric", default=HEADLINE_METRIC)
    args = parser.parse_args(argv)

    paths = glob.glob(os.path.join(args.root, args.glob))
    records = load_records(paths)
    ok, msgs = check(records, max_regression=args.max_regression,
                     metric=args.metric)
    for m in msgs:
        print(f"bench-trajectory: {m}")
    print(f"bench-trajectory: {'OK' if ok else 'REGRESSED'} "
          f"({len(records)} records)")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
