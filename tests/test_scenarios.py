"""The reference's envtest scenario catalog, ported.

Scenario families pinned by the reference's ~2k-line integration spec and
Makefile that round 1 did not cover (VERDICT #5):
- recreate-on-delete + drift-restore for EVERY owned object kind
  (odh notebook_controller_test.go:152,658,955)
- the SET_PIPELINE_RBAC=false/true double suite run (odh Makefile:112-117)
- long-name notebooks through the routing plane (:556 — 48-char name)
- the full kube-rbac-proxy object set end to end (:995-1230)
"""

from __future__ import annotations

import pytest

from kubeflow_tpu.api.types import Notebook
from kubeflow_tpu.core.notebook_controller import setup_core_controllers
from kubeflow_tpu.kube import ApiServer, FakeCluster, Manager
from kubeflow_tpu.odh import constants as C
from kubeflow_tpu.odh.controller import setup_odh_controllers
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.config import CoreConfig, OdhConfig

CENTRAL_NS = "opendatahub"


def build_env(odh_cfg: OdhConfig | None = None):
    api = ApiServer()
    cluster = FakeCluster(api)
    cluster.add_node("cpu-node", allocatable={"cpu": "64", "memory": "256Gi"})
    mgr = Manager(api, clock=FakeClock())
    cfg = odh_cfg or OdhConfig(controller_namespace=CENTRAL_NS)
    setup_core_controllers(mgr, CoreConfig())
    setup_odh_controllers(mgr, cfg)
    return api, cluster, mgr, cfg


@pytest.fixture()
def env():
    return build_env()


def create_nb(api, mgr, name="wb", ns="user1", annotations=None, labels=None):
    nb = Notebook.new(name, ns, annotations=annotations)
    if labels:
        nb.obj.metadata.labels.update(labels)
    api.create(nb.obj)
    mgr.run_until_idle()
    return nb


# -- recreate-on-delete for every owned kind ----------------------------------

# (kind, namespace-template, name-template) for each object the controllers
# own for a plain notebook; {ns}/{name} are the notebook's coordinates
OWNED_OBJECTS = [
    ("StatefulSet", "{ns}", "{name}"),
    ("Service", "{ns}", "{name}"),
    ("ConfigMap", "{ns}", C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP),
    ("NetworkPolicy", "{ns}", "{name}-ctrl-np"),
    ("NetworkPolicy", "{ns}",
     "{name}" + C.KUBE_RBAC_PROXY_NETWORK_POLICY_SUFFIX),
    ("HTTPRoute", CENTRAL_NS, "nb-{ns}-{name}"),
    ("ReferenceGrant", "{ns}", C.REFERENCEGRANT_NAME),
]


class TestRecreateOnDelete:
    """Level-triggered recovery: every owned object comes back after a manual
    delete (reference asserts this per kind, e.g. :152 HTTPRoute, :658
    second-notebook HTTPRoute, :955 NetworkPolicy)."""

    @pytest.fixture()
    def populated(self, env):
        api, _, mgr, _ = env
        # CA bundle source (must hold a structurally valid PEM cert — the
        # builder PEM-validates, ca_bundle.valid_pem_certificate) in the
        # NOTEBOOK namespace, where the reference reads it
        from kubeflow_tpu.kube import KubeObject, ObjectMeta
        from kubeflow_tpu.kube.certs import mint_serving_cert

        api.create(KubeObject(
            "v1", "ConfigMap",
            ObjectMeta(name=C.ODH_TRUSTED_CA_BUNDLE_CONFIGMAP,
                       namespace="user1"),
            body={"data": {
                C.TRUSTED_CA_BUNDLE_FILE:
                    mint_serving_cert().ca_cert_pem.decode()}}))
        create_nb(api, mgr)
        return api, mgr

    @pytest.mark.parametrize("kind,ns_tpl,name_tpl", OWNED_OBJECTS,
                             ids=[f"{k}:{n}" for k, _, n in OWNED_OBJECTS])
    def test_object_recreated(self, populated, kind, ns_tpl, name_tpl):
        api, mgr = populated
        ns = ns_tpl.format(ns="user1", name="wb")
        name = name_tpl.format(ns="user1", name="wb")
        assert api.try_get(kind, ns, name) is not None, \
            f"{kind} {ns}/{name} was never created"
        api.delete(kind, ns, name)
        mgr.run_until_idle()
        assert api.try_get(kind, ns, name) is not None, \
            f"{kind} {ns}/{name} not recreated after delete"

    def test_statefulset_drift_restored(self, populated):
        api, mgr = populated
        sts = api.get("StatefulSet", "user1", "wb")
        sts.spec["replicas"] = 7
        api.update(sts)
        mgr.run_until_idle()
        assert api.get("StatefulSet", "user1", "wb").spec["replicas"] == 1

    def test_all_owned_objects_garbage_collected_on_notebook_delete(
            self, populated):
        api, mgr = populated
        api.delete("Notebook", "user1", "wb")
        mgr.run_until_idle()
        assert api.try_get("Notebook", "user1", "wb") is None
        for kind, ns_tpl, name_tpl in OWNED_OBJECTS:
            if name_tpl == C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP:
                continue  # namespace-shared, not per-notebook
            ns = ns_tpl.format(ns="user1", name="wb")
            name = name_tpl.format(ns="user1", name="wb")
            assert api.try_get(kind, ns, name) is None, \
                f"{kind} {ns}/{name} leaked after notebook deletion"


# -- SET_PIPELINE_RBAC both modes (odh Makefile:112-117) ----------------------


class TestPipelineRbacBothModes:
    def _run(self, enabled: bool):
        api, _, mgr, _ = build_env(OdhConfig(
            controller_namespace=CENTRAL_NS, set_pipeline_rbac=enabled))
        if enabled:
            # the Role the binding targets must exist (checkRoleExists,
            # notebook_rbac.go:61-86)
            from kubeflow_tpu.kube import KubeObject, ObjectMeta

            api.create(KubeObject(
                "rbac.authorization.k8s.io/v1", "Role",
                ObjectMeta(name=C.PIPELINE_ROLE_NAME, namespace="user1"),
                body={"rules": []}))
        create_nb(api, mgr)
        return api

    def test_rolebinding_created_when_enabled(self):
        api = self._run(True)
        rb = api.try_get("RoleBinding", "user1", "elyra-pipelines-wb")
        assert rb is not None
        assert rb.body["roleRef"]["name"] == C.PIPELINE_ROLE_NAME
        assert rb.body["subjects"][0]["name"] == "wb"

    def test_no_rolebinding_when_disabled(self):
        api = self._run(False)
        assert api.try_get("RoleBinding", "user1", "elyra-pipelines-wb") is None


# -- long-name notebooks through the routing plane (:556) ---------------------


class TestLongNameRouting:
    NAME_48 = "test-notebook-with-a-very-long-name-thats-48char"

    def test_48char_name_routes_end_to_end(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr, name=self.NAME_48)
        route_name = f"nb-user1-{self.NAME_48}"
        if len(route_name) <= 63:
            route = api.get("HTTPRoute", CENTRAL_NS, route_name)
        else:
            routes = api.list("HTTPRoute", CENTRAL_NS,
                              {"notebook-name": self.NAME_48})
            assert len(routes) == 1
            route = routes[0]
        rule = route.spec["rules"][0]
        assert rule["matches"][0]["path"]["value"] == \
            f"/notebook/user1/{self.NAME_48}"
        assert rule["backendRefs"][0]["name"] == self.NAME_48
        grant = api.get("ReferenceGrant", "user1", C.REFERENCEGRANT_NAME)
        assert grant.spec["from"][0]["namespace"] == CENTRAL_NS

    def test_over_63_char_route_uses_generate_name_and_cleans_up(self, env):
        api, _, mgr, _ = env
        name = "n" * 60  # route prefix nb-user1- pushes it past 63
        create_nb(api, mgr, name=name)
        routes = api.list("HTTPRoute", CENTRAL_NS, {"notebook-name": name})
        assert len(routes) == 1 and len(routes[0].name) <= 63
        api.delete("Notebook", "user1", name)
        mgr.run_until_idle()
        assert api.list("HTTPRoute", CENTRAL_NS, {"notebook-name": name}) == []


# -- kube-rbac-proxy full object set (:995-1230) ------------------------------


class TestKubeRbacProxyObjectSet:
    @pytest.fixture()
    def auth_env(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr, name="auth-nb",
                  annotations={C.ANNOTATION_INJECT_AUTH: "true"})
        return api, mgr

    def test_sidecar_injected(self, auth_env):
        api, _ = auth_env
        nb = api.get("Notebook", "user1", "auth-nb")
        containers = nb.spec["template"]["spec"]["containers"]
        sidecar = next(c for c in containers if c["name"] == "kube-rbac-proxy")
        assert any(p["containerPort"] == C.KUBE_RBAC_PROXY_PORT
                   for p in sidecar["ports"])

    def test_dedicated_service_account(self, auth_env):
        api, _ = auth_env
        sa = api.get("ServiceAccount", "user1", "auth-nb")
        assert sa.metadata.owner_references[0].name == "auth-nb"

    def test_proxy_service_with_serving_cert(self, auth_env):
        api, _ = auth_env
        svc = api.get("Service", "user1",
                      "auth-nb" + C.KUBE_RBAC_PROXY_SERVICE_SUFFIX)
        assert svc.annotations[C.SERVING_CERT_ANNOTATION] == \
            "auth-nb" + C.KUBE_RBAC_PROXY_TLS_SECRET_SUFFIX
        assert svc.spec["ports"][0]["port"] == C.KUBE_RBAC_PROXY_PORT

    def test_sar_configmap_scoped_to_notebook(self, auth_env):
        api, _ = auth_env
        cm = api.get("ConfigMap", "user1",
                     "auth-nb" + C.KUBE_RBAC_PROXY_CONFIG_SUFFIX)
        cfg = cm.body["data"][C.KUBE_RBAC_PROXY_CONFIG_FILE]
        assert "resource: notebooks" in cfg
        assert "name: auth-nb" in cfg

    def test_cluster_role_binding_to_auth_delegator(self, auth_env):
        api, _ = auth_env
        crbs = [o for o in api.list("ClusterRoleBinding")
                if "auth-nb" in o.name]
        assert len(crbs) == 1
        assert crbs[0].body["roleRef"]["name"] == "system:auth-delegator"

    def test_route_targets_proxy_port(self, auth_env):
        api, _ = auth_env
        route = api.get("HTTPRoute", CENTRAL_NS, "nb-user1-auth-nb")
        backend = route.spec["rules"][0]["backendRefs"][0]
        assert backend["port"] == C.KUBE_RBAC_PROXY_PORT
        assert backend["name"] == "auth-nb" + C.KUBE_RBAC_PROXY_SERVICE_SUFFIX

    def test_route_modification_restored(self, auth_env):
        api, mgr = auth_env
        route = api.get("HTTPRoute", CENTRAL_NS, "nb-user1-auth-nb")
        route.spec["rules"][0]["backendRefs"][0]["name"] = "hacked"
        api.update(route)
        mgr.run_until_idle()
        route = api.get("HTTPRoute", CENTRAL_NS, "nb-user1-auth-nb")
        assert route.spec["rules"][0]["backendRefs"][0]["name"] == \
            "auth-nb" + C.KUBE_RBAC_PROXY_SERVICE_SUFFIX

    def test_proxy_objects_recreated_after_delete(self, auth_env):
        api, mgr = auth_env
        for kind, name in [
            ("Service", "auth-nb" + C.KUBE_RBAC_PROXY_SERVICE_SUFFIX),
            ("ConfigMap", "auth-nb" + C.KUBE_RBAC_PROXY_CONFIG_SUFFIX),
        ]:
            api.delete(kind, "user1", name)
            mgr.run_until_idle()
            assert api.try_get(kind, "user1", name) is not None, \
                f"{kind} {name} not recreated"

    def test_crb_cleaned_up_on_notebook_delete(self, auth_env):
        api, mgr = auth_env
        api.delete("Notebook", "user1", "auth-nb")
        mgr.run_until_idle()
        assert [o for o in api.list("ClusterRoleBinding")
                if "auth-nb" in o.name] == []

    def test_lock_removed_after_auth_objects_ready(self, auth_env):
        api, _ = auth_env
        nb = api.get("Notebook", "user1", "auth-nb")
        assert C.STOP_ANNOTATION not in nb.annotations, \
            "reconciliation lock must be removed once objects are ready"
        sts = api.get("StatefulSet", "user1", "auth-nb")
        assert sts.spec["replicas"] == 1

    def test_auth_mode_switch_replaces_route(self, auth_env):
        """Turning inject-auth off must swap the proxy route for the plain
        one (EnsureConflictingHTTPRouteAbsent, notebook_route.go:268-325)."""
        api, mgr = auth_env
        api.merge_patch("Notebook", "user1", "auth-nb", {
            "metadata": {"annotations": {C.ANNOTATION_INJECT_AUTH: "false"}}})
        mgr.run_until_idle()
        route = api.get("HTTPRoute", CENTRAL_NS, "nb-user1-auth-nb")
        backend = route.spec["rules"][0]["backendRefs"][0]
        assert backend["name"] == "auth-nb"
        assert backend["port"] == 8888
