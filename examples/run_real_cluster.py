"""Demo: the shipped manager CLI reconciling a cluster over real sockets.

Stands up a wire-protocol apiserver (kube/wire.py) + fake data plane in this
process — the "cluster" — then launches `python -m kubeflow_tpu.main
--kubeconfig ...` as a SEPARATE process, which connects over HTTP, acquires
the leader Lease, starts informers, and reconciles a TPU notebook to
Healthy.  The same CLI pointed at a real cluster's kubeconfig does the same
against real Kubernetes.

    python examples/run_real_cluster.py
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from kubeflow_tpu.api.types import Notebook, TPUSpec  # noqa: E402
from kubeflow_tpu.kube import FakeCluster  # noqa: E402
from kubeflow_tpu.kube.store import ApiServer  # noqa: E402
from kubeflow_tpu.kube.wire import KubeApiWireServer  # noqa: E402


def main() -> int:
    api = ApiServer()
    cluster = FakeCluster(api)
    cluster.add_node("cpu-node", allocatable={"cpu": "64", "memory": "256Gi"})
    tpu = TPUSpec("v5e", "4x4")
    shape = tpu.validate()
    cluster.add_tpu_slice_nodes(shape.accelerator.gke_label, shape.topology,
                                shape.num_hosts, shape.chips_per_host)
    srv = KubeApiWireServer(api, token="demo-token").start()
    print(f"wire apiserver: {srv.url}")

    with tempfile.NamedTemporaryFile("w", suffix=".yaml", delete=False) as f:
        json.dump({
            "apiVersion": "v1", "kind": "Config", "current-context": "demo",
            "contexts": [{"name": "demo",
                          "context": {"cluster": "demo", "user": "demo"}}],
            "clusters": [{"name": "demo", "cluster": {"server": srv.url}}],
            "users": [{"name": "demo", "user": {"token": "demo-token"}}],
        }, f)
        kubeconfig = f.name

    env = dict(os.environ, PYTHONPATH=REPO)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "kubeflow_tpu.main",
         "--kubeconfig", kubeconfig, "--enable-leader-election",
         "--leader-election-namespace", "default",
         "--webhook-port", "-1", "--metrics-addr", "18080",
         "--run-seconds", "120"],
        env=env)
    try:
        deadline = time.time() + 20
        while time.time() < deadline:
            if api.try_get("Lease", "default",
                           "kubeflow-tpu-notebook-controller"):
                break
            time.sleep(0.1)
        lease = api.get("Lease", "default", "kubeflow-tpu-notebook-controller")
        print("leader:", lease.body["spec"]["holderIdentity"])

        api.create(Notebook.new("demo-tpu", "default",
                                tpu=TPUSpec("v5e", "4x4")).obj)
        deadline = time.time() + 30
        nb = None
        while time.time() < deadline:
            nb = api.try_get("Notebook", "default", "demo-tpu")
            if nb and nb.body.get("status", {}).get("sliceHealth") == "Healthy":
                break
            time.sleep(0.2)
        status = (nb.body.get("status", {}) if nb else {})
        print(json.dumps({k: status.get(k) for k in
                          ("sliceHealth", "readyReplicas")}, indent=2))
        ok = status.get("sliceHealth") == "Healthy"
        print("RESULT:", "OK" if ok else "FAILED")
        return 0 if ok else 1
    finally:
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        srv.stop()
        os.unlink(kubeconfig)


if __name__ == "__main__":
    raise SystemExit(main())
