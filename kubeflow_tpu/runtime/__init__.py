"""In-notebook runtime: distributed bootstrap, checkpoint/cull hooks,
performance metrics, and the data-plane telemetry agent.  Ships inside
the TPU workbench image; everything the controller plane arranges (env
injection, headless DNS, cull signals) is consumed here.

Exports are lazy (PEP 562, same pattern as ops/__init__): the control
plane and the fast test lane import `runtime.telemetry` /
`runtime.roofline` / `runtime.metrics` / `runtime.checkpoint` without
executing the sibling imports, and `from kubeflow_tpu.runtime import
StepTimer` resolves exactly as before."""

import importlib

_LAZY = {
    "CheckpointManager": ".checkpoint",
    "CullSignalWatcher": ".checkpoint",
    "checkpoint_on_cull": ".checkpoint",
    "WorkerIdentity": ".init",
    "parse_worker_env": ".init",
    "tpu_init": ".init",
    "StepTimer": ".metrics",
    "hbm_usage_bytes": ".metrics",
    "TelemetryAgent": ".telemetry",
}

__all__ = [
    "CheckpointManager",
    "CullSignalWatcher",
    "StepTimer",
    "TelemetryAgent",
    "WorkerIdentity",
    "checkpoint_on_cull",
    "hbm_usage_bytes",
    "parse_worker_env",
    "tpu_init",
]


def __getattr__(name):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    mod = importlib.import_module(target, __name__)
    value = getattr(mod, name)
    globals()[name] = value  # cache: resolve each export once
    return value
