"""Routing-plane tests: HTTPRoute, ReferenceGrant, NetworkPolicies.

Analog of the reference envtest specs
(odh notebook_controller_test.go:52-330 HTTPRoute/ReferenceGrant lifecycle,
:827 NetworkPolicies) against the in-memory control plane.
"""

import pytest

from kubeflow_tpu.api.types import Notebook, TPUSpec
from kubeflow_tpu.core.notebook_controller import setup_core_controllers
from kubeflow_tpu.kube import ApiServer, FakeCluster, Manager
from kubeflow_tpu.odh import constants as C
from kubeflow_tpu.odh.controller import setup_odh_controllers
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.config import CoreConfig, OdhConfig

CENTRAL_NS = "opendatahub"


@pytest.fixture()
def env():
    api = ApiServer()
    cluster = FakeCluster(api)
    cluster.add_node("cpu-node", allocatable={"cpu": "64", "memory": "256Gi"})
    mgr = Manager(api, clock=FakeClock())
    cfg = OdhConfig(controller_namespace=CENTRAL_NS)
    setup_core_controllers(mgr, CoreConfig())
    setup_odh_controllers(mgr, cfg)
    return api, cluster, mgr, cfg


def create_nb(api, mgr, name="wb", ns="user1", annotations=None, tpu=None):
    nb = Notebook.new(name, ns, tpu=tpu, annotations=annotations)
    api.create(nb.obj)
    mgr.run_until_idle()
    return nb


class TestHTTPRoute:
    def test_route_created_in_central_namespace(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr)
        route = api.get("HTTPRoute", CENTRAL_NS, "nb-user1-wb")
        assert route.metadata.labels == {
            "notebook-name": "wb",
            "notebook-namespace": "user1",
        }
        spec = route.spec
        assert spec["parentRefs"] == [
            {"name": "data-science-gateway", "namespace": "openshift-ingress"}
        ]
        rule = spec["rules"][0]
        assert rule["matches"][0]["path"]["value"] == "/notebook/user1/wb"
        assert rule["backendRefs"][0] == {
            "name": "wb", "namespace": "user1", "port": 8888,
        }

    def test_route_recreated_after_manual_delete(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr)
        api.delete("HTTPRoute", CENTRAL_NS, "nb-user1-wb")
        mgr.run_until_idle()
        assert api.try_get("HTTPRoute", CENTRAL_NS, "nb-user1-wb") is not None

    def test_route_drift_reconciled(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr)
        route = api.get("HTTPRoute", CENTRAL_NS, "nb-user1-wb")
        route.spec["rules"][0]["matches"][0]["path"]["value"] = "/hacked"
        api.update(route)
        mgr.run_until_idle()
        route = api.get("HTTPRoute", CENTRAL_NS, "nb-user1-wb")
        assert route.spec["rules"][0]["matches"][0]["path"]["value"] == "/notebook/user1/wb"

    def test_long_name_uses_generate_name(self, env):
        api, _, mgr, _ = env
        long_name = "a" * 60
        create_nb(api, mgr, name=long_name)
        routes = api.list(
            "HTTPRoute", namespace=CENTRAL_NS,
            label_selector={"notebook-name": long_name},
        )
        assert len(routes) == 1
        assert len(routes[0].name) <= 63 + 6
        assert routes[0].name.startswith("nb-user1-" [:13])

    def test_route_deleted_with_notebook(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr)
        api.delete("Notebook", "user1", "wb")
        mgr.run_until_idle()
        assert api.try_get("Notebook", "user1", "wb") is None
        assert api.try_get("HTTPRoute", CENTRAL_NS, "nb-user1-wb") is None

    def test_auth_mode_switches_route_backend(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr)
        nb = api.get("Notebook", "user1", "wb")
        nb.metadata.annotations[C.ANNOTATION_INJECT_AUTH] = "true"
        api.update(nb)
        mgr.run_until_idle()
        routes = api.list(
            "HTTPRoute", namespace=CENTRAL_NS,
            label_selector={"notebook-name": "wb"},
        )
        assert len(routes) == 1
        backend = routes[0].spec["rules"][0]["backendRefs"][0]
        assert backend["name"] == "wb-kube-rbac-proxy"
        assert backend["port"] == 8443
        # flip back to non-auth
        nb = api.get("Notebook", "user1", "wb")
        del nb.metadata.annotations[C.ANNOTATION_INJECT_AUTH]
        api.update(nb)
        mgr.run_until_idle()
        routes = api.list(
            "HTTPRoute", namespace=CENTRAL_NS,
            label_selector={"notebook-name": "wb"},
        )
        assert len(routes) == 1
        backend = routes[0].spec["rules"][0]["backendRefs"][0]
        assert backend["port"] == 8888


class TestReferenceGrant:
    def test_grant_created_and_shared(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr, name="wb1")
        grant = api.get("ReferenceGrant", "user1", C.REFERENCEGRANT_NAME)
        assert grant.spec["from"][0]["namespace"] == CENTRAL_NS
        assert grant.spec["to"][0]["kind"] == "Service"
        rv = grant.metadata.resource_version
        create_nb(api, mgr, name="wb2")
        grant = api.get("ReferenceGrant", "user1", C.REFERENCEGRANT_NAME)
        assert grant.metadata.resource_version == rv  # untouched, shared

    def test_grant_survives_first_deletion_goes_with_last(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr, name="wb1")
        create_nb(api, mgr, name="wb2")
        api.delete("Notebook", "user1", "wb1")
        mgr.run_until_idle()
        assert api.try_get("ReferenceGrant", "user1", C.REFERENCEGRANT_NAME) is not None
        api.delete("Notebook", "user1", "wb2")
        mgr.run_until_idle()
        assert api.try_get("ReferenceGrant", "user1", C.REFERENCEGRANT_NAME) is None


class TestOAuthClientCleanup:
    """Legacy RHOAI 2.x OAuthClient removal on notebook deletion
    (notebook_oauth.go:67-96)."""

    def test_matching_client_deleted_with_notebook(self, env):
        from kubeflow_tpu.kube import KubeObject, ObjectMeta

        api, _, mgr, _ = env
        api.create(KubeObject(
            api_version="oauth.openshift.io/v1", kind="OAuthClient",
            metadata=ObjectMeta(name="wb-user1-oauth-client"),
            body={"grantMethod": "auto"}))
        # a DIFFERENT notebook's client must survive
        api.create(KubeObject(
            api_version="oauth.openshift.io/v1", kind="OAuthClient",
            metadata=ObjectMeta(name="other-user1-oauth-client"),
            body={"grantMethod": "auto"}))
        create_nb(api, mgr)
        api.delete("Notebook", "user1", "wb")
        mgr.run_until_idle()
        assert api.try_get("Notebook", "user1", "wb") is None
        assert api.try_get("OAuthClient", "", "wb-user1-oauth-client") \
            is None, "legacy client cleaned by the deletion finalizer"
        assert api.try_get("OAuthClient", "", "other-user1-oauth-client") \
            is not None

    def test_deletion_without_client_succeeds(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr)
        api.delete("Notebook", "user1", "wb")
        mgr.run_until_idle()
        assert api.try_get("Notebook", "user1", "wb") is None


class TestNetworkPolicies:
    def test_notebook_and_proxy_policies(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr)
        ctrl_np = api.get("NetworkPolicy", "user1", "wb-ctrl-np")
        ingress = ctrl_np.spec["ingress"][0]
        assert ingress["ports"] == [{"protocol": "TCP", "port": 8888}]
        assert ingress["from"][0]["namespaceSelector"]["matchLabels"] == {
            "kubernetes.io/metadata.name": CENTRAL_NS
        }
        proxy_np = api.get("NetworkPolicy", "user1", "wb-kube-rbac-proxy-np")
        assert proxy_np.spec["ingress"][0]["ports"] == [
            {"protocol": "TCP", "port": 8443}
        ]
        assert "from" not in proxy_np.spec["ingress"][0]
        # CPU notebook: no TPU worker policy
        assert api.try_get("NetworkPolicy", "user1", "wb-tpu-workers-np") is None

    def test_tpu_worker_policy(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr, tpu=TPUSpec("v5e", "2x4"))
        np = api.get("NetworkPolicy", "user1", "wb-tpu-workers-np")
        ingress = np.spec["ingress"][0]
        assert {"protocol": "TCP", "port": 8471} in ingress["ports"]
        assert ingress["from"][0]["podSelector"]["matchLabels"] == {
            "notebook-name": "wb"
        }

    def test_policies_garbage_collected(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr)
        api.delete("Notebook", "user1", "wb")
        mgr.run_until_idle()
        assert api.try_get("NetworkPolicy", "user1", "wb-ctrl-np") is None


class TestAuthResources:
    def test_auth_object_set(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr, annotations={C.ANNOTATION_INJECT_AUTH: "true"})
        assert api.try_get("ServiceAccount", "user1", "wb") is not None
        svc = api.get("Service", "user1", "wb-kube-rbac-proxy")
        assert svc.metadata.annotations[C.SERVING_CERT_ANNOTATION] == "wb-kube-rbac-proxy-tls"
        assert svc.spec["ports"][0]["port"] == 8443
        cm = api.get("ConfigMap", "user1", "wb-kube-rbac-proxy-config")
        config = cm.body["data"]["config-file.yaml"]
        assert "resource: notebooks" in config
        assert "name: wb" in config
        crb = api.get("ClusterRoleBinding", "", "wb-rbac-user1-auth-delegator")
        assert crb.body["roleRef"]["name"] == "system:auth-delegator"
        assert crb.body["subjects"][0] == {
            "kind": "ServiceAccount", "name": "wb", "namespace": "user1",
        }

    def test_crb_cleaned_on_delete(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr, annotations={C.ANNOTATION_INJECT_AUTH: "true"})
        api.delete("Notebook", "user1", "wb")
        mgr.run_until_idle()
        assert api.try_get("ClusterRoleBinding", "", "wb-rbac-user1-auth-delegator") is None

    def test_crb_cleaned_when_auth_disabled(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr, annotations={C.ANNOTATION_INJECT_AUTH: "true"})
        nb = api.get("Notebook", "user1", "wb")
        nb.metadata.annotations[C.ANNOTATION_INJECT_AUTH] = "false"
        api.update(nb)
        mgr.run_until_idle()
        assert api.try_get("ClusterRoleBinding", "", "wb-rbac-user1-auth-delegator") is None
