"""Bounded, queryable flight recorder for reconcile attempts.

PR 2 made every reconcile attempt a traced span with fault events — but
fire-and-forget: once exported (or dropped by the noop exporter) nothing
in the pod remembers it, so "why was notebook X slow to become Ready an
hour ago" needs an external trace backend the standalone/demo mode does
not have.  Production notebook platforms answer exactly these questions
from recent per-session history (NotebookOS, arXiv:2503.20591;
ElasticNotebook, arXiv:2309.11083).  This module keeps that history
in-process, bounded, and queryable:

  - a ring buffer of the last `capacity` completed attempt summaries
    (object key, controller, result, total + per-phase durations pulled
    from the span tree, trace id, error text, injected-fault events);
  - a capped per-object history, so one hot object cannot evict every
    other object's recent past from the queryable view;
  - separate retained sets for the SLOWEST and ERRORED attempts — the
    attempts an operator actually asks about — which survive ring
    eviction;
  - a capped trace store (span trees by trace id) backing
    `/debug/traces/<trace_id>` and OpenMetrics exemplar resolution.

The Manager feeds `record()` with each finished reconcile ROOT span
(kube/controller.py); spans always record in-process (utils/tracing.py),
so the recorder works with no exporter installed and is deterministic
under a FakeClock.  All durations come from span timestamps, which follow
`tracing.set_clock`.  Everything is O(bounds) memory and lock-guarded —
the recorder must never be the thing that takes down the control plane.
"""

from __future__ import annotations

import heapq
import threading
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Optional


def span_to_dict(span) -> dict:
    """Serialize a finished Span (and its children, recursively) to plain
    JSON-able data for the /debug endpoints."""
    return {
        "name": span.name,
        "trace_id": span.trace_id,
        "span_id": span.span_id,
        "parent_span_id": span.parent.span_id if span.parent else "",
        "start_time": span.start_time,
        "end_time": span.end_time,
        "duration_s": max(span.end_time - span.start_time, 0.0),
        "attributes": dict(span.attributes),
        "events": [
            {"name": e.name, "timestamp": e.timestamp,
             "attributes": dict(e.attributes)}
            for e in span.events
        ],
        "children": [span_to_dict(c) for c in span.children],
    }


def _phase_durations(root) -> dict[str, float]:
    """Per-phase seconds from the attempt's span tree.  A span counts as a
    phase when it carries a `phase` attribute (the controllers stamp
    render/apply/status, cert_trust/routing/auth, culling) or is a direct
    child of the root; keyed by that attribute (else the span name), with
    repeated phases summing.  Nested phases (odh's `auth` runs inside
    `routing`) report their own wall time AND count inside the enclosing
    phase — phase durations are attributions, not a partition."""
    out: dict[str, float] = {}

    def visit(span, direct: bool) -> None:
        for child in span.children:
            if direct or "phase" in child.attributes:
                phase = str(child.attributes.get("phase", child.name))
                out[phase] = out.get(phase, 0.0) + \
                    max(child.end_time - child.start_time, 0.0)
            visit(child, False)

    visit(root, True)
    return out


@dataclass
class AttemptRecord:
    """One completed reconcile attempt, summarized from its span tree."""

    object_key: str           # "namespace/name"
    controller: str
    attempt: int
    result: str               # success / error / requeue / requeue_after
    start_time: float
    end_time: float
    duration_s: float
    phases: dict[str, float] = field(default_factory=dict)
    trace_id: str = ""
    span_id: str = ""
    error: str = ""           # "ExceptionType: message" for errored attempts
    faults: list[dict] = field(default_factory=list)  # fault.injected events
    # real (monotonic) execution window stamped by the Manager: span times
    # follow the injected clock, which stands still during a FakeClock run,
    # so per-key serialization can only be audited against wall time
    mono_start: float = 0.0
    mono_end: float = 0.0

    def to_dict(self) -> dict:
        return {
            "object": self.object_key,
            "controller": self.controller,
            "attempt": self.attempt,
            "result": self.result,
            "start_time": self.start_time,
            "end_time": self.end_time,
            "duration_s": self.duration_s,
            "phases": dict(self.phases),
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "error": self.error,
            "faults": [dict(f) for f in self.faults],
            # the real-time execution window rides into diagnostics
            # bundles so the cross-process overlap sweep (ops/diagnose
            # --merge over several managers' bundles) can run offline
            "mono_start": self.mono_start,
            "mono_end": self.mono_end,
        }


def record_from_dict(d: dict) -> AttemptRecord:
    """Rebuild an AttemptRecord from its to_dict() form — the read half
    of the diagnostics-bundle round trip (ops/diagnose --merge)."""
    return AttemptRecord(
        object_key=str(d.get("object", "")),
        controller=str(d.get("controller", "")),
        attempt=int(d.get("attempt", 0)),
        result=str(d.get("result", "unknown")),
        start_time=float(d.get("start_time", 0.0)),
        end_time=float(d.get("end_time", 0.0)),
        duration_s=float(d.get("duration_s", 0.0)),
        phases=dict(d.get("phases") or {}),
        trace_id=str(d.get("trace_id", "")),
        span_id=str(d.get("span_id", "")),
        error=str(d.get("error", "")),
        faults=[dict(f) for f in d.get("faults") or ()],
        mono_start=float(d.get("mono_start", 0.0) or 0.0),
        mono_end=float(d.get("mono_end", 0.0) or 0.0),
    )


def sweep_overlaps(records) -> list[tuple[AttemptRecord, AttemptRecord]]:
    """Pairs of attempts for the SAME (controller, object) whose real-time
    execution windows overlap — each pair is a serialization violation.
    Takes ANY iterable of AttemptRecords (one recorder's history, or
    several managers' histories merged), so the same sweep audits a
    single process and a sharded fleet: two replicas reconciling one key
    in the same wall-clock window is exactly a cross-process
    double-reconcile.  Attempts without monotonic stamps are skipped.

    Sort-by-start sweep with an active min-heap on window end:
    O(n log n + v) per key; touching endpoints are clean."""
    per_key: dict[tuple[str, str], list[AttemptRecord]] = {}
    for r in records:
        if r.mono_end > r.mono_start > 0.0:
            per_key.setdefault((r.object_key, r.controller), []).append(r)
    violations: list[tuple[AttemptRecord, AttemptRecord]] = []
    for runs in per_key.values():
        runs.sort(key=lambda r: r.mono_start)
        active: list[tuple[float, int, AttemptRecord]] = []
        for i, cur in enumerate(runs):
            while active and active[0][0] <= cur.mono_start:
                heapq.heappop(active)
            for _, _, prev in active:
                violations.append((prev, cur))
            heapq.heappush(active, (cur.mono_end, i, cur))
    return violations


class FlightRecorder:
    """Ring buffer + retained sets + trace store; see module docstring.

    Bounds: `capacity` attempts in the ring, `per_object` attempts per
    object key across at most `max_objects` keys (LRU-evicted),
    `keep_slowest` / `keep_errored` retained attempts, `keep_traces` span
    trees (LRU-evicted; a retained attempt whose trace aged out still has
    its summary — only the span detail is gone)."""

    def __init__(self, capacity: int = 512, per_object: int = 32,
                 keep_slowest: int = 16, keep_errored: int = 16,
                 keep_traces: int = 256, max_objects: int = 1024) -> None:
        self.capacity = capacity
        self.per_object = per_object
        self.keep_slowest = keep_slowest
        self.keep_errored = keep_errored
        self.keep_traces = keep_traces
        self.max_objects = max_objects
        self._lock = threading.Lock()
        self._ring: deque[AttemptRecord] = deque(maxlen=capacity)
        self._by_object: "OrderedDict[str, deque[AttemptRecord]]" = \
            OrderedDict()
        self._slowest: list[AttemptRecord] = []
        self._errored: deque[AttemptRecord] = deque(maxlen=keep_errored)
        # trace_id -> list of attempt root-span trees (one per attempt of
        # the retry chain), serialized at record time
        self._traces: "OrderedDict[str, list[dict]]" = OrderedDict()
        self.recorded_total = 0

    # -- write side (Manager, on root-span completion) ------------------------
    def record(self, root_span) -> Optional[AttemptRecord]:
        """Summarize a finished reconcile root span into the recorder.
        Returns the record (tests introspect it), or None for spans that
        are not attempt roots."""
        if root_span is None or not root_span.recording or \
                root_span.parent is not None:
            return None
        attrs = root_span.attributes
        object_key = "%s/%s" % (attrs.get("namespace", ""),
                                attrs.get("name", ""))
        error = ""
        faults = []
        for ev in root_span.events:
            if ev.name == "reconcile.error":
                error = "%s: %s" % (
                    ev.attributes.get("exception.type", ""),
                    ev.attributes.get("exception.message", ""))
            elif ev.name == "fault.injected":
                faults.append(dict(ev.attributes))
        rec = AttemptRecord(
            object_key=object_key,
            controller=str(attrs.get("controller", "")),
            attempt=int(attrs.get("attempt", 0)),
            result=str(attrs.get("reconcile.result", "unknown")),
            start_time=root_span.start_time,
            end_time=root_span.end_time,
            duration_s=max(root_span.end_time - root_span.start_time, 0.0),
            phases=_phase_durations(root_span),
            trace_id=root_span.trace_id,
            span_id=root_span.span_id,
            error=error,
            faults=faults,
            mono_start=float(attrs.get("mono_start", 0.0) or 0.0),
            mono_end=float(attrs.get("mono_end", 0.0) or 0.0),
        )
        tree = span_to_dict(root_span)
        with self._lock:
            self.recorded_total += 1
            self._ring.append(rec)
            history = self._by_object.get(object_key)
            if history is None:
                history = deque(maxlen=self.per_object)
                self._by_object[object_key] = history
            history.append(rec)
            self._by_object.move_to_end(object_key)
            while len(self._by_object) > self.max_objects:
                self._by_object.popitem(last=False)
            if rec.result == "error" or rec.error:
                self._errored.append(rec)
            self._slowest.append(rec)
            self._slowest.sort(key=lambda r: r.duration_s, reverse=True)
            del self._slowest[self.keep_slowest:]
            attempts = self._traces.setdefault(rec.trace_id, [])
            attempts.append(tree)
            self._traces.move_to_end(rec.trace_id)
            while len(self._traces) > self.keep_traces:
                self._traces.popitem(last=False)
        return rec

    # -- read side (the /debug endpoints, tests) ------------------------------
    def attempts(self, object_key: Optional[str] = None
                 ) -> list[AttemptRecord]:
        """Recorded attempts, oldest first: the ring, or one object's
        capped history when `object_key` ("ns/name") is given."""
        with self._lock:
            if object_key is None:
                return list(self._ring)
            return list(self._by_object.get(object_key, ()))

    def slowest(self) -> list[AttemptRecord]:
        with self._lock:
            return list(self._slowest)

    def errored(self) -> list[AttemptRecord]:
        with self._lock:
            return list(self._errored)

    def trace(self, trace_id: str) -> Optional[dict]:
        """The recorded span trees of one trace (one root per attempt of
        the retry chain), or None if unknown / evicted."""
        with self._lock:
            attempts = self._traces.get(trace_id)
            if attempts is None:
                return None
            return {"trace_id": trace_id, "attempts": len(attempts),
                    "spans": [dict(t) for t in attempts]}

    def objects(self) -> dict[str, int]:
        """Object keys with recorded history -> attempt count retained."""
        with self._lock:
            return {k: len(v) for k, v in self._by_object.items()}

    def overlapping_attempts(self) -> list[tuple[AttemptRecord,
                                                 AttemptRecord]]:
        """Pairs of recorded attempts for the SAME (controller, object)
        whose real-time execution windows overlap — each pair is a per-key
        serialization violation (two workers reconciled one key at once).
        Checked over per-object histories (bounded by per_object), using
        the monotonic stamps the Manager rides on every root span; attempts
        without stamps (records from before the Manager stamped them) are
        skipped.

        Delegates to the module-level `sweep_overlaps`, which also runs
        over several managers' MERGED histories (the sharded fleet's
        cross-process audit and ops/diagnose --merge); equivalence
        against the brute-force all-pairs result is pinned by
        tests/test_slo.py."""
        with self._lock:
            histories = {k: list(v) for k, v in self._by_object.items()}
        violations: list[tuple[AttemptRecord, AttemptRecord]] = []
        for records in histories.values():
            violations.extend(sweep_overlaps(records))
        return violations

    def snapshot(self, object_key: Optional[str] = None) -> dict:
        """The /debug/reconciles body: bounds, totals, and the requested
        view (global ring or one object's history) plus retained sets."""
        with self._lock:
            view = (list(self._by_object.get(object_key, ()))
                    if object_key is not None else list(self._ring))
            return {
                "recorded_total": self.recorded_total,
                "bounds": {
                    "capacity": self.capacity,
                    "per_object": self.per_object,
                    "keep_slowest": self.keep_slowest,
                    "keep_errored": self.keep_errored,
                    "keep_traces": self.keep_traces,
                },
                "object": object_key,
                "attempts": [r.to_dict() for r in view],
                "slowest": [r.to_dict() for r in self._slowest],
                "errored": [r.to_dict() for r in self._errored],
                "objects": {k: len(v) for k, v in self._by_object.items()},
            }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._by_object.clear()
            self._slowest.clear()
            self._errored.clear()
            self._traces.clear()


__all__ = ["AttemptRecord", "FlightRecorder", "record_from_dict",
           "span_to_dict", "sweep_overlaps"]
