"""DSPA (Data Science Pipelines Application) / Elyra integration.

Port of notebook_dspa_secret.go: build the `ds-pipeline-config` Secret with
an Elyra runtime config (odh_dsp.json) from the namespace's DSPA CR — API
endpoint from DSPA status, S3 object-store coordinates + credentials from the
referenced Secret, public endpoint from the Gateway hostname — and mount it
at /opt/app-root/runtimes (notebook_dspa_secret.go:189-477).
"""

from __future__ import annotations

import base64
import json
from typing import Optional

from ..api.types import Notebook
from ..kube import ApiServer, KubeObject, ObjectMeta, set_controller_reference
from ..tpu.env import upsert_by_name
from ..utils.config import OdhConfig
from . import constants as C
from .gateway import get_hostname_for_public_endpoint


class DSPAConfigError(ValueError):
    """A DSPA CR exists but is unusable (missing objectStorage, creds, ...)."""


def get_dspa_instance(api: ApiServer, namespace: str) -> Optional[KubeObject]:
    """The namespace's DSPA CR, or None — absence is normal and means "no
    pipelines here", never an error (the nil-on-absent pattern,
    notebook_dspa_secret.go:49-66)."""
    instances = api.list("DataSciencePipelinesApplication", namespace=namespace)
    return instances[0] if instances else None


def _secret_value(secret: KubeObject, key: str, name: str) -> str:
    data = secret.body.get("data") or {}
    if key in data:
        try:
            return base64.b64decode(data[key]).decode()
        except Exception:
            return str(data[key])
    string_data = secret.body.get("stringData") or {}
    if key in string_data:
        return string_data[key]
    raise DSPAConfigError(f"missing key '{key}' in secret '{name}'")


def extract_elyra_runtime_config(
    api: ApiServer, nb: Notebook, dspa: KubeObject, cfg: OdhConfig
) -> dict:
    """Elyra-compatible runtime config dict
    (extractElyraRuntimeConfigInfo, notebook_dspa_secret.go:189-298)."""
    api_endpoint = (
        dspa.status.get("components", {}).get("apiServer", {}).get("externalUrl", "")
    )
    object_storage = dspa.spec.get("objectStorage")
    if not object_storage:
        raise DSPAConfigError("invalid DSPA CR: 'objectStorage' is not configured")
    external = object_storage.get("externalStorage")
    if not external:
        raise DSPAConfigError(
            "invalid DSPA CR: 'objectStorage.externalStorage' is not configured"
        )
    host = external.get("host", "")
    if not host:
        raise DSPAConfigError("invalid DSPA CR: missing or invalid 'host'")
    scheme = external.get("scheme") or "https"
    bucket = external.get("bucket", "")
    if not bucket:
        raise DSPAConfigError("invalid DSPA CR: missing or invalid 'bucket'")
    cred = external.get("s3CredentialSecret")
    if not cred:
        raise DSPAConfigError(
            "invalid DSPA CR: 'objectStorage.externalStorage.s3CredentialSecret'"
            " is not configured"
        )
    secret_name = cred.get("secretName", "")
    access_key = cred.get("accessKey", "")
    secret_key = cred.get("secretKey", "")
    if not secret_name or not access_key or not secret_key:
        raise DSPAConfigError(
            "invalid DSPA CR: incomplete s3CredentialSecret configuration"
        )
    secret = api.try_get("Secret", nb.namespace, secret_name)
    if secret is None:
        raise DSPAConfigError(f"failed to get secret '{secret_name}'")

    metadata: dict = {
        "tags": [],
        "display_name": "Pipeline",
        "engine": "Argo",
        "runtime_type": "KUBEFLOW_PIPELINES",
        "auth_type": "KUBERNETES_SERVICE_ACCOUNT_TOKEN",
        "cos_auth_type": "KUBERNETES_SECRET",
        "api_endpoint": api_endpoint,
        "cos_endpoint": f"{scheme}://{host}",
        "cos_bucket": bucket,
        "cos_username": _secret_value(secret, access_key, secret_name),
        "cos_password": _secret_value(secret, secret_key, secret_name),
        "cos_secret": secret_name,
    }
    hostname = get_hostname_for_public_endpoint(api, cfg)
    if hostname:
        metadata["public_api_endpoint"] = (
            f"https://{hostname}/external/elyra/{nb.namespace}"
        )
    return {"display_name": "Pipeline", "schema_name": "kfp", "metadata": metadata}


def sync_elyra_runtime_config_secret(
    api: ApiServer, nb: Notebook, cfg: OdhConfig
) -> Optional[KubeObject]:
    """Create/update `ds-pipeline-config` owned by the DSPA CR (so it dies
    with the DSPA, not the notebook) — SyncElyraRuntimeConfigSecret,
    notebook_dspa_secret.go:305-399.  No DSPA -> no-op."""
    dspa = get_dspa_instance(api, nb.namespace)
    if dspa is None:
        return None
    config = extract_elyra_runtime_config(api, nb, dspa, cfg)
    payload = json.dumps(config, sort_keys=True)
    desired = KubeObject(
        api_version="v1",
        kind="Secret",
        metadata=ObjectMeta(
            name=C.ELYRA_SECRET_NAME,
            namespace=nb.namespace,
            labels={"opendatahub.io/managed-by": "workbenches"},
        ),
        body={
            "type": "Opaque",
            "data": {
                C.ELYRA_SECRET_KEY: base64.b64encode(payload.encode()).decode()
            },
        },
    )
    set_controller_reference(dspa, desired)
    found = api.try_get("Secret", nb.namespace, C.ELYRA_SECRET_NAME)
    if found is None:
        return api.create(desired)
    if found.body.get("data") != desired.body.get("data"):
        found.body["data"] = desired.body["data"]
        return api.update(found)
    return found


def mount_elyra_runtime_config_secret(nb: Notebook) -> None:
    """Webhook-side mutation: mount the secret at /opt/app-root/runtimes in
    the first container (MountElyraRuntimeConfigSecret,
    notebook_dspa_secret.go:403-477)."""
    spec = nb.pod_spec
    upsert_by_name(
        spec.setdefault("volumes", []),
        {
            "name": C.ELYRA_VOLUME_NAME,
            "secret": {"secretName": C.ELYRA_SECRET_NAME, "optional": True},
        },
    )
    containers = spec.get("containers") or []
    if not containers:
        return
    upsert_by_name(
        containers[0].setdefault("volumeMounts", []),
        {"name": C.ELYRA_VOLUME_NAME, "mountPath": C.ELYRA_MOUNT_PATH},
    )
