"""Culler function library: stop/activity annotation manipulation.

Port of pkg/culler/culler.go — still the home of the shared stop-annotation
helpers, which the ODH controller imports
(odh-notebook-controller/controllers/notebook_controller.go:35,146), plus the
idleness math (NotebookNeedsCulling, culler.go:409)."""

from __future__ import annotations

import time
from typing import Optional

from ..kube import ObjectMeta
from ..utils.clock import Clock, parse_iso
from . import constants as C

KERNEL_EXECUTION_STATE_IDLE = "idle"
KERNEL_EXECUTION_STATE_BUSY = "busy"
KERNEL_EXECUTION_STATE_STARTING = "starting"


def stop_annotation_is_set(meta: ObjectMeta) -> bool:
    return C.STOP_ANNOTATION in meta.annotations


def set_stop_annotation(meta: ObjectMeta, clock: Clock) -> None:
    """Value is the cull timestamp (culler.go:119-137)."""
    meta.annotations[C.STOP_ANNOTATION] = clock.now_iso()


def remove_stop_annotation(meta: ObjectMeta) -> None:
    meta.annotations.pop(C.STOP_ANNOTATION, None)


def annotations_exist(meta: ObjectMeta) -> bool:
    return (
        C.LAST_ACTIVITY_ANNOTATION in meta.annotations
        and C.LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION in meta.annotations
    )


def initialize_annotations(meta: ObjectMeta, clock: Clock) -> None:
    now = clock.now_iso()
    meta.annotations[C.LAST_ACTIVITY_ANNOTATION] = now
    meta.annotations[C.LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION] = now


def remove_activity_annotations(meta: ObjectMeta) -> None:
    meta.annotations.pop(C.LAST_ACTIVITY_ANNOTATION, None)
    meta.annotations.pop(C.LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION, None)
    remove_checkpoint_annotations(meta)


def remove_checkpoint_annotations(meta: ObjectMeta) -> None:
    """Both sides of the checkpoint handshake: a stale checkpoint-complete
    from a previous cull cycle must not bypass the next grace window."""
    meta.annotations.pop(C.ANNOTATION_CHECKPOINT_REQUESTED, None)
    meta.annotations.pop(C.ANNOTATION_CHECKPOINT_COMPLETE, None)


def _parse(ts: Optional[str]) -> Optional[float]:
    if not ts:
        return None
    try:
        return parse_iso(ts)
    except ValueError:
        return None


def all_kernels_idle(kernels: list[dict]) -> bool:
    """allKernelsAreIdle (culling_controller.go:324-336)."""
    return all(
        k.get("execution_state") == KERNEL_EXECUTION_STATE_IDLE for k in kernels
    )


def most_recent_time(timestamps: list[str]) -> Optional[str]:
    """getNotebookRecentTime (:341-361): None on any unparsable entry."""
    parsed = []
    for t in timestamps:
        p = _parse(t)
        if p is None:
            return None
        parsed.append((p, t))
    if not parsed:
        return None
    return max(parsed)[1]


def update_last_activity_from_kernels(
    meta: ObjectMeta, kernels: Optional[list[dict]], clock: Clock
) -> None:
    """updateTimestampFromKernelsActivity (:380-411): a busy kernel bumps
    last-activity to now; otherwise take the most recent kernel
    last_activity, never moving backwards in time."""
    if not kernels:
        return
    if not all_kernels_idle(kernels):
        meta.annotations[C.LAST_ACTIVITY_ANNOTATION] = clock.now_iso()
        return
    recent = most_recent_time([k.get("last_activity", "") for k in kernels])
    _advance_last_activity(meta, recent)


def update_last_activity_from_terminals(
    meta: ObjectMeta, terminals: Optional[list[dict]], clock: Clock
) -> None:
    """updateTimestampFromTerminalsActivity (:413-448)."""
    if not terminals:
        return
    recent = most_recent_time([t.get("last_activity", "") for t in terminals])
    _advance_last_activity(meta, recent)


def _advance_last_activity(meta: ObjectMeta, recent: Optional[str]) -> None:
    if recent is None:
        return
    current = _parse(meta.annotations.get(C.LAST_ACTIVITY_ANNOTATION))
    candidate = _parse(recent)
    if candidate is None:
        return
    if current is not None and current > candidate:
        return  # never move backwards (compareAnnotationTimeToResource :363)
    meta.annotations[C.LAST_ACTIVITY_ANNOTATION] = recent


def update_last_culling_check_timestamp(meta: ObjectMeta, clock: Clock) -> None:
    meta.annotations[C.LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION] = clock.now_iso()


def culling_check_period_has_passed(
    meta: ObjectMeta, clock: Clock, period_min: int
) -> bool:
    """cullingCheckPeriodHasPassed (:206-218)."""
    stored = _parse(meta.annotations.get(C.LAST_ACTIVITY_CHECK_TIMESTAMP_ANNOTATION))
    if stored is None:
        return False
    return stored + period_min * 60 < clock.now()


def notebook_is_idle(meta: ObjectMeta, clock: Clock, cull_idle_min: int) -> bool:
    """notebookIsIdle (:221-242)."""
    if stop_annotation_is_set(meta):
        return False
    last = _parse(meta.annotations.get(C.LAST_ACTIVITY_ANNOTATION))
    if last is None:
        return False
    return clock.now() > last + cull_idle_min * 60
