"""Real-cluster backend integration: KubeClient over the k8s wire protocol.

The reference grounds its controllers against a real apiserver via envtest
(notebook-controller/controllers/suite_test.go:50-110) and serves admission
over HTTPS (odh main.go:285-311).  These tests do the same with this repo's
stack: the in-memory ApiServer is served over the genuine Kubernetes REST
protocol (kube/wire.py), the real HTTP KubeClient + Manager reconcile it
over real sockets, and the admission webhooks run as an HTTPS
AdmissionReview server that the apiserver calls out to.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from kubeflow_tpu.api.types import Notebook
from kubeflow_tpu.core.metrics import NotebookMetrics
from kubeflow_tpu.core.notebook_controller import setup_core_controllers
from kubeflow_tpu.kube import (
    ApiServer,
    ConflictError,
    FakeCluster,
    ForbiddenError,
    GoneError,
    KubeObject,
    Manager,
    NotFoundError,
    ObjectMeta,
)
from kubeflow_tpu.kube.certs import mint_serving_cert
from kubeflow_tpu.kube.client import KubeClient, RateLimiter, RestConfig
from kubeflow_tpu.kube.jsonpatch import apply_patch, diff
from kubeflow_tpu.kube.store import EventType
from kubeflow_tpu.kube.wire import KubeApiWireServer, parse_label_selector
from kubeflow_tpu.odh.webhook import (
    NotebookMutatingWebhook,
    NotebookValidatingWebhook,
)
from kubeflow_tpu.odh.webhook_server import (
    AdmissionReviewServer,
    RemoteAdmissionHook,
)
from kubeflow_tpu.utils.config import CoreConfig, OdhConfig


def wait_for(predicate, timeout=10.0, interval=0.05, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def make_notebook(name="wb", namespace="default", **kw) -> KubeObject:
    return Notebook.new(name, namespace, **kw).obj


@pytest.fixture()
def wire():
    """(server, client) pair over a real localhost socket."""
    api = ApiServer()
    srv = KubeApiWireServer(api).start()
    client = KubeClient(RestConfig(server=srv.url))
    yield api, srv, client
    client.stop_informers()
    srv.stop()


# -- watch-history / resume semantics (the etcd watch cache analog) ----------


class TestWatchHistory:
    def test_subscribe_replays_from_rv(self):
        api = ApiServer()
        api.create(KubeObject("v1", "ConfigMap",
                              ObjectMeta(name="a", namespace="ns")))
        rv = api.resource_version
        api.create(KubeObject("v1", "ConfigMap",
                              ObjectMeta(name="b", namespace="ns")))
        seen = []
        api.subscribe(lambda ev: seen.append(ev.obj.name), since_rv=rv)
        assert seen == ["b"], "only events after rv replay"
        api.create(KubeObject("v1", "ConfigMap",
                              ObjectMeta(name="c", namespace="ns")))
        assert seen == ["b", "c"], "live events continue after replay"

    def test_too_old_rv_raises_gone(self):
        api = ApiServer()
        for i in range(2200):  # overflow the 2048-event history window
            api.create(KubeObject("v1", "ConfigMap",
                                  ObjectMeta(name=f"cm{i}", namespace="ns")))
        with pytest.raises(GoneError):
            api.subscribe(lambda ev: None, since_rv=1)

    def test_delete_bumps_resource_version(self):
        api = ApiServer()
        obj = api.create(KubeObject("v1", "ConfigMap",
                                    ObjectMeta(name="a", namespace="ns")))
        rv_before = api.resource_version
        api.delete("ConfigMap", "ns", "a")
        assert api.resource_version > rv_before
        seen = []
        api.subscribe(lambda ev: seen.append((ev.type, ev.obj.name)),
                      since_rv=obj.metadata.resource_version)
        assert (EventType.DELETED, "a") in seen


# -- wire protocol CRUD ------------------------------------------------------


class TestWireProtocol:
    def test_crud_roundtrip(self, wire):
        _, _, client = wire
        created = client.create(make_notebook())
        assert created.metadata.uid and created.metadata.resource_version > 0
        got = client.get("Notebook", "default", "wb")
        assert got.metadata.uid == created.metadata.uid
        got.metadata.labels["x"] = "y"
        updated = client.update(got)
        assert updated.metadata.resource_version > got.metadata.resource_version
        client.delete("Notebook", "default", "wb")
        with pytest.raises(NotFoundError):
            client.get("Notebook", "default", "wb")

    def test_optimistic_concurrency_conflict(self, wire):
        _, _, client = wire
        client.create(make_notebook())
        a = client.get("Notebook", "default", "wb")
        b = client.get("Notebook", "default", "wb")
        a.metadata.labels["winner"] = "a"
        client.update(a)
        b.metadata.labels["winner"] = "b"
        with pytest.raises(ConflictError):
            client.update(b)

    def test_status_subresource_isolated(self, wire):
        _, _, client = wire
        client.create(make_notebook())
        cur = client.get("Notebook", "default", "wb")
        cur.body["status"] = {"readyReplicas": 3}
        client.update_status(cur)
        # a non-status update cannot overwrite status
        cur = client.get("Notebook", "default", "wb")
        cur.body["status"] = {"readyReplicas": 99}
        cur.metadata.labels["z"] = "1"
        client.update(cur)
        final = client.get("Notebook", "default", "wb")
        assert final.body["status"]["readyReplicas"] == 3

    def test_merge_patch_null_deletes(self, wire):
        _, _, client = wire
        client.create(make_notebook())
        client.merge_patch("Notebook", "default", "wb",
                           {"metadata": {"annotations": {"k": "v"}}})
        assert client.get("Notebook", "default", "wb").annotations["k"] == "v"
        client.merge_patch("Notebook", "default", "wb",
                           {"metadata": {"annotations": {"k": None}}})
        assert "k" not in client.get("Notebook", "default", "wb").annotations

    def test_label_selector_list(self, wire):
        _, _, client = wire
        for name, team in [("a", "ml"), ("b", "web"), ("c", "ml")]:
            nb = make_notebook(name)
            nb.metadata.labels["team"] = team
            client.create(nb)
        ml = client.list("Notebook", "default", {"team": "ml"})
        assert [o.name for o in ml] == ["a", "c"]

    def test_cluster_scoped_resource(self, wire):
        _, _, client = wire
        client.create(KubeObject(
            "rbac.authorization.k8s.io/v1", "ClusterRoleBinding",
            ObjectMeta(name="crb-1"), body={"subjects": []}))
        got = client.get("ClusterRoleBinding", "", "crb-1")
        assert got.name == "crb-1" and got.namespace == ""

    def test_paginated_list(self, wire):
        """limit/continue chunking (apiserver pagination): pages partition
        the set, each carries the snapshot RV, and the informer relist walks
        every page."""
        api, srv, client = wire
        for i in range(7):
            client.create(make_notebook(f"pg{i}"))
        seen: list[str] = []
        path = "/apis/kubeflow.org/v1/namespaces/default/notebooks"
        params = "limit=3"
        pages = 0
        while True:
            with urllib.request.urlopen(f"{srv.url}{path}?{params}",
                                        timeout=5) as resp:
                body = json.loads(resp.read())
            pages += 1
            seen.extend(i["metadata"]["name"] for i in body["items"])
            cont = body["metadata"].get("continue")
            if not cont:
                assert "remainingItemCount" not in body["metadata"]
                break
            assert len(body["items"]) == 3
            params = f"limit=3&continue={urllib.parse.quote(cont)}"
        assert pages == 3 and seen == [f"pg{i}" for i in range(7)]

    def test_paginated_list_is_snapshot_consistent(self, wire):
        """All pages of one list serve the SAME snapshot at the same rv
        (etcd serves continues at the original revision) — writes landing
        between pages must not leak in or punch holes."""
        api, srv, client = wire
        for i in range(6):
            client.create(make_notebook(f"sn{i}"))
        path = "/apis/kubeflow.org/v1/namespaces/default/notebooks"
        with urllib.request.urlopen(f"{srv.url}{path}?limit=3",
                                    timeout=5) as resp:
            page1 = json.loads(resp.read())
        # mutate between pages: delete a page-2 item, add a before-cursor item
        client.delete("Notebook", "default", "sn4")
        client.create(make_notebook("sn0a"))
        cont = urllib.parse.quote(page1["metadata"]["continue"])
        with urllib.request.urlopen(f"{srv.url}{path}?limit=3&continue={cont}",
                                    timeout=5) as resp:
            page2 = json.loads(resp.read())
        names = [i["metadata"]["name"] for i in page1["items"] + page2["items"]]
        assert names == [f"sn{i}" for i in range(6)], names  # the snapshot
        assert page2["metadata"]["resourceVersion"] == \
            page1["metadata"]["resourceVersion"]
        # a FRESH list sees the new state
        with urllib.request.urlopen(f"{srv.url}{path}", timeout=5) as resp:
            fresh = [i["metadata"]["name"]
                     for i in json.loads(resp.read())["items"]]
        assert "sn4" not in fresh and "sn0a" in fresh

    def test_pagination_error_codes(self, wire):
        _, srv, client = wire
        client.create(make_notebook("pe"))
        path = "/apis/kubeflow.org/v1/namespaces/default/notebooks"
        for query, code in [("limit=abc", 400), ("limit=2&continue=!!!", 400)]:
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(f"{srv.url}{path}?{query}", timeout=5)
            assert exc.value.code == code, query
        # an evicted snapshot answers 410 Expired -> client relists
        for i in range(40):
            client.create(make_notebook(f"evict{i:02d}"))
        with urllib.request.urlopen(f"{srv.url}{path}?limit=2",
                                    timeout=5) as resp:
            token = json.loads(resp.read())["metadata"]["continue"]
        for _ in range(33):  # churn past _MAX_SNAPSHOTS
            with urllib.request.urlopen(f"{srv.url}{path}?limit=2",
                                        timeout=5) as resp:
                json.loads(resp.read())
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"{srv.url}{path}?limit=2&continue={urllib.parse.quote(token)}",
                timeout=5)
        assert exc.value.code == 410

    def test_watch_bookmarks(self, wire):
        """allowWatchBookmarks: an idle stream emits BOOKMARK progress
        events carrying the current resourceVersion; clients advance their
        resume point without relisting (apiserver WatchBookmarks)."""
        api, srv, _ = wire
        api.create(make_notebook("bm"))
        rv = api.resource_version
        url = (f"{srv.url}/apis/kubeflow.org/v1/namespaces/default/notebooks"
               f"?watch=true&resourceVersion={rv}&allowWatchBookmarks=true")
        req = urllib.request.Request(url)
        resp = urllib.request.urlopen(req, timeout=10)
        try:
            line = resp.readline()  # idle stream -> first line is a bookmark
            ev = json.loads(line)
            assert ev["type"] == "BOOKMARK"
            assert int(ev["object"]["metadata"]["resourceVersion"]) >= rv
            assert ev["object"]["kind"] == "Notebook"
        finally:
            resp.close()
        # without the flag, an idle stream stays silent
        url_plain = url.replace("&allowWatchBookmarks=true", "")
        resp = urllib.request.urlopen(urllib.request.Request(url_plain),
                                      timeout=10)
        try:
            import socket as _socket

            resp.fp.raw._sock.settimeout(1.8)
            with pytest.raises((TimeoutError, _socket.timeout)):
                resp.readline()
        finally:
            resp.close()

    def test_namespace_scoped_informer(self, wire):
        """start_informers(namespace=...) must only see that namespace."""
        api, _, client = wire
        api.create(make_notebook("in-scope", namespace="team-a"))
        api.create(make_notebook("out-of-scope", namespace="team-b"))
        seen = []
        client.watch(lambda ev: seen.append(ev.obj.name))
        client.start_informers(["Notebook"], namespace="team-a")
        wait_for(lambda: "in-scope" in seen, msg="scoped informer sync")
        time.sleep(0.3)  # give an unscoped leak a chance to surface
        assert "out-of-scope" not in seen
        api.create(make_notebook("late", namespace="team-b"))
        api.create(make_notebook("late-a", namespace="team-a"))
        wait_for(lambda: "late-a" in seen, msg="scoped live event")
        assert "late" not in seen

    def test_generate_name(self, wire):
        _, _, client = wire
        obj = KubeObject("v1", "ConfigMap",
                         ObjectMeta(generate_name="cm-", namespace="default"))
        created = client.create(obj)
        assert created.name.startswith("cm-") and len(created.name) > 3

    def test_finalizer_gated_delete_over_wire(self, wire):
        _, _, client = wire
        nb = make_notebook()
        nb.metadata.finalizers = ["example.com/cleanup"]
        client.create(nb)
        client.delete("Notebook", "default", "wb")
        terminating = client.get("Notebook", "default", "wb")
        assert terminating.metadata.deletion_timestamp
        terminating.metadata.finalizers = []
        client.update(terminating)
        wait_for(lambda: client.try_get("Notebook", "default", "wb") is None,
                 msg="finalized delete")

    def test_watch_selector_parsing(self):
        assert parse_label_selector("a=b,c==d") == {"a": "b", "c": "d"}
        assert parse_label_selector("") == {}

    def test_informer_list_then_watch(self, wire):
        _, _, client = wire
        client.create(make_notebook("pre"))
        events: list[tuple[str, str]] = []
        client.watch(lambda ev: events.append((ev.type.value, ev.obj.name)))
        client.start_informers(["Notebook"])
        wait_for(lambda: ("ADDED", "pre") in events, msg="initial list ADDED")
        client.create(make_notebook("post"))
        wait_for(lambda: ("ADDED", "post") in events, msg="live watch ADDED")
        client.delete("Notebook", "default", "pre")
        wait_for(lambda: ("DELETED", "pre") in events, msg="DELETED event")

    def test_unauthorized_without_token(self):
        api = ApiServer()
        srv = KubeApiWireServer(api, token="s3cret").start()
        try:
            bad = KubeClient(RestConfig(server=srv.url, token="wrong"))
            with pytest.raises(ForbiddenError):
                bad.list("Notebook")
            good = KubeClient(RestConfig(server=srv.url, token="s3cret"))
            assert good.list("Notebook") == []
        finally:
            srv.stop()


# -- the full controller stack over real sockets ------------------------------


class TestControllersOverWire:
    @pytest.fixture()
    def stack(self):
        """Server side: ApiServer + FakeCluster (the 'cluster').  Client
        side: KubeClient + Manager running every core controller, exactly
        as `python -m kubeflow_tpu.main --kubeconfig` wires it."""
        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("node-1", allocatable={"cpu": "32", "memory": "64Gi"})
        srv = KubeApiWireServer(api).start()
        client = KubeClient(RestConfig(server=srv.url))
        mgr = Manager(client)
        cfg = CoreConfig.from_env({})
        metrics = NotebookMetrics(client)
        setup_core_controllers(mgr, cfg, metrics)
        client.start_informers(mgr.watched_kinds())
        mgr.start(poll_interval_s=0.01)
        yield api, cluster, client, mgr
        mgr.stop()
        client.stop_informers()
        srv.stop()

    def test_notebook_reconciles_to_running(self, stack):
        _, _, client, _ = stack
        client.create(make_notebook("real-nb"))
        sts = wait_for(
            lambda: client.try_get("StatefulSet", "default", "real-nb"),
            msg="StatefulSet created over the wire")
        assert sts.spec["replicas"] == 1
        # the reconciler creates the Service AFTER the StatefulSet — under
        # host load the gap is observable, so poll (was a load-dependent
        # flake: NotFoundError when compile-heavy suites share the box)
        svc = wait_for(
            lambda: client.try_get("Service", "default", "real-nb"),
            msg="Service created over the wire")
        ports = svc.spec["ports"]
        assert ports[0]["port"] == 80 and ports[0]["targetPort"] == 8888
        nb = wait_for(
            lambda: (lambda o: o if o and o.body.get("status", {})
                     .get("readyReplicas") == 1 else None)(
                client.try_get("Notebook", "default", "real-nb")),
            msg="status.readyReplicas=1 via the status subresource")
        assert nb.body["status"]["containerState"].get("running")

    def test_stop_annotation_scales_to_zero(self, stack):
        _, _, client, _ = stack
        client.create(make_notebook("real-nb"))
        wait_for(lambda: client.try_get("StatefulSet", "default", "real-nb"),
                 msg="sts")
        client.merge_patch(
            "Notebook", "default", "real-nb",
            {"metadata": {"annotations": {
                "kubeflow-resource-stopped": "2026-07-29T00:00:00Z"}}})
        wait_for(
            lambda: client.get("StatefulSet", "default",
                               "real-nb").spec["replicas"] == 0,
            msg="scale to zero on stop annotation")

    def test_drift_recreated_over_wire(self, stack):
        _, _, client, _ = stack
        client.create(make_notebook("real-nb"))
        # the reconcile creates the STS first, Service after — poll for the
        # Service itself before deleting it (deleting on the STS signal
        # alone races the first reconcile)
        first = wait_for(
            lambda: client.try_get("Service", "default", "real-nb"),
            msg="service created")
        client.delete("Service", "default", "real-nb")
        wait_for(
            lambda: (svc := client.try_get("Service", "default", "real-nb"))
            is not None and svc.metadata.uid != first.metadata.uid,
            msg="service recreated after delete (level-triggered)")


# -- HTTPS admission choreography ---------------------------------------------


class TestAdmissionOverHttps:
    @pytest.fixture()
    def admission_stack(self):
        api = ApiServer()
        cfg = OdhConfig.from_env({})
        bundle = mint_serving_cert()
        hooks = [NotebookMutatingWebhook(api, cfg).hook(),
                 NotebookValidatingWebhook(api, cfg).hook()]
        whsrv = AdmissionReviewServer(hooks, bundle=bundle).start()
        api.register_admission(RemoteAdmissionHook(
            whsrv.url, "/mutate-notebook-v1", mutating=True,
            ca_pem=bundle.ca_cert_pem).as_hook())
        api.register_admission(RemoteAdmissionHook(
            whsrv.url, "/validate-notebook-v1", mutating=False,
            ca_pem=bundle.ca_cert_pem,
            operations=("UPDATE",)).as_hook())
        srv = KubeApiWireServer(api).start()
        client = KubeClient(RestConfig(server=srv.url))
        yield client, whsrv
        srv.stop()
        whsrv.stop()

    def test_mutating_webhook_injects_lock_via_https(self, admission_stack):
        client, _ = admission_stack
        created = client.create(make_notebook())
        assert created.annotations.get("kubeflow-resource-stopped") == \
            "odh-notebook-controller-lock"

    def test_validating_webhook_denies_via_https(self, admission_stack):
        client, _ = admission_stack
        created = client.create(make_notebook())
        created.annotations["opendatahub.io/mlflow-instance"] = "mlf"
        del created.annotations["kubeflow-resource-stopped"]
        cur = client.update(created)
        del cur.annotations["opendatahub.io/mlflow-instance"]
        with pytest.raises(ForbiddenError, match="mlflow"):
            client.update(cur)

    def test_tpu_image_swap_via_https(self, admission_stack):
        """The TPU image swap — a spec.template mutation, not just an
        annotation — must survive the AdmissionReview JSONPatch round
        trip through the HTTPS callout."""
        from kubeflow_tpu.api.types import TPUSpec

        client, _ = admission_stack
        nb = Notebook.new(
            "tpu-wb", "default", tpu=TPUSpec("v5e", "2x2"),
            pod_spec={"containers": [
                {"name": "tpu-wb", "image": "cuda-notebook:1"}]}).obj
        created = client.create(nb)
        (c,) = created.body["spec"]["template"]["spec"]["containers"]
        assert c["image"] == "jupyter-tpu-jax:latest", \
            "CUDA image swapped for the JAX/libtpu image over the wire"

    def test_webhook_readyz(self, admission_stack):
        _, whsrv = admission_stack
        import ssl

        ctx = ssl._create_unverified_context()
        with urllib.request.urlopen(f"{whsrv.url}/readyz",
                                    context=ctx, timeout=5) as resp:
            assert resp.status == 200


# -- CRD conversion webhook: /convert + multi-version wire serving ------------


class TestConversionWebhook:
    """The CRD's spec.conversion choreography (deploy/manifests.py renders
    path /convert): non-storage-version clients must round-trip through the
    HTTPS conversion webhook exactly as on a real cluster.  Reference:
    api/v1/notebook_conversion.go:25-69."""

    @pytest.fixture()
    def conversion_stack(self):
        from kubeflow_tpu.odh.webhook_server import RemoteConverter

        api = ApiServer()
        bundle = mint_serving_cert()
        whsrv = AdmissionReviewServer([], bundle=bundle).start()
        converter = RemoteConverter(whsrv.url, ca_pem=bundle.ca_cert_pem)
        srv = KubeApiWireServer(api, converter=converter).start()
        yield api, srv
        srv.stop()
        whsrv.stop()

    def _request(self, srv, method, path, body=None):
        req = urllib.request.Request(
            srv.url + path,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"}, method=method)
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as err:
            return err.code, json.loads(err.read())

    def test_v1alpha1_create_v1_read_roundtrip(self, conversion_stack):
        api, srv = conversion_stack
        nb = Notebook.new("conv", "default", version="v1alpha1").obj.to_dict()
        code, created = self._request(
            srv, "POST",
            "/apis/kubeflow.org/v1alpha1/namespaces/default/notebooks", nb)
        assert code == 201
        # the client that wrote v1alpha1 reads back v1alpha1...
        assert created["apiVersion"] == "kubeflow.org/v1alpha1"
        # ...while storage (and v1 clients) see the storage version
        assert api.get("Notebook", "default", "conv").api_version == \
            "kubeflow.org/v1"
        code, got = self._request(
            srv, "GET", "/apis/kubeflow.org/v1/namespaces/default/notebooks/conv")
        assert code == 200 and got["apiVersion"] == "kubeflow.org/v1"
        # metadata survives conversion: uid + resourceVersion intact
        assert got["metadata"]["uid"] == created["metadata"]["uid"]

    def test_v1beta1_list_and_update_cross_version(self, conversion_stack):
        api, srv = conversion_stack
        api.create(make_notebook("wb1"))
        code, lst = self._request(
            srv, "GET", "/apis/kubeflow.org/v1beta1/namespaces/default/notebooks")
        assert code == 200
        assert [i["apiVersion"] for i in lst["items"]] == ["kubeflow.org/v1beta1"]
        item = lst["items"][0]
        item["metadata"].setdefault("labels", {})["touched"] = "yes"
        code, updated = self._request(
            srv, "PUT",
            "/apis/kubeflow.org/v1beta1/namespaces/default/notebooks/wb1", item)
        assert code == 200 and updated["apiVersion"] == "kubeflow.org/v1beta1"
        stored = api.get("Notebook", "default", "wb1")
        assert stored.api_version == "kubeflow.org/v1"
        assert stored.metadata.labels["touched"] == "yes"

    def test_cross_version_patch_keeps_storage_version(self, conversion_stack):
        """A merge patch on a v1beta1 path (kubectl-style, carrying its own
        apiVersion) must not smuggle the request version into storage."""
        api, srv = conversion_stack
        api.create(make_notebook("wbp"))
        code, patched = self._request(
            srv, "PATCH",
            "/apis/kubeflow.org/v1beta1/namespaces/default/notebooks/wbp",
            {"apiVersion": "kubeflow.org/v1beta1",
             "metadata": {"labels": {"patched": "yes"}}})
        assert code == 200
        assert patched["apiVersion"] == "kubeflow.org/v1beta1"
        stored = api.get("Notebook", "default", "wbp")
        assert stored.api_version == "kubeflow.org/v1"
        assert stored.metadata.labels["patched"] == "yes"

    def test_cross_version_strategic_patch(self, conversion_stack):
        """Strategic merge on an alias-version path: keyed-list semantics
        must apply to the REQUEST-version view (view_out/view_in hooks) and
        convert back to storage without smuggling the alias version."""
        api, srv = conversion_stack
        nb = make_notebook("wbs")
        nb.body["spec"]["template"]["spec"]["containers"] = [
            {"name": "wbs", "image": "jupyter:1",
             "env": [{"name": "NB_PREFIX", "value": "/nb"}]}]
        api.create(nb)
        req = urllib.request.Request(
            srv.url + "/apis/kubeflow.org/v1beta1/namespaces/default/"
            "notebooks/wbs",
            data=json.dumps({"spec": {"template": {"spec": {"containers": [
                {"name": "wbs", "image": "jupyter:2"}]}}}}).encode(),
            headers={"Content-Type":
                     "application/strategic-merge-patch+json"},
            method="PATCH")
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200
            body = json.loads(resp.read())
        assert body["apiVersion"] == "kubeflow.org/v1beta1"
        stored = api.get("Notebook", "default", "wbs")
        assert stored.api_version == "kubeflow.org/v1"
        (c,) = stored.body["spec"]["template"]["spec"]["containers"]
        assert c["image"] == "jupyter:2"
        assert c["env"] == [{"name": "NB_PREFIX", "value": "/nb"}], \
            "keyed merge through the conversion hooks keeps siblings"

    def test_alias_version_field_selector_list(self, conversion_stack):
        """fieldSelector on an alias-version list is evaluated on the
        converted view — and the filtered items come back in the request
        version."""
        api, srv = conversion_stack
        api.create(make_notebook("sel-a"))
        api.create(make_notebook("sel-b"))
        code, lst = self._request(
            srv, "GET",
            "/apis/kubeflow.org/v1beta1/namespaces/default/notebooks"
            "?fieldSelector=metadata.name%3Dsel-b")
        assert code == 200
        assert [i["metadata"]["name"] for i in lst["items"]] == ["sel-b"]
        assert lst["items"][0]["apiVersion"] == "kubeflow.org/v1beta1"

    def test_alias_version_404s_without_converter(self):
        """A wire server with no conversion webhook must NOT serve alias
        versions (mislabeled storage objects would be worse than a 404)."""
        api = ApiServer()
        api.create(make_notebook("wbx"))
        srv = KubeApiWireServer(api).start()
        try:
            req = urllib.request.Request(
                srv.url + "/apis/kubeflow.org/v1beta1/namespaces/default/notebooks")
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=5)
            assert exc.value.code == 404
            # the storage version still serves
            with urllib.request.urlopen(
                    srv.url + "/apis/kubeflow.org/v1/namespaces/default/notebooks",
                    timeout=5) as resp:
                assert resp.status == 200
        finally:
            srv.stop()

    def test_list_conversion_is_one_batched_callout(self, conversion_stack):
        api, srv = conversion_stack
        for i in range(5):
            api.create(make_notebook(f"wb{i}"))
        handler_cls = srv._httpd.RequestHandlerClass
        converter = handler_cls.converter
        calls = []
        orig = converter.convert_many

        def counting(objs, desired):
            calls.append(len(objs))
            return orig(objs, desired)

        converter.convert_many = counting
        try:
            code, lst = self._request(
                srv, "GET",
                "/apis/kubeflow.org/v1beta1/namespaces/default/notebooks")
            assert code == 200 and len(lst["items"]) == 5
            assert calls == [5], f"expected one batched callout, got {calls}"
        finally:
            converter.convert_many = orig

    def test_conversion_review_wire_format(self):
        """Direct ConversionReview v1 exchange against the served /convert."""
        from kubeflow_tpu.odh.webhook_server import handle_conversion_review
        from kubeflow_tpu.api.types import convert_notebook_dict

        nb = Notebook.new("x", "ns", version="v1").obj.to_dict()
        out = handle_conversion_review({
            "apiVersion": "apiextensions.k8s.io/v1",
            "kind": "ConversionReview",
            "request": {"uid": "u1", "desiredAPIVersion": "kubeflow.org/v1beta1",
                        "objects": [nb]},
        }, convert_notebook_dict)
        resp = out["response"]
        assert resp["uid"] == "u1"
        assert resp["result"]["status"] == "Success"
        assert resp["convertedObjects"][0]["apiVersion"] == "kubeflow.org/v1beta1"
        # failure is a Failure result, not an exception
        bad = handle_conversion_review({
            "request": {"uid": "u2", "desiredAPIVersion": "other.group/v9",
                        "objects": [nb]},
        }, convert_notebook_dict)
        assert bad["response"]["result"]["status"] == "Failure"

    def test_unconvertible_version_is_500_status(self, conversion_stack):
        api, srv = conversion_stack
        api.create(make_notebook("wb2"))
        # a served path with a converter that can't produce the version
        from kubeflow_tpu.kube.resources import DEFAULT_SCHEME, ResourceInfo

        DEFAULT_SCHEME.register_served(
            ResourceInfo("Notebook", "kubeflow.org", "v9broken", "notebooks"))
        try:
            code, body = self._request(
                srv, "GET",
                "/apis/kubeflow.org/v9broken/namespaces/default/notebooks/wb2")
            assert code == 500
            assert body["reason"] == "InternalError"
        finally:
            DEFAULT_SCHEME._by_path.pop(
                ("kubeflow.org", "v9broken", "notebooks"), None)


# -- the shipped CLI against a kubeconfig -------------------------------------


class TestManagerCli:
    def test_kubeconfig_manager_reconciles(self, tmp_path):
        """VERDICT round-1 'done' criterion: `python -m kubeflow_tpu.main
        --kubeconfig ...` reconciles a Notebook on a (wire-served) cluster."""
        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("node-1", allocatable={"cpu": "32", "memory": "64Gi"})
        srv = KubeApiWireServer(api, token="cli-test-token").start()
        kubeconfig = tmp_path / "kubeconfig.yaml"
        kubeconfig.write_text(json.dumps({
            "apiVersion": "v1", "kind": "Config",
            "current-context": "wire",
            "contexts": [{"name": "wire",
                          "context": {"cluster": "wire", "user": "wire",
                                      "namespace": "default"}}],
            "clusters": [{"name": "wire", "cluster": {"server": srv.url}}],
            "users": [{"name": "wire", "user": {"token": "cli-test-token"}}],
        }))
        env = dict(os.environ)
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "kubeflow_tpu.main",
             "--kubeconfig", str(kubeconfig),
             "--webhook-port", "-1",
             "--metrics-addr", "0",
             "--run-seconds", "30"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        try:
            api.create(make_notebook("cli-nb"))
            wait_for(lambda: api.try_get("StatefulSet", "default", "cli-nb"),
                     timeout=25,
                     msg="external manager process reconciled the Notebook")
            sts = api.get("StatefulSet", "default", "cli-nb")
            assert sts.spec["replicas"] == 1
        finally:
            proc.send_signal(signal.SIGINT)
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
            srv.stop()


# -- JSON Patch ---------------------------------------------------------------


class TestDiscovery:
    """API discovery documents (/api, /apis, APIResourceList) — kubectl's
    first requests against any server; built from the scheme registry."""

    @pytest.fixture()
    def wire(self):
        api = ApiServer()
        srv = KubeApiWireServer(api).start()
        yield srv
        srv.stop()

    def _get(self, srv, path):
        with urllib.request.urlopen(srv.url + path, timeout=5) as resp:
            return json.loads(resp.read())

    def test_core_and_group_listing(self, wire):
        assert self._get(wire, "/api")["versions"] == ["v1"]
        groups = self._get(wire, "/apis")
        assert groups["kind"] == "APIGroupList"
        names = {g["name"] for g in groups["groups"]}
        assert {"kubeflow.org", "apps", "gateway.networking.k8s.io"} <= names

    def test_core_resource_list(self, wire):
        doc = self._get(wire, "/api/v1")
        assert doc["kind"] == "APIResourceList"
        by_name = {r["name"]: r for r in doc["resources"]}
        assert by_name["configmaps"]["namespaced"] is True
        assert by_name["nodes"]["namespaced"] is False
        assert by_name["namespaces"]["namespaced"] is False, \
            "a RESTMapper building paths from discovery needs this right"
        assert "deletecollection" in by_name["configmaps"]["verbs"]

    def test_no_converter_advertises_storage_only(self, wire):
        """Without a conversion webhook the alias versions 404 on the data
        path — discovery must not advertise what cannot be served."""
        grp = self._get(wire, "/apis/kubeflow.org")
        assert {v["version"] for v in grp["versions"]} == {"v1"}
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                wire.url + "/apis/kubeflow.org/v1beta1", timeout=5)
        assert exc.value.code == 404

    def test_group_versions_and_preferred_with_converter(self):
        from kubeflow_tpu.odh.webhook_server import RemoteConverter

        api = ApiServer()
        bundle = mint_serving_cert()
        whsrv = AdmissionReviewServer([], bundle=bundle).start()
        converter = RemoteConverter(whsrv.url, ca_pem=bundle.ca_cert_pem)
        srv = KubeApiWireServer(api, converter=converter).start()
        try:
            grp = self._get(srv, "/apis/kubeflow.org")
            versions = {v["version"] for v in grp["versions"]}
            assert versions == {"v1", "v1beta1", "v1alpha1"}
            assert grp["preferredVersion"]["version"] == "v1", \
                "storage version is preferred"
            doc = self._get(srv, "/apis/kubeflow.org/v1beta1")
            assert [r["kind"] for r in doc["resources"]] == ["Notebook"]
        finally:
            srv.stop()
            whsrv.stop()

    def test_unknown_paths_still_404(self, wire):
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(wire.url + "/apis/nope.io/v1", timeout=5)
        assert exc.value.code == 404


class TestOpenApi:
    """OpenAPI schema endpoints (docs/wire_compat.md row): /openapi/v2 and
    the v3 discovery root + per-groupVersion docs, with strategic-merge
    metadata that matches what the server's merge engine actually does."""

    @pytest.fixture()
    def wire(self):
        srv = KubeApiWireServer(ApiServer()).start()
        yield srv
        srv.stop()

    def _get(self, srv, path):
        with urllib.request.urlopen(srv.url + path, timeout=5) as resp:
            return json.loads(resp.read())

    def test_v2_document_shape(self, wire):
        doc = self._get(wire, "/openapi/v2")
        assert doc["swagger"] == "2.0"
        defs = doc["definitions"]
        nb = defs["kubeflow.org.v1.Notebook"]
        assert nb["x-kubernetes-group-version-kind"] == [
            {"group": "kubeflow.org", "kind": "Notebook", "version": "v1"}]
        # collection paths advertised for every served resource
        assert any(p.endswith("/notebooks") for p in doc["paths"])

    def test_v2_merge_metadata_matches_engine(self, wire):
        """The schema's patch metadata must be generated FROM the merge
        engine's tables — a client deriving strategy from this document
        computes the merges the server executes."""
        from kubeflow_tpu.kube.strategicmerge import (
            MERGE_KEYS,
            PRIMITIVE_MERGE_FIELDS,
        )

        defs = self._get(wire, "/openapi/v2")["definitions"]
        node = defs["dev.kubeflow-tpu.MergeAwareObject"]
        props = node["properties"]
        for fname, keys in MERGE_KEYS.items():
            assert props[fname]["x-kubernetes-patch-merge-key"] == keys[0]
            assert props[fname]["x-kubernetes-patch-strategy"] == "merge"
            # self-referential: nested lists resolve merge keys at depth
            assert props[fname]["items"]["$ref"].endswith("MergeAwareObject")
        for fname in PRIMITIVE_MERGE_FIELDS:
            assert props[fname]["x-kubernetes-patch-strategy"] == "merge"
            assert "x-kubernetes-patch-merge-key" not in props[fname]

    def test_openapi_agrees_with_discovery_on_alias_versions(self, wire):
        """Without a conversion webhook the alias versions 404 on the data
        path; discovery hides them and OpenAPI must agree — a
        schema-driven client must never target a groupVersion the server
        can't serve."""
        defs = self._get(wire, "/openapi/v2")["definitions"]
        assert "kubeflow.org.v1.Notebook" in defs
        assert "kubeflow.org.v1beta1.Notebook" not in defs
        root = self._get(wire, "/openapi/v3")
        assert "apis/kubeflow.org/v1" in root["paths"]
        assert "apis/kubeflow.org/v1beta1" not in root["paths"]

    def test_v3_root_and_group_docs(self, wire):
        root = self._get(wire, "/openapi/v3")
        assert "apis/kubeflow.org/v1" in root["paths"]
        assert "api/v1" in root["paths"]
        gv = self._get(wire, "/openapi/v3/apis/kubeflow.org/v1")
        assert gv["openapi"].startswith("3.")
        assert "kubeflow.org.v1.Notebook" in gv["components"]["schemas"]
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                wire.url + "/openapi/v3/apis/nope/v9", timeout=5)
        assert exc.value.code == 404


class TestJsonPatch:
    def test_diff_apply_roundtrip(self):
        old = {"a": 1, "b": {"c": [1, 2, 3], "d": "x"}, "gone": True}
        new = {"a": 2, "b": {"c": [1, 9, 3, 4], "d": "x"}, "added": {"e": None}}
        ops = diff(old, new)
        assert apply_patch(old, ops) == new

    def test_escaping(self):
        old = {"metadata": {"annotations": {}}}
        new = {"metadata": {"annotations": {"a/b~c": "v"}}}
        ops = diff(old, new)
        assert apply_patch(old, ops) == new
        assert "~1" in ops[0]["path"] and "~0" in ops[0]["path"]

    def test_list_shrink(self):
        old = {"x": [1, 2, 3, 4]}
        new = {"x": [1]}
        assert apply_patch(old, diff(old, new)) == new

    def test_type_change(self):
        old = {"x": {"y": 1}}
        new = {"x": [1, 2]}
        assert apply_patch(old, diff(old, new)) == new

    def test_test_move_copy_ops(self):
        doc = {"a": {"b": 1}, "c": [1, 2]}
        out = apply_patch(doc, [
            {"op": "test", "path": "/a/b", "value": 1},
            {"op": "copy", "from": "/a/b", "path": "/d"},
            {"op": "move", "from": "/c/0", "path": "/c/-"},
        ])
        assert out == {"a": {"b": 1}, "c": [2, 1], "d": 1}
        from kubeflow_tpu.kube.jsonpatch import PatchTestFailed

        with pytest.raises(PatchTestFailed):
            apply_patch(doc, [{"op": "test", "path": "/a/b", "value": 99}])

    def test_json_patch_over_wire(self, wire):
        """client-go's types.JSONPatchType path: RFC 6902 list body with
        application/json-patch+json (previously 415)."""
        _, _, client = wire
        client.create(make_notebook("jp"))
        patched = client.json_patch("Notebook", "default", "jp", [
            {"op": "add", "path": "/metadata/labels/patched", "value": "yes"},
        ])
        assert patched.metadata.labels["patched"] == "yes"
        # a failed `test` precondition is 422 Invalid, not retried
        from kubeflow_tpu.kube import InvalidError

        with pytest.raises(InvalidError, match="test failed"):
            client.json_patch("Notebook", "default", "jp", [
                {"op": "test", "path": "/metadata/labels/patched",
                 "value": "no"},
                {"op": "remove", "path": "/metadata/labels/patched"},
            ])
        assert client.get("Notebook", "default", "jp") \
            .metadata.labels["patched"] == "yes"


# -- rate limiter -------------------------------------------------------------


class TestRateLimiter:
    def test_burst_then_throttle(self):
        rl = RateLimiter(qps=100.0, burst=5)
        t0 = time.monotonic()
        for _ in range(5):
            rl.acquire()  # burst: no wait
        assert time.monotonic() - t0 < 0.04
        rl.acquire()  # 6th must wait ~10ms for a token
        assert time.monotonic() - t0 >= 0.008

    def test_zero_qps_unlimited(self):
        rl = RateLimiter(qps=0.0, burst=0)
        t0 = time.monotonic()
        for _ in range(1000):
            rl.acquire()
        assert time.monotonic() - t0 < 0.1


class TestReflector410:
    """The reflector must treat a mid-stream ERROR Status event with code
    410 / reason Expired as GoneError (the informer loop then RELISTS,
    never resuming from the dead resourceVersion) — real apiservers send
    exactly this when the watch cache compacts past the client's rv."""

    def _client_with_stream(self, monkeypatch, lines):
        client = KubeClient(RestConfig(server="http://127.0.0.1:1"))

        class FakeResp:
            status = 200

            def readline(self):
                return lines.pop(0) if lines else b""

            def read(self):
                return b""

        class FakeConn:
            sock = None

            def request(self, *a, **kw):
                pass

            def getresponse(self):
                return FakeResp()

            def close(self):
                pass

        monkeypatch.setattr(client, "_connect",
                            lambda timeout: FakeConn())
        return client

    def test_error_410_event_raises_gone(self, monkeypatch):
        from kubeflow_tpu.kube.client import _Informer

        lines = [json.dumps({
            "type": "ERROR",
            "object": {"kind": "Status", "code": 410, "reason": "Expired",
                       "message": "too old resource version 5"},
        }).encode() + b"\n"]
        client = self._client_with_stream(monkeypatch, lines)
        info = client.scheme_registry.by_kind("Pod")
        inf = _Informer("Pod", thread=None)
        with pytest.raises(GoneError):
            client._watch_stream(info, 5, inf)

    def test_error_event_without_410_is_server_error(self, monkeypatch):
        from kubeflow_tpu.kube.client import _Informer
        from kubeflow_tpu.kube.errors import ServerError

        lines = [json.dumps({
            "type": "ERROR",
            "object": {"kind": "Status", "code": 500,
                       "message": "internal"},
        }).encode() + b"\n"]
        client = self._client_with_stream(monkeypatch, lines)
        info = client.scheme_registry.by_kind("Pod")
        inf = _Informer("Pod", thread=None)
        with pytest.raises(ServerError):
            client._watch_stream(info, 5, inf)


class TestAuditLog:
    """The wire server's request-audit trail (envtest audit-log analog,
    odh suite_test.go:126-156): one JSONL line per request."""

    def test_requests_recorded(self, tmp_path):
        import json as _json

        from kubeflow_tpu.kube import ApiServer
        from kubeflow_tpu.kube.wire import KubeApiWireServer

        audit = tmp_path / "audit.jsonl"
        srv = KubeApiWireServer(ApiServer(), audit_log=str(audit)).start()
        try:
            import urllib.request

            with urllib.request.urlopen(srv.url + "/api/v1") as resp:
                assert resp.status == 200
            try:
                urllib.request.urlopen(
                    srv.url + "/api/v1/namespaces/default/configmaps/nope")
            except urllib.error.HTTPError as err:
                assert err.code == 404
        finally:
            srv.stop()
        lines = [_json.loads(ln) for ln in
                 audit.read_text().splitlines()]
        assert len(lines) == 2
        assert lines[0]["verb"] == "GET" and lines[0]["code"] == 200
        assert lines[1]["path"].endswith("/configmaps/nope")
        assert lines[1]["code"] == 404
        assert all("ts" in ln for ln in lines)


class TestOpenApiCrdFieldModels:
    """Per-field type models come from stored CRD objects — a created
    CustomResourceDefinition's openAPIV3Schema replaces the generic
    spec/status nodes for its kind, as on a real apiserver."""

    def test_notebook_spec_fields_served_from_crd(self):
        from kubeflow_tpu.deploy.manifests import notebook_crd
        from kubeflow_tpu.kube.meta import KubeObject

        api = ApiServer()
        api.create(KubeObject.from_dict(notebook_crd(
            conversion_webhook=False)))
        srv = KubeApiWireServer(api).start()
        try:
            with urllib.request.urlopen(srv.url + "/openapi/v2",
                                        timeout=5) as resp:
                doc = json.loads(resp.read())
        finally:
            srv.stop()
        nb = doc["definitions"]["kubeflow.org.v1.Notebook"]
        spec_props = nb["properties"]["spec"]["properties"]
        # the CRD's per-field models, not the generic merge node
        assert "tpu" in spec_props
        tpu = spec_props["tpu"]["properties"]
        assert {"accelerator", "topology", "slices"} <= set(tpu)

    def test_without_crd_generic_node_stays(self):
        srv = KubeApiWireServer(ApiServer()).start()
        try:
            with urllib.request.urlopen(srv.url + "/openapi/v2",
                                        timeout=5) as resp:
                doc = json.loads(resp.read())
        finally:
            srv.stop()
        nb = doc["definitions"]["kubeflow.org.v1.Notebook"]
        assert "$ref" in nb["properties"]["spec"]
