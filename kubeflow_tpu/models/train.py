"""Sharded training loop: init, train step, MFU accounting.

The in-notebook training harness for the BASELINE workloads: pjit-style
automatic SPMD — parameters and optimizer state sharded by the logical rules
in parallel.sharding, activations constrained inside the model — plus the
MFU math the north-star metric is measured with (BASELINE.md).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax.training import train_state
from jax.sharding import Mesh

from ..parallel.sharding import DEFAULT_RULES, logical_sharding
from .configs import TransformerConfig
from .transformer import Transformer


class TrainState(train_state.TrainState):
    pass


@dataclass
class TrainSetup:
    """Everything a notebook needs to run sharded steps."""

    mesh: Mesh
    model: nn.Module
    state: TrainState
    state_shardings: Any
    train_step: Callable[[TrainState, dict], tuple[TrainState, dict]]
    config: TransformerConfig


def default_optimizer(
    learning_rate: float = 3e-4,
    warmup_steps: int = 100,
    total_steps: int = 10_000,
    weight_decay: float = 0.1,
    max_grad_norm: float = 1.0,
    mu_dtype: Optional[str] = None,
) -> optax.GradientTransformation:
    """AdamW with warmup-cosine.  mu_dtype="bfloat16" halves the
    first-moment HBM (the second moment stays fp32 for numerics) — the
    standard knob for fitting bigger batches on one chip."""
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1)
    )
    return optax.chain(
        optax.clip_by_global_norm(max_grad_norm),
        optax.adamw(schedule, b1=0.9, b2=0.95, weight_decay=weight_decay,
                    mu_dtype=mu_dtype),
    )


def cross_entropy_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token NLL in fp32.  Targets are inputs shifted by the
    caller; full [B, S] weight (no padding in the bench path)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def chunked_cross_entropy(
    hidden: jax.Array,
    targets: jax.Array,
    head_kernel: jax.Array,
    num_chunks: int,
    softcap: float = 0.0,
) -> jax.Array:
    """Mean NLL computed per sequence chunk without ever materializing the
    full [tokens, vocab] fp32 logits.

    Each chunk's logits are produced, reduced to NLL, and (thanks to
    `jax.checkpoint`) recomputed in the backward pass — peak HBM for the
    loss drops from tokens*vocab*4B to tokens/num_chunks*vocab*4B, which is
    what lets single-chip batches grow past the logits wall.  hidden:
    [B, S, D]; head_kernel: [D, V] (transposed embed table when tied)."""
    batch, seq, dim = hidden.shape
    tokens = batch * seq
    if tokens % num_chunks != 0:
        raise ValueError(f"{tokens} tokens not divisible by {num_chunks} chunks")
    h = hidden.reshape(num_chunks, tokens // num_chunks, dim)
    t = targets.reshape(num_chunks, tokens // num_chunks)

    @jax.checkpoint
    def chunk_nll(h_c, t_c):
        # bf16 matmul with fp32 accumulation, matching the dense lm_head
        logits = jnp.einsum(
            "nd,dv->nv",
            h_c,
            head_kernel.astype(h_c.dtype),
            preferred_element_type=jnp.float32,
        )
        if softcap > 0.0:
            logits = jnp.tanh(logits / softcap) * softcap
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.sum(jnp.take_along_axis(logp, t_c[..., None], axis=-1))

    def body(carry, xs):
        h_c, t_c = xs
        return carry + chunk_nll(h_c, t_c), None

    # inside a pipeline stage the accumulator joins a carry varying over
    # the manual axis; match VMA types (shared helper with the engines)
    from ..parallel.pipeline import match_vma

    total, _ = jax.lax.scan(body, match_vma(jnp.float32(0.0), hidden), (h, t))
    return total / tokens


def make_pipeline_forward(model: nn.Module, mesh: Mesh,
                          microbatches: int):
    """Forward pass with the layer stack run as a GPipe pipeline
    (parallel.pipeline): embed and head use the model's methods, the stack
    applies one DecoderLayer per local layer under the pipeline schedule.
    Parameters are the SAME tree as the single-program path — "layers" is
    simply sharded stage-wise by rules_for_mesh."""
    from ..parallel.pipeline import gpipe
    from .transformer import DecoderLayer

    cfg = model.cfg
    if not cfg.scan_layers:
        raise ValueError("pipeline parallelism requires scan_layers=True "
                         "(the stacked layers axis is what gets staged)")
    moe = cfg.moe_experts > 0
    template = DecoderLayer(cfg, model.mesh)

    def forward(params, tokens, return_hidden: bool = False):
        """Returns (out, aux) matching the return_aux=True model path."""
        x = model.apply({"params": params}, tokens, method="embed_tokens")

        def apply_one(layer_params, x_mb):
            positions = jnp.broadcast_to(
                jnp.arange(x_mb.shape[1]), x_mb.shape[:2])
            # logical rules OFF inside the stage body: the engine owns
            # pipeline placement, and flax's logical constraints (written
            # for the global view) misvalidate under the partially-manual
            # mesh; dp/fsdp/tp sharding still flows from input shardings
            # through the auto axes
            with nn.logical_axis_rules(()):
                return template.apply({"params": layer_params}, x_mb,
                                      positions)

        from .transformer import _REMAT_POLICIES

        # unbox: the sliced per-layer params must not carry the stacked
        # tree's ("layers", ...) partition metadata — the engine owns the
        # stage placement, and a stale box would re-constrain rank-reduced
        # slices with the stacked spec
        result = gpipe(apply_one, nn.unbox(params["layers"]), x, mesh,
                       microbatches, remat_layer=cfg.remat,
                       remat_policy=_REMAT_POLICIES[cfg.remat_policy](),
                       layer_has_aux=moe)
        x, aux = result if moe else (result, jnp.float32(0.0))
        out = model.apply({"params": params}, x, return_hidden,
                          method="head")
        return out, aux

    return forward


def make_1f1b_train_step(model: nn.Module, optimizer, rules=DEFAULT_RULES,
                         mesh: Optional[Mesh] = None,
                         pipeline_microbatches: int = 0):
    """Train step on the 1F1B pipeline engine (parallel.pipeline.
    pipeline_1f1b): the engine owns the schedule AND the gradients, so
    this step assembles the grad tree manually instead of differentiating
    a forward — embed gradients come from an outer vjp fed the engine's
    input cotangent, head/final-norm gradients from the engine's in-
    schedule loss vjp, layer gradients stage-sharded from the engine."""
    from ..parallel.pipeline import pipeline_1f1b
    from .transformer import _REMAT_POLICIES, DecoderLayer

    cfg = model.cfg
    if not cfg.scan_layers:
        raise ValueError("pipeline parallelism requires scan_layers=True")
    moe = cfg.moe_experts > 0
    loss_chunks = cfg.loss_chunks or 1
    microbatches = pipeline_microbatches or 2 * int(mesh.shape["pipeline"])
    template = DecoderLayer(cfg, model.mesh)

    def apply_one(layer_params, x_mb):
        positions = jnp.broadcast_to(
            jnp.arange(x_mb.shape[1]), x_mb.shape[:2])
        with nn.logical_axis_rules(()):
            return template.apply({"params": layer_params}, x_mb, positions)

    def head_loss(hp, y_mb, t_mb):
        # final norm (model.head with return_hidden) + chunked CE against
        # the LM head kernel — the per-microbatch mean loss whose vjp is
        # what enters the backward ring on the last stage
        with nn.logical_axis_rules(()):
            hidden = model.apply({"params": hp}, y_mb, True, method="head")
            if cfg.tie_embeddings:
                kernel = nn.unbox(hp["embed"]["embedding"]).T
            else:
                kernel = nn.unbox(hp["lm_head"]["kernel"])
            return chunked_cross_entropy(hidden, t_mb, kernel, loss_chunks,
                                         cfg.logits_softcap)

    def step(state: TrainState, batch: dict) -> tuple[TrainState, dict]:
        params = state.params
        head_keys = ["final_norm"] + (
            ["embed"] if cfg.tie_embeddings else ["lm_head"])
        hp = {k: params[k] for k in head_keys}

        with nn.logical_axis_rules(list(rules)):
            x, embed_vjp = jax.vjp(
                lambda ep: model.apply({"params": {"embed": ep}},
                                       batch["inputs"],
                                       method="embed_tokens"),
                params["embed"])
            loss, aux, dlayers, dhead, dx = pipeline_1f1b(
                apply_one, nn.unbox(params["layers"]), head_loss, hp,
                x, batch["targets"], mesh, microbatches,
                remat_layer=cfg.remat,
                remat_policy=_REMAT_POLICIES[cfg.remat_policy](),
                layer_has_aux=moe, aux_weight=cfg.moe_aux_weight)
            (dembed,) = embed_vjp(dx)

        # rebox the raw layer grads with the stacked tree's partitioning
        # metadata so the grad tree mirrors the (boxed) param tree
        def rebox(box, g):
            if isinstance(box, nn.Partitioned):
                return box.replace_boxed(g)
            return g

        grads = {
            "embed": dembed,
            "layers": jax.tree.map(
                rebox, params["layers"], dlayers,
                is_leaf=lambda b: isinstance(b, nn.Partitioned)),
            **{k: dhead[k] for k in head_keys if k not in ("embed",)},
        }
        if cfg.tie_embeddings:
            # the embedding gets cotangents from both uses (lookup + head)
            grads["embed"] = jax.tree.map(
                lambda a, b: a + b, grads["embed"], dhead["embed"])
        new_state = state.apply_gradients(grads=grads)
        total = loss + cfg.moe_aux_weight * aux if moe else loss
        metrics = {
            "loss": total,
            "grad_norm": optax.global_norm(grads),
            "step": state.step,
        }
        if moe:
            metrics["ce_loss"] = loss
            metrics["moe_aux_loss"] = aux
        return new_state, metrics

    return step


def make_train_step(model: nn.Module, optimizer, rules=DEFAULT_RULES,
                    mesh: Optional[Mesh] = None,
                    pipeline_microbatches: int = 0,
                    pipeline_schedule: str = "gpipe"):
    cfg = getattr(model, "cfg", None)
    loss_chunks = getattr(cfg, "loss_chunks", 0) or 0
    moe = getattr(cfg, "moe_experts", 0) > 0
    stages = int(mesh.shape.get("pipeline", 1)) if mesh is not None else 1
    if stages > 1 and pipeline_schedule == "1f1b":
        return make_1f1b_train_step(model, optimizer, rules, mesh,
                                    pipeline_microbatches)
    if pipeline_schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline schedule {pipeline_schedule!r}")
    if stages > 1:
        microbatches = pipeline_microbatches or 2 * stages
        forward = make_pipeline_forward(model, mesh, microbatches)
    else:
        def forward(params, tokens, return_hidden=False):
            out, aux = model.apply({"params": params}, tokens,
                                   return_hidden=return_hidden,
                                   return_aux=True)
            return out, aux

    def step(state: TrainState, batch: dict) -> tuple[TrainState, dict]:
        def loss_fn(params):
            if loss_chunks > 0:
                hidden, aux = forward(params, batch["inputs"],
                                      return_hidden=True)
                if cfg.tie_embeddings:
                    kernel = nn.unbox(params["embed"]["embedding"]).T
                else:
                    kernel = nn.unbox(params["lm_head"]["kernel"])
                ce = chunked_cross_entropy(
                    hidden,
                    batch["targets"],
                    kernel,
                    loss_chunks,
                    cfg.logits_softcap,
                )
            else:
                logits, aux = forward(params, batch["inputs"])
                ce = cross_entropy_loss(logits, batch["targets"])
            total = ce + cfg.moe_aux_weight * aux if moe else ce
            return total, (ce, aux)

        with nn.logical_axis_rules(list(rules)):
            (loss, (ce, aux)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)
        new_state = state.apply_gradients(grads=grads)
        metrics = {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
            "step": state.step,
        }
        if moe:
            metrics["ce_loss"] = ce
            metrics["moe_aux_loss"] = aux
        return new_state, metrics

    return step


def setup_training(
    config: TransformerConfig,
    mesh: Mesh,
    rng: Optional[jax.Array] = None,
    optimizer: Optional[optax.GradientTransformation] = None,
    rules=None,
    batch_shape: Optional[tuple[int, int]] = None,
    pipeline_microbatches: int = 0,
    pipeline_schedule: str = "gpipe",
) -> TrainSetup:
    """Initialize a sharded TrainState on `mesh` and return a jitted train
    step with explicit in/out shardings (single compiled SPMD program; XLA
    inserts the psums/all-gathers the rules imply).  A populated "pipeline"
    mesh axis runs the layer stack under `pipeline_schedule`:
    "gpipe" (default — forward pipeline differentiated by outer AD) or
    "1f1b" (parallel.pipeline.pipeline_1f1b — in-schedule backward,
    activation stash capped at `stages` microbatches), with
    `pipeline_microbatches` microbatches (default 2x stages)."""
    from ..parallel.sharding import rules_for_mesh

    rng = rng if rng is not None else jax.random.PRNGKey(0)
    rules = rules if rules is not None else rules_for_mesh(mesh)
    model = Transformer(config, mesh)
    batch_shape = batch_shape or (max(len(mesh.devices.flat), 1), 256)
    sample = jnp.zeros(batch_shape, jnp.int32)
    optimizer = optimizer or default_optimizer()

    def init_fn(rng):
        params = model.init(rng, sample)["params"]
        return TrainState.create(
            apply_fn=model.apply, params=params, tx=optimizer
        )

    with mesh, nn.logical_axis_rules(list(rules)):
        abstract = jax.eval_shape(init_fn, rng)
        # logical names recorded by nn.with_logical_partitioning -> physical
        logical_specs = nn.get_partition_spec(abstract)
        state_shardings = nn.logical_to_mesh_sharding(
            logical_specs, mesh, list(rules)
        )
        state = jax.jit(init_fn, out_shardings=state_shardings)(rng)

        batch_sharding = logical_sharding(mesh, ("batch", None), rules)
        step = jax.jit(
            make_train_step(model, optimizer, rules, mesh=mesh,
                            pipeline_microbatches=pipeline_microbatches,
                            pipeline_schedule=pipeline_schedule),
            in_shardings=(state_shardings, {"inputs": batch_sharding,
                                            "targets": batch_sharding}),
            out_shardings=(state_shardings, None),
            donate_argnums=(0,),
        )
    return TrainSetup(mesh, model, state, state_shardings, step, config)


# -- MFU accounting -------------------------------------------------------------


def model_flops_per_step(config: TransformerConfig, batch: int, seq: int) -> float:
    return config.flops_per_token(seq) * batch * seq


def mfu(
    tokens_per_second: float,
    config: TransformerConfig,
    seq_len: int,
    num_chips: int,
    accelerator: str = "v5e",
) -> float:
    """Achieved fraction of the slice's bf16 peak — ONE definition,
    shared with the worker-side TelemetryAgent and bench.py through
    runtime.roofline so the headline number cannot fork."""
    from ..runtime.roofline import mfu as roofline_mfu

    return roofline_mfu(tokens_per_second, config, seq_len, num_chips,
                        accelerator)


def timed_steps(
    setup: TrainSetup,
    batch: dict,
    num_steps: int = 10,
    warmup: int = 2,
) -> dict:
    """Run steps synchronously and report wall-clock throughput + MFU inputs."""
    state = setup.state
    for _ in range(warmup):
        state, _ = setup.train_step(state, batch)
    jax.block_until_ready(state.params)
    t0 = time.perf_counter()
    for _ in range(num_steps):
        state, metrics = setup.train_step(state, batch)
    loss = float(jax.block_until_ready(metrics["loss"]))
    dt = time.perf_counter() - t0
    setup.state = state
    b, s = batch["inputs"].shape
    step_time = dt / num_steps
    return {
        "loss": loss,
        "step_time_s": step_time,
        "tokens_per_s": b * s / step_time,
        "flops_per_step": model_flops_per_step(setup.config, b, s),
    }
