"""Speculative decoding: draft gamma tokens, verify in one target pass.

Two modes:
- `speculative_generate` — greedy (temperature 0): the longest agreeing
  prefix is accepted; EXACT by construction (token-identical to the
  target's own greedy decode).
- `speculative_sample` — temperature > 0 serving via the standard
  rejection-sampling rule (Leviathan et al. 2023): accept draft token x_i
  with probability min(1, p_i(x_i)/q_i(x_i)); at the first rejection,
  resample from the normalized residual max(0, p_i - q_i).  The emitted
  tokens are distributed EXACTLY as target-only sampling — the draft
  changes speed, never the distribution (tests/test_speculative.py pins
  this with a chi-square gate against enumerated target marginals).


Serving accelerator for the in-notebook compute plane: a small DRAFT
model proposes `gamma` greedy tokens autoregressively; the TARGET model
scores all of them in ONE forward (gamma+1 positions through its KV
cache); the longest prefix where the target's greedy choice agrees is
accepted, plus one corrected token from the target.  Greedy speculative
decoding is EXACT — the emitted sequence equals the target's own greedy
decode no matter how bad the draft is; the draft only changes speed
(per outer step the target does one multi-token pass instead of
accepted+1 single-token passes, and decode is weight-bandwidth bound, so
a gamma-token pass costs nearly the same as a 1-token pass).

This framework's KV-cache design makes the rewind free: the cache is a
static ring indexed by a scalar `cache_index`, and causality masks
positions >= the query's global offset, so rejecting draft tokens is a
pure index reset — stale entries beyond the index are masked until
overwritten (models/transformer.py decode path).

Batch semantics: acceptance is the MINIMUM across rows.  That stays
exact (rows that would have accepted more agreed with the target at the
correction position anyway, so the emitted token is identical) and keeps
one scalar cache index; it is conservative in speed only.

The reference ships no inference path (SURVEY.md §2.5); this extends the
serving story alongside int8 weight streaming (models/quant.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .configs import TransformerConfig
from .generate import prepare_decode
from .transformer import Transformer


def _rewind(cache, new_index):
    """Set every layer's scalar cache_index (a pure pytree update — the
    ring's stale tail is masked by causality until overwritten)."""
    def fix(path, leaf):
        if path and getattr(path[-1], "key", None) == "cache_index":
            return jnp.full_like(leaf, new_index)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


def speculative_generate(
    target_cfg: TransformerConfig,
    target_params,
    draft_cfg: TransformerConfig,
    draft_params,
    prompt: jax.Array,
    max_new_tokens: int,
    gamma: int = 4,
):
    """prompt [B, P] -> ([B, P + max_new_tokens] greedy tokens,
    outer_steps) — token-identical to `generate(target_cfg, ...)` with
    temperature=0; `outer_steps` (a traced scalar) is the number of
    draft-verify rounds, the speed diagnostic.  A round emits at most
    gamma tokens (gamma-1 accepted + 1 correction, see the acceptance
    cap below) and the first token comes from prefill, so the ideal is
    ceil((N-1)/gamma) rounds at full acceptance, N-1 at zero."""
    if gamma < 2:
        raise ValueError("gamma must be >= 2 (acceptance caps at gamma-1)")
    t_cfg, target_params = prepare_decode(target_cfg, target_params)
    d_cfg, draft_params = prepare_decode(draft_cfg, draft_params)
    # staged KV writes assume a forward-only fill; the rewind would have
    # to re-seed the stage from the main cache — keep the simple path
    t_cfg = t_cfg.with_(staged_kv=False)
    d_cfg = d_cfg.with_(staged_kv=False)
    batch, prompt_len = prompt.shape
    total = prompt_len + max_new_tokens
    # the verify pass appends up to gamma+1 positions past the last
    # accepted token, so the ring needs headroom past `total`
    t_cfg = t_cfg.with_(max_seq_len=total + gamma + 1)
    d_cfg = d_cfg.with_(max_seq_len=total + gamma + 1)
    target = Transformer(t_cfg)
    draft = Transformer(d_cfg)

    # prefill both caches on the prompt; the first emitted token is the
    # target's greedy continuation
    (t_logits, _), t_cache = target.apply(
        {"params": target_params}, prompt, return_aux=True, decode=True,
        mutable=["cache"])
    (_, _), d_cache = draft.apply(
        {"params": draft_params}, prompt, return_aux=True, decode=True,
        mutable=["cache"])
    first = jnp.argmax(t_logits[:, -1, :], axis=-1)  # [B]

    tokens = jnp.zeros((batch, total + gamma + 1), jnp.int32)
    tokens = tokens.at[:, :prompt_len].set(prompt)
    tokens = tokens.at[:, prompt_len].set(first)

    def position(n):  # [B, 1] global position for a single-token step
        return jnp.broadcast_to(n, (batch, 1))

    def draft_one(cache, tok, pos):
        (logits, _), new_cache = draft.apply(
            {"params": draft_params, **cache}, tok[:, None],
            return_aux=True, decode=True, positions=position(pos),
            mutable=["cache"])
        return new_cache, jnp.argmax(logits[:, -1, :], axis=-1)

    def body(carry):
        tokens, t_cache, d_cache, n, steps = carry
        # n = index of the next token to produce; tokens[:, n-1] is the
        # last accepted token.  Draft gamma greedy continuations.
        def scan_step(c, i):
            cache, tok = c
            # tok is the token AT position n-1+i; its consumption writes
            # cache index n-1+i (kept aligned by the rewinds)
            cache, nxt = draft_one(cache, tok, n - 1 + i)
            return (cache, nxt), nxt

        last = tokens[jnp.arange(batch), n - 1]
        (d_cache2, _), proposals = jax.lax.scan(
            scan_step, (d_cache, last), jnp.arange(gamma))
        proposals = jnp.moveaxis(proposals, 0, 1)       # [B, gamma]

        # one target pass over [last, proposals]: logits[i] scores the
        # continuation AFTER consuming token i of the block
        block = jnp.concatenate([last[:, None], proposals], axis=1)
        positions = n - 1 + jnp.broadcast_to(
            jnp.arange(gamma + 1), (batch, gamma + 1))
        (logits, _), t_cache2 = target.apply(
            {"params": target_params, **t_cache}, block, return_aux=True,
            decode=True, positions=positions, mutable=["cache"])
        greedy = jnp.argmax(logits, axis=-1)            # [B, gamma+1]

        agree = (greedy[:, :gamma] == proposals)
        m = jnp.min(jnp.sum(jnp.cumprod(agree.astype(jnp.int32),
                                        axis=1), axis=1))
        # cap at gamma-1: the draft only consumed its first gamma-1
        # proposals (it never sees its own last one), so accepting all
        # gamma would leave position n+gamma-1 missing from the draft
        # cache after the rewind.  Costs at most one token per round.
        m = jnp.minimum(m, gamma - 1)
        # emit the m accepted proposals + the target's correction; exact
        # for every row (rows accepting > m agreed at position m anyway)
        width = tokens.shape[1]
        col = jnp.arange(width)[None, :]
        sel = (col >= n) & (col <= n + m)
        src_idx = jnp.clip(col - n, 0, gamma - 1)
        # place proposals[:, col - n] wherever sel; gather along axis 1
        gathered = jnp.take_along_axis(
            proposals, jnp.broadcast_to(src_idx, (batch, width)), axis=1)
        # correction token sits at n+m regardless of how many proposals
        # were accepted
        corr = greedy[jnp.arange(batch), m]
        gathered = jnp.where(col == n + m, corr[:, None], gathered)
        tokens = jnp.where(sel, gathered, tokens)

        # rewind both caches to the accepted frontier: the target
        # consumed gamma+1 positions from n-1, the draft gamma from n
        t_cache2 = _rewind(t_cache2, n + m)
        d_cache2 = _rewind(d_cache2, n + m)
        return tokens, t_cache2, d_cache2, n + m + 1, steps + 1

    def cond(carry):
        *_, n, _steps = carry
        return n < total

    tokens, _, _, n, steps = jax.lax.while_loop(
        cond, body, (tokens, t_cache, d_cache,
                     jnp.int32(prompt_len + 1), jnp.int32(0)))
    return tokens[:, :total], steps


def speculative_sample(
    target_cfg: TransformerConfig,
    target_params,
    draft_cfg: TransformerConfig,
    draft_params,
    prompt: jax.Array,
    max_new_tokens: int,
    gamma: int = 4,
    temperature: float = 1.0,
    rng: jax.Array | None = None,
):
    """Temperature-sampling speculative decode.

    prompt [B, P] -> ([B, P + max_new_tokens] tokens, outer_steps,
    accept_rate).  Emitted tokens are distributed exactly as the target's
    own temperature sampling; `accept_rate` is the fraction of drafted
    tokens accepted (the speed diagnostic: speedup ~ (m+1)/round).

    Batch semantics: the round advances by m = min over rows of the
    per-row accepted-prefix length (capped at gamma-1, same draft-cache
    argument as the greedy path).  At position n+m a row that REJECTED
    there emits the residual resample; a row that accepted x_m emits x_m
    itself.  Rows that had accepted beyond m simply regenerate those
    positions with fresh randomness next round — conditioned on the
    prefix the regenerated tokens have the same law, so per-row
    exactness survives the shared frontier."""
    if gamma < 2:
        raise ValueError("gamma must be >= 2 (acceptance caps at gamma-1)")
    if temperature <= 0.0:
        raise ValueError("temperature must be > 0; use "
                         "speculative_generate for greedy")
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    t_cfg, target_params = prepare_decode(target_cfg, target_params)
    d_cfg, draft_params = prepare_decode(draft_cfg, draft_params)
    t_cfg = t_cfg.with_(staged_kv=False)
    d_cfg = d_cfg.with_(staged_kv=False)
    batch, prompt_len = prompt.shape
    total = prompt_len + max_new_tokens
    t_cfg = t_cfg.with_(max_seq_len=total + gamma + 1)
    d_cfg = d_cfg.with_(max_seq_len=total + gamma + 1)
    target = Transformer(t_cfg)
    draft = Transformer(d_cfg)
    inv_t = 1.0 / temperature

    (t_logits, _), t_cache = target.apply(
        {"params": target_params}, prompt, return_aux=True, decode=True,
        mutable=["cache"])
    (_, _), d_cache = draft.apply(
        {"params": draft_params}, prompt, return_aux=True, decode=True,
        mutable=["cache"])
    rng, k_first = jax.random.split(rng)
    first = jax.random.categorical(
        k_first, t_logits[:, -1, :].astype(jnp.float32) * inv_t, axis=-1)

    tokens = jnp.zeros((batch, total + gamma + 1), jnp.int32)
    tokens = tokens.at[:, :prompt_len].set(prompt)
    tokens = tokens.at[:, prompt_len].set(first)

    def position(n):
        return jnp.broadcast_to(n, (batch, 1))

    def draft_one(cache, tok, pos, key):
        (logits, _), new_cache = draft.apply(
            {"params": draft_params, **cache}, tok[:, None],
            return_aux=True, decode=True, positions=position(pos),
            mutable=["cache"])
        row = logits[:, -1, :].astype(jnp.float32) * inv_t
        q = jax.nn.softmax(row, axis=-1)
        nxt = jax.random.categorical(key, row, axis=-1)
        return new_cache, nxt, q

    def body(carry):
        tokens, t_cache, d_cache, n, steps, accepted, rng = carry
        rng, k_draft, k_accept, k_res = jax.random.split(rng, 4)

        def scan_step(c, inp):
            cache, tok = c
            i, key = inp
            cache, nxt, q = draft_one(cache, tok, n - 1 + i, key)
            return (cache, nxt), (nxt, q)

        last = tokens[jnp.arange(batch), n - 1]
        (d_cache2, _), (proposals, qs) = jax.lax.scan(
            scan_step, (d_cache, last),
            (jnp.arange(gamma), jax.random.split(k_draft, gamma)))
        proposals = jnp.moveaxis(proposals, 0, 1)        # [B, gamma]
        qs = jnp.moveaxis(qs, 0, 1)                      # [B, gamma, V]

        block = jnp.concatenate([last[:, None], proposals], axis=1)
        positions = n - 1 + jnp.broadcast_to(
            jnp.arange(gamma + 1), (batch, gamma + 1))
        (logits, _), t_cache2 = target.apply(
            {"params": target_params, **t_cache}, block, return_aux=True,
            decode=True, positions=positions, mutable=["cache"])
        p = jax.nn.softmax(logits.astype(jnp.float32) * inv_t, axis=-1)

        # accept x_i w.p. min(1, p_i(x_i)/q_i(x_i))
        p_prop = jnp.take_along_axis(
            p[:, :gamma], proposals[..., None], axis=-1)[..., 0]
        q_prop = jnp.take_along_axis(
            qs, proposals[..., None], axis=-1)[..., 0]
        u = jax.random.uniform(k_accept, (batch, gamma))
        accept = u * q_prop < p_prop                      # [B, gamma]
        acc_count = jnp.sum(
            jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)  # [B]
        m = jnp.minimum(jnp.min(acc_count), gamma - 1)

        # residual resample at position m for rows that rejected there
        p_m = jax.lax.dynamic_index_in_dim(p, m, axis=1, keepdims=False)
        q_m = jax.lax.dynamic_index_in_dim(qs, m, axis=1, keepdims=False)
        residual = jnp.maximum(p_m - q_m, 0.0)
        res_sum = jnp.sum(residual, axis=-1, keepdims=True)
        # p == q makes the residual empty; rejection then has probability
        # 0, but guard the log anyway by falling back to p
        residual = jnp.where(res_sum > 0.0, residual / res_sum, p_m)
        x_res = jax.random.categorical(
            k_res, jnp.log(residual + 1e-30), axis=-1)
        rejected_here = acc_count == m
        prop_m = jax.lax.dynamic_index_in_dim(
            proposals, m, axis=1, keepdims=False)
        emit_m = jnp.where(rejected_here, x_res, prop_m)

        width = tokens.shape[1]
        col = jnp.arange(width)[None, :]
        sel = (col >= n) & (col <= n + m)
        src_idx = jnp.clip(col - n, 0, gamma - 1)
        gathered = jnp.take_along_axis(
            proposals, jnp.broadcast_to(src_idx, (batch, width)), axis=1)
        gathered = jnp.where(col == n + m, emit_m[:, None], gathered)
        tokens = jnp.where(sel, gathered, tokens)

        t_cache2 = _rewind(t_cache2, n + m)
        d_cache2 = _rewind(d_cache2, n + m)
        return (tokens, t_cache2, d_cache2, n + m + 1, steps + 1,
                accepted + m, rng)

    def cond(carry):
        _, _, _, n, *_ = carry
        return n < total

    tokens, _, _, n, steps, accepted, _ = jax.lax.while_loop(
        cond, body, (tokens, t_cache, d_cache,
                     jnp.int32(prompt_len + 1), jnp.int32(0),
                     jnp.int32(0), rng))
    accept_rate = accepted.astype(jnp.float32) / jnp.maximum(
        steps.astype(jnp.float32) * gamma, 1.0)
    return tokens[:, :total], steps, accept_rate


__all__ = ["speculative_generate", "speculative_sample"]
