"""MoE + expert parallelism: routing, dispatch/combine, training.

The load-bearing check is dispatch-identity: with every expert holding
IDENTICAL weights and generous capacity, the MoE layer must reproduce a
plain dense FFN exactly (the combine weights sum to 1 per token) — a wrong
position calculation, capacity mask, or combine einsum breaks equality.
Expert-sharded training must then match the unsharded run, the same bar as
the multichip dryrun.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubeflow_tpu.models.configs import TINY
from kubeflow_tpu.models.moe import MoEMLP, load_balance_loss
from kubeflow_tpu.models.train import setup_training


from kubeflow_tpu.parallel.mesh import MeshConfig, make_mesh
from kubeflow_tpu.parallel.sharding import rules_for_mesh


def const_opt():
    """Plain constant-lr SGD for update-equivalence checks: the training
    default's warmup starts at lr=0 (zero first update — vacuous
    comparison), and one-step Adam is ~lr*sign(grad), so fp32 noise on
    near-zero gradients flips signs into 2*lr param diffs; under SGD the
    parameter delta is proportional to the gradient."""
    return optax.sgd(0.05)

MOE_TINY = TINY.with_(moe_experts=4, moe_top_k=2, moe_capacity_factor=2.0)


class TestMoELayer:
    def _layer(self, cfg, x, rng=0):
        import flax.linen as nn

        mod = MoEMLP(cfg)
        with nn.logical_axis_rules(list(rules_for_mesh(
                make_mesh(MeshConfig(data=8))))):
            params = mod.init(jax.random.PRNGKey(rng), x)["params"]
            return mod, params

    def test_forward_shape_and_aux(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, TINY.embed_dim))
        mod, params = self._layer(MOE_TINY, x)
        out, aux = mod.apply({"params": params}, x)
        assert out.shape == x.shape
        assert jnp.isfinite(out).all()
        # aux >= 1 with equality only under perfectly uniform routing
        assert 0.9 < float(aux) < MOE_TINY.moe_experts + 1

    def test_identical_experts_match_dense_ffn(self):
        """All experts equal + capacity ample -> MoE == one dense FFN."""
        import flax.linen as nn

        cfg = MOE_TINY.with_(moe_capacity_factor=8.0)
        x = jax.random.normal(jax.random.PRNGKey(2), (2, 16, cfg.embed_dim))
        mod, params = self._layer(cfg, x)
        # overwrite every expert's stack with expert 0's weights
        experts = nn.unbox(params["experts"])
        tied = jax.tree.map(
            lambda a: jnp.broadcast_to(a[0], a.shape), experts)
        params = {**params, "experts": tied}
        out, _ = mod.apply({"params": params}, x)

        def dense_ffn(x):
            one = jax.tree.map(lambda a: a[0], tied)
            gate = x @ one["gate"]["kernel"]
            up = x @ one["up"]["kernel"]
            return (jax.nn.silu(gate) * up) @ one["down"]["kernel"]

        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(dense_ffn(x)),
                                   rtol=2e-5, atol=2e-5)

    def test_capacity_drops_are_passthrough_not_nan(self):
        cfg = MOE_TINY.with_(moe_capacity_factor=0.1)  # starve capacity
        x = jax.random.normal(jax.random.PRNGKey(3), (2, 32, cfg.embed_dim))
        mod, params = self._layer(cfg, x)
        out, aux = mod.apply({"params": params}, x)
        assert jnp.isfinite(out).all() and jnp.isfinite(aux)
        # dropped tokens produce zero MLP output (residual carries them)
        norms = jnp.linalg.norm(out, axis=-1).ravel()
        assert float(jnp.min(norms)) == pytest.approx(0.0, abs=1e-6)

    def test_sort_dispatch_matches_einsum(self):
        """With ample capacity (no drops) the sort-based dispatch routes
        identically to the one-hot einsum path: same outputs, same
        gradients, same params tree — it only skips the dispatch FLOPs."""
        ein = MOE_TINY.with_(moe_capacity_factor=8.0)
        srt = ein.with_(moe_dispatch="sort")
        x = jax.random.normal(jax.random.PRNGKey(5), (2, 16, ein.embed_dim))
        mod_e, params = self._layer(ein, x)
        mod_s = MoEMLP(srt)

        out_e, aux_e = mod_e.apply({"params": params}, x)
        out_s, aux_s = mod_s.apply({"params": params}, x)
        np.testing.assert_allclose(np.asarray(out_e), np.asarray(out_s),
                                   rtol=2e-5, atol=2e-5)
        assert float(aux_e) == pytest.approx(float(aux_s), rel=1e-6)

        def loss(mod):
            return lambda p: jnp.sum(mod.apply({"params": p}, x)[0] ** 2)

        g_e = jax.grad(loss(mod_e))(params)
        g_s = jax.grad(loss(mod_s))(params)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
            jax.tree.map(lambda v: v, g_e), jax.tree.map(lambda v: v, g_s))

    def test_sort_dispatch_drops_when_oversubscribed(self):
        cfg = MOE_TINY.with_(moe_dispatch="sort", moe_capacity_factor=0.1)
        x = jax.random.normal(jax.random.PRNGKey(6), (2, 32, cfg.embed_dim))
        mod, params = self._layer(cfg, x)
        out, aux = mod.apply({"params": params}, x)
        assert jnp.isfinite(out).all() and jnp.isfinite(aux)
        norms = jnp.linalg.norm(out, axis=-1).ravel()
        assert float(jnp.min(norms)) == pytest.approx(0.0, abs=1e-6)

    def test_load_balance_loss_uniform_is_one(self):
        probs = jnp.full((128, 4), 0.25)
        mask = jax.nn.one_hot(jnp.arange(128) % 4, 4)
        assert float(load_balance_loss(probs, mask)) == pytest.approx(1.0, rel=1e-5)
        # collapsed routing scores worse
        collapsed = jax.nn.one_hot(jnp.zeros(128, jnp.int32), 4)
        peaky = jnp.concatenate([jnp.full((128, 1), 0.97),
                                 jnp.full((128, 3), 0.01)], axis=-1)
        assert float(load_balance_loss(peaky, collapsed)) > 2.0


class TestMoETraining:
    def test_expert_parallel_step_matches_unsharded(self):
        """ep=4 vs single device: same loss, same parameter updates."""
        batch_shape = (8, 64)
        data = {"inputs": jax.random.randint(jax.random.PRNGKey(5),
                                             batch_shape, 0, TINY.vocab_size)}
        data["targets"] = jnp.roll(data["inputs"], -1, axis=1)

        ref_mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
        ref = setup_training(MOE_TINY, ref_mesh, batch_shape=batch_shape,
                             optimizer=const_opt())
        # host copy BEFORE the step: train_step donates the input state
        init_leaf = np.asarray(
            jax.device_get(jax.tree_util.tree_leaves(ref.state.params)[0]))
        ref_state, ref_metrics = ref.train_step(ref.state, data)
        # the comparison must not be vacuous: the step moved the weights
        new_leaf = np.asarray(jax.device_get(
            jax.tree_util.tree_leaves(ref_state.params)[0]))
        assert float(np.max(np.abs(new_leaf - init_leaf))) > 0.0

        ep_mesh = make_mesh(MeshConfig(data=-1, expert=4))
        ep = setup_training(MOE_TINY, ep_mesh, batch_shape=batch_shape,
                            optimizer=const_opt())
        ep_state, ep_metrics = ep.train_step(ep.state, data)

        assert abs(float(ep_metrics["loss"]) -
                   float(ref_metrics["loss"])) < 1e-4
        assert "moe_aux_loss" in ep_metrics
        mismatch = []

        def cmp(path, a, b):
            if not np.allclose(a, b, rtol=1e-4, atol=1e-4):
                mismatch.append(jax.tree_util.keystr(path))

        jax.tree_util.tree_map_with_path(
            cmp, jax.device_get(ref_state.params),
            jax.device_get(ep_state.params))
        assert not mismatch, mismatch

    def test_moe_learns_on_fixed_batch(self):
        mesh = make_mesh(MeshConfig(data=-1, expert=2, tensor=2))
        setup = setup_training(MOE_TINY, mesh, batch_shape=(8, 64))
        data = {"inputs": jax.random.randint(jax.random.PRNGKey(7), (8, 64),
                                             0, TINY.vocab_size)}
        data["targets"] = jnp.roll(data["inputs"], -1, axis=1)
        state = setup.state
        first = None
        for _ in range(5):
            state, metrics = setup.train_step(state, data)
            if first is None:
                first = float(metrics["ce_loss"])
        assert float(metrics["ce_loss"]) < first

    def test_moe_under_pipeline_matches_single_program(self):
        """pp=2 over MoE layers: the CE loss and parameter updates must
        match the plain run; the aux term is threaded through the GPipe
        carry with bubble masking and agrees up to the documented
        per-microbatch estimator difference (mean of per-group f·P
        products vs product of global means — parallel.pipeline.gpipe)."""
        batch_shape = (8, 64)
        data = {"inputs": jax.random.randint(jax.random.PRNGKey(9),
                                             batch_shape, 0, TINY.vocab_size)}
        data["targets"] = jnp.roll(data["inputs"], -1, axis=1)

        # parameter comparison runs with the aux WEIGHT off: the pipelined
        # aux is a per-microbatch estimator (documented in gpipe), so its
        # gradient differs legitimately; the CE gradient path through the
        # pipelined MoE layers must be exact
        cfg = MOE_TINY.with_(moe_aux_weight=0.0)
        plain_mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
        plain = setup_training(cfg, plain_mesh, batch_shape=batch_shape,
                               optimizer=const_opt())
        plain_state, pm = plain.train_step(plain.state, data)

        pp_mesh = make_mesh(MeshConfig(data=-1, pipeline=2))
        pp = setup_training(cfg, pp_mesh, batch_shape=batch_shape,
                            pipeline_microbatches=4, optimizer=const_opt())
        pp_state, m = pp.train_step(pp.state, data)

        assert abs(float(m["ce_loss"]) - float(pm["ce_loss"])) < 1e-4
        # the aux STATISTIC still agrees within the estimator bound
        assert abs(float(m["moe_aux_loss"]) - float(pm["moe_aux_loss"])) \
            < 0.05 * float(pm["moe_aux_loss"])
        mismatch = []

        def cmp(path, a, b):
            if not np.allclose(a, b, rtol=1e-4, atol=1e-4):
                mismatch.append(jax.tree_util.keystr(path))

        jax.tree_util.tree_map_with_path(
            cmp, jax.device_get(plain_state.params),
            jax.device_get(pp_state.params))
        assert not mismatch, mismatch

    def test_moe_flops_accounting_counts_activated_only(self):
        dense = TINY
        moe = TINY.with_(moe_experts=8, moe_top_k=2)
        assert moe.num_params > dense.num_params  # all experts are params
        # activated FLOPs: k=2 experts ~= 2x the dense MLP, not 8x
        f_dense = dense.flops_per_token(64)
        f_moe = moe.flops_per_token(64)
        assert f_moe < dense.flops_per_token(64) * 3
        assert f_moe > f_dense


class TestHybridDispatch:
    """The round-5 gather-combine path must be bit-for-bit routing-
    equivalent to the GShard einsum path — INCLUDING capacity drops
    (same per-row cumsum positions), outputs, and router gradients."""

    def _layer(self, cfg, x):
        mod = MoEMLP(cfg)
        params = mod.init(jax.random.PRNGKey(0), x)["params"]
        return mod, params

    @pytest.mark.parametrize("cf", [8.0, 1.0, 0.4])
    def test_hybrid_matches_einsum(self, cf):
        ein = MOE_TINY.with_(moe_capacity_factor=cf)
        hyb = ein.with_(moe_dispatch="hybrid")
        x = jax.random.normal(jax.random.PRNGKey(7), (2, 16, ein.embed_dim))
        mod_e, params = self._layer(ein, x)
        mod_h = MoEMLP(hyb)

        out_e, aux_e = mod_e.apply({"params": params}, x)
        out_h, aux_h = mod_h.apply({"params": params}, x)
        np.testing.assert_allclose(np.asarray(out_e), np.asarray(out_h),
                                   rtol=2e-5, atol=2e-5)
        assert float(aux_e) == pytest.approx(float(aux_h), rel=1e-6)

        def loss(mod):
            return lambda p: jnp.sum(mod.apply({"params": p}, x)[0] ** 2)

        g_e = jax.grad(loss(mod_e))(params)
        g_h = jax.grad(loss(mod_h))(params)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
            g_e, g_h)

    def test_hybrid_trains_in_the_full_model(self):
        from kubeflow_tpu.models.train import default_optimizer, setup_training
        from kubeflow_tpu.parallel.mesh import MeshConfig, make_mesh

        cfg = MOE_TINY.with_(moe_dispatch="hybrid")
        mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
        setup = setup_training(cfg, mesh, optimizer=default_optimizer(),
                               batch_shape=(2, 16))
        data = {"inputs": jax.random.randint(
            jax.random.PRNGKey(0), (2, 16), 0, cfg.vocab_size)}
        data["targets"] = jnp.roll(data["inputs"], -1, axis=1)
        state, metrics = setup.train_step(setup.state, data)
        assert jnp.isfinite(metrics["loss"])
