"""Mutating/validating webhook tests — exercised *through the API server*,
as the reference does (webhook behavior asserted by creating Notebooks and
observing the stored mutation, odh suite_test.go:121-124 +
notebook_mutating_webhook_test.go)."""

import base64

import pytest

from kubeflow_tpu.api.types import Notebook, TPUSpec
from kubeflow_tpu.core.notebook_controller import setup_core_controllers
from kubeflow_tpu.kube import (
    ApiServer,
    FakeCluster,
    ForbiddenError,
    KubeObject,
    Manager,
    ObjectMeta,
)
from kubeflow_tpu.odh import constants as C
from kubeflow_tpu.odh.controller import setup_odh_controllers
from kubeflow_tpu.utils import tracing
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.config import CoreConfig, OdhConfig

CENTRAL_NS = "opendatahub"

def fake_cert(tag: bytes = b"") -> str:
    """Minimal structurally-valid PEM (base64 DER starting with a SEQUENCE
    tag); `tag` is embedded in the payload so merged bundles can be
    checked for WHICH source contributed."""
    der = b"\x30\x82\x01\x0a" + tag + b"\x00" * (32 - len(tag))
    return ("-----BEGIN CERTIFICATE-----\n"
            + base64.b64encode(der).decode()
            + "\n-----END CERTIFICATE-----")


FAKE_CERT = fake_cert()


def make_env(**cfg_kwargs):
    api = ApiServer()
    cluster = FakeCluster(api)
    cluster.add_node("cpu-node", allocatable={"cpu": "64", "memory": "256Gi"})
    mgr = Manager(api, clock=FakeClock())
    cfg = OdhConfig(controller_namespace=CENTRAL_NS, **cfg_kwargs)
    setup_core_controllers(mgr, CoreConfig())
    setup_odh_controllers(mgr, cfg)
    return api, cluster, mgr, cfg


@pytest.fixture()
def env():
    return make_env()


def make_cm(api, name, key, value, ns="user1"):
    """Source-ConfigMap helper shared by the CA-bundle/runtime-image
    scenario classes."""
    api.create(KubeObject(
        api_version="v1", kind="ConfigMap",
        metadata=ObjectMeta(name=name, namespace=ns),
        body={"data": {key: value}}))


def create_nb(api, mgr, name="wb", ns="user1", annotations=None, labels=None,
              tpu=None, pod_spec=None):
    nb = Notebook.new(name, ns, tpu=tpu, pod_spec=pod_spec,
                      annotations=annotations, labels=labels)
    api.create(nb.obj)
    mgr.run_until_idle()
    return api.get("Notebook", ns, name)


class TestReconciliationLock:
    def test_lock_injected_then_removed(self, env):
        api, _, mgr, _ = env
        nb = Notebook.new("wb", "user1")
        created = api.create(nb.obj)
        # webhook stamped the lock before storage
        assert created.metadata.annotations[C.STOP_ANNOTATION] == (
            C.RECONCILIATION_LOCK_VALUE
        )
        mgr.run_until_idle()
        # ODH controller removed it once its objects were ready
        live = api.get("Notebook", "user1", "wb")
        assert C.STOP_ANNOTATION not in live.metadata.annotations
        # and the workload scaled up
        sts = api.get("StatefulSet", "user1", "wb")
        assert sts.spec["replicas"] == 1

    def test_lock_not_reapplied_on_update(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr)
        nb = api.get("Notebook", "user1", "wb")
        nb.metadata.labels["touched"] = "true"
        api.update(nb)
        live = api.get("Notebook", "user1", "wb")
        assert C.STOP_ANNOTATION not in live.metadata.annotations


class TestTpuImageSwap:
    def test_default_swap(self, env):
        api, _, mgr, _ = env
        live = create_nb(
            api, mgr,
            tpu=TPUSpec("v5e", "2x2"),
            pod_spec={"containers": [{"name": "wb", "image": "cuda-notebook:1"}]},
        )
        image = Notebook(live).pod_spec["containers"][0]["image"]
        assert image == "jupyter-tpu-jax:latest"

    def test_mapped_swap(self):
        api, _, mgr, _ = make_env(
            tpu_image_map={"cuda-notebook:1": "tpu-notebook:9"}
        )
        live = create_nb(
            api, mgr,
            tpu=TPUSpec("v5e", "2x2"),
            pod_spec={"containers": [{"name": "wb", "image": "cuda-notebook:1"}]},
        )
        assert Notebook(live).pod_spec["containers"][0]["image"] == "tpu-notebook:9"

    def test_tpu_image_kept(self, env):
        api, _, mgr, _ = env
        live = create_nb(
            api, mgr,
            tpu=TPUSpec("v5e", "2x2"),
            pod_spec={"containers": [{"name": "wb", "image": "my-jax-image:2"}]},
        )
        assert Notebook(live).pod_spec["containers"][0]["image"] == "my-jax-image:2"

    def test_cpu_notebook_untouched(self, env):
        api, _, mgr, _ = env
        live = create_nb(
            api, mgr,
            pod_spec={"containers": [{"name": "wb", "image": "minimal:1"}]},
        )
        assert Notebook(live).pod_spec["containers"][0]["image"] == "minimal:1"


class TestImageStreamResolution:
    def _make_imagestream(self, api, ns=CENTRAL_NS):
        api.create(KubeObject(
            api_version="image.openshift.io/v1",
            kind="ImageStream",
            metadata=ObjectMeta(name="datascience-notebook", namespace=ns),
            body={
                "status": {
                    "tags": [
                        {
                            "tag": "2024.1",
                            "items": [
                                {
                                    "created": "2024-01-01T00:00:00Z",
                                    "dockerImageReference": "registry/ds@sha256:old",
                                },
                                {
                                    "created": "2024-06-01T00:00:00Z",
                                    "dockerImageReference": "registry/ds@sha256:new",
                                },
                            ],
                        }
                    ]
                }
            },
        ))

    def test_resolves_most_recent_tag_item(self, env):
        api, _, mgr, _ = env
        self._make_imagestream(api)
        live = create_nb(
            api, mgr,
            annotations={C.ANNOTATION_LAST_IMAGE_SELECTION: "datascience-notebook:2024.1"},
            pod_spec={"containers": [{
                "name": "wb", "image": "stale",
                "env": [{"name": "JUPYTER_IMAGE", "value": "x"}],
            }]},
        )
        main = Notebook(live).pod_spec["containers"][0]
        assert main["image"] == "registry/ds@sha256:new"
        assert {"name": "JUPYTER_IMAGE", "value": "datascience-notebook:2024.1"} in main["env"]

    def test_internal_registry_untouched(self, env):
        api, _, mgr, _ = env
        self._make_imagestream(api)
        image = "image-registry.openshift-image-registry.svc:5000/ns/img:1"
        live = create_nb(
            api, mgr,
            annotations={C.ANNOTATION_LAST_IMAGE_SELECTION: "datascience-notebook:2024.1"},
            pod_spec={"containers": [{"name": "wb", "image": image}]},
        )
        assert Notebook(live).pod_spec["containers"][0]["image"] == image

    def test_missing_imagestream_records_span_event(self, env):
        api, _, mgr, _ = env
        exporter = tracing.InMemorySpanExporter()
        tracing.set_exporter(exporter)
        try:
            create_nb(
                api, mgr,
                annotations={C.ANNOTATION_LAST_IMAGE_SELECTION: "nope:1"},
                pod_spec={"containers": [{"name": "wb", "image": "stale"}]},
            )
            assert "ImageStreamNotFound" in exporter.events()
        finally:
            tracing.set_exporter(None)


class TestCABundle:
    def _install_bundles(self, api, ns="user1"):
        api.create(KubeObject(
            api_version="v1", kind="ConfigMap",
            metadata=ObjectMeta(name=C.ODH_TRUSTED_CA_BUNDLE_CONFIGMAP, namespace=ns),
            body={"data": {"ca-bundle.crt": FAKE_CERT, "odh-ca-bundle.crt": ""}},
        ))
        api.create(KubeObject(
            api_version="v1", kind="ConfigMap",
            metadata=ObjectMeta(name=C.KUBE_ROOT_CA_CONFIGMAP, namespace=ns),
            body={"data": {"ca.crt": FAKE_CERT}},
        ))

    def test_workbench_bundle_built_and_mounted(self, env):
        api, _, mgr, _ = env
        self._install_bundles(api)
        create_nb(api, mgr, name="first")  # first notebook builds the CM
        cm = api.get("ConfigMap", "user1", C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP)
        bundle = cm.body["data"]["ca-bundle.crt"]
        assert bundle.count("BEGIN CERTIFICATE") == 2
        # the bundle now exists, so the next notebook mounts it at CREATE
        live = create_nb(api, mgr, name="wb")
        spec = Notebook(live).pod_spec
        vols = [v["name"] for v in spec.get("volumes", [])]
        assert C.TRUSTED_CA_BUNDLE_VOLUME in vols
        main = spec["containers"][0]
        env_names = {e["name"] for e in main.get("env", [])}
        assert set(C.CA_BUNDLE_ENV_VARS) <= env_names

    def test_invalid_pem_skipped(self, env):
        api, _, mgr, _ = env
        api.create(KubeObject(
            api_version="v1", kind="ConfigMap",
            metadata=ObjectMeta(name=C.ODH_TRUSTED_CA_BUNDLE_CONFIGMAP, namespace="user1"),
            body={"data": {"ca-bundle.crt": "not a certificate"}},
        ))
        create_nb(api, mgr)
        cm = api.try_get("ConfigMap", "user1", C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP)
        assert cm is None

    def test_cert_config_unset_when_cm_deleted(self, env):
        api, _, mgr, _ = env
        self._install_bundles(api)
        create_nb(api, mgr, name="first")  # builds workbench-trusted-ca-bundle
        live = create_nb(api, mgr, name="wb")  # mounts it at CREATE
        vols = [v["name"] for v in Notebook(live).pod_spec.get("volumes", [])]
        assert C.TRUSTED_CA_BUNDLE_VOLUME in vols
        # delete sources + the derived bundle; controller strips the mount
        api.delete("ConfigMap", "user1", C.ODH_TRUSTED_CA_BUNDLE_CONFIGMAP)
        api.delete("ConfigMap", "user1", C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP)
        mgr.run_until_idle()
        spec = Notebook(api.get("Notebook", "user1", "wb")).pod_spec
        vols = [v["name"] for v in spec.get("volumes", [])]
        assert C.TRUSTED_CA_BUNDLE_VOLUME not in vols
        env_names = {e["name"] for e in spec["containers"][0].get("env", [])}
        assert not (set(C.CA_BUNDLE_ENV_VARS) & env_names)


class TestAuthSidecar:
    def test_sidecar_injected(self, env):
        api, _, mgr, _ = env
        live = create_nb(api, mgr, annotations={C.ANNOTATION_INJECT_AUTH: "true"})
        spec = Notebook(live).pod_spec
        sidecar = next(
            c for c in spec["containers"] if c["name"] == "kube-rbac-proxy"
        )
        assert any("--secure-listen-address=0.0.0.0:8443" in a for a in sidecar["args"])
        assert sidecar["resources"]["requests"] == {"cpu": "100m", "memory": "64Mi"}
        assert sidecar["resources"]["limits"] == {"cpu": "100m", "memory": "64Mi"}
        vols = {v["name"] for v in spec["volumes"]}
        assert {"kube-rbac-proxy-config", "kube-rbac-proxy-tls-certificates"} <= vols
        assert spec["serviceAccountName"] == "wb"

    def test_sidecar_resources_from_annotations(self, env):
        api, _, mgr, _ = env
        live = create_nb(api, mgr, annotations={
            C.ANNOTATION_INJECT_AUTH: "true",
            C.ANNOTATION_AUTH_SIDECAR_CPU_REQUEST: "250m",
            C.ANNOTATION_AUTH_SIDECAR_MEMORY_LIMIT: "256Mi",
        })
        sidecar = next(
            c for c in Notebook(live).pod_spec["containers"]
            if c["name"] == "kube-rbac-proxy"
        )
        assert sidecar["resources"]["requests"]["cpu"] == "250m"
        assert sidecar["resources"]["limits"]["cpu"] == "250m"
        assert sidecar["resources"]["limits"]["memory"] == "256Mi"
        assert sidecar["resources"]["requests"]["memory"] == "64Mi"

    def test_invalid_resources_denied(self, env):
        api, _, mgr, _ = env
        nb = Notebook.new("wb", "user1", annotations={
            C.ANNOTATION_INJECT_AUTH: "true",
            C.ANNOTATION_AUTH_SIDECAR_CPU_REQUEST: "not-a-quantity",
        })
        with pytest.raises(ForbiddenError):
            api.create(nb.obj)


class TestRestartBlocking:
    def _running_nb(self, api, mgr, cfg_env):
        api_, _, mgr_, _ = cfg_env
        return create_nb(api_, mgr_)

    def test_webhook_only_change_blocked(self, env):
        api, _, mgr, cfg = env
        create_nb(api, mgr)
        # a config change makes the webhook want to mutate the pod spec of
        # the RUNNING notebook: flip the default TPU image via feast label?
        # Simplest: install a CA bundle after creation -> webhook would mount
        api.create(KubeObject(
            api_version="v1", kind="ConfigMap",
            metadata=ObjectMeta(name=C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP,
                                namespace="user1"),
            body={"data": {"ca-bundle.crt": FAKE_CERT}},
        ))
        # user touches only metadata -> webhook mutation must be blocked
        nb = api.get("Notebook", "user1", "wb")
        nb.metadata.labels["touch"] = "1"
        api.update(nb)
        live = api.get("Notebook", "user1", "wb")
        spec = Notebook(live).pod_spec
        vols = [v["name"] for v in spec.get("volumes", [])]
        assert C.TRUSTED_CA_BUNDLE_VOLUME not in vols
        pending = live.metadata.annotations[C.ANNOTATION_UPDATE_PENDING]
        assert pending  # human-readable first difference recorded

    def test_user_pod_change_not_blocked(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr)
        api.create(KubeObject(
            api_version="v1", kind="ConfigMap",
            metadata=ObjectMeta(name=C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP,
                                namespace="user1"),
            body={"data": {"ca-bundle.crt": FAKE_CERT}},
        ))
        nb = api.get("Notebook", "user1", "wb")
        Notebook(nb).pod_spec["containers"][0]["image"] = "new-image:2"
        api.update(nb)
        live = api.get("Notebook", "user1", "wb")
        spec = Notebook(live).pod_spec
        vols = [v["name"] for v in spec.get("volumes", [])]
        assert C.TRUSTED_CA_BUNDLE_VOLUME in vols  # mutation went through
        assert C.ANNOTATION_UPDATE_PENDING not in live.metadata.annotations

    def test_stopped_notebook_not_blocked(self, env):
        api, _, mgr, _ = env
        create_nb(api, mgr)
        api.create(KubeObject(
            api_version="v1", kind="ConfigMap",
            metadata=ObjectMeta(name=C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP,
                                namespace="user1"),
            body={"data": {"ca-bundle.crt": FAKE_CERT}},
        ))
        nb = api.get("Notebook", "user1", "wb")
        nb.metadata.annotations[C.STOP_ANNOTATION] = "2024-01-01T00:00:00Z"
        api.update(nb)
        live = api.get("Notebook", "user1", "wb")
        vols = [v["name"] for v in Notebook(live).pod_spec.get("volumes", [])]
        assert C.TRUSTED_CA_BUNDLE_VOLUME in vols

    def test_tpu_topology_change_not_blocked(self, env):
        api, _, mgr, _ = env
        create_nb(
            api, mgr, tpu=TPUSpec("v5e", "2x2"),
            pod_spec={"containers": [{"name": "wb", "image": "my-jax-image:1"}]},
        )
        api.create(KubeObject(
            api_version="v1", kind="ConfigMap",
            metadata=ObjectMeta(name=C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP,
                                namespace="user1"),
            body={"data": {"ca-bundle.crt": FAKE_CERT}},
        ))
        nb = api.get("Notebook", "user1", "wb")
        nb.spec["tpu"]["topology"] = "2x4"
        api.update(nb)
        live = api.get("Notebook", "user1", "wb")
        # topology edit restarts anyway -> webhook mutations pass through
        vols = [v["name"] for v in Notebook(live).pod_spec.get("volumes", [])]
        assert C.TRUSTED_CA_BUNDLE_VOLUME in vols
        assert C.ANNOTATION_UPDATE_PENDING not in live.metadata.annotations


class TestFeast:
    def test_mount_and_unmount(self, env):
        api, _, mgr, _ = env
        live = create_nb(api, mgr, labels={C.LABEL_FEAST_INTEGRATION: "true"})
        spec = Notebook(live).pod_spec
        assert any(v["name"] == C.FEAST_VOLUME_NAME for v in spec["volumes"])
        mount = next(
            m for m in spec["containers"][0]["volumeMounts"]
            if m["name"] == C.FEAST_VOLUME_NAME
        )
        assert mount["mountPath"] == C.FEAST_MOUNT_PATH
        # remove the label -> unmount (pod change is user-visible: restart ok)
        nb = api.get("Notebook", "user1", "wb")
        del nb.metadata.labels[C.LABEL_FEAST_INTEGRATION]
        nb.metadata.annotations[C.STOP_ANNOTATION] = "stopped"
        api.update(nb)
        spec = Notebook(api.get("Notebook", "user1", "wb")).pod_spec
        assert not any(
            v["name"] == C.FEAST_VOLUME_NAME for v in spec.get("volumes", [])
        )


class TestMLflow:
    def _gateway(self, api):
        api.create(KubeObject(
            api_version="gateway.networking.k8s.io/v1", kind="Gateway",
            metadata=ObjectMeta(name="data-science-gateway", namespace="openshift-ingress"),
            body={"spec": {"listeners": [{"hostname": "apps.example.com"}]}},
        ))

    def test_env_vars_injected(self):
        api, _, mgr, _ = make_env(mlflow_enabled=True)
        self._gateway(api)
        live = create_nb(api, mgr, annotations={C.ANNOTATION_MLFLOW_INSTANCE: "team-a"})
        env_vars = {
            e["name"]: e["value"]
            for e in Notebook(live).pod_spec["containers"][0]["env"]
        }
        assert env_vars[C.MLFLOW_TRACKING_URI_ENV] == "https://apps.example.com/mlflow-team-a"
        assert env_vars[C.MLFLOW_K8S_INTEGRATION_ENV] == "true"
        assert env_vars[C.MLFLOW_TRACKING_AUTH_ENV] == "kubernetes-namespaced"

    def test_rolebinding_waits_for_clusterrole(self):
        api, _, mgr, _ = make_env(mlflow_enabled=True, gateway_url="apps.example.com")
        create_nb(api, mgr, annotations={C.ANNOTATION_MLFLOW_INSTANCE: "mlflow"})
        assert api.try_get("RoleBinding", "user1", "wb-mlflow") is None
        assert mgr.pending_delayed()  # requeued until the ClusterRole exists
        api.create(KubeObject(
            api_version="rbac.authorization.k8s.io/v1", kind="ClusterRole",
            metadata=ObjectMeta(name=C.MLFLOW_CLUSTER_ROLE),
            body={"rules": []},
        ))
        mgr.advance(31)
        rb = api.get("RoleBinding", "user1", "wb-mlflow")
        assert rb.body["roleRef"]["name"] == C.MLFLOW_CLUSTER_ROLE

    def test_validating_webhook_blocks_annotation_removal(self):
        api, _, mgr, _ = make_env(mlflow_enabled=True, gateway_url="apps.example.com")
        api.create(KubeObject(
            api_version="rbac.authorization.k8s.io/v1", kind="ClusterRole",
            metadata=ObjectMeta(name=C.MLFLOW_CLUSTER_ROLE),
            body={"rules": []},
        ))
        create_nb(api, mgr, annotations={C.ANNOTATION_MLFLOW_INSTANCE: "mlflow"})
        nb = api.get("Notebook", "user1", "wb")
        del nb.metadata.annotations[C.ANNOTATION_MLFLOW_INSTANCE]
        with pytest.raises(ForbiddenError):
            api.update(nb)
        # stopped notebooks may remove it
        nb = api.get("Notebook", "user1", "wb")
        nb.metadata.annotations[C.STOP_ANNOTATION] = "stopped"
        api.update(nb)
        nb = api.get("Notebook", "user1", "wb")
        del nb.metadata.annotations[C.ANNOTATION_MLFLOW_INSTANCE]
        api.update(nb)  # no raise


class TestClusterProxyEnv:
    """HTTP(S)_PROXY/NO_PROXY injection from the cluster Proxy CR under
    INJECT_CLUSTER_PROXY_ENV (notebook_mutating_webhook.go:648-698)."""

    @pytest.fixture()
    def proxy_env(self):
        return make_env(inject_cluster_proxy_env=True)

    def _proxy_cr(self, api, http="http://proxy:3128",
                  https="https://proxy:3129", no="cluster.local"):
        api.create(KubeObject(
            api_version="config.openshift.io/v1", kind="Proxy",
            metadata=ObjectMeta(name="cluster"),
            body={"status": {"httpProxy": http, "httpsProxy": https,
                             "noProxy": no}}))

    def test_env_injected_from_proxy_status(self, proxy_env):
        api, _, mgr, _ = proxy_env
        self._proxy_cr(api)
        live = create_nb(api, mgr)
        env = {e["name"]: e["value"]
               for e in Notebook(live).pod_spec["containers"][0]["env"]}
        assert env["HTTP_PROXY"] == "http://proxy:3128"
        assert env["HTTPS_PROXY"] == "https://proxy:3129"
        assert env["NO_PROXY"] == "cluster.local"

    def test_user_value_overwritten_empty_skipped(self, proxy_env):
        api, _, mgr, _ = proxy_env
        self._proxy_cr(api, https="", no="")
        live = create_nb(api, mgr, pod_spec={"containers": [{
            "name": "wb",
            "env": [{"name": "HTTP_PROXY", "value": "http://stale:1"}]}]})
        env_list = Notebook(live).pod_spec["containers"][0]["env"]
        # the stale entry is updated IN PLACE — assert on the whole list so
        # an append-instead-of-overwrite regression (duplicate env var)
        # cannot hide behind a last-wins dict collapse
        assert env_list == [
            {"name": "HTTP_PROXY", "value": "http://proxy:3128"},
        ], env_list

    def test_no_proxy_cr_is_noop(self, proxy_env):
        api, _, mgr, _ = proxy_env
        live = create_nb(api, mgr)
        env = {e["name"] for e in
               Notebook(live).pod_spec["containers"][0].get("env", [])}
        assert not ({"HTTP_PROXY", "HTTPS_PROXY", "NO_PROXY"} & env)

    def test_disabled_by_default(self, env):
        api, _, mgr, _ = env
        self._proxy_cr(api)
        live = create_nb(api, mgr)
        names = {e["name"] for e in
                 Notebook(live).pod_spec["containers"][0].get("env", [])}
        assert "HTTP_PROXY" not in names


class TestCABundleSources:
    """The workbench bundle merges THREE namespace ConfigMaps
    (notebook_controller.go:549-635): odh-trusted-ca-bundle (gate),
    kube-root-ca.crt, openshift-service-ca.crt."""

    def test_three_sources_each_contribute_once(self, env):
        api, _, mgr, _ = env
        odh, root, svc = (fake_cert(b"odh"), fake_cert(b"root"),
                          fake_cert(b"svc"))
        make_cm(api, C.ODH_TRUSTED_CA_BUNDLE_CONFIGMAP,
                C.TRUSTED_CA_BUNDLE_FILE, odh)
        make_cm(api, C.KUBE_ROOT_CA_CONFIGMAP, "ca.crt", root)
        make_cm(api, C.OPENSHIFT_SERVICE_CA_CONFIGMAP, "service-ca.crt", svc)
        create_nb(api, mgr)
        bundle = api.get("ConfigMap", "user1",
                         C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP)
        merged = bundle.body["data"][C.TRUSTED_CA_BUNDLE_FILE]
        for cert in (odh, root, svc):
            assert merged.count(cert) == 1, "each source exactly once"

    def test_absent_odh_bundle_gates_everything(self, env):
        # without odh-trusted-ca-bundle, cert injection is someone else's
        # job — the other two sources alone must NOT produce a bundle
        api, _, mgr, _ = env
        make_cm(api, C.KUBE_ROOT_CA_CONFIGMAP, "ca.crt", FAKE_CERT)
        make_cm(api, C.OPENSHIFT_SERVICE_CA_CONFIGMAP, "service-ca.crt",
                FAKE_CERT)
        create_nb(api, mgr)
        assert api.try_get("ConfigMap", "user1",
                           C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP) is None

    def test_empty_odh_key_means_injector_handles_it(self, env):
        api, _, mgr, _ = env
        make_cm(api, C.ODH_TRUSTED_CA_BUNDLE_CONFIGMAP,
                C.TRUSTED_CA_BUNDLE_FILE, "")
        make_cm(api, C.KUBE_ROOT_CA_CONFIGMAP, "ca.crt", FAKE_CERT)
        create_nb(api, mgr)
        assert api.try_get("ConfigMap", "user1",
                           C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP) is None


class TestFirstDifference:
    """FirstDifferenceReporter analog (notebook_mutating_webhook.go:601-646):
    the update-pending annotation carries ONE human-readable difference."""

    def test_nested_dict_path(self):
        from kubeflow_tpu.odh.diff import first_difference

        a = {"spec": {"containers": [{"name": "wb", "image": "jupyter:1"}]}}
        b = {"spec": {"containers": [{"name": "wb", "image": "jupyter:2"}]}}
        msg = first_difference(a, b)
        assert msg == ".spec.containers[0].image: 'jupyter:1' != 'jupyter:2'"

    def test_absent_key_and_list_growth(self):
        from kubeflow_tpu.odh.diff import first_difference

        assert "<absent>" in first_difference({"a": 1}, {})
        msg = first_difference({"envs": [1]}, {"envs": [1, 2]})
        assert msg == ".envs[1]: <absent> != 2"

    def test_type_change_reported_not_crash(self):
        from kubeflow_tpu.odh.diff import first_difference

        msg = first_difference({"replicas": 1}, {"replicas": "1"})
        assert ".replicas" in msg and "1" in msg

    def test_equal_structures_empty(self):
        from kubeflow_tpu.odh.diff import first_difference

        assert first_difference({"x": [1, {"y": 2}]}, {"x": [1, {"y": 2}]}) == ""


def make_dspa(ns="user1", **overrides):
    """A structurally-valid DSPA CR (notebook_dspa_secret.go test fixtures)."""
    spec = {
        "objectStorage": {
            "externalStorage": {
                "host": "minio.svc",
                "scheme": "http",
                "bucket": "pipelines",
                "s3CredentialSecret": {
                    "secretName": "s3-creds",
                    "accessKey": "AWS_ACCESS_KEY_ID",
                    "secretKey": "AWS_SECRET_ACCESS_KEY",
                },
            }
        }
    }
    spec.update(overrides)
    return KubeObject(
        api_version="datasciencepipelinesapplications.opendatahub.io/v1",
        kind="DataSciencePipelinesApplication",
        metadata=ObjectMeta(name="dspa", namespace=ns),
        body={
            "spec": spec,
            "status": {"components": {"apiServer": {
                "externalUrl": "https://dspa.apps/pipelines"}}},
        },
    )


def make_s3_secret(ns="user1"):
    return KubeObject(
        api_version="v1", kind="Secret",
        metadata=ObjectMeta(name="s3-creds", namespace=ns),
        body={"data": {
            "AWS_ACCESS_KEY_ID": base64.b64encode(b"minio-user").decode(),
            "AWS_SECRET_ACCESS_KEY": base64.b64encode(b"minio-pass").decode(),
        }},
    )


class TestElyraSecret:
    """ds-pipeline-config Secret from the namespace DSPA CR
    (notebook_dspa_secret.go:189-477)."""

    @pytest.fixture()
    def elyra_env(self):
        return make_env(set_pipeline_secret=True)

    def test_secret_built_from_dspa_and_mounted(self, elyra_env):
        import json as _json

        api, _, mgr, _ = elyra_env
        api.create(make_dspa())
        api.create(make_s3_secret())
        live = create_nb(api, mgr)
        secret = api.get("Secret", "user1", C.ELYRA_SECRET_NAME)
        payload = _json.loads(base64.b64decode(
            secret.body["data"][C.ELYRA_SECRET_KEY]))
        md = payload["metadata"]
        assert payload["schema_name"] == "kfp"
        assert md["api_endpoint"] == "https://dspa.apps/pipelines"
        assert md["cos_endpoint"] == "http://minio.svc"
        assert md["cos_bucket"] == "pipelines"
        assert md["cos_username"] == "minio-user", "creds decoded from Secret"
        assert md["cos_password"] == "minio-pass"
        # owned by the DSPA, not the notebook: dies with the DSPA
        (ref,) = secret.metadata.owner_references
        assert ref.kind == "DataSciencePipelinesApplication"
        # webhook mounted it at the Elyra runtimes path
        spec = Notebook(live).pod_spec
        assert any(v["name"] == C.ELYRA_VOLUME_NAME
                   for v in spec["volumes"])
        mount = next(m for m in spec["containers"][0]["volumeMounts"]
                     if m["name"] == C.ELYRA_VOLUME_NAME)
        assert mount["mountPath"] == C.ELYRA_MOUNT_PATH

    def test_no_dspa_is_quiet_noop(self, elyra_env):
        api, _, mgr, _ = elyra_env
        create_nb(api, mgr)
        assert api.try_get("Secret", "user1", C.ELYRA_SECRET_NAME) is None

    def test_broken_dspa_does_not_block_admission(self, elyra_env):
        api, _, mgr, _ = elyra_env
        api.create(make_dspa(objectStorage={}))  # unusable: no storage
        live = create_nb(api, mgr)
        assert live is not None, "admission must tolerate a broken DSPA"
        assert api.try_get("Secret", "user1", C.ELYRA_SECRET_NAME) is None
        # the volume still mounts (secret is optional:True), so Elyra
        # starts working the moment the DSPA is fixed
        spec = Notebook(live).pod_spec
        assert any(v["name"] == C.ELYRA_VOLUME_NAME for v in spec["volumes"])

    def test_public_endpoint_from_gateway_listener(self, elyra_env):
        import json as _json

        api, _, mgr, cfg = elyra_env
        api.create(KubeObject(
            api_version="gateway.networking.k8s.io/v1", kind="Gateway",
            metadata=ObjectMeta(name=cfg.gateway_name,
                                namespace=cfg.gateway_namespace),
            body={"spec": {"listeners": [
                {"name": "https", "hostname": "ds.apps.example.com"}]}},
        ))
        api.create(make_dspa())
        api.create(make_s3_secret())
        create_nb(api, mgr)
        secret = api.get("Secret", "user1", C.ELYRA_SECRET_NAME)
        payload = _json.loads(base64.b64decode(
            secret.body["data"][C.ELYRA_SECRET_KEY]))
        assert payload["metadata"]["public_api_endpoint"] == \
            "https://ds.apps.example.com/external/elyra/user1"

    def test_public_endpoint_route_fallback_requires_ownership(
            self, elyra_env):
        import json as _json

        api, _, mgr, cfg = elyra_env
        gw = api.create(KubeObject(
            api_version="gateway.networking.k8s.io/v1", kind="Gateway",
            metadata=ObjectMeta(name=cfg.gateway_name,
                                namespace=cfg.gateway_namespace),
            body={"spec": {"listeners": [{"name": "https"}]}},  # no hostname
        ))
        # an UNRELATED route must not leak into the endpoint
        api.create(KubeObject(
            api_version="route.openshift.io/v1", kind="Route",
            metadata=ObjectMeta(name="stray", namespace=cfg.gateway_namespace),
            body={"spec": {"host": "stray.apps"}}))
        labeled = KubeObject(
            api_version="route.openshift.io/v1", kind="Route",
            metadata=ObjectMeta(
                name="gw-route", namespace=cfg.gateway_namespace,
                labels={"gateway.networking.k8s.io/gateway-name": gw.name}),
            body={"spec": {"host": "gw.apps.example.com"}})
        api.create(labeled)
        api.create(make_dspa())
        api.create(make_s3_secret())
        create_nb(api, mgr)
        secret = api.get("Secret", "user1", C.ELYRA_SECRET_NAME)
        payload = _json.loads(base64.b64decode(
            secret.body["data"][C.ELYRA_SECRET_KEY]))
        assert payload["metadata"]["public_api_endpoint"] == \
            "https://gw.apps.example.com/external/elyra/user1"

    def test_route_fallback_by_owner_uid(self, elyra_env):
        """The Route fallback also accepts routes OWNED by the gateway
        (ownerReference uid match), not just labeled ones
        (notebook_dspa_secret.go:152-186)."""
        from kubeflow_tpu.odh.gateway import get_hostname_for_public_endpoint

        api, _, _, cfg = elyra_env
        gw = api.create(KubeObject(
            api_version="gateway.networking.k8s.io/v1", kind="Gateway",
            metadata=ObjectMeta(name=cfg.gateway_name,
                                namespace=cfg.gateway_namespace),
            body={"spec": {"listeners": [{"name": "https"}]}}))
        # decoy FIRST in list order: owned by some OTHER object — a uid
        # mismatch must be skipped, not treated as "has an owner"
        stranger = api.create(KubeObject(
            api_version="v1", kind="ConfigMap",
            metadata=ObjectMeta(name="stranger",
                                namespace=cfg.gateway_namespace)))
        decoy = KubeObject(
            api_version="route.openshift.io/v1", kind="Route",
            metadata=ObjectMeta(name="a-decoy",
                                namespace=cfg.gateway_namespace),
            body={"spec": {"host": "decoy.apps.example.com"}})
        decoy.metadata.owner_references.append(stranger.owner_reference())
        api.create(decoy)
        owned = KubeObject(
            api_version="route.openshift.io/v1", kind="Route",
            metadata=ObjectMeta(name="gw-owned",
                                namespace=cfg.gateway_namespace),
            body={"spec": {"host": "owned.apps.example.com"}})
        owned.metadata.owner_references.append(gw.owner_reference())
        api.create(owned)
        assert get_hostname_for_public_endpoint(api, cfg) == \
            "owned.apps.example.com"

    def test_secret_updates_when_dspa_changes(self, elyra_env):
        import json as _json

        api, _, mgr, _ = elyra_env
        api.create(make_dspa())
        api.create(make_s3_secret())
        create_nb(api, mgr)
        dspa = api.get("DataSciencePipelinesApplication", "user1", "dspa")
        dspa.spec["objectStorage"]["externalStorage"]["bucket"] = "nextgen"
        api.update(dspa)
        mgr.run_until_idle()
        # a later reconcile (any notebook event) refreshes the payload
        nb = api.get("Notebook", "user1", "wb")
        nb.metadata.labels["touch"] = "1"
        api.update(nb)
        mgr.run_until_idle()
        secret = api.get("Secret", "user1", C.ELYRA_SECRET_NAME)
        payload = _json.loads(base64.b64decode(
            secret.body["data"][C.ELYRA_SECRET_KEY]))
        assert payload["metadata"]["cos_bucket"] == "nextgen"


class TestRuntimeImages:
    def test_key_name_sanitization(self):
        from kubeflow_tpu.odh.runtime_images import format_key_name

        # formatKeyName (notebook_runtime.go:174-183): lowercase, invalid
        # chars collapse to single dashes, edges trimmed
        assert format_key_name("Data Science Runtime") == \
            "data-science-runtime.json"
        assert format_key_name("  PyTorch + CUDA (2024a)! ") == \
            "pytorch-cuda-2024a.json"
        assert format_key_name("___") == ""
        assert format_key_name("") == ""

    def test_metadata_parse_failures_yield_empty_object(self):
        from kubeflow_tpu.odh.runtime_images import parse_runtime_image_metadata

        assert parse_runtime_image_metadata("not json", "img") == "{}"
        assert parse_runtime_image_metadata("{}", "img") == "{}"
        assert parse_runtime_image_metadata("[]", "img") == "{}"
        out = parse_runtime_image_metadata(
            '[{"display_name": "R", "metadata": {}}]', "reg/r:1")
        assert '"image_name": "reg/r:1"' in out

    def test_unlabeled_imagestreams_ignored(self, env):
        api, _, mgr, _ = env
        api.create(KubeObject(
            api_version="image.openshift.io/v1", kind="ImageStream",
            metadata=ObjectMeta(name="plain-is", namespace=CENTRAL_NS),
            body={"spec": {"tags": [{
                "name": "1", "from": {"name": "reg/x:1"},
                "annotations": {
                    C.ANNOTATION_RUNTIME_IMAGE_METADATA:
                        '[{"display_name": "X", "metadata": {}}]'},
            }]}},
        ))
        create_nb(api, mgr)
        # no labeled runtime images -> no ConfigMap is created at all
        assert api.try_get(
            "ConfigMap", "user1", C.RUNTIME_IMAGES_CONFIGMAP) is None

    def test_sync_and_mount(self, env):
        api, _, mgr, _ = env
        api.create(KubeObject(
            api_version="image.openshift.io/v1", kind="ImageStream",
            metadata=ObjectMeta(
                name="runtime-ds", namespace=CENTRAL_NS,
                labels={C.LABEL_RUNTIME_IMAGE: "true"},
            ),
            body={"spec": {"tags": [{
                "name": "2024.1",
                "from": {"name": "registry/runtime:2024.1"},
                "annotations": {
                    C.ANNOTATION_RUNTIME_IMAGE_METADATA:
                        '[{"display_name": "Data Science Runtime", "metadata": {}}]'
                },
            }]}},
        ))
        live = create_nb(api, mgr)
        cm = api.get("ConfigMap", "user1", C.RUNTIME_IMAGES_CONFIGMAP)
        key = "data-science-runtime.json"
        assert key in cm.body["data"]
        assert "registry/runtime:2024.1" in cm.body["data"][key]
        spec = Notebook(live).pod_spec
        assert any(v["name"] == C.RUNTIME_IMAGES_VOLUME for v in spec["volumes"])
        mount = next(
            m for m in spec["containers"][0]["volumeMounts"]
            if m["name"] == C.RUNTIME_IMAGES_VOLUME
        )
        assert mount["mountPath"] == C.RUNTIME_IMAGES_MOUNT_PATH
