"""Ring attention: exact causal attention over a sequence-sharded axis.

Long-context path (SURVEY.md §5 "Long-context/sequence parallelism"): the
sequence dimension is sharded across the mesh's "sequence" axis; each device
holds a [B, S/n, H, D] block of q/k/v.  K/V blocks rotate around the ICI
ring with `lax.ppermute` while each device folds every visiting block into a
numerically-stable online softmax (flash-attention style m/l accumulators) —
full attention without ever materializing [S, S] or gathering K/V.

Compute/communication overlap is XLA's job: the ppermute for step i+1 is
independent of step i's einsum, and latency hiding on TPU comes from the
async collective scheduler.  Causality is enforced per-block with global
position offsets; fully-masked blocks still traverse the ring (uniform
control flow keeps the collective schedule identical on every shard).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .attention import _repeat_kv


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool,
    softmax_scale: Optional[float],
) -> jax.Array:
    """Per-shard body (runs under shard_map).  q/k/v: [B, S_blk, H, D]."""
    n = jax.lax.axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    batch, q_len, num_heads, head_dim = q.shape
    kv_len = k.shape[1]
    scale = softmax_scale if softmax_scale is not None else head_dim**-0.5
    k = _repeat_kv(k, num_heads)
    v = _repeat_kv(v, num_heads)

    # the accumulators join a carry with device-varying k/v blocks; pvary
    # marks the zero inits as varying over the same manual axes as q so the
    # loop carry is VMA-consistent (check_vma=True catches the unreduced-
    # cotangent bugs that silently broke nesting under the pipeline axis)
    vma = tuple(jax.typeof(q).vma)
    out = jax.lax.pvary(
        jnp.zeros((batch, num_heads, q_len, head_dim), jnp.float32), vma)
    row_max = jax.lax.pvary(
        jnp.full((batch, num_heads, q_len), -jnp.inf, jnp.float32), vma)
    row_sum = jax.lax.pvary(
        jnp.zeros((batch, num_heads, q_len), jnp.float32), vma)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, carry):
        out, row_max, row_sum, k_blk, v_blk = carry
        # after i rotations we hold the block originally on shard my_idx - i
        src = (my_idx - i) % n
        scores = (
            jnp.einsum(
                "bqhd,bkhd->bhqk", q, k_blk, preferred_element_type=jnp.float32
            )
            * scale
        )
        if causal:
            q_pos = my_idx * q_len + jnp.arange(q_len)
            kv_pos = src * kv_len + jnp.arange(kv_len)
            bias = jnp.where(
                q_pos[:, None] >= kv_pos[None, :], 0.0, -jnp.inf
            ).astype(jnp.float32)
            scores = scores + bias
        blk_max = jnp.max(scores, axis=-1)
        new_max = jnp.maximum(row_max, blk_max)
        # fully-masked rows keep -inf max; exp(-inf - -inf) guards below
        correction = jnp.exp(row_max - new_max)
        correction = jnp.where(jnp.isfinite(row_max), correction, 0.0)
        probs = jnp.exp(scores - new_max[..., None])
        probs = jnp.where(jnp.isfinite(scores), probs, 0.0)
        out = out * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd",
            probs,
            v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        row_sum = row_sum * correction + jnp.sum(probs, axis=-1)
        row_max = new_max
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return out, row_max, row_sum, k_blk, v_blk

    out, row_max, row_sum, _, _ = jax.lax.fori_loop(
        0, n, step, (out, row_max, row_sum, k, v)
    )
    out = out / jnp.maximum(row_sum, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str = "sequence",
    causal: bool = True,
    softmax_scale: Optional[float] = None,
    batch_axes=("data", "fsdp"),
    head_axis: Optional[str] = "tensor",
) -> jax.Array:
    """Sequence-parallel exact attention.  Inputs [B, S, H, D] with S
    sharded over `axis_name`; composes with batch sharding over
    `batch_axes` and head (tensor) sharding over `head_axis`."""
    spec = P(batch_axes, axis_name, head_axis, None)
    # when already inside a (partially-)manual shard_map — the pipeline
    # engine's stage body — the nested shard_map must be built against the
    # CONTEXT mesh (same axes, some already manual), not the concrete one
    context = jax.sharding.get_abstract_mesh()
    local = jax.shard_map(
        lambda q_, k_, v_: _ring_attention_local(
            q_, k_, v_, axis_name, causal, softmax_scale
        ),
        mesh=mesh if context.empty else context,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=True,
    )
    return local(q, k, v)
