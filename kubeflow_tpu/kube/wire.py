"""Kubernetes REST wire-protocol server over the in-memory ApiServer.

This is the repo's envtest: the reference's integration tier boots a *real*
etcd+kube-apiserver (notebook-controller/controllers/suite_test.go:50-110) so
controllers are exercised through genuine HTTP/watch semantics.  We get the
same grounding by serving the deterministic in-memory store over the actual
apiserver wire protocol — `/api/v1/...` + `/apis/{group}/{version}/...`
paths, list/get/create/update/patch/delete verbs, `/status` subresource,
`?watch=true&resourceVersion=` chunked event streams with 410 Gone replay
semantics, Status error bodies — so the real `KubeClient` (kube/client.py)
and the controllers above it run over real sockets end to end.
"""

from __future__ import annotations

import base64
import json
import logging
import queue
import ssl
import threading
from datetime import datetime, timezone
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlsplit

from ..utils.clock import Clock
from .errors import (
    ApiError,
    GoneError,
    InvalidError,
    NotFoundError,
    ServerError,
)
from .meta import KubeObject
from .resources import DEFAULT_SCHEME, ResourceInfo, Scheme
from .store import ApiServer, WatchEvent, match_labels

logger = logging.getLogger("kubeflow_tpu.kube.wire")

_REASON_CODE = {
    "NotFound": 404,
    "AlreadyExists": 409,
    "Conflict": 409,
    "Invalid": 422,
    "Forbidden": 403,
    "Expired": 410,
    "BadRequest": 400,
}


def status_body(code: int, reason: str, message: str) -> dict:
    return {
        "kind": "Status",
        "apiVersion": "v1",
        "metadata": {},
        "status": "Failure",
        "message": message,
        "reason": reason,
        "code": code,
    }


def parse_label_selector(raw: str) -> dict[str, str]:
    """Equality-based selector only — all the notebook stack uses."""
    out: dict[str, str] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if "==" in part:
            k, v = part.split("==", 1)
        elif "=" in part:
            k, v = part.split("=", 1)
        else:
            continue  # existence selectors unsupported
        out[k.strip()] = v.strip()
    return out


def parse_field_selector(raw: str) -> list[tuple[str, bool, str]]:
    """fieldSelector grammar: comma-joined `path=value` / `path==value` /
    `path!=value` terms over dotted field paths (metadata.name,
    involvedObject.kind, spec.nodeName, ...).  Returns (path, equals,
    value) triples."""
    out: list[tuple[str, bool, str]] = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if "!=" in part:
            k, v = part.split("!=", 1)
            out.append((k.strip(), False, v.strip()))
        elif "==" in part:
            k, v = part.split("==", 1)
            out.append((k.strip(), True, v.strip()))
        elif "=" in part:
            k, v = part.split("=", 1)
            out.append((k.strip(), True, v.strip()))
        # a bare term with no operator is invalid; the real apiserver
        # answers 400 — callers validate via the ValueError below
        else:
            raise ValueError(f"invalid field selector segment {part!r}")
    return out


def match_fields(obj_dict: dict,
                 selectors: list[tuple[str, bool, str]]) -> bool:
    """Evaluate parsed fieldSelector terms against the object's dict form.
    Unset paths compare as the empty string (apiserver convention: a
    selector on an unset field matches ""); non-scalar values never match.
    The real apiserver restricts selectable fields per resource; a dynamic
    server accepts any dotted path — a documented superset
    (docs/wire_compat.md)."""
    for path, equals, want in selectors:
        cur: object = obj_dict
        for seg in path.split("."):
            if isinstance(cur, dict):
                cur = cur.get(seg)
            else:
                cur = None
                break
        if cur is None:
            have = ""
        elif isinstance(cur, bool):
            have = "true" if cur else "false"
        elif isinstance(cur, (str, int, float)):
            have = str(cur)
        else:
            return False  # list/map-valued path: nothing to compare
        if (have == want) != equals:
            return False
    return True


class _Route:
    """Decoded request path: which resource, namespace, name, subresource."""

    def __init__(self, info: ResourceInfo, namespace: Optional[str],
                 name: Optional[str], subresource: str = ""):
        self.info = info
        self.namespace = namespace
        self.name = name
        self.subresource = subresource


def route_path(scheme: Scheme, path: str) -> Optional[_Route]:
    parts = [p for p in path.split("/") if p]
    # /api/v1/... or /apis/{group}/{version}/...
    if len(parts) >= 2 and parts[0] == "api" and parts[1] == "v1":
        group, version, rest = "", "v1", parts[2:]
    elif len(parts) >= 3 and parts[0] == "apis":
        group, version, rest = parts[1], parts[2], parts[3:]
    else:
        return None
    namespace: Optional[str] = None
    if len(rest) >= 2 and rest[0] == "namespaces":
        # /namespaces/{ns}/{plural}[/{name}[/{subresource}]]
        # (but bare /api/v1/namespaces[/{name}] is the Namespace resource)
        if len(rest) == 2 and group == "":
            info = scheme.by_path("", "v1", "namespaces")
            return _Route(info, None, rest[1]) if info else None
        namespace, rest = rest[1], rest[2:]
    if not rest:
        if group == "" and namespace is None:
            info = scheme.by_path("", "v1", "namespaces")
            return _Route(info, None, None) if info else None
        return None
    info = scheme.by_path(group, version, rest[0])
    if info is None:
        return None
    name = rest[1] if len(rest) > 1 else None
    sub = rest[2] if len(rest) > 2 else ""
    return _Route(info, namespace, name, sub)


class _WireHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "kubeflow-tpu-apiserver"
    api: ApiServer = None  # type: ignore[assignment]
    scheme: Scheme = None  # type: ignore[assignment]
    token: Optional[str] = None
    # injectable time source for audit-trail timestamps (clock discipline:
    # the wire layer never reads the wall clock directly)
    clock: Clock = Clock()
    # multi-version kinds: (obj_dict, desired_apiVersion) -> obj_dict.  A
    # real apiserver calls the CRD's conversion webhook here; wiring a
    # RemoteConverter (odh/webhook_server.py) reproduces that callout.
    converter = None  # Optional[Callable[[dict, str], dict]]
    # paginated-list snapshots: token id -> (rv, [dicts], converted) —
    # `converted` says whether the dicts are already in request-version
    # form (field-filtered lists convert up front; plain lists convert per
    # page).  Every page of one list is served from the SAME snapshot (etcd
    # serves continue requests at the original revision); bounded,
    # eviction -> 410 Expired and the client relists, exactly client-go's
    # pager fallback
    _list_snapshots: "dict[int, tuple[int, list, bool]]" = {}
    _snapshot_lock = threading.Lock()
    _snapshot_seq = [0]
    _MAX_SNAPSHOTS = 32

    # request-audit trail (envtest's apiserver audit-log analog,
    # odh suite_test.go:126-156): one JSON line per request when wired
    _audit_fh = None
    _audit_lock: Optional[threading.Lock] = None

    # -- plumbing -------------------------------------------------------------
    def log_message(self, *args):  # route through logging, not stderr
        logger.debug("%s", args)

    def log_request(self, code="-", size="-"):  # noqa: A002
        if self._audit_fh is None:
            return
        line = json.dumps({
            "ts": datetime.fromtimestamp(
                self.clock.now(), timezone.utc).isoformat(),
            "verb": self.command,
            "path": self.path,
            "code": int(code) if str(code).isdigit() else str(code),
            "userAgent": self.headers.get("User-Agent", ""),
        })
        with self._audit_lock:
            self._audit_fh.write(line + "\n")
            self._audit_fh.flush()

    def _authorized(self) -> bool:
        if not self.token:
            return True
        return self.headers.get("Authorization", "") == f"Bearer {self.token}"

    def _send_json(self, code: int, body: dict) -> None:
        data = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_error_status(self, err: ApiError) -> None:
        code = _REASON_CODE.get(err.reason, 500)
        self._send_json(code, status_body(code, err.reason, err.message))

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length) if length else b"{}"
        return json.loads(raw or b"{}")

    def _route(self) -> Optional[_Route]:
        parsed = urlsplit(self.path)
        rt = route_path(self.scheme, parsed.path)
        if rt is None:
            self._send_json(404, status_body(
                404, "NotFound", f"unknown path {parsed.path}"))
            return None
        # an alias (non-storage) version is servable only through a
        # conversion webhook; without one the version is not served —
        # mislabeling storage objects would be worse than the 404
        try:
            storage = self.scheme.by_kind(rt.info.kind).api_version
        except KeyError:
            storage = rt.info.api_version
        if rt.info.api_version != storage and self.converter is None:
            self._send_json(404, status_body(
                404, "NotFound",
                f"version {rt.info.api_version} not served "
                "(no conversion webhook configured)"))
            return None
        return rt

    def _query(self) -> dict[str, str]:
        q = parse_qs(urlsplit(self.path).query)
        return {k: v[0] for k, v in q.items()}

    def _guard(self) -> bool:
        if not self._authorized():
            self._send_json(401, status_body(401, "Unauthorized", "bad token"))
            return False
        return True

    # -- version conversion ---------------------------------------------------
    def _convert_out(self, d: dict, rt: "_Route") -> dict:
        """Storage version -> the version the request path asked for."""
        desired = rt.info.api_version
        if self.converter is None or d.get("apiVersion") == desired:
            return d
        try:
            return type(self).converter(d, desired)
        except Exception as err:  # conversion webhook failure -> 500 Status
            raise ServerError(f"conversion to {desired} failed: {err}") from err

    def _convert_out_many(self, items: list[dict], rt: "_Route") -> list[dict]:
        """List conversion in ONE webhook callout when the converter can
        batch (kube-apiserver sends a whole list as a single
        ConversionReview; N round-trips for N items would multiply list
        latency by N)."""
        desired = rt.info.api_version
        need = [d for d in items if d.get("apiVersion") != desired]
        if self.converter is None or not need:
            return items
        batch = getattr(type(self).converter, "convert_many", None)
        if batch is None:
            return [self._convert_out(d, rt) for d in items]
        try:
            converted = iter(batch(need, desired))
        except Exception as err:
            raise ServerError(f"conversion to {desired} failed: {err}") from err
        return [next(converted) if d.get("apiVersion") != desired else d
                for d in items]

    def _convert_in(self, obj: KubeObject, rt: "_Route") -> KubeObject:
        """Request-path version -> the kind's storage version before the
        store sees it (what the apiserver does on every write)."""
        storage = self.scheme.by_kind(rt.info.kind).api_version
        if self.converter is None or obj.api_version == storage:
            return obj
        try:
            return KubeObject.from_dict(
                type(self).converter(obj.to_dict(), storage))
        except ApiError:
            raise
        except Exception as err:
            raise ServerError(f"conversion to {storage} failed: {err}") from err

    # -- verbs ----------------------------------------------------------------
    def do_GET(self):  # noqa: N802
        if not self._guard():
            return
        if self._serve_openapi():
            return
        if self._serve_discovery():
            return
        rt = self._route()
        if rt is None:
            return
        q = self._query()
        try:
            if rt.name is not None:
                obj = self.api.get(rt.info.kind, rt.namespace or "", rt.name)
                self._send_json(200, self._convert_out(obj.to_dict(), rt))
            elif q.get("watch") in ("true", "1"):
                self._serve_watch(rt, q)
            else:
                self._serve_list(rt, q)
        except ApiError as err:
            self._send_error_status(err)

    # standard verbs discovery advertises for every resource; the server
    # serves all of them (deletecollection included)
    _VERBS = ["create", "delete", "deletecollection", "get", "list",
              "patch", "update", "watch"]

    _MERGE_NODE = "dev.kubeflow-tpu.MergeAwareObject"

    def _served_infos(self) -> list:
        """Served resources the DATA PATH can actually answer for: without
        a conversion webhook, alias versions 404, so neither discovery nor
        OpenAPI may advertise them (per kind — another kind's storage
        version in the same group does not make this kind's alias
        servable)."""
        infos = self.scheme.served()
        if self.converter is not None:
            return infos

        def is_storage(i) -> bool:
            s = self.scheme.by_kind(i.kind)
            return (s.group, s.version) == (i.group, i.version)

        return [i for i in infos if is_storage(i)]

    def _openapi_schemas(self, ref_prefix: str) -> dict:
        """Schema definitions for every served kind, plus one
        self-referential "merge-aware object" node carrying the
        strategic-merge metadata (x-kubernetes-patch-merge-key /
        patch-strategy) for each mergeable list field.

        Fidelity note: this server's strategic-merge engine keys on FIELD
        NAMES at any depth (kube/strategicmerge.py MERGE_KEYS — mirroring
        the patchMergeKey struct tags, which are consistent per field name
        across k8s.io/api), so the schema expresses exactly that: every
        object is the same merge-aware node whose list properties declare
        their merge keys, self-referencing through items and
        additionalProperties.  A client deriving patch strategy from this
        document computes the same merges the server executes — the gap
        docs/wire_compat.md used to document as "absent"."""
        from .strategicmerge import MERGE_KEYS, PRIMITIVE_MERGE_FIELDS

        node_ref = {"$ref": f"{ref_prefix}{self._MERGE_NODE}"}
        props: dict = {}
        for fname, keys in sorted(MERGE_KEYS.items()):
            props[fname] = {
                "type": "array",
                "items": dict(node_ref),
                "x-kubernetes-patch-merge-key": keys[0],
                "x-kubernetes-patch-strategy": "merge",
            }
            if len(keys) > 1:
                # candidate keys beyond the first (Container.ports keys on
                # containerPort, ServiceSpec.ports on port) — a server
                # extension; kubectl uses the primary
                props[fname]["x-kubeflow-tpu-merge-key-candidates"] = \
                    list(keys)
        for fname in sorted(PRIMITIVE_MERGE_FIELDS):
            props[fname] = {
                "type": "array",
                "items": {"type": "string"},
                "x-kubernetes-patch-strategy": "merge",
            }
        schemas = {
            self._MERGE_NODE: {
                "type": "object",
                "properties": props,
                "additionalProperties": dict(node_ref),
            }
        }
        crd_schemas = self._crd_field_schemas()
        for i in self._served_infos():
            group = i.group or "core"
            name = f"{group}.{i.version}.{i.kind}"
            schemas[name] = {
                "type": "object",
                "x-kubernetes-group-version-kind": [
                    {"group": i.group, "kind": i.kind, "version": i.version}
                ],
                "properties": {
                    "apiVersion": {"type": "string"},
                    "kind": {"type": "string"},
                    "metadata": dict(node_ref),
                    "spec": dict(node_ref),
                    "status": dict(node_ref),
                },
                "additionalProperties": dict(node_ref),
            }
            # per-field models come from the CRD object itself, exactly
            # like a real apiserver: a stored CustomResourceDefinition's
            # openAPIV3Schema overrides the generic spec/status nodes for
            # its kind+version (main.py --serve-api seeds the Notebook
            # CRD so the standalone profile serves its field models)
            crd = crd_schemas.get((i.group, i.version, i.kind))
            if crd:
                for field in ("spec", "status"):
                    if field in crd.get("properties", {}):
                        schemas[name]["properties"][field] = \
                            crd["properties"][field]
        return schemas

    def _crd_field_schemas(self) -> dict:
        """(group, version, kind) -> openAPIV3Schema from stored CRDs."""
        out: dict = {}
        try:
            crds = self.api.list("CustomResourceDefinition")
        except Exception:
            return out
        for crd in crds:
            spec = crd.body.get("spec", {})
            group = spec.get("group", "")
            kind = spec.get("names", {}).get("kind", "")
            for v in spec.get("versions", []):
                schema = (v.get("schema") or {}).get("openAPIV3Schema")
                if schema and group and kind:
                    out[(group, v.get("name", ""), kind)] = schema
        return out

    def _serve_openapi(self) -> bool:
        """/openapi/v2 (swagger 2.0) and /openapi/v3 (discovery root +
        per-groupVersion documents), built from the scheme registry the
        same way discovery is."""
        parts = [p for p in urlsplit(self.path).path.split("/") if p]
        if not parts or parts[0] != "openapi":
            return False
        if parts[1:] == ["v2"]:
            self._send_json(200, {
                "swagger": "2.0",
                "info": {"title": "kubeflow-tpu wire apiserver",
                         "version": "v1"},
                "paths": {
                    i.collection_path(None if not i.namespaced
                                      else "{namespace}"): {}
                    for i in self._served_infos()
                },
                "definitions": self._openapi_schemas("#/definitions/"),
            })
            return True
        if parts[1:] == ["v3"]:
            gvs = sorted({
                (f"api/{i.version}" if not i.group
                 else f"apis/{i.group}/{i.version}")
                for i in self._served_infos()
            })
            self._send_json(200, {"paths": {
                gv: {"serverRelativeURL": f"/openapi/v3/{gv}"} for gv in gvs
            }})
            return True
        if len(parts) >= 3 and parts[1] == "v3":
            want = "/".join(parts[2:])
            gvs = {
                (f"api/{i.version}" if not i.group
                 else f"apis/{i.group}/{i.version}")
                for i in self._served_infos()
            }
            if want not in gvs:
                self._send_json(404, status_body(
                    404, "NotFound", f"no OpenAPI doc for {want}"))
                return True
            self._send_json(200, {
                "openapi": "3.0.0",
                "info": {"title": "kubeflow-tpu wire apiserver",
                         "version": "v1"},
                "paths": {},
                "components": {
                    "schemas": self._openapi_schemas(
                        "#/components/schemas/"),
                },
            })
            return True
        return False

    def _serve_discovery(self) -> bool:
        """API discovery: /api, /apis, /api/v1, /apis/{g}[/{v}] built from
        the scheme — the first thing kubectl asks any server for."""
        parts = [p for p in urlsplit(self.path).path.split("/") if p]
        # cheap shape check first: data-plane GETs (4+ segments, or
        # /api/v1/{plural}) must not pay the scheme scan
        if not parts or parts[0] not in ("api", "apis") or len(parts) > 3 \
                or (parts[0] == "api" and len(parts) > 2):
            return False
        storage = self.scheme.storage_versions()
        infos = self._served_infos()
        groups: dict[str, set[str]] = {}
        for i in infos:
            if i.group:
                groups.setdefault(i.group, set()).add(i.version)

        def resource_list(group: str, version: str) -> dict:
            gv = f"{group}/{version}" if group else version
            return {
                "kind": "APIResourceList",
                "apiVersion": "v1",
                "groupVersion": gv,
                "resources": [
                    {"name": i.plural, "singularName": "",
                     "namespaced": i.namespaced, "kind": i.kind,
                     "verbs": self._VERBS}
                    for i in infos
                    if i.group == group and i.version == version
                ],
            }

        def group_doc(name: str) -> dict:
            versions = sorted(groups[name])
            pref = next((v for v in versions if (name, v) in storage),
                        versions[0])
            return {
                "name": name,
                "versions": [{"groupVersion": f"{name}/{v}", "version": v}
                             for v in versions],
                "preferredVersion": {"groupVersion": f"{name}/{pref}",
                                     "version": pref},
            }

        if parts == ["api"]:
            self._send_json(200, {"kind": "APIVersions", "versions": ["v1"],
                                  "serverAddressByClientCIDRs": []})
        elif parts == ["api", "v1"]:
            self._send_json(200, resource_list("", "v1"))
        elif parts == ["apis"]:
            self._send_json(200, {
                "kind": "APIGroupList", "apiVersion": "v1",
                "groups": [group_doc(g) for g in sorted(groups)]})
        elif len(parts) == 2 and parts[0] == "apis" and parts[1] in groups:
            self._send_json(200, {"kind": "APIGroup", "apiVersion": "v1"}
                            | group_doc(parts[1]))
        elif len(parts) == 3 and parts[0] == "apis" \
                and parts[1] in groups and parts[2] in groups[parts[1]]:
            self._send_json(200, resource_list(parts[1], parts[2]))
        else:
            return False
        return True

    def _serve_list(self, rt: "_Route", q: dict[str, str]) -> None:
        """LIST with limit/continue pagination.  Every page of one list is
        served from the same snapshot at the same resourceVersion, so a
        list-then-watch client resuming from the returned rv replays
        exactly the events that landed after the snapshot — including any
        that landed between pages."""
        try:
            limit = int(q.get("limit") or 0)
        except ValueError:
            self._send_json(400, status_body(
                400, "BadRequest", f"invalid limit {q.get('limit')!r}"))
            return
        limit = max(0, limit)
        cls = type(self)
        if q.get("continue"):
            try:
                token = json.loads(base64.b64decode(q["continue"]).decode())
                snap_id, cursor = int(token["snap"]), int(token["cursor"])
            except Exception:
                self._send_json(400, status_body(
                    400, "BadRequest", "malformed continue token"))
                return
            with cls._snapshot_lock:
                snap = cls._list_snapshots.get(snap_id)
            if snap is None:
                self._send_json(410, status_body(
                    410, "Expired",
                    "continue token expired; restart the list"))
                return
            rv, all_items, converted = snap
            items = all_items[cursor:]
        else:
            parsed = self._parse_selectors(q)
            if parsed is None:
                return
            selector, fields = parsed
            objs, rv = self.api.list_with_rv(rt.info.kind, rt.namespace,
                                             selector or None)
            if fields:
                # field selectors are written in request-version field
                # names: convert the whole collection up front, filter on
                # the converted view, and serve those dicts directly
                items = self._convert_out_many(
                    [o.to_dict() for o in objs], rt)
                items = [d for d in items if match_fields(d, fields)]
            else:
                # no field filtering: keep raw dicts and convert per page
                # below — a limit=50 first page of a 5000-object alias-
                # version collection must not pay a 5000-item conversion
                items = [o.to_dict() for o in objs]
            cursor = 0
            all_items = items
            converted = bool(fields)
        meta: dict = {"resourceVersion": str(rv)}
        if limit and len(items) > limit:
            shown, rest = items[:limit], items[limit:]
            if cursor == 0:
                # first page of a truncated list: snapshot it for the
                # continuation requests (converted flag records whether the
                # dicts are already in request-version form)
                with cls._snapshot_lock:
                    cls._snapshot_seq[0] += 1
                    snap_id = cls._snapshot_seq[0]
                    cls._list_snapshots[snap_id] = (rv, all_items, converted)
                    while len(cls._list_snapshots) > cls._MAX_SNAPSHOTS:
                        cls._list_snapshots.pop(
                            next(iter(cls._list_snapshots)))
            meta["continue"] = base64.b64encode(json.dumps(
                {"snap": snap_id, "cursor": cursor + limit}).encode()).decode()
            meta["remainingItemCount"] = len(rest)
            items = shown
        self._send_json(200, {
            "kind": f"{rt.info.kind}List",
            "apiVersion": rt.info.api_version,
            "metadata": meta,
            # unconverted pages convert HERE — per page, not per collection
            "items": items if converted else self._convert_out_many(items, rt),
        })

    def do_POST(self):  # noqa: N802
        if not self._guard():
            return
        rt = self._route()
        if rt is None:
            return
        try:
            body = self._read_body()
            obj = KubeObject.from_dict(body)
            obj.kind = rt.info.kind
            obj.api_version = obj.api_version or rt.info.api_version
            if rt.namespace:
                obj.metadata.namespace = rt.namespace
            created = self.api.create(self._convert_in(obj, rt))
            self._send_json(201, self._convert_out(created.to_dict(), rt))
        except ApiError as err:
            self._send_error_status(err)

    def do_PUT(self):  # noqa: N802
        if not self._guard():
            return
        rt = self._route()
        if rt is None:
            return
        if rt.subresource not in ("", "status"):
            self._send_json(404, status_body(
                404, "NotFound", f"unknown subresource {rt.subresource}"))
            return
        try:
            body = self._read_body()
            obj = KubeObject.from_dict(body)
            obj.kind = rt.info.kind
            obj.api_version = obj.api_version or rt.info.api_version
            if rt.namespace:
                obj.metadata.namespace = rt.namespace
            if rt.name:
                obj.metadata.name = rt.name
            updated = self.api.update(self._convert_in(obj, rt),
                                      subresource=rt.subresource)
            self._send_json(200, self._convert_out(updated.to_dict(), rt))
        except ApiError as err:
            self._send_error_status(err)

    def do_PATCH(self):  # noqa: N802
        if not self._guard():
            return
        rt = self._route()
        if rt is None or rt.name is None:
            return
        ctype = self.headers.get("Content-Type", "")
        try:
            patch = self._read_body()
            # cross-version patches apply to the REQUEST-version view and
            # convert back to storage — a verbatim merge would smuggle the
            # request apiVersion (and any version-specific fields) into the
            # stored object
            storage = self.scheme.by_kind(rt.info.kind).api_version
            cross = self.converter is not None and \
                rt.info.api_version != storage
            hooks = dict(
                view_out=lambda d: self._convert_out(d, rt),
                view_in=lambda o: self._convert_in(o, rt),
            ) if cross else {}
            if "json-patch" in ctype and "merge" not in ctype:
                # RFC 6902; a failed `test` op answers 422 Invalid
                if not isinstance(patch, list):
                    raise InvalidError("json patch body must be an op list")
                updated = self.api.json_patch(
                    rt.info.kind, rt.namespace or "", rt.name, patch, **hooks)
            elif "apply-patch" in ctype:
                # server-side apply: ?fieldManager=...&force=true|false
                if not isinstance(patch, dict):
                    raise InvalidError("apply body must be a JSON object")
                q = self._query()
                manager = q.get("fieldManager", "")
                if not manager:
                    raise InvalidError(
                        "fieldManager query parameter is required for apply")
                force = q.get("force", "false") in ("true", "1")
                updated, created = self.api.apply(
                    rt.info.kind, rt.namespace or "", rt.name, patch,
                    field_manager=manager, force=force,
                    return_created=True, **hooks)
                # apply is an upsert: a create answers 201 like POST
                self._send_json(200 if not created else 201,
                                self._convert_out(updated.to_dict(), rt))
                return
            elif "strategic-merge" in ctype:
                # patchMergeKey-keyed list merge + $patch directives
                # (kube.strategicmerge) — what kubectl sends for core types
                if not isinstance(patch, dict):
                    raise InvalidError("strategic merge patch body must be "
                                       "a JSON object")
                updated = self.api.strategic_merge_patch(
                    rt.info.kind, rt.namespace or "", rt.name, patch, **hooks)
            else:
                if not isinstance(patch, dict):
                    raise InvalidError("merge patch body must be a JSON "
                                       "object")
                updated = self.api.merge_patch(
                    rt.info.kind, rt.namespace or "", rt.name, patch, **hooks)
            self._send_json(200, self._convert_out(updated.to_dict(), rt))
        except ApiError as err:
            self._send_error_status(err)

    def do_DELETE(self):  # noqa: N802
        if not self._guard():
            return
        rt = self._route()
        if rt is None:
            return
        try:
            if rt.name is None:
                self._delete_collection(rt)
                return
            self.api.delete(rt.info.kind, rt.namespace or "", rt.name)
            self._send_json(200, status_body(200, "", "deleted")
                            | {"status": "Success"})
        except ApiError as err:
            self._send_error_status(err)

    def _parse_selectors(self, q: dict[str, str]):
        """(labels, fields) from the query, or None after answering 400 —
        the one selector-parsing path for list/watch/deletecollection."""
        selector = parse_label_selector(q.get("labelSelector", ""))
        try:
            fields = parse_field_selector(q.get("fieldSelector", ""))
        except ValueError as err:
            self._send_json(400, status_body(400, "BadRequest", str(err)))
            return None
        return selector, fields

    def _delete_collection(self, rt: "_Route") -> None:
        """DELETE on a collection path (kubectl delete --all): remove every
        object matching the label/field selectors and answer the list of
        deleted items, as the apiserver's deletecollection verb does.
        Finalizer-bearing objects begin terminating rather than vanish —
        identical to per-object deletes."""
        parsed = self._parse_selectors(self._query())
        if parsed is None:
            return
        selector, fields = parsed
        objs, _ = self.api.list_with_rv(rt.info.kind, rt.namespace,
                                        selector or None)
        items = self._convert_out_many([o.to_dict() for o in objs], rt)
        if fields:
            items = [d for d in items if match_fields(d, fields)]
        for d in items:
            try:
                # each item's OWN namespace: a cluster-scope collection
                # delete spans namespaces (rt.namespace is None there)
                self.api.delete(rt.info.kind,
                                d["metadata"].get("namespace", ""),
                                d["metadata"]["name"])
            except NotFoundError:
                pass  # raced another deleter: already gone
        self._send_json(200, {
            "kind": f"{rt.info.kind}List",
            "apiVersion": rt.info.api_version,
            "metadata": {"resourceVersion": str(self.api.resource_version)},
            "items": items,
        })

    # -- watch streaming ------------------------------------------------------
    def _serve_watch(self, rt: _Route, q: dict[str, str]) -> None:
        parsed = self._parse_selectors(q)
        if parsed is None:
            return
        selector, fields = parsed
        since_rv = int(q["resourceVersion"]) if q.get("resourceVersion") else None
        events: "queue.Queue[Optional[WatchEvent]]" = queue.Queue()

        def on_event(ev: WatchEvent) -> None:
            obj = ev.obj
            if obj.kind != rt.info.kind:
                return
            if rt.namespace and obj.namespace != rt.namespace:
                return
            # label AND field selectors are evaluated in the stream loop,
            # post-conversion, with selected-set transition synthesis —
            # filtering here would drop the edit-out events the synthesis
            # needs to turn into DELETED
            events.put(ev)

        try:
            # filtered at the dispatch index: this stream only ever costs
            # the store a callback for events of its own kind/namespace
            self.api.subscribe(on_event, since_rv=since_rv,
                               kinds=[rt.info.kind],
                               namespace=rt.namespace or None)
        except GoneError as err:
            self._send_error_status(err)
            return
        bookmarks = q.get("allowWatchBookmarks") in ("true", "1")
        idle_ticks = 0
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            while not getattr(self.server, "_shutting_down", False):
                try:
                    ev = events.get(timeout=0.25)
                except queue.Empty:
                    idle_ticks += 1
                    if bookmarks and idle_ticks >= 4:
                        # ~1s idle: progress-notify BOOKMARK so clients can
                        # advance their resume RV without real events (the
                        # apiserver's WatchBookmarks feature)
                        idle_ticks = 0
                        mark = json.dumps({
                            "type": "BOOKMARK",
                            "object": {
                                "kind": rt.info.kind,
                                "apiVersion": rt.info.api_version,
                                "metadata": {"resourceVersion":
                                             str(self.api.resource_version)},
                            },
                        }).encode() + b"\n"
                        self.wfile.write(
                            b"%x\r\n" % len(mark) + mark + b"\r\n")
                        self.wfile.flush()
                    continue
                idle_ticks = 0
                if ev is None:
                    break
                try:
                    out_obj = self._convert_out(ev.obj.to_dict(), rt)
                except ApiError:
                    continue  # conversion failure drops the event, not the stream
                ev_type = ev.type.value
                if selector or fields:
                    # apiserver selected-set semantics (the cacher keeps the
                    # previous state per event for exactly this): an object
                    # editing OUT of the selector emits a synthetic DELETED
                    # carrying its LAST IN-SET state — plain skipping would
                    # strand stale objects in informer caches forever;
                    # editing IN emits ADDED.  Applies to label and field
                    # selectors alike, evaluated on the request-version view.
                    def _selected(d: dict) -> bool:
                        labels = (d.get("metadata") or {}).get("labels") or {}
                        if selector and not match_labels(labels, selector):
                            return False
                        return not fields or match_fields(d, fields)

                    matches = _selected(out_obj)
                    if ev_type == "MODIFIED" and ev.prev is not None:
                        try:
                            prev_obj = self._convert_out(
                                ev.prev.to_dict(), rt)
                        except ApiError:
                            continue
                        prev_match = _selected(prev_obj)
                        if matches and not prev_match:
                            ev_type = "ADDED"
                        elif prev_match and not matches:
                            # the client must see the object as it last
                            # matched (the new state is outside its view),
                            # but stamped with the EVENT's resourceVersion
                            # so watch resume stays monotonic — exactly the
                            # cacher's synthetic-delete shape
                            ev_type = "DELETED"
                            rv_now = (out_obj.get("metadata") or {}).get(
                                "resourceVersion")
                            out_obj = prev_obj
                            out_obj.setdefault(
                                "metadata", {})["resourceVersion"] = rv_now
                        elif not matches:
                            continue
                    elif not matches:
                        continue  # ADDED/DELETED outside the selected set
                line = json.dumps(
                    {"type": ev_type, "object": out_obj}
                ).encode() + b"\n"
                self.wfile.write(b"%x\r\n" % len(line) + line + b"\r\n")
                self.wfile.flush()
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, ssl.SSLError):
            pass  # client hung up — normal watch teardown
        finally:
            self.api.unwatch(on_event)
            self.close_connection = True


class KubeApiWireServer:
    """Serve an ApiServer over the k8s REST protocol on localhost."""

    def __init__(self, api: ApiServer, scheme: Optional[Scheme] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 token: Optional[str] = None,
                 ssl_context: Optional[ssl.SSLContext] = None,
                 converter=None, audit_log: Optional[str] = None,
                 clock: Optional[Clock] = None) -> None:
        self.api = api
        # audit_log: path for a JSONL request trail (ts/verb/path/code) —
        # the debugging knob envtest exposes via the apiserver audit log
        self._audit_fh = open(audit_log, "a") if audit_log else None
        handler = type("Handler", (_WireHandler,), {
            "api": api, "scheme": scheme or DEFAULT_SCHEME, "token": token,
            "clock": clock or Clock(),
            "converter": staticmethod(converter) if converter else None,
            "_audit_fh": self._audit_fh,
            "_audit_lock": threading.Lock() if audit_log else None,
            # per-server pagination snapshots (a class attr on the subclass,
            # NOT the shared base — two servers must not see each other's
            # continue tokens)
            "_list_snapshots": {}, "_snapshot_lock": threading.Lock(),
            "_snapshot_seq": [0],
        })
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._httpd._shutting_down = False  # type: ignore[attr-defined]
        if ssl_context is not None:
            self._httpd.socket = ssl_context.wrap_socket(
                self._httpd.socket, server_side=True)
        self._thread: Optional[threading.Thread] = None
        self.scheme = "https" if ssl_context is not None else "http"

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"{self.scheme}://{host}:{port}"

    def start(self) -> "KubeApiWireServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="wire-apiserver")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd._shutting_down = True  # type: ignore[attr-defined]
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        if self._audit_fh is not None:
            self._audit_fh.close()


__all__ = ["KubeApiWireServer", "parse_label_selector",
           "parse_field_selector", "match_fields", "route_path",
           "status_body"]
