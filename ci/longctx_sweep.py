"""Long-context (seq 4096) MFU sweep on the real chip.

Round-3 shipped 0.28 single-window at seq 4096 (BASELINE.md) — below the
0.35 bar the repo set itself.  This driver sweeps the levers whose
economics change when the causal-attention FLOP share doubles at 4k:
Pallas flash tile sizes (kv length doubles, so bigger block_k amortizes
the q-block revisits), the `attn` remat policy (saving flash outputs costs
2x the HBM at 4k but also saves 2x the recompute), loss chunking, and
batch.  One subprocess per config via mfu_sweep.py --run so an OOM can't
poison later runs; results append to ci/longctx_results.jsonl.
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

HERE = Path(__file__).parent
RESULTS = HERE / "longctx_results.jsonl"
MFU_SWEEP = HERE / "mfu_sweep.py"

BASE = {"seq": 4096, "batch": 24, "loss_chunks": 32, "mu_dtype": "bfloat16"}

STAGES: list[list[dict]] = [
    # stage 0: reproduce round 3's committed config, then tile variants
    [
        {},  # round-3 anchor: flash 256x256 (config default)
        {"flash_block_q": 256, "flash_block_k": 512},
        {"flash_block_q": 512, "flash_block_k": 512},
        {"flash_block_q": 256, "flash_block_k": 1024},
        {"flash_block_q": 512, "flash_block_k": 1024},
        {"flash_block_q": 128, "flash_block_k": 512},
    ],
    # stage 1: remat policy + batch at promising tiles
    [
        {"remat_policy": "attn", "batch": 16},
        {"remat_policy": "attn", "batch": 16,
         "flash_block_q": 256, "flash_block_k": 512},
        {"batch": 16, "flash_block_q": 256, "flash_block_k": 512},
        {"batch": 32, "flash_block_q": 256, "flash_block_k": 512},
        {"batch": 32},
    ],
    # stage 2: loss chunking interaction at the surviving batch
    [
        {"loss_chunks": 64, "batch": 32},
        {"loss_chunks": 16, "batch": 32,
         "flash_block_q": 256, "flash_block_k": 512},
    ],
]


def drive() -> None:
    for stage_i, stage in enumerate(STAGES):
        for spec in stage:
            merged = {**BASE, **spec}
            label = json.dumps(merged, sort_keys=True)
            print(f"[stage {stage_i}] {label}", flush=True)
            proc = subprocess.run(
                [sys.executable, str(MFU_SWEEP), "--run", json.dumps(merged)],
                capture_output=True, text=True, timeout=1800,
            )
            line = (proc.stdout.strip().splitlines()[-1]
                    if proc.stdout.strip() else "")
            try:
                result = json.loads(line)
            except (json.JSONDecodeError, IndexError):
                result = {"error": (proc.stderr or "no output")[-2000:],
                          "rc": proc.returncode}
            record = {"spec": merged, **result}
            with RESULTS.open("a") as f:
                f.write(json.dumps(record) + "\n")
            ok = {k: v for k, v in result.items() if k != "error"}
            print(f"    -> {json.dumps(ok) if 'error' not in result else 'FAILED rc=' + str(proc.returncode)}",
                  flush=True)

    ranked = [json.loads(x) for x in RESULTS.read_text().splitlines()]
    ranked = [r for r in ranked if "mfu" in r]
    ranked.sort(key=lambda r: -r["mfu"])
    print("\n=== ranked (seq 4096) ===")
    for r in ranked[:10]:
        print(f"mfu={r['mfu']:.4f} tok/s={r['tokens_per_s']:>8} "
              f"{json.dumps(r['spec'], sort_keys=True)}")


if __name__ == "__main__":
    drive()
