"""Fake cluster data plane: kubelet + scheduler + node inventory.

The reference never needed this — envtest has no kubelet and its single-pod
workloads never run in tests (SURVEY.md §4.5).  A TPU framework does need it:
multi-host slice scheduling must be testable without TPUs.  FakeCluster
realizes StatefulSets into Pods (honoring ordinals), schedules them onto fake
nodes with `google.com/tpu` allocatable capacity and
`cloud.google.com/gke-tpu-*` labels (the fake device plugin), marks them
Running/Ready, and emulates the OpenShift controller that mints a dockercfg
pull secret per ServiceAccount (which the ODH lock-removal flow waits on,
odh notebook_controller.go:155-186).
"""

from __future__ import annotations

import copy
import threading
from typing import Optional

from ..utils import invariants
from .errors import NotFoundError
from .meta import KubeObject, ObjectMeta, set_controller_reference
from .store import ApiServer, EventType, WatchEvent

# mirrored from core.constants (string-identical; kept literal here so the
# kube substrate stays importable without the core package)
_NOTEBOOK_NAME_LABEL = "notebook-name"
_TPU_SLICE_LABEL = "notebooks.kubeflow.org/tpu-slice"
_TELEMETRY_ANNOTATION = "notebooks.kubeflow.org/telemetry"
_RESTORED_GENERATION_ANNOTATION = \
    "notebooks.kubeflow.org/restored-generation"
_RESTORED_DIGEST_ANNOTATION = "notebooks.kubeflow.org/restored-digest"
_REPLICA_LABEL = "notebooks.kubeflow.org/replica"
_REPLICA_GENERATION_ANNOTATION = \
    "notebooks.kubeflow.org/replica-generation"
_REPLICA_SEQ_ANNOTATION = "notebooks.kubeflow.org/replica-seq"
_REPLICA_DIGEST_ANNOTATION = "notebooks.kubeflow.org/replica-digest"
_GKE_TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
_GKE_TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"
_GKE_NODEPOOL_LABEL = "cloud.google.com/gke-nodepool"
_TPU_RESOURCE = "google.com/tpu"


def parse_quantity(q) -> float:
    """Minimal k8s resource.Quantity parser (enough for cpu/memory/tpu)."""
    if isinstance(q, (int, float)):
        return float(q)
    s = str(q)
    suffixes = {
        "m": 1e-3, "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12,
        "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40,
    }
    for suf in sorted(suffixes, key=len, reverse=True):
        if s.endswith(suf):
            return float(s[: -len(suf)]) * suffixes[suf]
    return float(s)


class FakeCluster:
    """Subscribes to the ApiServer and plays kubelet/scheduler/cloud.

    Fault-exempt by construction: an installed kube.faults.FaultPlan models
    client<->apiserver failures, and the data plane (kubelet, scheduler,
    the SA secret controller) lives on the cluster side of that boundary —
    its API calls run inside `api.fault_exempt()` so injected chaos breaks
    the controllers under test, never the cluster's own machinery."""

    def __init__(self, api: ApiServer, auto_ready: bool = True) -> None:
        self.api = api
        self.auto_ready = auto_ready
        self._pod_ip_counter = 0
        self._failed_pods: set[tuple[str, str]] = set()
        # checkpoint-restore latency model: with restore_hold on, a pod
        # recreated with CHECKPOINT_RESTORE_* env stays Pending
        # ("RestoringCheckpoint") until release_restores() — tests advance
        # the fake clock across the hold so snapshot->restore pays its
        # real-world reload time while promotion (no pod recreate) does not
        self.restore_hold = False
        self._held_restores: set[tuple[str, str]] = set()
        # (namespace, sts_name) -> failure reason: pods (re)created for a
        # poisoned StatefulSet come up Failed (see poison_statefulset)
        self._poisoned: dict[tuple[str, str], str] = {}
        # session-state plumbing (attach_session_store): the fake data
        # plane plays the checkpoint sidecar — it answers the control
        # plane's final-snapshot requests for reachable slices and stamps
        # restored-generation/digest onto pods created with
        # CHECKPOINT_RESTORE_* env, the audit trail restored-state
        # equivalence drills assert against
        self._session_store = None
        self._session_payload: dict[tuple[str, str], bytes] = {}
        # incremental scheduler accounting: per-node used resources kept in
        # lockstep with pod bind/delete events, so one placement decision
        # costs O(nodes) instead of O(pods x nodes).  _bound remembers each
        # accounted pod's (node, requests) so re-deliveries stay idempotent.
        self._node_used: dict[str, dict[str, float]] = {}
        self._bound: dict[tuple[str, str], tuple[str, dict[str, float]]] = {}
        # incremental kubelet indexes, maintained from the same event
        # stream: pods per owning StatefulSet (so STS reconcile/status is
        # O(its pods), never a namespace scan) and the unschedulable
        # Pending set (so a capacity change retries exactly the starved
        # pods instead of sweeping the fleet)
        self._sts_pods: dict[tuple[str, str], set[str]] = {}
        self._pending: set[tuple[str, str]] = set()
        # one lock serializes the whole data-plane handler: watch fan-out
        # delivers from whichever worker thread committed the write, and
        # the kubelet's maps must see those deliveries one at a time
        # (reentrant: handlers issue writes whose events nest on the same
        # thread)
        self._mutex = invariants.tracked(
            threading.RLock(), "FakeCluster._mutex")
        # the data plane only reacts to these kinds — register filtered so
        # Notebook/Service/Event churn never reaches it
        api.watch(self._on_event,
                  kinds=["StatefulSet", "Pod", "Node", "ServiceAccount"])
        # prime the accounting for pods that predate this cluster (a data
        # plane attached to an already-populated store)
        with api.fault_exempt():
            for pod in api.list("Pod"):
                self._account_pod(pod)
                self._index_pod(pod)

    # -- node inventory --------------------------------------------------------
    def add_node(
        self,
        name: str,
        labels: Optional[dict[str, str]] = None,
        allocatable: Optional[dict[str, str]] = None,
    ) -> KubeObject:
        node = KubeObject(
            api_version="v1",
            kind="Node",
            metadata=ObjectMeta(name=name, labels=dict(labels or {})),
            body={
                "status": {
                    "allocatable": dict(allocatable or {"cpu": "8", "memory": "32Gi"}),
                    "conditions": [{"type": "Ready", "status": "True"}],
                }
            },
        )
        with self.api.fault_exempt():
            return self.api.create(node)

    def add_tpu_slice_nodes(
        self,
        accelerator: str,
        topology: str,
        num_hosts: int,
        chips_per_host: int,
        name_prefix: str = "tpu-node",
        pool: Optional[str] = None,
    ) -> list[KubeObject]:
        """Fake GKE TPU node pool: one node per slice host, labeled the way
        GKE labels TPU nodes so nodeSelector scheduling is exercised.  Every
        node carries a `cloud.google.com/gke-nodepool` label (one call = one
        pool unless overridden) — the grouping the topology-aware slice
        scheduler packs gangs by."""
        pool = pool or f"{name_prefix}-{accelerator}"
        nodes = []
        for i in range(num_hosts):
            nodes.append(
                self.add_node(
                    f"{name_prefix}-{accelerator}-{i}",
                    labels={
                        _GKE_TPU_ACCELERATOR_LABEL: accelerator,
                        _GKE_TPU_TOPOLOGY_LABEL: topology,
                        _GKE_NODEPOOL_LABEL: pool,
                    },
                    allocatable={
                        "cpu": "96",
                        "memory": "192Gi",
                        _TPU_RESOURCE: str(chips_per_host),
                    },
                )
            )
        return nodes

    # -- cloud provider (warm-pool provisioner hook) ---------------------------
    def provision_slice(self, shape, pool: str) -> list[str]:
        """Turn up one TPU slice's node set for the warm pool
        (core/scheduler.WarmPoolController): num_hosts nodes labeled with
        the given nodepool, each exposing chips_per_host `google.com/tpu`.
        Idempotent — a conflict-retried or crash-resumed provisioning pass
        skips nodes that already exist."""
        names = []
        with self.api.fault_exempt():
            for i in range(shape.num_hosts):
                name = f"{pool}-{i}"
                names.append(name)
                if self.api.try_get("Node", "", name) is not None:
                    continue
                self.add_node(
                    name,
                    labels={
                        _GKE_TPU_ACCELERATOR_LABEL:
                            shape.accelerator.gke_label,
                        _GKE_TPU_TOPOLOGY_LABEL: shape.topology,
                        _GKE_NODEPOOL_LABEL: pool,
                    },
                    allocatable={
                        "cpu": "96",
                        "memory": "192Gi",
                        _TPU_RESOURCE: str(shape.chips_per_host),
                    },
                )
        return names

    def deprovision_slice(self, pool: str) -> None:
        """Tear a warm slice's node set back down (autoscaler shrink).
        Nodes still carrying bound pods are left standing: callers only
        retire idle slices, but a shared/user-created pool label must
        never let a teardown yank nodes out from under running pods (and
        silently wreck their used-resources accounting)."""
        with self._mutex, self.api.fault_exempt():
            doomed = [
                n.name for n in self.api.list("Node")
                if n.metadata.labels.get(_GKE_NODEPOOL_LABEL) == pool
                and not self._node_used.get(n.name)
            ]
            for name in doomed:
                try:
                    self.api.delete("Node", "", name)
                except NotFoundError:
                    pass

    # -- failure injection -----------------------------------------------------
    def fail_pod(self, namespace: str, name: str, reason: str = "TPUUnhealthy") -> None:
        """Chaos hook: mark a pod failed (analog of the operator-chaos harness,
        chaos/knowledge/workbenches.yaml)."""
        with self._mutex:
            with self.api.fault_exempt():
                self._fail_pod(namespace, name, reason)

    def _fail_pod(self, namespace: str, name: str, reason: str) -> None:
        pod = self.api.get("Pod", namespace, name)
        pod.status = {
            "phase": "Failed",
            "reason": reason,
            "conditions": [{"type": "Ready", "status": "False", "reason": reason}],
            "containerStatuses": [
                {
                    "name": c.get("name", "main"),
                    "ready": False,
                    "state": {"terminated": {"exitCode": 137, "reason": reason}},
                }
                for c in pod.spec.get("containers", [])
            ],
        }
        self._failed_pods.add((namespace, name))
        self.api.update_status(pod)
        self._sync_sts_status_for_pod(pod)

    def crashloop_pod(self, namespace: str, name: str) -> None:
        """Chaos hook: the pod's container is stuck in the kubelet's
        CrashLoopBackOff — pod phase stays Running but the container
        waits out restart backoffs forever and the pod never turns
        Ready (the state core.selfheal classifies as crash-loop)."""
        with self._mutex, self.api.fault_exempt():
            pod = self.api.get("Pod", namespace, name)
            pod.status = {
                "phase": "Running",
                "conditions": [
                    {"type": "PodScheduled", "status": "True"},
                    {"type": "Ready", "status": "False",
                     "reason": "ContainersNotReady"},
                ],
                "containerStatuses": [
                    {
                        "name": c.get("name", "main"),
                        "ready": False,
                        "restartCount": 7,
                        "state": {"waiting": {
                            "reason": "CrashLoopBackOff",
                            "message": "back-off 5m0s restarting failed "
                                       "container",
                        }},
                    }
                    for c in pod.spec.get("containers", [])
                ],
            }
            self.api.update_status(pod)
            self._sync_sts_status_for_pod(pod)

    def delete_node(self, name: str) -> None:
        """Chaos hook: node-driven disruption (preemption / pool
        scale-down): the Node object vanishes while its pods linger with
        a dangling nodeName — exactly what a TPU host preemption looks
        like to a controller between node-controller sweeps."""
        with self.api.fault_exempt():
            try:
                self.api.delete("Node", "", name)
            except NotFoundError:
                pass

    def cordon_node(self, name: str) -> None:
        """Chaos hook: mark a node unschedulable (kubectl cordon) — the
        voluntary-migration trigger.  Pods already on the node keep
        running; the fake scheduler stops placing new ones there."""
        with self.api.fault_exempt():
            node = self.api.try_get("Node", "", name)
            if node is None:
                return
            node.spec["unschedulable"] = True
            self.api.update(node)

    def uncordon_node(self, name: str) -> None:
        with self.api.fault_exempt():
            node = self.api.try_get("Node", "", name)
            if node is None:
                return
            node.spec.pop("unschedulable", None)
            self.api.update(node)
            # schedule capacity came back: pods the cordon left Pending must
            # retry NOW, not whenever the next unrelated node/capacity event
            # happens to land (a no-op update notifies no watcher, so the
            # Node-MODIFIED retry path alone cannot be relied on)
            with self._mutex:
                self._retry_pending_pods()

    def mark_running(self, namespace: str, name: str) -> None:
        """Drive a created-but-not-yet-Ready pod to Running/Ready by hand —
        the auto_ready=False escape hatch failover drills use to freeze the
        cluster mid-recreate and resume it under a different manager."""
        with self._mutex:
            with self.api.fault_exempt():
                pod = self.api.try_get("Pod", namespace, name)
                if pod is None or not pod.spec.get("nodeName"):
                    return
                self._mark_running(pod)
                self._sync_sts_status_for_pod(pod)

    # -- data-plane telemetry --------------------------------------------------
    def stamp_worker_telemetry(
        self,
        namespace: str,
        notebook: str,
        step_time_s: float = 1.0,
        *,
        flops_per_token: float = 0.0,
        config=None,
        batch: int = 1,
        seq_len: int = 1,
        num_chips: int = 4,
        accelerator: str = "v5e",
        steps: int = 3,
        slow_worker: Optional[object] = None,
        slow_factor: float = 4.0,
        now: float = 0.0,
    ) -> dict[str, dict]:
        """Play the data plane's training loops: run a real
        runtime.telemetry.TelemetryAgent per worker pod of `notebook`
        (the identical code path — and therefore the identical
        roofline-derived MFU — a worker publishes) and stamp each rolling
        summary into the pod's telemetry annotation for the control
        plane's WorkerTelemetryAggregator to read watch-fed.

        `slow_worker` (a pod name or an ordinal into the sorted pod
        list) records `slow_factor` x the step time — the deliberately
        slow worker straggler drills inject.  Returns pod name ->
        published summary."""
        from ..runtime.telemetry import TelemetryAgent, annotation_payload

        with self.api.fault_exempt():
            pods = sorted(
                (p for p in self.api.list("Pod", namespace=namespace)
                 if p.metadata.labels.get(_NOTEBOOK_NAME_LABEL) == notebook
                 and p.metadata.deletion_timestamp is None),
                key=lambda p: p.name)
            out: dict[str, dict] = {}
            for i, pod in enumerate(pods):
                dt = step_time_s
                if slow_worker is not None and \
                        slow_worker in (i, pod.name):
                    dt = step_time_s * slow_factor
                agent = TelemetryAgent(
                    config=config, flops_per_token=flops_per_token,
                    batch=batch, seq_len=seq_len, num_chips=num_chips,
                    accelerator=accelerator, worker=pod.name,
                    time_fn=lambda t=now: t, hbm_fn=lambda: {})
                for _ in range(max(1, steps)):
                    agent.record_step(dt)
                summary = agent.summary()
                live = self.api.get("Pod", namespace, pod.name).deepcopy()
                live.metadata.annotations[_TELEMETRY_ANNOTATION] = \
                    annotation_payload(summary)
                self.api.update(live)
                out[pod.name] = summary
            return out

    def clear_worker_telemetry(self, namespace: str, notebook: str) -> None:
        """Drop the telemetry annotations (a worker that stopped
        reporting — the aggregator must zero its series)."""
        with self.api.fault_exempt():
            for p in self.api.list("Pod", namespace=namespace):
                if p.metadata.labels.get(_NOTEBOOK_NAME_LABEL) != notebook:
                    continue
                if _TELEMETRY_ANNOTATION not in p.metadata.annotations:
                    continue
                live = p.deepcopy()
                del live.metadata.annotations[_TELEMETRY_ANNOTATION]
                self.api.update(live)

    # -- session-state data plane ----------------------------------------------
    def attach_session_store(self, store,
                             default_payload: bytes = b"jax-session") -> None:
        """Wire a core.sessionstate store: this cluster now answers
        `request_final_snapshot` (a reachable slice flushes its current
        session payload as a `final` snapshot; an unreachable one returns
        None) and stamps restore annotations onto pods that boot with
        CHECKPOINT_RESTORE_* env."""
        self._session_store = store
        self._session_default_payload = default_payload
        store.set_final_snapshot_handler(self._final_snapshot)

    def set_session_payload(self, namespace: str, notebook: str,
                            payload: bytes) -> None:
        """The simulated in-memory kernel state of one notebook — what
        snapshots capture and restores must reproduce."""
        self._session_payload[(namespace, notebook)] = bytes(payload)

    def session_payload(self, namespace: str, notebook: str) -> bytes:
        return self._session_payload.get(
            (namespace, notebook),
            getattr(self, "_session_default_payload", b"jax-session"))

    def snapshot_sessions(self, namespace: str, notebook: str,
                          trigger: str = "periodic") -> list:
        """Simulate the in-pod sidecar's periodic snapshot tick: write one
        snapshot of the current session payload per live slice."""
        assert self._session_store is not None, "attach_session_store first"
        infos = []
        with self.api.fault_exempt():
            for slice_id in sorted(self._slice_ids(namespace, notebook)):
                infos.append(self._session_store.put(
                    namespace, notebook, slice_id,
                    self.session_payload(namespace, notebook),
                    trigger=trigger))
        return infos

    def stream_session_delta(self, namespace: str, notebook: str,
                             delta: bytes,
                             writer_epoch: Optional[int] = None) -> list:
        """Simulate the primary kernel appending one increment of live
        session state: every slice's delta chain grows by `delta` (lazily
        seeding a base snapshot from the current payload when the chain
        has no anchor yet) and the simulated in-memory payload advances.
        `writer_epoch` carries the primary's fencing token — a demoted
        primary calling this after promotion raised the fence gets
        StaleWriterError from the store and the payload does NOT advance
        (the zombie-write near-miss the failover soak counts)."""
        assert self._session_store is not None, "attach_session_store first"
        store = self._session_store
        payload = self.session_payload(namespace, notebook)
        infos = []
        with self.api.fault_exempt():
            for slice_id in sorted(self._slice_ids(namespace, notebook)):
                if store.latest(namespace, notebook, slice_id) is None:
                    store.put(namespace, notebook, slice_id, payload,
                              writer_epoch=writer_epoch)
                infos.append(store.append_delta(
                    namespace, notebook, slice_id, bytes(delta),
                    writer_epoch=writer_epoch))
        self._session_payload[(namespace, notebook)] = \
            payload + bytes(delta)
        return infos

    def sync_followers(self, namespace: str, notebook: str,
                       lag: int = 0) -> int:
        """Play the follower runtimes' catch-up loops: every replica-
        labeled worker pod of `notebook` replays its slice's delta chain
        (through head minus `lag` steps) and stamps the replica-freshness
        annotations the election in core/selfheal.py reads as positive
        evidence.  Returns the number of pods stamped."""
        assert self._session_store is not None, "attach_session_store first"
        from ..core.sessionstate import payload_digest

        store = self._session_store
        stamped = 0
        with self.api.fault_exempt():
            for pod in self.api.list("Pod", namespace=namespace):
                labels = pod.metadata.labels
                if labels.get(_NOTEBOOK_NAME_LABEL) != notebook:
                    continue
                if _REPLICA_LABEL not in labels:
                    continue
                try:
                    slice_id = int(labels.get(_TPU_SLICE_LABEL, "0"))
                except ValueError:
                    continue
                head = store.chain_head(namespace, notebook, slice_id)
                if head is None:
                    continue
                gen, head_seq, head_digest = head
                seq = max(head_seq - max(lag, 0), 0)
                if seq == head_seq:
                    digest = head_digest
                else:
                    state = store.materialize(
                        namespace, notebook, slice_id, upto_seq=seq)
                    digest = payload_digest(state or b"")
                live = self.api.get("Pod", namespace, pod.name).deepcopy()
                ann = live.metadata.annotations
                if ann.get(_REPLICA_GENERATION_ANNOTATION) == str(gen) \
                        and ann.get(_REPLICA_SEQ_ANNOTATION) == str(seq) \
                        and ann.get(_REPLICA_DIGEST_ANNOTATION) == digest:
                    continue
                ann[_REPLICA_GENERATION_ANNOTATION] = str(gen)
                ann[_REPLICA_SEQ_ANNOTATION] = str(seq)
                ann[_REPLICA_DIGEST_ANNOTATION] = digest
                self.api.update(live)
                stamped += 1
        return stamped

    def _slice_ids(self, namespace: str, notebook: str) -> set[int]:
        out = set()
        for pod in self.api.list("Pod", namespace=namespace):
            labels = pod.metadata.labels
            if labels.get(_NOTEBOOK_NAME_LABEL) != notebook:
                continue
            try:
                out.add(int(labels.get(_TPU_SLICE_LABEL, "0")))
            except ValueError:
                continue
        return out

    def _final_snapshot(self, namespace: str, notebook: str,
                        slice_id: int):
        """The control plane asked the slice to flush NOW.  Reachable =
        every worker pod of the slice exists, is Running with live
        containers, and still has its (Ready) node — then the current
        session payload lands as a `final` snapshot.  Anything less
        returns None and the engine falls back to stored checkpoints.
        Holds _mutex (reentrant): the failed-pod set mutates on the chaos
        and watch threads while the recovery thread calls in here."""
        with self._mutex, self.api.fault_exempt():
            pods = [
                p for p in self.api.list("Pod", namespace=namespace)
                if p.metadata.labels.get(_NOTEBOOK_NAME_LABEL) == notebook
                and p.metadata.labels.get(_TPU_SLICE_LABEL,
                                          "0") == str(slice_id)
            ]
            if not pods:
                return None
            for pod in pods:
                if (namespace, pod.name) in self._failed_pods:
                    return None
                status = pod.body.get("status", {}) or {}
                if status.get("phase") != "Running":
                    return None
                for cs in status.get("containerStatuses", []) or []:
                    waiting = (cs.get("state") or {}).get("waiting") or {}
                    if waiting.get("reason") == "CrashLoopBackOff":
                        return None
                node_name = pod.spec.get("nodeName", "")
                node = self.api.try_get("Node", "", node_name) \
                    if node_name else None
                if node_name and (node is None or not any(
                        c.get("type") == "Ready"
                        and c.get("status") == "True"
                        for c in node.body.get("status", {}).get(
                            "conditions", []))):
                    return None
            return self._session_store.put(
                namespace, notebook, slice_id,
                self.session_payload(namespace, notebook), trigger="final")

    def _apply_restore_stamp(self, pod: KubeObject) -> None:
        """A pod whose template carries CHECKPOINT_RESTORE_* env boots by
        restoring that snapshot — the fake kubelet records what the
        runtime would have done as annotations on the pod."""
        if self._session_store is None:
            return
        env = {}
        for c in pod.spec.get("containers", []):
            for e in c.get("env", []) or []:
                if "value" in e:
                    env.setdefault(e.get("name"), e["value"])
        gen_raw = env.get("CHECKPOINT_RESTORE_GENERATION")
        if gen_raw is None:
            return
        try:
            generation = int(gen_raw)
        except ValueError:
            return
        notebook = pod.metadata.labels.get(_NOTEBOOK_NAME_LABEL, "")
        try:
            slice_id = int(pod.metadata.labels.get(_TPU_SLICE_LABEL, "0"))
        except ValueError:
            slice_id = 0
        info = self._session_store.info(
            pod.namespace, notebook, slice_id, generation)
        if info is None:
            return
        pod.metadata.annotations[_RESTORED_GENERATION_ANNOTATION] = \
            str(generation)
        pod.metadata.annotations[_RESTORED_DIGEST_ANNOTATION] = info.digest

    def poison_statefulset(self, namespace: str, name: str,
                           reason: str = "TPUUnhealthy") -> None:
        """Chaos hook: every pod (re)created for this StatefulSet comes up
        Failed — a permanently broken slice (bad host, torn interconnect).
        Self-healing must exhaust its restart budget on it, not churn
        forever.  Existing pods fail immediately."""
        with self._mutex:
            self._poisoned[(namespace, name)] = reason
            with self.api.fault_exempt():
                for pod_name in sorted(
                        self._sts_pods.get((namespace, name), ())):
                    self._fail_pod(namespace, pod_name, reason)

    def heal_statefulset(self, namespace: str, name: str) -> None:
        """Undo poison_statefulset: the next slice restart comes up
        clean (the operator replaced the broken hardware)."""
        with self._mutex:
            self._poisoned.pop((namespace, name), None)

    # -- event loop ------------------------------------------------------------
    def _on_event(self, ev: WatchEvent) -> None:
        with self._mutex:
            with self.api.fault_exempt():
                self._handle_event(ev)

    def _index_pod(self, pod: KubeObject) -> None:
        """Fold one live pod into the kubelet indexes (idempotent)."""
        key = (pod.namespace, pod.name)
        owner = pod.metadata.controller_owner()
        if owner is not None and owner.kind == "StatefulSet":
            self._sts_pods.setdefault(
                (pod.namespace, owner.name), set()).add(pod.name)
        phase = pod.body.get("status", {}).get("phase")
        if phase == "Pending" and not pod.spec.get("nodeName"):
            self._pending.add(key)
        else:
            self._pending.discard(key)

    def _unindex_pod(self, pod: KubeObject) -> None:
        key = (pod.namespace, pod.name)
        self._pending.discard(key)
        owner = pod.metadata.controller_owner()
        if owner is not None and owner.kind == "StatefulSet":
            skey = (pod.namespace, owner.name)
            pods = self._sts_pods.get(skey)
            if pods is not None:
                pods.discard(pod.name)
                if not pods:
                    del self._sts_pods[skey]

    def _handle_event(self, ev: WatchEvent) -> None:
        kind = ev.obj.kind
        if kind == "StatefulSet":
            if ev.type in (EventType.ADDED, EventType.MODIFIED):
                self._reconcile_sts(ev.obj.namespace, ev.obj.name)
            elif ev.type == EventType.DELETED:
                pass  # pods cascade via owner-ref GC
        elif kind == "Pod":
            if ev.type == EventType.DELETED:
                self._unaccount_pod(ev.obj)
                self._unindex_pod(ev.obj)
                self._failed_pods.discard((ev.obj.namespace, ev.obj.name))
                self._held_restores.discard((ev.obj.namespace, ev.obj.name))
                owner = ev.obj.metadata.controller_owner()
                if owner is not None and owner.kind == "StatefulSet":
                    self._reconcile_sts(ev.obj.namespace, owner.name)
                self._retry_pending_pods()  # freed capacity may unblock others
            else:
                # bind accounting: the synchronous watch stream means the
                # used-resources map is current before the write that bound
                # the pod even returns to its caller
                self._account_pod(ev.obj)
                self._index_pod(ev.obj)
        elif kind == "Node" and ev.type in (EventType.ADDED, EventType.MODIFIED):
            self._retry_pending_pods()
        elif kind == "ServiceAccount" and ev.type == EventType.ADDED:
            self._mint_pull_secret(ev.obj)

    # -- kubelet/scheduler -----------------------------------------------------
    def _reconcile_sts(self, namespace: str, name: str) -> None:
        sts = self.api.try_get("StatefulSet", namespace, name)
        if sts is None:
            return
        want = int(sts.spec.get("replicas", 1))
        for ordinal in range(want):
            pod_name = f"{name}-{ordinal}"
            if self.api.try_get("Pod", namespace, pod_name) is None:
                self._create_pod(sts, ordinal)
        # scale-down: delete pods beyond want (highest ordinal first) —
        # off the incremental owner index, O(this STS's pods)
        owned = self._sts_pods.get((namespace, name), set())
        extra = [
            pod_name for pod_name in owned
            if _ordinal_of(pod_name, name) is not None
            and _ordinal_of(pod_name, name) >= want
        ]
        for pod_name in sorted(
                extra, key=lambda n: -(_ordinal_of(n, name) or 0)):
            try:
                self.api.delete("Pod", namespace, pod_name)
            except NotFoundError:
                pass
        self._sync_sts_status(namespace, name)

    def _create_pod(self, sts: KubeObject, ordinal: int) -> None:
        namespace, name = sts.namespace, f"{sts.name}-{ordinal}"
        template = sts.spec.get("template", {})
        tmeta = template.get("metadata", {})
        pod = KubeObject(
            api_version="v1",
            kind="Pod",
            metadata=ObjectMeta(
                name=name,
                namespace=namespace,
                labels=dict(tmeta.get("labels") or {}),
                annotations=dict(tmeta.get("annotations") or {}),
            ),
            body={"spec": copy.deepcopy(template.get("spec", {}))},
        )
        # indexed-statefulset identity: hostname + subdomain give each worker
        # a stable DNS name through the headless service — the property
        # TPU_WORKER_HOSTNAMES depends on
        pod.spec["hostname"] = name
        if sts.spec.get("serviceName"):
            pod.spec["subdomain"] = sts.spec["serviceName"]
        pod.metadata.labels["apps.kubernetes.io/pod-index"] = str(ordinal)
        pod.metadata.labels.setdefault(
            "statefulset.kubernetes.io/pod-name", name
        )
        self._apply_restore_stamp(pod)
        sts_live = self.api.get("StatefulSet", namespace, sts.name)
        set_controller_reference(sts_live, pod)

        node = self._schedule(pod)
        pod = self.api.create(pod)
        if node is None:
            pod.status = {
                "phase": "Pending",
                "conditions": [
                    {
                        "type": "PodScheduled",
                        "status": "False",
                        "reason": "Unschedulable",
                        "message": "no node satisfies nodeSelector/resources",
                    }
                ],
            }
            self.api.update_status(pod)
            return
        pod.spec["nodeName"] = node.name
        pod = self.api.update(pod)
        poison = self._poisoned.get((namespace, sts.name))
        if poison is not None:
            self._fail_pod(namespace, name, poison)
        elif self.auto_ready:
            if self.restore_hold and \
                    _RESTORED_GENERATION_ANNOTATION in pod.metadata.annotations:
                self._hold_restore(pod)
            else:
                self._mark_running(pod)

    def _hold_restore(self, pod: KubeObject) -> None:
        """Park a restore-stamped pod in Pending while the modeled
        checkpoint reload runs; release_restores() flips it Ready."""
        self._held_restores.add((pod.namespace, pod.name))
        pod.status = {
            "phase": "Pending",
            "conditions": [
                {"type": "PodScheduled", "status": "True"},
                {
                    "type": "Ready",
                    "status": "False",
                    "reason": "RestoringCheckpoint",
                    "message": "reloading session snapshot into the runtime",
                },
            ],
        }
        self.api.update_status(pod)

    def release_restores(self) -> int:
        """Complete every in-flight checkpoint reload: flip held pods to
        Running/Ready.  Call after advancing the fake clock by the restore
        time the drill wants snapshot->restore recoveries to pay."""
        with self._mutex:
            held = sorted(self._held_restores)
            self._held_restores.clear()
        for ns, name in held:
            self.mark_running(ns, name)
        return len(held)

    def _mark_running(self, pod: KubeObject) -> None:
        self._pod_ip_counter += 1
        pod.status = {
            "phase": "Running",
            "podIP": f"10.0.{self._pod_ip_counter // 256}.{self._pod_ip_counter % 256}",
            "conditions": [
                {"type": "PodScheduled", "status": "True"},
                {"type": "Initialized", "status": "True"},
                {"type": "ContainersReady", "status": "True"},
                {"type": "Ready", "status": "True"},
            ],
            "containerStatuses": [
                {
                    "name": c.get("name", "main"),
                    "ready": True,
                    "restartCount": 0,
                    "image": c.get("image", ""),
                    "state": {"running": {"startedAt": pod.metadata.creation_timestamp}},
                }
                for c in pod.spec.get("containers", [])
            ],
        }
        self.api.update_status(pod)

    @staticmethod
    def _pod_requests(pod_spec: dict) -> dict[str, float]:
        requests: dict[str, float] = {}
        for c in pod_spec.get("containers", []):
            for res, q in (c.get("resources", {}).get("requests") or {}).items():
                requests[res] = requests.get(res, 0.0) + parse_quantity(q)
        return requests

    def _account_pod(self, pod: KubeObject) -> None:
        """Fold a bound pod into the per-node used map (idempotent: a
        re-delivered event with unchanged node+requests is a no-op)."""
        key = (pod.namespace, pod.name)
        node = pod.spec.get("nodeName") or ""
        requests = self._pod_requests(pod.spec) if node else {}
        prev = self._bound.get(key)
        if prev is not None and prev == (node, requests):
            return
        if prev is not None:
            self._subtract_used(*prev)
            del self._bound[key]
        if not node:
            return
        self._bound[key] = (node, requests)
        used = self._node_used.setdefault(node, {})
        for res, v in requests.items():
            used[res] = used.get(res, 0.0) + v

    def _unaccount_pod(self, pod: KubeObject) -> None:
        prev = self._bound.pop((pod.namespace, pod.name), None)
        if prev is not None:
            self._subtract_used(*prev)

    def _subtract_used(self, node: str, requests: dict[str, float]) -> None:
        used = self._node_used.get(node)
        if used is None:
            return
        for res, v in requests.items():
            left = used.get(res, 0.0) - v
            if left > 1e-9:
                used[res] = left
            else:
                used.pop(res, None)
        if not used:
            del self._node_used[node]

    def node_used(self, name: str) -> dict[str, float]:
        """Incrementally-maintained used resources of one node (the sum of
        requests of pods bound there) — the equivalence tests compare this
        against the brute-force recount."""
        with self._mutex:
            return dict(self._node_used.get(name, {}))

    def _schedule(self, pod: KubeObject) -> Optional[KubeObject]:
        selector = pod.spec.get("nodeSelector") or {}
        requests = self._pod_requests(pod.spec)
        for node in self.api.list("Node"):
            if node.spec.get("unschedulable"):
                continue  # cordoned: kube-scheduler never places here
            node_labels = node.metadata.labels
            if not all(node_labels.get(k) == v for k, v in selector.items()):
                continue
            alloc = node.body.get("status", {}).get("allocatable", {})
            # used resources come from the incrementally-maintained map —
            # O(1) per node instead of a full pod-list resum per candidate
            used = self._node_used.get(node.name, {})
            if all(
                parse_quantity(alloc.get(res, 0)) - used.get(res, 0.0) >= need
                for res, need in requests.items()
            ):
                return node
        return None

    def _retry_pending_pods(self) -> None:
        """Re-run scheduling for pods that previously found no fitting node
        (real kube-scheduler retries on Node add / capacity change).  Walks
        the incrementally-maintained Pending set, never the whole fleet;
        each candidate is re-fetched so the mutation happens on a private
        copy (listed objects are read-only shared snapshots)."""
        for ns, pod_name in sorted(self._pending):
            pod = self.api.try_get("Pod", ns, pod_name)
            if pod is None:
                self._pending.discard((ns, pod_name))
                continue
            status = pod.body.get("status", {})
            if status.get("phase") != "Pending" or pod.spec.get("nodeName"):
                continue
            node = self._schedule(pod)
            if node is None:
                continue
            pod.spec["nodeName"] = node.name
            pod = self.api.update(pod)
            ref = pod.metadata.controller_owner()
            poison = self._poisoned.get((pod.namespace, ref.name)) \
                if ref is not None and ref.kind == "StatefulSet" else None
            if poison is not None:
                self._fail_pod(pod.namespace, pod.name, poison)
            elif self.auto_ready:
                if self.restore_hold and _RESTORED_GENERATION_ANNOTATION \
                        in pod.metadata.annotations:
                    self._hold_restore(pod)
                else:
                    self._mark_running(pod)
            self._sync_sts_status_for_pod(pod)

    def _sync_sts_status_for_pod(self, pod: KubeObject) -> None:
        ref = pod.metadata.controller_owner()
        if ref is not None and ref.kind == "StatefulSet":
            self._sync_sts_status(pod.namespace, ref.name)

    def _sync_sts_status(self, namespace: str, name: str) -> None:
        sts = self.api.try_get("StatefulSet", namespace, name)
        if sts is None:
            return
        pods = []
        for pod_name in sorted(self._sts_pods.get((namespace, name), ())):
            pod = self.api.try_get("Pod", namespace, pod_name)
            if pod is not None:
                pods.append(pod)
        ready = sum(
            1
            for p in pods
            if any(
                c.get("type") == "Ready" and c.get("status") == "True"
                for c in p.body.get("status", {}).get("conditions", [])
            )
        )
        sts.status = {
            "replicas": len(pods),
            "readyReplicas": ready,
            "currentReplicas": len(pods),
            "observedGeneration": sts.metadata.generation,
        }
        self.api.update_status(sts)

    # -- openshift service-account controller ---------------------------------
    def _mint_pull_secret(self, sa: KubeObject) -> None:
        secret = KubeObject(
            api_version="v1",
            kind="Secret",
            metadata=ObjectMeta(
                name=f"{sa.name}-dockercfg",
                namespace=sa.namespace,
                annotations={"kubernetes.io/service-account.name": sa.name},
            ),
            body={"type": "kubernetes.io/dockercfg", "data": {".dockercfg": "e30="}},
        )
        try:
            self.api.create(secret)
        except Exception:
            pass
        live = self.api.get("ServiceAccount", sa.namespace, sa.name)
        secrets = live.body.setdefault("imagePullSecrets", [])
        if {"name": secret.name} not in secrets:
            secrets.append({"name": secret.name})
            self.api.update(live)


def _ordinal_of(pod_name: str, sts_name: str) -> Optional[int]:
    prefix = sts_name + "-"
    if not pod_name.startswith(prefix):
        return None
    suffix = pod_name[len(prefix):]
    return int(suffix) if suffix.isdigit() else None
