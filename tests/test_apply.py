"""Server-side apply: managedFields ownership, conflicts, pruning
(kube/apply.py + ApiServer.apply + the wire route).

The reference relies on the real apiserver for these semantics when users
run `kubectl apply --server-side` against its CRDs; the wire server must
arbitrate the same way (docs/wire_compat.md).
"""

from __future__ import annotations

import pytest

from kubeflow_tpu.api.types import Notebook
from kubeflow_tpu.kube import ApiServer, ConflictError, KubeObject
from kubeflow_tpu.kube.apply import field_set, leaf_paths
from kubeflow_tpu.kube.client import KubeClient, RestConfig
from kubeflow_tpu.kube.wire import KubeApiWireServer


def applied_nb(name="wb", **spec_extra):
    d = {
        "apiVersion": "kubeflow.org/v1",
        "kind": "Notebook",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"template": {"spec": {"containers": [
            {"name": name, "image": "jupyter:1"}]}}},
    }
    d["spec"].update(spec_extra)
    return d


class TestFieldSet:
    def test_scalars_and_maps(self):
        fs = field_set({"spec": {"replicas": 1, "paused": False},
                        "metadata": {"labels": {"team": "ml"}}})
        assert fs == {
            "f:spec": {"f:replicas": {}, "f:paused": {}},
            "f:metadata": {"f:labels": {"f:team": {}}},
        }

    def test_keyed_list_items(self):
        fs = field_set({"spec": {"containers": [
            {"name": "wb", "image": "j:1"}]}})
        item = fs["f:spec"]["f:containers"]['k:{"name":"wb"}']
        assert item["."] == {} and item["f:image"] == {}

    def test_atomic_list_is_leaf(self):
        fs = field_set({"spec": {"args": ["--a", "--b"]}})
        assert fs["f:spec"]["f:args"] == {}

    def test_empty_map_claims_nothing(self):
        # applying `spec: {}` must not own the spec subtree (it would
        # conflict with every other manager's spec fields)
        assert field_set({"spec": {}}) == {}
        assert field_set({"spec": {"template": {}}}) == {}

    def test_server_metadata_excluded(self):
        fs = field_set({"metadata": {
            "name": "wb", "uid": "x", "resourceVersion": "3",
            "labels": {"a": "1"}, "managedFields": [{}]}})
        assert fs == {"f:metadata": {"f:labels": {"f:a": {}}}}

    def test_leaf_paths(self):
        fs = field_set({"spec": {"containers": [{"name": "c", "image": "i"}]}})
        paths = set(leaf_paths(fs))
        assert ("f:spec", "f:containers", 'k:{"name":"c"}', ".") in paths
        assert ("f:spec", "f:containers", 'k:{"name":"c"}', "f:image") in paths


class TestApplySemantics:
    def test_apply_creates_and_records_ownership(self):
        api = ApiServer()
        out = api.apply("Notebook", "default", "wb", applied_nb(),
                        field_manager="alice")
        (entry,) = out.metadata.managed_fields
        assert entry["manager"] == "alice" and entry["operation"] == "Apply"
        assert "f:spec" in entry["fieldsV1"]
        assert api.get("Notebook", "default", "wb").metadata.uid

    def test_disjoint_managers_compose(self):
        api = ApiServer()
        api.apply("Notebook", "default", "wb", applied_nb(),
                  field_manager="alice")
        api.apply("Notebook", "default", "wb", {
            "apiVersion": "kubeflow.org/v1", "kind": "Notebook",
            "metadata": {"name": "wb", "namespace": "default",
                         "labels": {"team": "ml"}},
        }, field_manager="bob")
        got = api.get("Notebook", "default", "wb")
        assert got.metadata.labels["team"] == "ml"
        (c,) = got.body["spec"]["template"]["spec"]["containers"]
        assert c["image"] == "jupyter:1", "bob's apply must not prune alice's"
        assert {e["manager"] for e in got.metadata.managed_fields} == \
            {"alice", "bob"}

    def test_conflict_unless_forced(self):
        api = ApiServer()
        api.apply("Notebook", "default", "wb", applied_nb(),
                  field_manager="alice")
        contested = applied_nb()
        contested["spec"]["template"]["spec"]["containers"][0]["image"] = "j:2"
        with pytest.raises(ConflictError, match="alice"):
            api.apply("Notebook", "default", "wb", contested,
                      field_manager="bob")
        # force steals the field; alice's set loses it
        out = api.apply("Notebook", "default", "wb", contested,
                        field_manager="bob", force=True)
        (c,) = out.body["spec"]["template"]["spec"]["containers"]
        assert c["image"] == "j:2"
        alice = next(e for e in out.metadata.managed_fields
                     if e["manager"] == "alice")
        item = alice["fieldsV1"]["f:spec"]["f:template"]["f:spec"][
            "f:containers"]['k:{"name":"wb"}']
        assert "f:image" not in item

    def test_equal_value_co_owns_without_conflict(self):
        api = ApiServer()
        api.apply("Notebook", "default", "wb", applied_nb(),
                  field_manager="alice")
        # same image value: no conflict, both own it
        api.apply("Notebook", "default", "wb", applied_nb(),
                  field_manager="bob")
        got = api.get("Notebook", "default", "wb")
        assert {e["manager"] for e in got.metadata.managed_fields} == \
            {"alice", "bob"}

    def test_dropped_field_is_pruned(self):
        api = ApiServer()
        first = applied_nb()
        first["metadata"]["labels"] = {"team": "ml", "tier": "gold"}
        api.apply("Notebook", "default", "wb", first, field_manager="alice")
        second = applied_nb()
        second["metadata"]["labels"] = {"team": "ml"}
        api.apply("Notebook", "default", "wb", second, field_manager="alice")
        got = api.get("Notebook", "default", "wb")
        assert "tier" not in got.metadata.labels, \
            "apply is declarative: dropped fields are removed"
        assert got.metadata.labels["team"] == "ml"

    def test_co_owned_field_survives_one_managers_drop(self):
        api = ApiServer()
        first = applied_nb()
        first["metadata"]["labels"] = {"team": "ml"}
        api.apply("Notebook", "default", "wb", first, field_manager="alice")
        api.apply("Notebook", "default", "wb", {
            "apiVersion": "kubeflow.org/v1", "kind": "Notebook",
            "metadata": {"name": "wb", "namespace": "default",
                         "labels": {"team": "ml"}},
        }, field_manager="bob")
        # alice drops the label; bob still owns it -> it stays
        api.apply("Notebook", "default", "wb", applied_nb(),
                  field_manager="alice")
        assert api.get("Notebook", "default",
                       "wb").metadata.labels.get("team") == "ml"

    def test_keyed_list_items_owned_independently(self):
        api = ApiServer()
        api.apply("Notebook", "default", "wb", applied_nb(),
                  field_manager="alice")
        sidecar = {
            "apiVersion": "kubeflow.org/v1", "kind": "Notebook",
            "metadata": {"name": "wb", "namespace": "default"},
            "spec": {"template": {"spec": {"containers": [
                {"name": "proxy", "image": "p:1"}]}}},
        }
        api.apply("Notebook", "default", "wb", sidecar, field_manager="bob")
        names = [c["name"] for c in api.get("Notebook", "default", "wb")
                 .body["spec"]["template"]["spec"]["containers"]]
        assert names == ["wb", "proxy"]
        # alice re-applies her config (without the sidecar): bob's item stays
        api.apply("Notebook", "default", "wb", applied_nb(),
                  field_manager="alice")
        names = [c["name"] for c in api.get("Notebook", "default", "wb")
                 .body["spec"]["template"]["spec"]["containers"]]
        assert names == ["wb", "proxy"]
        # bob drops his sidecar -> pruned
        api.apply("Notebook", "default", "wb", {
            "apiVersion": "kubeflow.org/v1", "kind": "Notebook",
            "metadata": {"name": "wb", "namespace": "default"},
        }, field_manager="bob")
        names = [c["name"] for c in api.get("Notebook", "default", "wb")
                 .body["spec"]["template"]["spec"]["containers"]]
        assert names == ["wb"]

    def test_disjoint_fields_of_one_item_compose(self):
        """Two managers owning different fields of the SAME container must
        compose without conflict — item membership always co-owns."""
        api = ApiServer()
        api.apply("Notebook", "default", "wb", applied_nb(),
                  field_manager="alice")
        bob_cfg = {
            "apiVersion": "kubeflow.org/v1", "kind": "Notebook",
            "metadata": {"name": "wb", "namespace": "default"},
            "spec": {"template": {"spec": {"containers": [
                {"name": "wb", "resources": {"limits": {"cpu": "1"}}}]}}},
        }
        api.apply("Notebook", "default", "wb", bob_cfg, field_manager="bob")
        (c,) = api.get("Notebook", "default", "wb") \
            .body["spec"]["template"]["spec"]["containers"]
        assert c["image"] == "jupyter:1" and c["resources"] == {
            "limits": {"cpu": "1"}}

    def test_malformed_managed_fields_tolerated(self):
        """A plain create can write arbitrary managedFields; the next
        apply must treat a malformed fieldsV1 as empty, not crash."""
        api = ApiServer()
        bogus = applied_nb()
        bogus["metadata"]["managedFields"] = [
            "not-even-a-dict",
            {"manager": "weird", "operation": "Apply",
             "fieldsV1": ["not-a-tree"]}]
        api.create(KubeObject.from_dict(bogus))
        out = api.apply("Notebook", "default", "wb", applied_nb(),
                        field_manager="alice")
        assert any(e["manager"] == "alice"
                   for e in out.metadata.managed_fields)

    def test_empty_maps_cleaned_inside_keyed_items(self):
        api = ApiServer()
        first = applied_nb()
        first["spec"]["template"]["spec"]["containers"][0]["resources"] = {
            "limits": {"cpu": "1"}}
        api.apply("Notebook", "default", "wb", first, field_manager="alice")
        api.apply("Notebook", "default", "wb", applied_nb(),
                  field_manager="alice")
        (c,) = api.get("Notebook", "default", "wb") \
            .body["spec"]["template"]["spec"]["containers"]
        assert "resources" not in c, \
            "maps emptied by pruning inside keyed items must disappear"

    def test_apply_upserts_through_racing_delete(self, monkeypatch):
        """apply is an upsert: a delete racing the read-modify-write must
        fall back to the create path, not surface a 404."""
        api = ApiServer()
        api.apply("Notebook", "default", "wb", applied_nb(),
                  field_manager="alice")
        real_update = api.update
        raced = {"done": False}

        def delete_then_update(obj, subresource=""):
            if not raced["done"]:
                raced["done"] = True
                api.delete("Notebook", "default", "wb")
            return real_update(obj, subresource=subresource)

        monkeypatch.setattr(api, "update", delete_then_update)
        out = api.apply("Notebook", "default", "wb", applied_nb(),
                        field_manager="alice")
        assert out.metadata.uid, "recreated through the upsert path"

    def test_identical_reapply_is_a_noop(self):
        """A GitOps loop re-applies the same config on a timer; identical
        applies must not bump resourceVersion (or wake watchers)."""
        api = ApiServer()
        first = api.apply("Notebook", "default", "wb", applied_nb(),
                          field_manager="gitops")
        events = []
        api.subscribe(lambda ev: events.append(ev),
                      since_rv=first.metadata.resource_version)
        again = api.apply("Notebook", "default", "wb", applied_nb(),
                          field_manager="gitops")
        assert again.metadata.resource_version == \
            first.metadata.resource_version
        assert events == []

    def test_alternating_managers_reapply_is_a_noop(self):
        """Entry ORDER must stay stable across applies — two managers
        alternating identical re-applies must settle, not flip the
        managedFields list and bump the RV forever."""
        api = ApiServer()
        bob_cfg = {
            "apiVersion": "kubeflow.org/v1", "kind": "Notebook",
            "metadata": {"name": "wb", "namespace": "default",
                         "labels": {"team": "ml"}},
        }
        api.apply("Notebook", "default", "wb", applied_nb(),
                  field_manager="alice")
        api.apply("Notebook", "default", "wb", bob_cfg, field_manager="bob")
        settled = api.get("Notebook", "default", "wb")
        rvs = []
        for _ in range(3):
            rvs.append(api.apply("Notebook", "default", "wb", applied_nb(),
                                 field_manager="alice")
                       .metadata.resource_version)
            rvs.append(api.apply("Notebook", "default", "wb", bob_cfg,
                                 field_manager="bob")
                       .metadata.resource_version)
        assert set(rvs) == {settled.metadata.resource_version}, rvs

    def test_reapply_of_read_object_is_clean(self):
        """Read-modify-apply: server-populated metadata in the sent body
        (uid, resourceVersion, managedFields) must not be applied."""
        api = ApiServer()
        api.apply("Notebook", "default", "wb", applied_nb(),
                  field_manager="alice")
        read = api.get("Notebook", "default", "wb").to_dict()
        read["metadata"]["labels"] = {"edited": "yes"}
        out = api.apply("Notebook", "default", "wb", read,
                        field_manager="alice")
        assert out.metadata.labels["edited"] == "yes"
        (entry,) = out.metadata.managed_fields
        assert entry["manager"] == "alice"
        fs = entry["fieldsV1"]
        assert "f:managedFields" not in fs.get("f:metadata", {})


class TestApplyOverTheWire:
    @pytest.fixture()
    def wire(self):
        api = ApiServer()
        srv = KubeApiWireServer(api).start()
        client = KubeClient(RestConfig(server=srv.url))
        yield api, client
        client.stop_informers()
        srv.stop()

    def test_apply_upsert_and_conflict(self, wire):
        api, client = wire
        nb = KubeObject.from_dict(applied_nb())
        out = client.apply(nb, field_manager="gitops")
        assert out.metadata.managed_fields[0]["manager"] == "gitops"
        contested = KubeObject.from_dict(applied_nb())
        contested.body["spec"]["template"]["spec"]["containers"][0][
            "image"] = "j:9"
        with pytest.raises(ConflictError):
            client.apply(contested, field_manager="dev")
        forced = client.apply(contested, field_manager="dev", force=True)
        (c,) = forced.body["spec"]["template"]["spec"]["containers"]
        assert c["image"] == "j:9"

    def test_missing_field_manager_is_422(self, wire):
        import json as _json
        import urllib.error
        import urllib.request
        _, client = wire
        req = urllib.request.Request(
            client.config.server
            + "/apis/kubeflow.org/v1/namespaces/default/notebooks/wb",
            data=_json.dumps(applied_nb()).encode(),
            headers={"Content-Type": "application/apply-patch+yaml"},
            method="PATCH")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req)
        assert exc.value.code == 422

    def test_controllers_tolerate_applied_notebooks(self, wire):
        """An applied Notebook must reconcile like a created one — the
        manager consumes it through the same watch stream."""
        from kubeflow_tpu.core.metrics import NotebookMetrics
        from kubeflow_tpu.core.notebook_controller import (
            setup_core_controllers,
        )
        from kubeflow_tpu.kube import FakeCluster, Manager
        from kubeflow_tpu.utils.config import CoreConfig
        import time

        api, client = wire
        FakeCluster(api).add_node(
            "n1", allocatable={"cpu": "8", "memory": "16Gi"})
        mgr = Manager(client)
        setup_core_controllers(mgr, CoreConfig(), NotebookMetrics(client))
        client.start_informers(mgr.watched_kinds())
        mgr.start(poll_interval_s=0.01)
        try:
            client.apply(KubeObject.from_dict(applied_nb()),
                         field_manager="gitops")
            deadline = time.time() + 10
            while time.time() < deadline:
                if client.try_get("StatefulSet", "default", "wb"):
                    break
                time.sleep(0.05)
            else:
                raise AssertionError("applied notebook never reconciled")
        finally:
            mgr.stop()
            client.stop_informers()


class TestApplyThroughConversion:
    def test_alias_version_apply(self):
        """Apply on an alias version routes through the view hooks like
        every other patch verb."""
        from kubeflow_tpu.kube.certs import mint_serving_cert
        from kubeflow_tpu.odh.webhook_server import RemoteConverter
        from kubeflow_tpu.odh.webhook_server import AdmissionReviewServer

        api = ApiServer()
        bundle = mint_serving_cert()
        whsrv = AdmissionReviewServer([], bundle=bundle).start()
        converter = RemoteConverter(whsrv.url, ca_pem=bundle.ca_cert_pem)
        srv = KubeApiWireServer(api, converter=converter).start()
        try:
            import json as _json
            import urllib.request

            nb = Notebook.new("wb", "default", version="v1beta1").obj
            req = urllib.request.Request(
                srv.url + "/apis/kubeflow.org/v1beta1/namespaces/default/"
                "notebooks/wb?fieldManager=gitops",
                data=_json.dumps(nb.to_dict()).encode(),
                headers={"Content-Type": "application/apply-patch+yaml"},
                method="PATCH")
            with urllib.request.urlopen(req, timeout=10) as resp:
                out = _json.load(resp)
            assert out["apiVersion"] == "kubeflow.org/v1beta1"
            stored = api.get("Notebook", "default", "wb")
            assert stored.api_version == "kubeflow.org/v1"
            assert stored.metadata.managed_fields[0]["manager"] == "gitops"
        finally:
            srv.stop()
            whsrv.stop()
