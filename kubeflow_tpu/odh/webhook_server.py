"""HTTPS AdmissionReview v1 server + apiserver-side webhook callout.

The reference serves its admission webhooks from the manager's webhook
server (odh main.go:285-311 registers /mutate-notebook-v1 and
/validate-notebook-v1 with TLS from the serving-cert secret).  Here the same
AdmissionHook objects that the in-memory ApiServer runs in-process are
exposed over real HTTPS speaking the AdmissionReview v1 wire format:
request.object/oldObject in, JSONPatch (mutating) or allowed=false
(validating) out.

`RemoteAdmissionHook` is the other half of the choreography: installed into
a (wire-served) ApiServer it POSTs the AdmissionReview to the webhook URL
during the write path and applies the returned patch — exactly what a real
kube-apiserver does with a MutatingWebhookConfiguration, so integration
tests exercise admission over real sockets end to end.
"""

from __future__ import annotations

import base64
import json
import logging
import ssl
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..kube import AdmissionDenied, AdmissionHook, KubeObject
from ..kube.certs import CertBundle
from ..kube.jsonpatch import apply_patch, diff

logger = logging.getLogger("kubeflow_tpu.odh.webhook_server")

_NOTEBOOK_CONVERT = object()  # sentinel: default to the Notebook converter


def handle_admission_review(hooks: list[AdmissionHook], path: str,
                            review: dict) -> dict:
    """Run the hook registered at `path` over one AdmissionReview request."""
    req = review.get("request", {})
    uid = req.get("uid", "")
    op = req.get("operation", "CREATE")
    obj_dict = req.get("object") or {}
    old_dict = req.get("oldObject")
    obj = KubeObject.from_dict(obj_dict)
    old = KubeObject.from_dict(old_dict) if old_dict else None

    response: dict = {"uid": uid, "allowed": True}
    hook = next((h for h in hooks if f"/{h.name}" == path), None)
    if hook is None:
        response = {"uid": uid, "allowed": False,
                    "status": {"message": f"no webhook at {path}", "code": 404}}
    elif obj.kind not in hook.kinds or op not in hook.operations:
        pass  # not a match: allow unmodified (apiserver filters, we tolerate)
    else:
        try:
            mutated = hook.handler(op, old, obj.deepcopy())
            if hook.mutating and mutated is not None:
                ops = diff(obj_dict, mutated.to_dict())
                if ops:
                    response["patchType"] = "JSONPatch"
                    response["patch"] = base64.b64encode(
                        json.dumps(ops).encode()).decode()
        except AdmissionDenied as err:
            response = {"uid": uid, "allowed": False,
                        "status": {"message": err.message, "code": 403}}
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "response": response,
    }


def handle_conversion_review(review: dict, convert_fn) -> dict:
    """ConversionReview v1: convert request.objects to desiredAPIVersion.

    The other half of the CRD's `spec.conversion` clause
    (deploy/manifests.py renders path /convert) — what kube-apiserver calls
    on every read/write of a non-storage version.  Reference:
    notebook-controller/api/v1/notebook_conversion.go:25-69 + the
    conversion-webhook patches under its config/crd/."""
    req = review.get("request") or {}
    uid = req.get("uid", "")
    desired = req.get("desiredAPIVersion", "")
    try:
        converted = [convert_fn(o, desired) for o in req.get("objects") or []]
        response = {"uid": uid, "convertedObjects": converted,
                    "result": {"status": "Success"}}
    except Exception as err:  # a Failure result, not a dead connection
        logger.exception("conversion to %s failed", desired)
        response = {"uid": uid,
                    "result": {"status": "Failure", "message": str(err)}}
    return {
        "apiVersion": review.get("apiVersion") or "apiextensions.k8s.io/v1",
        "kind": "ConversionReview",
        "response": response,
    }


class _AdmissionHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    hooks: list[AdmissionHook] = []
    convert_fn = None  # (obj_dict, desired_api_version) -> obj_dict

    def log_message(self, *args):
        logger.debug("%s", args)

    def do_POST(self):  # noqa: N802
        length = int(self.headers.get("Content-Length", 0))
        try:
            review = json.loads(self.rfile.read(length) or b"{}")
            if self.path == "/convert" and self.convert_fn is not None:
                out = handle_conversion_review(review, type(self).convert_fn)
            else:
                out = handle_admission_review(self.hooks, self.path, review)
            data = json.dumps(out).encode()
            self.send_response(200)
        except Exception as err:  # a broken review must not kill the server
            logger.exception("admission handler failed")
            data = json.dumps({"error": str(err)}).encode()
            self.send_response(500)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802  — readyz probe for the webhook port
        data = b"ok"
        self.send_response(200 if self.path in ("/readyz", "/healthz") else 404)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


class AdmissionReviewServer:
    """TLS server exposing AdmissionHooks at /{hook.name}."""

    def __init__(self, hooks: list[AdmissionHook],
                 bundle: Optional[CertBundle] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 cert_file: str = "", key_file: str = "",
                 convert_fn=_NOTEBOOK_CONVERT) -> None:
        self.hooks = hooks
        self.bundle = bundle
        if convert_fn is _NOTEBOOK_CONVERT:
            from ..api.types import convert_notebook_dict

            convert_fn = convert_notebook_dict
        handler = type("Handler", (_AdmissionHandler,), {
            "hooks": hooks,
            "convert_fn": staticmethod(convert_fn) if convert_fn else None,
        })
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        if cert_file:
            ctx.load_cert_chain(cert_file, key_file or None)
        elif bundle is not None:
            ctx = bundle.server_ssl_context()
        else:
            raise ValueError("AdmissionReviewServer needs a cert: "
                             "pass bundle= or cert_file=/key_file=")
        self._httpd.socket = ctx.wrap_socket(self._httpd.socket,
                                             server_side=True)
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"https://{host}:{port}"

    def start(self) -> "AdmissionReviewServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="webhook-server")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


def _webhook_client_ssl(ca_pem: Optional[bytes],
                        insecure_skip_verify: bool) -> ssl.SSLContext:
    """Verified-by-default client TLS for webhook callouts.  A provided CA
    is trusted with full hostname checking (minted serving certs carry the
    host IP SAN, kube/certs.py); skipping verification is an explicit
    opt-in, mirroring kubeconfig's insecure-skip-tls-verify."""
    if insecure_skip_verify:
        return ssl._create_unverified_context()
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.verify_mode = ssl.CERT_REQUIRED
    ctx.check_hostname = True
    if ca_pem is not None:
        ctx.load_verify_locations(cadata=ca_pem.decode())
    else:
        ctx.load_default_certs()
    return ctx


class RemoteAdmissionHook:
    """ApiServer-side callout to a remote AdmissionReview endpoint.

    Wraps a webhook URL as an in-process AdmissionHook so the wire-served
    apiserver invokes it during writes, like kube-apiserver with a
    MutatingWebhookConfiguration (deploy/manifests.py renders that object
    for real clusters)."""

    def __init__(self, url: str, path: str, mutating: bool,
                 ca_pem: Optional[bytes] = None,
                 kinds: tuple[str, ...] = ("Notebook",),
                 operations: tuple[str, ...] = ("CREATE", "UPDATE"),
                 timeout_s: float = 10.0,
                 insecure_skip_verify: bool = False) -> None:
        self.endpoint = url.rstrip("/") + path
        self.path = path
        self.mutating = mutating
        self.kinds = kinds
        self.operations = operations
        self.timeout_s = timeout_s
        self._ctx = _webhook_client_ssl(ca_pem, insecure_skip_verify)

    def __call__(self, op: str, old: Optional[KubeObject],
                 obj: KubeObject) -> Optional[KubeObject]:
        obj_dict = obj.to_dict()
        review = {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {
                "uid": obj.metadata.uid or "pending",
                "operation": op,
                "object": obj_dict,
                "oldObject": old.to_dict() if old else None,
            },
        }
        req = urllib.request.Request(
            self.endpoint, data=json.dumps(review).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s,
                                    context=self._ctx) as resp:
            out = json.loads(resp.read())
        response = out.get("response", {})
        if not response.get("allowed", False):
            msg = response.get("status", {}).get("message", "denied")
            raise AdmissionDenied(msg)
        patch_b64 = response.get("patch")
        if self.mutating and patch_b64:
            ops = json.loads(base64.b64decode(patch_b64))
            return KubeObject.from_dict(apply_patch(obj_dict, ops))
        return None

    def as_hook(self, name: str = "") -> AdmissionHook:
        return AdmissionHook(
            kinds=self.kinds, handler=self.__call__,
            operations=self.operations, mutating=self.mutating,
            name=name or self.path.lstrip("/"))


class RemoteConverter:
    """Apiserver-side ConversionReview callout to /convert.

    Plugs into KubeApiWireServer(converter=...) so version-crossing reads
    and writes go over the wire to the webhook server — the CRD
    `spec.conversion` choreography end to end, like kube-apiserver with a
    Webhook conversion strategy."""

    def __init__(self, url: str, ca_pem: Optional[bytes] = None,
                 timeout_s: float = 10.0,
                 insecure_skip_verify: bool = False) -> None:
        self.endpoint = url.rstrip("/") + "/convert"
        self.timeout_s = timeout_s
        self._ctx = _webhook_client_ssl(ca_pem, insecure_skip_verify)
        self._uid = 0

    def __call__(self, obj: dict, desired_api_version: str) -> dict:
        return self.convert_many([obj], desired_api_version)[0]

    def convert_many(self, objs: list[dict],
                     desired_api_version: str) -> list[dict]:
        """One ConversionReview for the whole batch — the apiserver converts
        an entire LIST in a single callout, and so does the wire server
        (kube/wire.py _convert_out_many)."""
        self._uid += 1
        review = {
            "apiVersion": "apiextensions.k8s.io/v1",
            "kind": "ConversionReview",
            "request": {
                "uid": f"conv-{self._uid}",
                "desiredAPIVersion": desired_api_version,
                "objects": objs,
            },
        }
        req = urllib.request.Request(
            self.endpoint, data=json.dumps(review).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s,
                                    context=self._ctx) as resp:
            out = json.loads(resp.read())
        response = out.get("response") or {}
        result = response.get("result") or {}
        if result.get("status") != "Success":
            raise RuntimeError(
                f"conversion webhook failed: {result.get('message', result)}")
        converted = response.get("convertedObjects") or []
        if len(converted) != len(objs):
            raise RuntimeError(
                f"conversion webhook returned {len(converted)} objects "
                f"for {len(objs)}")
        return converted


__all__ = ["AdmissionReviewServer", "RemoteAdmissionHook", "RemoteConverter",
           "handle_admission_review", "handle_conversion_review"]
