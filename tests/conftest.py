"""Test-wide environment: force an 8-device virtual CPU mesh.

The reference tests controllers with envtest (real apiserver, no kubelet:
components/notebook-controller/controllers/suite_test.go:50-110).  Our analog
is the in-memory API server in kubeflow_tpu.kube; for the compute plane we
emulate a TPU slice with 8 virtual CPU devices so sharding/collective code is
exercised without hardware.  Must run before the first `import jax`.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# this image's site hook re-registers the hardware PJRT plugin and overrides
# jax_platforms after env processing; pin the config explicitly so tests
# always see the 8-device virtual CPU mesh
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


# -- suite lanes ------------------------------------------------------------
# The suite splits into two lanes so CI can run them as separate jobs and
# developers get a fast control-plane loop (the compute lane is dominated
# by XLA compiles):
#   pytest -m controlplane   (~2 min: kube substrate, controllers, odh)
#   pytest -m compute        (models/ops/parallel/runtime; XLA-heavy)
_COMPUTE_MODULES = {
    "test_compute", "test_data", "test_generate", "test_moe",
    "test_pipeline", "test_quant", "test_runtime", "test_speculative",
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "compute: XLA-compile-heavy compute-plane tests")
    config.addinivalue_line(
        "markers", "controlplane: in-memory control-plane tests (fast lane)")


def pytest_collection_modifyitems(config, items):
    import re

    import pytest

    # fail-open guard: a module is XLA-heavy iff it imports the compute
    # plane — a new model-test module missing from _COMPUTE_MODULES must
    # fail collection loudly, not silently join the fast lane
    # runtime.{checkpoint,metrics,roofline,telemetry}, models.configs and
    # ops.diagnose are exempt: their jax imports are lazy/absent
    # (cull-signal + session-store plumbing, the roofline math and the
    # telemetry agent are pure stdlib, configs.py is dataclasses only;
    # the ops/models/runtime package __init__s resolve their compute
    # exports lazily), so importing them does not drag XLA into the fast
    # lane
    compute_import = re.compile(
        r"kubeflow_tpu\.(models(?!\.configs\b)|ops(?!\.diagnose\b)|parallel"
        r"|runtime(?!\.(checkpoint|metrics|roofline|telemetry)\b))")
    jax_import = re.compile(r"^\s*(?:import|from)\s+jax\b", re.M)
    seen_modules = {}
    for item in items:
        module = item.module.__name__.rsplit(".", 1)[-1]
        if module not in seen_modules:
            src = open(item.module.__file__).read()
            heavy = bool(compute_import.search(src) or jax_import.search(src))
            if heavy != (module in _COMPUTE_MODULES):
                raise pytest.UsageError(
                    f"{module} {'imports' if heavy else 'does not import'} "
                    "the compute plane but is "
                    f"{'missing from' if heavy else 'listed in'} "
                    "_COMPUTE_MODULES (tests/conftest.py) — keep the lane "
                    "split honest")
            seen_modules[module] = heavy
        lane = "compute" if seen_modules[module] else "controlplane"
        item.add_marker(getattr(pytest.mark, lane))
