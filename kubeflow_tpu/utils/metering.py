"""Per-tenant (namespace) usage ledger: chip-second metering,
control-plane attribution, and noisy-neighbor detection.

Every observability surface so far aggregates fleet-wide or per-notebook;
none of it answers "which tenant is consuming the chips, the workqueue,
and the apiserver — and who is being starved by whom?".  This module is
that accounting layer, and the fair-share/preemption work (ROADMAP item 3)
gates on it.  Three feeds, all push-style and all cheap:

* **Chip-seconds** — ``sample(census)`` receives the current placement
  census ``{(namespace, name): (bucket, chips)}`` (built by the caller
  from the InformerCache ``add_aggregate`` pattern over the placement
  annotation + sliceHealth, with an api-list fallback) and accrues
  ``chips x dt`` off the injected clock into per-tenant buckets:
  ``ready`` / ``scheduling`` / ``recovering`` / ``idle`` (stop-annotated
  past the cull threshold).  Per notebook the ledger keeps an interval
  meter; **conservation is the falsifiability contract**: the bucketed
  seconds of one placement interval must sum to the interval's measured
  wall time (``last_sample - interval_start``, kept independently of the
  per-bucket accumulation), tolerance-gated exactly like the lifecycle
  ledger — any double-count or bucket leak breaks the equality and shows
  up in ``conservation()`` / ``violations()``.

* **Control-plane attribution** — ``observe_dispatch`` (workqueue
  dispatch: queue-wait and event->reconcile seconds, stamped on enqueue
  in kube/controller.py next to the event-cause stamp) and
  ``ingest_apiserver`` (cumulative per-(verb, kind, namespace) counts
  from ApiServer.tenant_verb_counts(), delta'd here).  Exported as the
  bounded-cardinality ``notebook_tenant_*_total`` families.

* **Noisy-neighbor detector** — per ``evaluate()``, each tenant's
  control-plane units (dispatches + apiserver requests) over a rolling
  window of evaluation deltas are compared against fair share; a tenant
  whose window share exceeds ``fairshare_factor x (total / tenants)``
  while any *other* tenant's recent event->reconcile p99 has degraded
  past its latched baseline is flagged: exactly one deduped Warning
  event naming the tenant (EventRecorder aggregates identical events by
  count), a latched exemplar handed to the SLO engine's
  ``tenant_fairness`` objective, and a ``noisy`` fairness verdict on the
  ``notebook_tenant_fairness_checks_total`` counter.  The flag clears
  when the tenant's window share drops back under the threshold.

Cardinality is bounded twice: tenants past ``max_tenants`` fold into a
reserved ``other`` tenant (never flagged), and the metric families
themselves sit behind the registry's label-set cap (utils/metrics.py).
Utils idiom: plain locks, injected clock only, O(bounds) memory, never
raises into the reconcile loop's feed path.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Optional

from .metrics import Registry

# The closed bucket vocabulary for placed wall time (bounded label set).
BUCKET_READY = "ready"
BUCKET_SCHEDULING = "scheduling"
BUCKET_RECOVERING = "recovering"
BUCKET_IDLE = "idle"
BUCKETS = (BUCKET_READY, BUCKET_SCHEDULING, BUCKET_RECOVERING, BUCKET_IDLE)

# Reserved fold target once max_tenants distinct namespaces are tracked;
# excluded from fairness verdicts (it is not one tenant).
OTHER_TENANT = "other"

REASON_NOISY = "NoisyNeighbor"

# Dispatch units below which a tenant's window share is not judged —
# avoids flagging during near-idle periods where shares are all noise.
_MIN_WINDOW_UNITS = 10.0


def register_metering_metrics(registry: Registry) -> dict:
    """The tenant metering families (registered by NotebookMetrics so the
    inventory is stable whether or not a ledger is attached; the ledger
    re-registers identically and gets the same objects back)."""
    return {
        "chip_seconds": registry.counter(
            "notebook_tenant_chip_seconds_total",
            "Chip-seconds accrued by a tenant's placed notebooks, "
            "partitioned by lifecycle bucket (conserving partition; see "
            "/debug/tenants)",
            labels=("namespace", "bucket")),
        "apiserver": registry.counter(
            "notebook_tenant_apiserver_requests_total",
            "ApiServer requests attributed to the owning tenant, by verb",
            labels=("namespace", "verb")),
        "queue": registry.counter(
            "notebook_tenant_queue_seconds_total",
            "Workqueue seconds attributed to the owning tenant: "
            "queue_wait (enqueue->dispatch) and event_to_reconcile "
            "(cause->dispatch)",
            labels=("namespace", "phase")),
        "fairness": registry.counter(
            "notebook_tenant_fairness_checks_total",
            "Noisy-neighbor fairness verdicts per evaluation round "
            "(result=ok|noisy); the SLO tenant_fairness objective burns "
            "on the noisy share",
            labels=("result",)),
    }


class _TenantRef:
    """Duck-typed involvedObject for EventRecorder: the tenant namespace."""

    api_version = "v1"
    kind = "Namespace"

    class _Meta:
        uid = ""

    def __init__(self, namespace: str) -> None:
        self.name = namespace
        self.namespace = namespace
        self.metadata = self._Meta()


@dataclass
class _Meter:
    """One placement interval of one notebook.  ``wall`` is measured
    independently (interval_start .. last_ts) while the buckets
    accumulate per-sample deltas — conservation compares the two."""

    tenant: str
    interval_start: float
    last_ts: float
    bucket: str
    chips: float
    buckets: dict = field(default_factory=dict)


@dataclass
class _Tenant:
    """Cumulative usage plus the detector's rolling state for one
    namespace."""

    chip_seconds: dict = field(default_factory=dict)   # bucket -> seconds
    verbs: dict = field(default_factory=dict)          # verb -> count
    queue_s: float = 0.0
    e2r_s: float = 0.0
    dispatches: int = 0
    notebooks_metered: int = 0
    # detector state
    recent_e2r: deque = field(
        default_factory=lambda: deque(maxlen=512))
    baseline_p99: Optional[float] = None
    unit_deltas: deque = field(default_factory=deque)  # maxlen set at init
    units_prev: float = 0.0
    last_trace: str = ""
    flagged: bool = False
    fired_total: int = 0


class TenantMeteringLedger:
    """See module docstring.  One ledger may serve a whole sharded fleet
    (every replica's manager points at the same object), which is what
    makes tenant attribution survive shard handoffs."""

    def __init__(self, clock, registry: Optional[Registry] = None,
                 recorder=None, *,
                 max_tenants: int = 64,
                 max_notebooks: int = 4096,
                 tolerance: float = 0.05,
                 fairshare_factor: float = 3.0,
                 top_k: int = 8,
                 degrade_factor: float = 2.0,
                 degrade_floor_s: float = 1.0,
                 baseline_samples: int = 32,
                 window_evals: int = 16,
                 keep_conservation: int = 4096,
                 slo_engine=None) -> None:
        self.clock = clock
        self.recorder = recorder
        self.slo_engine = slo_engine
        self.max_tenants = max(1, max_tenants)
        self.max_notebooks = max(1, max_notebooks)
        self.tolerance = tolerance
        self.fairshare_factor = fairshare_factor
        self.top_k = max(1, top_k)
        self.degrade_factor = degrade_factor
        self.degrade_floor_s = degrade_floor_s
        self.baseline_samples = max(1, baseline_samples)
        self.window_evals = max(1, window_evals)
        self._lock = threading.Lock()
        self._meters: "OrderedDict[tuple, _Meter]" = OrderedDict()
        self._tenants: dict[str, _Tenant] = {}
        self._verb_snapshot: dict[tuple, int] = {}
        self._conservation: deque = deque(maxlen=keep_conservation)
        self._violations: deque = deque(maxlen=keep_conservation)
        self.finalized_total = 0
        self.evaluations_total = 0
        self.checks = {"ok": 0, "noisy": 0}
        self._max_rel_err = 0.0
        self._metrics = (register_metering_metrics(registry)
                         if registry is not None else None)

    # -- tenant bookkeeping ----------------------------------------------------
    def _tenant(self, namespace: str) -> tuple[str, _Tenant]:
        """Resolve (possibly folding) a namespace to its tenant record.
        Called under the lock."""
        ns = namespace or OTHER_TENANT
        if ns not in self._tenants and len(self._tenants) >= self.max_tenants:
            ns = OTHER_TENANT
        t = self._tenants.get(ns)
        if t is None:
            t = _Tenant()
            t.unit_deltas = deque(maxlen=self.window_evals)
            self._tenants[ns] = t
        return ns, t

    # -- write side: workqueue + reconcile attempts (kube/controller.py) -------
    def observe_dispatch(self, namespace: str, queue_s: float,
                         e2r_s: float) -> None:
        """One workqueue dispatch of a request owned by `namespace`:
        queue-wait and event->reconcile seconds (same clock-domain values
        the fleet histograms observe)."""
        queue_s = max(queue_s, 0.0)
        e2r_s = max(e2r_s, 0.0)
        with self._lock:
            ns, t = self._tenant(namespace)
            t.queue_s += queue_s
            t.e2r_s += e2r_s
            t.dispatches += 1
            t.recent_e2r.append(e2r_s)
            if (t.baseline_p99 is None
                    and len(t.recent_e2r) >= self.baseline_samples):
                t.baseline_p99 = self._p99(t.recent_e2r)
        if self._metrics is not None:
            q = self._metrics["queue"]
            q.labels(ns, "queue_wait").inc(queue_s)
            q.labels(ns, "event_to_reconcile").inc(e2r_s)

    def observe_attempt(self, rec) -> None:
        """Latch the most recent trace per tenant off the attempt stream
        (same call site that feeds the flight recorder) — the exemplar a
        fired fairness alert resolves at /debug/traces."""
        if rec is None or not getattr(rec, "trace_id", ""):
            return
        key = getattr(rec, "object_key", "")
        namespace = key.split("/", 1)[0] if "/" in key else ""
        if not namespace:
            return
        with self._lock:
            _, t = self._tenant(namespace)
            t.last_trace = rec.trace_id

    # -- write side: placement census (core/metrics.py scrape) -----------------
    def sample(self, census: dict, now: Optional[float] = None) -> None:
        """Accrue chip-seconds from the current placement census
        ``{(namespace, name): (bucket, chips)}``.  Notebooks that left the
        census since the previous sample are finalized (conservation
        record); re-placement opens a fresh meter."""
        if now is None:
            now = self.clock.now()
        chip_feed: list[tuple[str, str, float]] = []
        with self._lock:
            for key, (bucket, chips) in census.items():
                m = self._meters.get(key)
                if m is None:
                    ns, t = self._tenant(key[0])
                    t.notebooks_metered += 1
                    self._meters[key] = _Meter(
                        tenant=ns, interval_start=now, last_ts=now,
                        bucket=bucket, chips=float(chips))
                    self._meters.move_to_end(key)
                    continue
                dt = max(now - m.last_ts, 0.0)
                if dt > 0.0:
                    # the interval since the last sample was spent in the
                    # bucket observed THEN; the new bucket starts now
                    m.buckets[m.bucket] = m.buckets.get(m.bucket, 0.0) + dt
                    _, t = self._tenant(m.tenant)
                    t.chip_seconds[m.bucket] = \
                        t.chip_seconds.get(m.bucket, 0.0) + m.chips * dt
                    if m.chips > 0.0:
                        chip_feed.append((m.tenant, m.bucket, m.chips * dt))
                m.last_ts = now
                m.bucket = bucket
                m.chips = float(chips)
                self._meters.move_to_end(key)
            for key in [k for k in self._meters if k not in census]:
                self._finalize(key, self._meters.pop(key))
            while len(self._meters) > self.max_notebooks:
                key, m = self._meters.popitem(last=False)
                self._finalize(key, m)
        if self._metrics is not None:
            c = self._metrics["chip_seconds"]
            for ns, bucket, v in chip_feed:
                c.labels(ns, bucket).inc(v)

    def _finalize(self, key: tuple, m: _Meter) -> None:
        """Close one placement interval: the conservation check compares
        the bucketed accumulation against the independently measured wall
        time.  Called under the lock."""
        wall = max(m.last_ts - m.interval_start, 0.0)
        attributed = sum(m.buckets.values())
        rel_err = abs(attributed - wall) / wall if wall > 1e-9 else 0.0
        self._max_rel_err = max(self._max_rel_err, rel_err)
        record = {
            "namespace": key[0], "name": key[1], "tenant": m.tenant,
            "wall_s": wall, "attributed_s": attributed,
            "buckets": dict(m.buckets), "chips": m.chips,
            "rel_err": rel_err,
        }
        self._conservation.append(record)
        self.finalized_total += 1
        if rel_err > self.tolerance:
            self._violations.append(record)

    # -- write side: apiserver attribution (kube/store.py accessor) ------------
    def ingest_apiserver(self, verb_counts: dict) -> None:
        """Fold a cumulative ``{(verb, kind, namespace): count}`` snapshot
        (ApiServer.tenant_verb_counts()) into per-tenant verb totals;
        deltas are computed here so the feed is idempotent per snapshot."""
        feed: dict[tuple[str, str], float] = {}
        with self._lock:
            for k, count in verb_counts.items():
                delta = count - self._verb_snapshot.get(k, 0)
                if delta <= 0:
                    continue
                self._verb_snapshot[k] = count
                verb, _, namespace = k
                if not namespace:
                    continue  # cluster-scoped: no owning tenant
                ns, t = self._tenant(namespace)
                t.verbs[verb] = t.verbs.get(verb, 0) + delta
                feed[(ns, verb)] = feed.get((ns, verb), 0.0) + delta
        if self._metrics is not None:
            a = self._metrics["apiserver"]
            for (ns, verb), v in feed.items():
                a.labels(ns, verb).inc(v)

    # -- the detector ----------------------------------------------------------
    @staticmethod
    def _p99(samples) -> float:
        """Nearest-rank p99 (same convention as the lifecycle ledger)."""
        if not samples:
            return 0.0
        ordered = sorted(samples)
        n = len(ordered)
        return ordered[min(max((99 * n + 99) // 100 - 1, 0), n - 1)]

    def _units(self, t: _Tenant) -> float:
        return float(t.dispatches + sum(t.verbs.values()))

    def _degraded(self, t: _Tenant) -> bool:
        if t.baseline_p99 is None:
            return False
        p99 = self._p99(t.recent_e2r)
        return p99 > max(t.baseline_p99 * self.degrade_factor,
                         self.degrade_floor_s)

    def evaluate(self, census: Optional[dict] = None,
                 verb_counts: Optional[dict] = None,
                 now: Optional[float] = None) -> dict:
        """One metering round: fold the optional feeds, roll the
        per-tenant control-plane window forward, and run the
        noisy-neighbor check.  Returns {"noisy": [...], "fired": [...],
        "cleared": [...]} (tenant names)."""
        if census is not None:
            self.sample(census, now=now)
        if verb_counts is not None:
            self.ingest_apiserver(verb_counts)
        fired: list[tuple[str, str]] = []
        cleared: list[str] = []
        noisy: list[str] = []
        with self._lock:
            self.evaluations_total += 1
            real = {ns: t for ns, t in self._tenants.items()
                    if ns != OTHER_TENANT}
            for t in real.values():
                cum = self._units(t)
                t.unit_deltas.append(cum - t.units_prev)
                t.units_prev = cum
            window = {ns: sum(t.unit_deltas) for ns, t in real.items()}
            total = sum(window.values())
            n = len(real)
            if n >= 2 and total >= _MIN_WINDOW_UNITS:
                fair = total / n
                for ns, t in real.items():
                    over = window[ns] > self.fairshare_factor * fair
                    if over:
                        victim = any(self._degraded(v)
                                     for vns, v in real.items() if vns != ns)
                        if victim:
                            noisy.append(ns)
                            if not t.flagged:
                                t.flagged = True
                                t.fired_total += 1
                                fired.append((ns, t.last_trace))
                            continue
                    if t.flagged and not over:
                        t.flagged = False
                        cleared.append(ns)
                noisy.extend(ns for ns, t in real.items()
                             if t.flagged and ns not in noisy)
        if self._metrics is not None:
            self._metrics["fairness"].labels(
                "noisy" if noisy else "ok").inc()
        with self._lock:
            self.checks["noisy" if noisy else "ok"] += 1
        # side effects outside the lock: event emission and exemplar
        # latching call into other subsystems
        for ns, trace in fired:
            if self.slo_engine is not None and trace:
                try:
                    self.slo_engine.latch_exemplar(
                        "tenant_fairness",
                        {"trace_id": trace, "tenant": ns})
                except Exception:  # noqa: BLE001 — observability feed
                    pass
            if self.recorder is not None:
                try:
                    # STABLE message (no varying numbers): EventRecorder
                    # aggregates identical events by count, which is the
                    # exactly-one-Warning guarantee
                    self.recorder.event(
                        _TenantRef(ns), "Warning", REASON_NOISY,
                        f"tenant {ns} control-plane share exceeds "
                        f"{self.fairshare_factor:g}x its fair share while "
                        "other tenants' event->reconcile p99 is degraded")
                except Exception:  # noqa: BLE001 — observability feed
                    pass
        return {"noisy": sorted(noisy), "fired": [ns for ns, _ in fired],
                "cleared": sorted(cleared)}

    # -- read side (/debug/tenants, loadtest, tests) ---------------------------
    def conservation(self) -> dict:
        """The falsifiability summary: every closed placement interval's
        bucketed sum vs its measured wall time, PLUS the live meters (so
        a fleet that never releases anything still gets checked)."""
        with self._lock:
            recs = list(self._conservation)
            live_checked = 0
            live_violations = 0
            max_err = self._max_rel_err
            errs = [r["rel_err"] for r in recs]
            for m in self._meters.values():
                wall = max(m.last_ts - m.interval_start, 0.0)
                if wall <= 1e-9:
                    continue
                rel = abs(sum(m.buckets.values()) - wall) / wall
                live_checked += 1
                errs.append(rel)
                max_err = max(max_err, rel)
                if rel > self.tolerance:
                    live_violations += 1
            return {
                "finalized": self.finalized_total,
                "checked": len(recs) + live_checked,
                "live_checked": live_checked,
                "violations": len(self._violations) + live_violations,
                "tolerance": self.tolerance,
                "max_rel_err": max_err,
                "mean_rel_err": (sum(errs) / len(errs)) if errs else 0.0,
            }

    def violations(self) -> list[dict]:
        with self._lock:
            out = [dict(r) for r in self._violations]
            for key, m in self._meters.items():
                wall = max(m.last_ts - m.interval_start, 0.0)
                if wall <= 1e-9:
                    continue
                attributed = sum(m.buckets.values())
                rel = abs(attributed - wall) / wall
                if rel > self.tolerance:
                    out.append({
                        "namespace": key[0], "name": key[1],
                        "tenant": m.tenant, "wall_s": wall,
                        "attributed_s": attributed,
                        "buckets": dict(m.buckets), "chips": m.chips,
                        "rel_err": rel, "live": True,
                    })
            return out

    def tenant_table(self) -> dict:
        """Per-tenant usage rollup — the /debug/tenants table body."""
        with self._lock:
            out = {}
            for ns, t in sorted(self._tenants.items()):
                chips_total = sum(t.chip_seconds.values())
                out[ns] = {
                    "chip_seconds": dict(sorted(t.chip_seconds.items())),
                    "chip_seconds_total": chips_total,
                    "apiserver": dict(sorted(t.verbs.items())),
                    "apiserver_total": int(sum(t.verbs.values())),
                    "dispatches": t.dispatches,
                    "queue_s": t.queue_s,
                    "event_to_reconcile_s": t.e2r_s,
                    "e2r_p99_recent_s": self._p99(t.recent_e2r),
                    "e2r_p99_baseline_s": t.baseline_p99,
                    "control_units_window": sum(t.unit_deltas),
                    "notebooks_metered": t.notebooks_metered,
                    "flagged": t.flagged,
                    "fired_total": t.fired_total,
                    "last_trace": t.last_trace,
                }
            return out

    def top_consumers(self) -> dict:
        """Top-K tenants by chip-seconds and by control-plane units."""
        table = self.tenant_table()
        by_chips = sorted(table.items(),
                          key=lambda kv: kv[1]["chip_seconds_total"],
                          reverse=True)[:self.top_k]
        by_control = sorted(
            table.items(),
            key=lambda kv: kv[1]["apiserver_total"] + kv[1]["dispatches"],
            reverse=True)[:self.top_k]
        return {
            "chip_seconds": [
                {"tenant": ns, "chip_seconds": row["chip_seconds_total"]}
                for ns, row in by_chips if row["chip_seconds_total"] > 0.0],
            "control_plane": [
                {"tenant": ns,
                 "units": row["apiserver_total"] + row["dispatches"]}
                for ns, row in by_control
                if row["apiserver_total"] + row["dispatches"] > 0],
        }

    def tenant_chip_series(self) -> dict[str, float]:
        """Tenant -> cumulative chip-seconds for the top-K consumers (the
        TSDB's per-tenant series on /debug/timeline)."""
        return {row["tenant"]: row["chip_seconds"]
                for row in self.top_consumers()["chip_seconds"]}

    def flagged(self) -> list[str]:
        with self._lock:
            return sorted(ns for ns, t in self._tenants.items() if t.flagged)

    def snapshot(self) -> dict:
        """The /debug/tenants body (also embedded in /debug/fleet and the
        diagnose bundle)."""
        base = {
            "enabled": True,
            "bounds": {
                "max_tenants": self.max_tenants,
                "max_notebooks": self.max_notebooks,
                "top_k": self.top_k,
            },
            "buckets": list(BUCKETS),
            "tenants": self.tenant_table(),
            "top": self.top_consumers(),
            "conservation": self.conservation(),
            "violations": self.violations(),
        }
        with self._lock:
            base["fairness"] = {
                "fairshare_factor": self.fairshare_factor,
                "degrade_factor": self.degrade_factor,
                "degrade_floor_s": self.degrade_floor_s,
                "window_evals": self.window_evals,
                "evaluations": self.evaluations_total,
                "checks": dict(self.checks),
                "flagged": sorted(ns for ns, t in self._tenants.items()
                                  if t.flagged),
            }
            base["live_meters"] = len(self._meters)
        return base

    def clear(self) -> None:
        with self._lock:
            self._meters.clear()
            self._tenants.clear()
            self._verb_snapshot.clear()
            self._conservation.clear()
            self._violations.clear()
            self.finalized_total = 0
            self.evaluations_total = 0
            self.checks = {"ok": 0, "noisy": 0}
            self._max_rel_err = 0.0


__all__ = ["TenantMeteringLedger", "register_metering_metrics", "BUCKETS",
           "OTHER_TENANT", "REASON_NOISY"]
