"""Structured JSON logging with trace correlation.

The reference logs through controller-runtime's zap JSON logger; the piece
that matters for observability is CORRELATION — a log line emitted inside a
reconcile must carry the ids of the live span so operators can pivot from a
log line to the exact trace timeline (and back) in one query.  This module
is that layer: a stdlib `logging.Formatter` that renders one JSON object
per line and injects `trace_id`/`span_id` from the active span context
(utils.tracing), plus a `setup_structured_logging` entrypoint `main.py`
wires behind `--log-format json`.

Extra key/values travel via ``logger.info(..., extra={"namespace": ns})``
— any non-reserved record attribute lands in the JSON object.
"""

from __future__ import annotations

import io
import json
import logging
import time
from typing import Optional

from . import tracing

# logging.LogRecord's own attributes; everything else on a record came in
# via `extra=` and belongs in the rendered object
_RESERVED = frozenset(vars(
    logging.LogRecord("", 0, "", 0, "", (), None)
)) | {"message", "asctime", "taskName"}


class JsonFormatter(logging.Formatter):
    """One JSON object per line: ts/level/logger/msg plus trace correlation
    ids from the live span (omitted when no span is active) and any
    `extra=` fields."""

    def format(self, record: logging.LogRecord) -> str:
        data: dict = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S",
                                time.gmtime(record.created))
            + ".%03dZ" % (record.msecs),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        span = tracing.current_span()
        if span.recording:
            data["trace_id"] = span.trace_id
            data["span_id"] = span.span_id
        for key, value in record.__dict__.items():
            if key not in _RESERVED and not key.startswith("_"):
                data[key] = value
        if record.exc_info and record.exc_info[0] is not None:
            data["exc"] = self.formatException(record.exc_info)
        return json.dumps(data, default=str)


def setup_structured_logging(level: int = logging.INFO,
                             stream: Optional[io.TextIOBase] = None
                             ) -> logging.Handler:
    """Install a JSON handler on the root logger (replacing existing
    handlers, as logging.basicConfig(force=True) would) and return it so
    callers/tests can detach or inspect it."""
    handler = logging.StreamHandler(stream)
    handler.setFormatter(JsonFormatter())
    root = logging.getLogger()
    for h in list(root.handlers):
        root.removeHandler(h)
    root.addHandler(handler)
    root.setLevel(level)
    return handler
