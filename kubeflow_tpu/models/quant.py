"""Int8 weight streaming for decode.

Decode is weight-bandwidth bound (every matmul weight streams from HBM
once per token step — BASELINE.md's decode roofline), so halving the
bytes halves the floor.  This module provides the opt-in int8 path:

- `Int8DenseGeneral`: a DenseGeneral twin whose parameters are an int8
  `kernel_q` plus a per-output-channel `kernel_scale`; at apply time the
  kernel is upcast and scaled right at the matmul operand
  (`w = kernel_q.astype(bf16) * scale`), which XLA fuses into the operand
  load — the int8 bytes are what crosses HBM.
- `quantize_params`: post-training transform from a trained param tree
  (fp32/bf16 `kernel`s) to the quantized tree (`kernel_q`,
  `kernel_scale`) the int8 model consumes.  Symmetric per-output-channel
  absmax quantization; norms/router/embedding stay in their original
  dtype (tiny, and the embedding is a lookup, not a stream).

Use: `cfg.with_(weight_dtype="int8")` makes the Transformer build its
dense layers as Int8DenseGeneral; feed it `quantize_params(params)`.
The reference has no inference path at all (notebook controller); this
extends the in-notebook compute plane.
"""

from __future__ import annotations

from typing import Any, Sequence, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class Int8DenseGeneral(nn.Module):
    """Drop-in for nn.DenseGeneral(use_bias=False) with quantized weights.

    Kernel layout matches DenseGeneral exactly — (contract dims...,
    feature dims...) — so `quantize_params` is a pure tree transform."""

    features: Union[int, Sequence[int]]
    axis: Union[int, Sequence[int]] = -1
    dtype: Any = jnp.bfloat16
    logical_axes: tuple = ()    # kernel's logical axis names, as _dense
                                # passes DenseGeneral — int8 weights shard
                                # by the same rule table as full-precision

    @nn.compact
    def __call__(self, x):
        features = (self.features if isinstance(self.features, (tuple, list))
                    else (self.features,))
        axis = (self.axis if isinstance(self.axis, (tuple, list))
                else (self.axis,))
        axis = tuple(a % x.ndim for a in axis)
        contract_shape = tuple(x.shape[a] for a in axis)
        kernel_shape = contract_shape + tuple(features)
        # per-OUTPUT-CHANNEL scales (see _quantize_kernel): one scale per
        # feature coordinate, broadcast over the contract dims only — a
        # fused qkv kernel [D, H+2kvH, Dh] gets independent scales per
        # projection and head instead of one shared [Dh] row (round-5
        # review finding)
        scale_shape = (1,) * len(contract_shape) + tuple(features)

        k_axes = self.logical_axes or (None,) * len(kernel_shape)
        s_axes = ((None,) * len(contract_shape)
                  + tuple(k_axes[len(contract_shape):]))
        kq = self.param("kernel_q",
                        nn.with_logical_partitioning(
                            nn.initializers.zeros_init(), tuple(k_axes)),
                        kernel_shape, jnp.int8)
        ks = self.param("kernel_scale",
                        nn.with_logical_partitioning(
                            nn.initializers.ones_init(), s_axes),
                        scale_shape, jnp.bfloat16)
        kq, ks = nn.unbox(kq), nn.unbox(ks)
        w = kq.astype(self.dtype) * ks.astype(self.dtype)
        return jax.lax.dot_general(
            x.astype(self.dtype), w,
            (((tuple(axis)), tuple(range(len(contract_shape)))), ((), ())),
        )


def _quantize_kernel(kernel: jax.Array, lead: int = 0,
                     n_contract: int = 1) -> dict:
    """Symmetric per-OUTPUT-CHANNEL absmax int8: one scale per feature
    coordinate, reduced over the contract dims only — [in, heads, dh]
    gets [1, heads, dh] scales (each head its own), and a fused qkv
    kernel never shares scales across projections.  `lead` keeps that
    many leading STACK axes per-slice (scan layers: [L, ...] -> scales
    [L, ...]; vmapped experts add another) — what nn.scan/nn.vmap
    variable_axes slicing expects.  `n_contract` is the number of
    contracted dims after the stack axes (2 for the attention out
    projection [heads, dh, embed]; 1 everywhere else in this family)."""
    k32 = kernel.astype(jnp.float32)
    axes = tuple(range(lead, lead + n_contract))
    absmax = jnp.max(jnp.abs(k32), axis=axes, keepdims=True)
    scale = jnp.maximum(absmax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(k32 / scale), -127, 127).astype(jnp.int8)
    return {"kernel_q": q, "kernel_scale": scale.astype(jnp.bfloat16)}


def quantize_params(params, skip: tuple = ("embed", "router")) -> Any:
    """Trained params -> the tree Int8DenseGeneral expects.

    Every dict holding a `kernel` leaf is rewritten to
    {kernel_q, kernel_scale}; subtrees named in `skip` and non-kernel
    params (norm scales) pass through unchanged.  The default skip list:
    the embedding (a lookup, not a weight stream) and the MoE router
    (fp32 on purpose — routing is precision-sensitive, moe.py).  The
    expert FFNs quantize per expert: their kernels carry a leading
    expert axis from nn.vmap, handled like the scan-layer stack."""
    def walk(node, name="", lead=0):
        if isinstance(node, dict):
            if name in skip:
                return node
            if "kernel" in node and not isinstance(node["kernel"], dict):
                kernel = nn.unbox(node["kernel"])
                # the attention out projection ([heads, dh, embed]) is
                # the family's one multi-dim-contract kernel
                n_contract = 2 if (name == "out"
                                   and kernel.ndim - lead == 3) else 1
                rest = {k: v for k, v in node.items() if k != "kernel"}
                return {**rest,
                        **_quantize_kernel(kernel, lead=lead,
                                           n_contract=n_contract)}
            return {k: walk(v, k,
                            lead + (1 if k in ("layers", "experts") else 0))
                    for k, v in node.items()}
        return node

    return walk(nn.unbox(params))


def quantized_bytes(params, exclude: tuple = ("embed",)) -> int:
    """HBM bytes one decode step STREAMS with the quantized tree.

    Subtrees named in `exclude` are not counted: the embedding table is a
    per-token row lookup (B rows/step), not a full weight stream, so
    counting it would understate the roofline ceiling and flatter the
    achieved fraction (round-4 advisor finding — ~4% at 7B scale).  The
    untied LM head DOES stream (it is a full [embed, vocab] matmul) and
    lives outside the "embed" subtree, so it counts.  For tied-embedding
    configs pass exclude=() — the table then is the head matmul weight.
    Pass exclude=() as well to get total-resident bytes for capacity
    math."""
    from collections.abc import Mapping

    def walk(node, name=""):
        if isinstance(node, Mapping):
            if name in exclude:
                return 0
            return sum(walk(v, k) for k, v in node.items())
        return sum(leaf.size * leaf.dtype.itemsize
                   for leaf in jax.tree_util.tree_leaves(nn.unbox(node)))

    return walk(params)


# ---------------------------------------------------------------------------
# int4: nibble-packed int8 storage + per-group scales


INT4_GROUP = 64  # contract-dim group size per scale


class Int4DenseGeneral(nn.Module):
    """DenseGeneral with 4-bit weights packed two-per-int8 byte.

    Storage is int8 (the relay cannot transfer jnp.int4 arrays), packed
    along the FIRST contract dim: byte i holds rows 2i (low nibble) and
    2i+1 (high nibble), sign-extended with arithmetic shifts.  Scales are
    per (contract-group, last-dim) — INT4_GROUP rows share a scale, which
    keeps 4-bit error acceptable where a whole-column absmax would not.

    MEASURED NEGATIVE on v5e (round 4, BASELINE.md): int4 decodes SLOWER
    than int8 on this XLA version — 5.9k vs 10.4k tok/s on the 470M
    bench.  The interleaving unpack materializes the bf16 weights (1.7k
    tok/s); the shipped even/odd split-matmul form (x @ W == x_even @ lo
    + x_odd @ hi, operands pure elementwise shifts) recovers to 5.9k but
    the group-scale reshape-multiply still defeats full operand fusion.
    Kept as an option: the capacity win is real (a 13B-class model fits
    one chip), and a Pallas dequant-matmul kernel is the known fix."""

    features: Union[int, Sequence[int]]
    axis: Union[int, Sequence[int]] = -1
    dtype: Any = jnp.bfloat16
    logical_axes: tuple = ()

    @nn.compact
    def __call__(self, x):
        features = (self.features if isinstance(self.features, (tuple, list))
                    else (self.features,))
        axis = (self.axis if isinstance(self.axis, (tuple, list))
                else (self.axis,))
        axis = tuple(a % x.ndim for a in axis)
        contract_shape = tuple(x.shape[a] for a in axis)
        kernel_shape = contract_shape + tuple(features)
        flat_in = 1
        for d in contract_shape:
            flat_in *= d
        flat_out = 1
        for d in features:
            flat_out *= d
        if flat_in % (2 * INT4_GROUP) != 0:
            raise ValueError(
                f"contract size {flat_in} not divisible by "
                f"2*INT4_GROUP={2 * INT4_GROUP}")

        k_axes = self.logical_axes or (None,) * len(kernel_shape)
        kq = self.param("kernel_q4",
                        nn.with_logical_partitioning(
                            nn.initializers.zeros_init(),
                            (None, k_axes[-1])),
                        (flat_in // 2, flat_out), jnp.int8)
        ks = self.param("kernel_scale",
                        nn.with_logical_partitioning(
                            nn.initializers.ones_init(),
                            (None, None, k_axes[-1])),
                        (flat_in // INT4_GROUP, 1, flat_out), jnp.bfloat16)
        kq, ks = nn.unbox(kq), nn.unbox(ks)

        x2 = x.reshape(x.shape[:min(axis)] + (flat_in,)) \
            if len(axis) > 1 else x
        x2 = x2.astype(self.dtype)
        lead = x2.shape[:-1]
        rows = 1
        for d in lead:
            rows *= d

        from ..ops import int4_matmul as i4

        if jax.default_backend() == "tpu" and i4.supported(
                rows, flat_in, flat_out, INT4_GROUP):
            # Pallas dequant-matmul: each packed tile is unpacked+scaled
            # in VMEM and fed to the MXU — HBM sees exactly the int4
            # bytes (ops/int4_matmul.py)
            out = i4.int4_matmul(x2.reshape(rows, flat_in), kq, ks,
                                 group=INT4_GROUP, out_dtype=self.dtype)
            out = out.reshape(lead + (flat_out,))
        else:
            # XLA fallback.  NO interleave anywhere: byte i holds contract
            # rows 2i (lo) and 2i+1 (hi), so instead of re-interleaving
            # the weight matrix (which XLA cannot fuse into the dot
            # operand — it materializes the bf16 copy, measured as a big
            # slowdown), the INPUT's even and odd contract rows each
            # matmul their own half:
            #   x @ W  ==  x[..., 0::2] @ lo + x[..., 1::2] @ hi
            # where lo/hi are pure elementwise shifts+scales of the
            # packed buffer.
            lo = jax.lax.shift_right_arithmetic(
                jax.lax.shift_left(kq, jnp.int8(4)), jnp.int8(4))
            hi = jax.lax.shift_right_arithmetic(kq, jnp.int8(4))
            half_group = INT4_GROUP // 2
            sc = ks.astype(self.dtype)

            def dequant(part):  # [in/2, out] int8 -> scaled, group-wise
                g = part.astype(self.dtype).reshape(
                    flat_in // INT4_GROUP, half_group, flat_out)
                return (g * sc).reshape(flat_in // 2, flat_out)

            dn = (((x2.ndim - 1,), (0,)), ((), ()))
            out = (jax.lax.dot_general(x2[..., 0::2], dequant(lo), dn)
                   + jax.lax.dot_general(x2[..., 1::2], dequant(hi), dn))
        return out.reshape(out.shape[:-1] + tuple(features)) \
            if len(features) > 1 else out


def _quantize_kernel_int4(kernel: jax.Array, n_contract: int = 1) -> dict:
    """Kernel [contract..., features...] -> nibble-packed int8 + group
    scales, in Int4DenseGeneral's flat [in, out] layout.  `n_contract`
    says how many LEADING dims are contracted (1 for [in, out] and
    [in, heads, dh]; 2 for the attention out projection [h, dh, out])."""
    k32 = np.asarray(jax.device_get(kernel), dtype=np.float32)
    shape = k32.shape
    n_in = 1
    for d in shape[:n_contract]:
        n_in *= d
    flat = k32.reshape(n_in, -1)
    n_out = flat.shape[1]
    g = flat.reshape(n_in // INT4_GROUP, INT4_GROUP, n_out)
    absmax = np.max(np.abs(g), axis=1, keepdims=True)
    scale = np.maximum(absmax / 7.0, 1e-12)
    q = np.clip(np.round(g / scale), -8, 7).astype(np.int8)
    q = q.reshape(n_in, n_out)
    packed = ((q[1::2] << 4) | (q[0::2] & 0x0F)).astype(np.int8)
    return {"kernel_q4": jnp.asarray(packed),
            "kernel_scale": jnp.asarray(scale.astype("float32")
                                        ).astype(jnp.bfloat16)}


def quantize_params_int4(params, skip: tuple = ("embed", "router")):
    """Trained params -> the Int4DenseGeneral tree (see quantize_params
    for the walk/skips).  MoE trees are REJECTED outright (ValueError
    below) rather than skipped — the int4 Transformer would build
    Int4DenseGeneral for expert kernels and fail on the missing
    kernel_q4 params.  A stacked scan_layers=True training tree is
    unrolled first (decode always unrolls; the layer count comes from the
    stacked leading dim).  The attention out projection
    ([heads, head_dim, embed]) is the model family's one
    multi-dim-contract kernel; everything else contracts a single
    leading dim."""
    params = nn.unbox(params)
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    if any(any(getattr(k, "key", None) == "experts" for k in path)
           for path, _ in flat):
        raise ValueError(
            "quantize_params_int4 cannot quantize MoE expert kernels: the "
            "flat nibble-packed layout does not survive nn.vmap expert "
            "stacking, and the int4 Transformer would look for kernel_q4 "
            "params it skips.  Use quantize_params (int8) for MoE serving."
        )
    if isinstance(params, dict) and "layers" in params:
        from .generate import unroll_params

        num_layers = jax.tree_util.tree_leaves(params["layers"])[0].shape[0]
        params = unroll_params(params, num_layers)

    def walk(node, name=""):
        if isinstance(node, dict):
            if name in skip:
                return node
            if "kernel" in node and not isinstance(node["kernel"], dict):
                rest = {k: v for k, v in node.items() if k != "kernel"}
                kernel = nn.unbox(node["kernel"])
                n_contract = 2 if name == "out" and kernel.ndim == 3 else 1
                return {**rest,
                        **_quantize_kernel_int4(kernel, n_contract)}
            return {k: walk(v, k) for k, v in node.items()}
        return node

    return walk(nn.unbox(params))


__all__ = ["Int8DenseGeneral", "Int4DenseGeneral", "quantize_params",
           "quantize_params_int4", "quantized_bytes", "INT4_GROUP"]
