"""Group/Version/Resource registry: kind -> REST path mapping.

The reference gets this from the client-go scheme + RESTMapper (every typed
client call resolves a GVK to a request path).  We keep an explicit table for
the kinds the notebook stack touches; unknown kinds can be registered at
runtime (the analog of AddToScheme, notebook-controller/main.go:47-56).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class ResourceInfo:
    kind: str
    group: str          # "" for the core group
    version: str
    plural: str
    namespaced: bool = True

    @property
    def api_version(self) -> str:
        return self.version if not self.group else f"{self.group}/{self.version}"

    def prefix(self) -> str:
        """URL prefix up to (not including) the namespace/resource segments."""
        if not self.group:
            return f"/api/{self.version}"
        return f"/apis/{self.group}/{self.version}"

    def collection_path(self, namespace: str | None) -> str:
        if self.namespaced and namespace:
            return f"{self.prefix()}/namespaces/{namespace}/{self.plural}"
        return f"{self.prefix()}/{self.plural}"

    def object_path(self, namespace: str | None, name: str) -> str:
        return f"{self.collection_path(namespace)}/{name}"


_CORE = [
    ("Pod", "pods"), ("Service", "services"), ("ConfigMap", "configmaps"),
    ("Secret", "secrets"), ("ServiceAccount", "serviceaccounts"),
    ("Event", "events"),
]

_BUILTIN: list[ResourceInfo] = [
    *[ResourceInfo(k, "", "v1", p) for k, p in _CORE],
    ResourceInfo("Node", "", "v1", "nodes", namespaced=False),
    # cluster-scoped: discovery must say namespaced=false (the route
    # special-case in wire.route_path already treats it that way)
    ResourceInfo("Namespace", "", "v1", "namespaces", namespaced=False),
    ResourceInfo("StatefulSet", "apps", "v1", "statefulsets"),
    ResourceInfo("Deployment", "apps", "v1", "deployments"),
    ResourceInfo("NetworkPolicy", "networking.k8s.io", "v1", "networkpolicies"),
    ResourceInfo("Role", "rbac.authorization.k8s.io", "v1", "roles"),
    ResourceInfo("RoleBinding", "rbac.authorization.k8s.io", "v1", "rolebindings"),
    ResourceInfo("ClusterRole", "rbac.authorization.k8s.io", "v1",
                 "clusterroles", namespaced=False),
    ResourceInfo("ClusterRoleBinding", "rbac.authorization.k8s.io", "v1",
                 "clusterrolebindings", namespaced=False),
    ResourceInfo("Lease", "coordination.k8s.io", "v1", "leases"),
    ResourceInfo("Notebook", "kubeflow.org", "v1", "notebooks"),
    ResourceInfo("HTTPRoute", "gateway.networking.k8s.io", "v1", "httproutes"),
    ResourceInfo("Gateway", "gateway.networking.k8s.io", "v1", "gateways"),
    ResourceInfo("ReferenceGrant", "gateway.networking.k8s.io", "v1beta1",
                 "referencegrants"),
    # v1alpha3 matches what the controller renders (workload.py
    # generate_virtual_service; reference notebook_controller.go:581)
    ResourceInfo("VirtualService", "networking.istio.io", "v1alpha3",
                 "virtualservices"),
    ResourceInfo("ImageStream", "image.openshift.io", "v1", "imagestreams"),
    ResourceInfo("Route", "route.openshift.io", "v1", "routes"),
    ResourceInfo("Proxy", "config.openshift.io", "v1", "proxies", namespaced=False),
    ResourceInfo("APIServer", "config.openshift.io", "v1", "apiservers",
                 namespaced=False),
    ResourceInfo("OAuthClient", "oauth.openshift.io", "v1", "oauthclients",
                 namespaced=False),
    ResourceInfo("DataSciencePipelinesApplication",
                 "datasciencepipelinesapplications.opendatahub.io", "v1",
                 "datasciencepipelinesapplications"),
    ResourceInfo("CustomResourceDefinition", "apiextensions.k8s.io", "v1",
                 "customresourcedefinitions", namespaced=False),
    ResourceInfo("MutatingWebhookConfiguration", "admissionregistration.k8s.io",
                 "v1", "mutatingwebhookconfigurations", namespaced=False),
    ResourceInfo("ValidatingWebhookConfiguration", "admissionregistration.k8s.io",
                 "v1", "validatingwebhookconfigurations", namespaced=False),
]


class Scheme:
    """Kind <-> resource-path mapping with runtime registration."""

    def __init__(self) -> None:
        self._by_kind: dict[str, ResourceInfo] = {}
        self._by_path: dict[tuple[str, str, str], ResourceInfo] = {}
        for info in _BUILTIN:
            self.register(info)
        # Notebook serves three versions (reference CRD: v1 storage, all
        # served — api/v1/notebook_types.go:65-68); the extra versions are
        # path aliases so /apis/kubeflow.org/v1beta1/... routes, while
        # by_kind (the storage version clients default to) stays v1.
        for v in ("v1alpha1", "v1beta1"):
            self.register_served(ResourceInfo("Notebook", "kubeflow.org", v,
                                              "notebooks"))

    def register(self, info: ResourceInfo) -> None:
        self._by_kind[info.kind] = info
        self._by_path[(info.group, info.version, info.plural)] = info

    def register_served(self, info: ResourceInfo) -> None:
        """Register an additional served version: routable by path, but not
        the kind's storage/default version."""
        self._by_path[(info.group, info.version, info.plural)] = info

    def by_kind(self, kind: str) -> ResourceInfo:
        info = self._by_kind.get(kind)
        if info is None:
            raise KeyError(f"kind {kind!r} not registered in scheme")
        return info

    def served(self) -> list[ResourceInfo]:
        """Every served (group, version, plural) mapping — the discovery
        document source (storage versions AND path aliases)."""
        return list(self._by_path.values())

    def storage_versions(self) -> set[tuple[str, str]]:
        """(group, version) pairs that are some kind's storage/default
        version — discovery marks these preferred."""
        return {(i.group, i.version) for i in self._by_kind.values()}

    def by_path(self, group: str, version: str, plural: str) -> ResourceInfo | None:
        return self._by_path.get((group, version, plural))

    def kinds(self) -> list[str]:
        return sorted(self._by_kind)


DEFAULT_SCHEME = Scheme()
