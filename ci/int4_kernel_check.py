"""Numerics gate for the Pallas int4 dequant-matmul kernel (run on TPU).

Compares ops/int4_matmul.py against a host-side dequantized reference at
the bench shapes.  Mirrors ci/flash_numerics.py's role for the flash
kernel; the CPU test suite only exercises the XLA fallback path, so this
is the kernel's correctness pin.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from kubeflow_tpu.models.quant import INT4_GROUP, _quantize_kernel_int4  # noqa: E402
from kubeflow_tpu.ops.int4_matmul import int4_matmul, supported  # noqa: E402


def check(m: int, k_dim: int, n: int, seed: int = 0) -> float:
    k = jax.random.normal(jax.random.PRNGKey(seed), (k_dim, n)) * 0.05
    packed = _quantize_kernel_int4(k)
    x = jax.random.normal(jax.random.PRNGKey(seed + 1), (m, k_dim),
                          jnp.bfloat16)
    assert supported(m, k_dim, n, INT4_GROUP), (m, k_dim, n)
    out = int4_matmul(x, packed["kernel_q4"], packed["kernel_scale"],
                      group=INT4_GROUP)

    q4 = np.asarray(packed["kernel_q4"])
    lo = ((q4.astype(np.int8) << 4) >> 4).astype(np.float32)
    hi = (q4.astype(np.int8) >> 4).astype(np.float32)
    w = np.zeros((k_dim, n), np.float32)
    w[0::2] = lo
    w[1::2] = hi
    sc = np.asarray(packed["kernel_scale"], np.float32).reshape(
        k_dim // INT4_GROUP, n)
    w = (w.reshape(k_dim // INT4_GROUP, INT4_GROUP, n)
         * sc[:, None, :]).reshape(k_dim, n)
    ref = np.asarray(x, np.float32) @ w
    got = np.asarray(out, np.float32)
    return float(np.max(np.abs(got - ref)) / (np.max(np.abs(ref)) + 1e-9))


def main() -> None:
    if jax.default_backend() != "tpu":
        print("int4 kernel check: SKIP (needs TPU)")
        return
    shapes = [
        (16, 1536, 6144),    # mlp up, decode batch
        (16, 6144, 1536),    # mlp down
        (16, 1536, 32000 // 2 * 2),  # lm_head-ish (bn=256 path)
        (128, 1536, 1536),   # prefill rows
    ]
    for m, k_dim, n in shapes:
        err = check(m, k_dim, n)
        status = "OK" if err < 0.02 else "FAIL"
        print(f"int4 kernel [{m}x{k_dim}x{n}]: rel_err={err:.5f} {status}")
        assert err < 0.02, (m, k_dim, n, err)
    print("int4 kernel numerics: PASS")


if __name__ == "__main__":
    main()
