"""ODH extension-plane constants: the annotation/label/finalizer API surface.

Mirrors the reference constants scattered across
components/odh-notebook-controller/controllers/notebook_controller.go:56-74,
notebook_mutating_webhook.go:79-102, notebook_kube_rbac_auth.go:36-40,
notebook_network.go:36-39, plus the TPU extensions this framework adds.
"""

# -- reconciliation lock (webhook <-> ODH controller protocol) ----------------
# notebook_mutating_webhook.go:106-122 / odh notebook_controller.go:155-186
STOP_ANNOTATION = "kubeflow-resource-stopped"
RECONCILIATION_LOCK_VALUE = "odh-notebook-controller-lock"

# -- user-facing annotations (odh notebook_controller.go:56-67) ---------------
ANNOTATION_INJECT_AUTH = "notebooks.opendatahub.io/inject-auth"
ANNOTATION_AUTH_SIDECAR_CPU_REQUEST = "notebooks.opendatahub.io/auth-sidecar-cpu-request"
ANNOTATION_AUTH_SIDECAR_MEMORY_REQUEST = "notebooks.opendatahub.io/auth-sidecar-memory-request"
ANNOTATION_AUTH_SIDECAR_CPU_LIMIT = "notebooks.opendatahub.io/auth-sidecar-cpu-limit"
ANNOTATION_AUTH_SIDECAR_MEMORY_LIMIT = "notebooks.opendatahub.io/auth-sidecar-memory-limit"
ANNOTATION_LAST_IMAGE_SELECTION = "notebooks.opendatahub.io/last-image-selection"
ANNOTATION_UPDATE_PENDING = "notebooks.opendatahub.io/update-pending"
ANNOTATION_MLFLOW_INSTANCE = "opendatahub.io/mlflow-instance"
ANNOTATION_WORKBENCH_IMAGE_NAMESPACE = "opendatahub.io/workbench-image-namespace"
LABEL_FEAST_INTEGRATION = "opendatahub.io/feast-integration"
LABEL_RUNTIME_IMAGE = "opendatahub.io/runtime-image"
ANNOTATION_RUNTIME_IMAGE_METADATA = "opendatahub.io/runtime-image-metadata"

# -- finalizers (odh notebook_controller.go:69-74) ----------------------------
HTTPROUTE_FINALIZER = "notebook.opendatahub.io/httproute-cleanup"
REFERENCEGRANT_FINALIZER = "notebook.opendatahub.io/referencegrant-cleanup"
KUBE_RBAC_PROXY_FINALIZER = "notebook.opendatahub.io/kube-rbac-proxy-cleanup"
OAUTH_CLIENT_FINALIZER = "notebook.opendatahub.io/oauth-client-cleanup"

# -- routing (notebook_route.go:36-44) ----------------------------------------
HTTPROUTE_NAME_MAX_LEN = 63
NOTEBOOK_NAME_LABEL = "notebook-name"
NOTEBOOK_NAMESPACE_LABEL = "notebook-namespace"
REFERENCEGRANT_NAME = "notebook-httproute-access"
NOTEBOOK_PORT = 8888

# -- kube-rbac-proxy (notebook_kube_rbac_auth.go:36-40,
#    notebook_mutating_webhook.go:79-102, notebook_network.go:36-39) ----------
KUBE_RBAC_PROXY_PORT = 8443
KUBE_RBAC_PROXY_HEALTH_PORT = 8444
KUBE_RBAC_PROXY_PORT_NAME = "kube-rbac-proxy"
KUBE_RBAC_PROXY_CONTAINER_NAME = "kube-rbac-proxy"
KUBE_RBAC_PROXY_SERVICE_SUFFIX = "-kube-rbac-proxy"
KUBE_RBAC_PROXY_CONFIG_SUFFIX = "-kube-rbac-proxy-config"
KUBE_RBAC_PROXY_TLS_SECRET_SUFFIX = "-kube-rbac-proxy-tls"
KUBE_RBAC_PROXY_CONFIG_VOLUME = "kube-rbac-proxy-config"
KUBE_RBAC_PROXY_CONFIG_MOUNT_PATH = "/etc/kube-rbac-proxy"
KUBE_RBAC_PROXY_CONFIG_FILE = "config-file.yaml"
KUBE_RBAC_PROXY_TLS_VOLUME = "kube-rbac-proxy-tls-certificates"
KUBE_RBAC_PROXY_TLS_MOUNT_PATH = "/etc/tls/private"
KUBE_RBAC_PROXY_NETWORK_POLICY_SUFFIX = "-kube-rbac-proxy-np"
KUBE_RBAC_PROXY_DEFAULT_CPU = "100m"
KUBE_RBAC_PROXY_DEFAULT_MEMORY = "64Mi"
SERVING_CERT_ANNOTATION = "service.beta.openshift.io/serving-cert-secret-name"

# -- CA bundle (odh notebook_controller.go:528-635,
#    notebook_mutating_webhook.go:100-102) ------------------------------------
ODH_TRUSTED_CA_BUNDLE_CONFIGMAP = "odh-trusted-ca-bundle"
WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP = "workbench-trusted-ca-bundle"
KUBE_ROOT_CA_CONFIGMAP = "kube-root-ca.crt"
OPENSHIFT_SERVICE_CA_CONFIGMAP = "openshift-service-ca.crt"
TRUSTED_CA_BUNDLE_VOLUME = "trusted-ca"
TRUSTED_CA_MOUNT_PATH = "/etc/pki/tls/custom-certs"
TRUSTED_CA_BUNDLE_FILE = "ca-bundle.crt"
CA_BUNDLE_ENV_VARS = (
    "PIP_CERT",
    "REQUESTS_CA_BUNDLE",
    "SSL_CERT_FILE",
    "PIPELINES_SSL_SA_CERTS",
    "GIT_SSL_CAINFO",
)

# -- pipelines / Elyra (notebook_dspa_secret.go, notebook_rbac.go) ------------
ELYRA_SECRET_NAME = "ds-pipeline-config"
ELYRA_SECRET_KEY = "odh_dsp.json"
ELYRA_MOUNT_PATH = "/opt/app-root/runtimes"
ELYRA_VOLUME_NAME = "elyra-dsp-config"
PIPELINE_ROLEBINDING_PREFIX = "elyra-pipelines-"
PIPELINE_ROLE_NAME = "ds-pipeline-user-access-dspa"
RUNTIME_IMAGES_CONFIGMAP = "pipeline-runtime-images"
RUNTIME_IMAGES_VOLUME = "runtime-images"
RUNTIME_IMAGES_MOUNT_PATH = "/opt/app-root/pipeline-runtimes"

# -- Feast (notebook_feast_config.go:26-29) -----------------------------------
FEAST_CONFIGMAP_SUFFIX = "-feast-config"
FEAST_VOLUME_NAME = "feast-config"
FEAST_MOUNT_PATH = "/opt/app-root/src/feast-config"

# -- MLflow (notebook_mlflow.go) ----------------------------------------------
MLFLOW_ROLEBINDING_SUFFIX = "-mlflow"
MLFLOW_CLUSTER_ROLE = "mlflow-operator-mlflow-integration"
MLFLOW_TRACKING_URI_ENV = "MLFLOW_TRACKING_URI"
MLFLOW_K8S_INTEGRATION_ENV = "MLFLOW_K8S_INTEGRATION"
MLFLOW_TRACKING_AUTH_ENV = "MLFLOW_TRACKING_AUTH"
MLFLOW_TRACKING_AUTH_VALUE = "kubernetes-namespaced"

# -- cluster proxy env (notebook_mutating_webhook.go:473-490) -----------------
PROXY_ENV_VARS = ("HTTP_PROXY", "HTTPS_PROXY", "NO_PROXY")

# -- TPU extension: per-worker slice-internal traffic -------------------------
TPU_WORKER_NETWORK_POLICY_SUFFIX = "-tpu-workers-np"
