"""Int8 weight streaming for decode.

Decode is weight-bandwidth bound (every matmul weight streams from HBM
once per token step — BASELINE.md's decode roofline), so halving the
bytes halves the floor.  This module provides the opt-in int8 path:

- `Int8DenseGeneral`: a DenseGeneral twin whose parameters are an int8
  `kernel_q` plus a per-output-channel `kernel_scale`; at apply time the
  kernel is upcast and scaled right at the matmul operand
  (`w = kernel_q.astype(bf16) * scale`), which XLA fuses into the operand
  load — the int8 bytes are what crosses HBM.
- `quantize_params`: post-training transform from a trained param tree
  (fp32/bf16 `kernel`s) to the quantized tree (`kernel_q`,
  `kernel_scale`) the int8 model consumes.  Symmetric per-output-channel
  absmax quantization; norms/router/embedding stay in their original
  dtype (tiny, and the embedding is a lookup, not a stream).

Use: `cfg.with_(weight_dtype="int8")` makes the Transformer build its
dense layers as Int8DenseGeneral; feed it `quantize_params(params)`.
The reference has no inference path at all (notebook controller); this
extends the in-notebook compute plane.
"""

from __future__ import annotations

from typing import Any, Sequence, Union

import flax.linen as nn
import jax
import jax.numpy as jnp


class Int8DenseGeneral(nn.Module):
    """Drop-in for nn.DenseGeneral(use_bias=False) with quantized weights.

    Kernel layout matches DenseGeneral exactly — (contract dims...,
    feature dims...) — so `quantize_params` is a pure tree transform."""

    features: Union[int, Sequence[int]]
    axis: Union[int, Sequence[int]] = -1
    dtype: Any = jnp.bfloat16
    logical_axes: tuple = ()    # kernel's logical axis names, as _dense
                                # passes DenseGeneral — int8 weights shard
                                # by the same rule table as full-precision

    @nn.compact
    def __call__(self, x):
        features = (self.features if isinstance(self.features, (tuple, list))
                    else (self.features,))
        axis = (self.axis if isinstance(self.axis, (tuple, list))
                else (self.axis,))
        axis = tuple(a % x.ndim for a in axis)
        contract_shape = tuple(x.shape[a] for a in axis)
        kernel_shape = contract_shape + tuple(features)
        # per-LAST-dim scales (see _quantize_kernel): broadcast over every
        # other kernel dim
        scale_shape = (1,) * (len(kernel_shape) - 1) + (kernel_shape[-1],)

        k_axes = self.logical_axes or (None,) * len(kernel_shape)
        s_axes = (None,) * (len(scale_shape) - 1) + (k_axes[-1],)
        kq = self.param("kernel_q",
                        nn.with_logical_partitioning(
                            nn.initializers.zeros_init(), tuple(k_axes)),
                        kernel_shape, jnp.int8)
        ks = self.param("kernel_scale",
                        nn.with_logical_partitioning(
                            nn.initializers.ones_init(), s_axes),
                        scale_shape, jnp.bfloat16)
        kq, ks = nn.unbox(kq), nn.unbox(ks)
        w = kq.astype(self.dtype) * ks.astype(self.dtype)
        return jax.lax.dot_general(
            x.astype(self.dtype), w,
            (((tuple(axis)), tuple(range(len(contract_shape)))), ((), ())),
        )


def _quantize_kernel(kernel: jax.Array, stacked: bool = False) -> dict:
    """Symmetric per-LAST-dim absmax int8: one scale per slot of the
    kernel's final dimension, shared across every other dim.  Exact
    per-output-channel for rank-2 kernels ([in, out]); coarser for
    multi-dim features ([in, heads, head_dim] shares a scale across
    heads) — the tree transform cannot know how many trailing dims are
    features, and the last dim is always an output dim in this model's
    layouts.  `stacked` additionally keeps the leading scan-layer axis
    (kernels [L, ..., out] quantize per layer, scales [L, 1, ..., out] —
    what nn.scan's variable_axes slicing expects)."""
    k32 = kernel.astype(jnp.float32)
    axes = tuple(range(1 if stacked else 0, k32.ndim - 1))
    absmax = jnp.max(jnp.abs(k32), axis=axes, keepdims=True)
    scale = jnp.maximum(absmax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(k32 / scale), -127, 127).astype(jnp.int8)
    return {"kernel_q": q, "kernel_scale": scale.astype(jnp.bfloat16)}


def quantize_params(params,
                    skip: tuple = ("embed", "router", "experts")) -> Any:
    """Trained params -> the tree Int8DenseGeneral expects.

    Every dict holding a `kernel` leaf is rewritten to
    {kernel_q, kernel_scale}; subtrees named in `skip` and non-kernel
    params (norm scales) pass through unchanged.  The default skip list:
    the embedding (a lookup, not a weight stream), the MoE router
    (fp32 on purpose — routing is precision-sensitive, moe.py), and the
    expert FFNs (MoEMLP has no int8 module yet — quantizing their
    kernels would produce a tree the model cannot consume)."""
    def walk(node, name="", stacked=False):
        if isinstance(node, dict):
            if name in skip:
                return node
            if "kernel" in node and not isinstance(node["kernel"], dict):
                rest = {k: v for k, v in node.items() if k != "kernel"}
                return {**rest,
                        **_quantize_kernel(nn.unbox(node["kernel"]),
                                           stacked=stacked)}
            return {k: walk(v, k, stacked or k == "layers")
                    for k, v in node.items()}
        return node

    return walk(nn.unbox(params))


def quantized_bytes(params) -> int:
    """HBM bytes one decode step streams with the quantized tree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total


__all__ = ["Int8DenseGeneral", "quantize_params", "quantized_bytes"]
