"""Minimal Prometheus-style metrics registry (counters, gauges, histograms
with labels) with text exposition, standing in for the controller-runtime
metrics registry the reference uses (pkg/metrics/metrics.go:13-64).

Histograms follow the Prometheus data model exactly: cumulative `_bucket`
series with an `le` label (including the implicit `+Inf`), plus `_sum` and
`_count`.  The registry rejects duplicate registrations (two `# HELP`/
`# TYPE` blocks for one family is a scrape error in Prometheus) but returns
the existing metric on an identical re-registration, so idempotent setup
paths stay cheap.

Histogram observations may carry an EXEMPLAR — a small label set (e.g.
{"trace_id": ...}) pinning one concrete observation per bucket — rendered
only in the OpenMetrics exposition (`render(openmetrics=True)`:
`name_bucket{le="x"} n # {trace_id="..."} value`, counter families
declared without their `_total` suffix, `# EOF` appended by the serving
layer).  That is the metrics→traces pivot: a scrape shows a fat latency
bucket AND a trace id an operator can open in /debug/traces.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Optional

# Per-family label-set cap (cardinality guard): past this many distinct
# label sets, new ones fold into a reserved "other" series instead of
# growing the registry — a per-namespace family can never explode a
# scrape.  Families opt out with max_label_sets=0; the env knob is read
# once per Registry so tests can override it.
DEFAULT_MAX_LABEL_SETS = 1024

# The reserved label value every overflowing label set folds into.
OVERFLOW_LABEL = "other"


class _Metric:
    def __init__(self, name: str, help_: str, label_names: tuple[str, ...],
                 max_label_sets: int = 0):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self.max_label_sets = max_label_sets
        self.labelsets_dropped = 0
        self._values: dict[tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def _admit(self, known, key: tuple[str, ...]) -> tuple[str, ...]:
        """Resolve a label-set key against the cardinality cap: known keys
        and keys under the cap pass through; the rest fold into the
        reserved ``("other", ...)`` series and count a drop.  Called under
        ``self._lock`` with the metric's key store."""
        if not self.label_names or self.max_label_sets <= 0 \
                or key in known or len(known) < self.max_label_sets:
            return key
        self.labelsets_dropped += 1
        return (OVERFLOW_LABEL,) * len(self.label_names)

    def labels(self, *values: str) -> "_Child":
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, got {values}"
            )
        return _Child(self, tuple(values))

    def _set(self, key: tuple[str, ...], v: float) -> None:
        with self._lock:
            self._values[self._admit(self._values, key)] = v

    def _add(self, key: tuple[str, ...], v: float) -> None:
        with self._lock:
            key = self._admit(self._values, key)
            self._values[key] = self._values.get(key, 0.0) + v

    def _observe(self, key: tuple[str, ...], v: float,
                 exemplar: Optional[dict] = None) -> None:
        raise TypeError(f"{self.name}: observe() requires a histogram")

    def value(self, *values: str) -> float:
        return self._values.get(tuple(values), 0.0)

    def kind(self) -> str:
        raise NotImplementedError

    def collect(self) -> dict[tuple[str, ...], float]:
        return dict(self._values)

    def _label_str(self, key: tuple[str, ...], extra: str = "") -> str:
        parts = [f'{n}="{val}"' for n, val in zip(self.label_names, key)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def sample_lines(self, openmetrics: bool = False) -> list[str]:
        lines = []
        for key, v in sorted(self.collect().items()):
            lines.append(f"{self.name}{self._label_str(key)} {v:g}")
        return lines


class _Child:
    def __init__(self, metric: _Metric, key: tuple[str, ...]):
        self._metric = metric
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        self._metric._add(self._key, amount)

    def set(self, v: float) -> None:
        self._metric._set(self._key, v)

    def observe(self, v: float, exemplar: Optional[dict] = None) -> None:
        self._metric._observe(self._key, v, exemplar)


class Counter(_Metric):
    def kind(self) -> str:
        return "counter"

    def inc(self, amount: float = 1.0) -> None:
        self._add((), amount)


class Gauge(_Metric):
    def kind(self) -> str:
        return "gauge"

    def set(self, v: float) -> None:
        self._set((), v)

    def set_function(self, fn: Callable[[], float]) -> None:
        # a labeled gauge has no single value for one callback to feed; the
        # callback would render an unlabeled sample inside a labeled family,
        # which Prometheus rejects
        if self.label_names:
            raise ValueError(
                f"{self.name}: set_function() requires an unlabeled gauge "
                f"(labels {self.label_names} declared)")
        self._fn = fn

    def collect(self) -> dict[tuple[str, ...], float]:
        fn = getattr(self, "_fn", None)
        if fn is not None:
            self._set((), float(fn()))
        return super().collect()


# The Prometheus client_golang DefBuckets — what controller-runtime's
# reconcile-time histogram uses below its long exponential tail; plenty of
# resolution for both sub-ms in-memory reconciles and multi-second backoffs.
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0)


class Histogram(_Metric):
    """Cumulative-bucket histogram (`le`-labeled `_bucket` series plus
    `_sum`/`_count`), the exposition shape of
    controller_runtime_reconcile_time_seconds."""

    def __init__(self, name: str, help_: str, label_names: tuple[str, ...],
                 buckets: Optional[tuple[float, ...]] = None,
                 max_label_sets: int = 0):
        super().__init__(name, help_, label_names,
                         max_label_sets=max_label_sets)
        bounds = tuple(sorted(set(buckets if buckets is not None
                                  else DEFAULT_BUCKETS)))
        if not bounds:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        self.buckets = bounds  # upper bounds, +Inf implicit
        # key -> per-bucket counts (len(buckets)+1, last is +Inf)
        self._counts: dict[tuple[str, ...], list[int]] = {}
        self._sums: dict[tuple[str, ...], float] = {}
        # key -> bucket index -> (labels, observed value): the most recent
        # exemplar per bucket, pinned to the bucket the observation FELL in
        # so the OpenMetrics invariant (exemplar value <= le) holds
        self._exemplars: dict[tuple[str, ...],
                              dict[int, tuple[dict, float]]] = {}

    def kind(self) -> str:
        return "histogram"

    def observe(self, v: float, exemplar: Optional[dict] = None) -> None:
        self._observe((), v, exemplar)

    def _observe(self, key: tuple[str, ...], v: float,
                 exemplar: Optional[dict] = None) -> None:
        with self._lock:
            key = self._admit(self._counts, key)
            counts = self._counts.setdefault(
                key, [0] * (len(self.buckets) + 1))
            idx = len(self.buckets)
            for i, bound in enumerate(self.buckets):
                if v <= bound:
                    counts[i] += 1
                    idx = i
                    break
            else:
                counts[-1] += 1
            self._sums[key] = self._sums.get(key, 0.0) + v
            if exemplar:
                self._exemplars.setdefault(key, {})[idx] = (
                    {str(k): str(val) for k, val in exemplar.items()},
                    float(v))

    def _set(self, key: tuple[str, ...], v: float) -> None:
        raise TypeError(f"{self.name}: set() is not valid on a histogram")

    def _add(self, key: tuple[str, ...], v: float) -> None:
        raise TypeError(f"{self.name}: inc() is not valid on a histogram")

    # -- read side (tests assert on these) ------------------------------------
    def count_value(self, *values: str) -> int:
        with self._lock:
            return sum(self._counts.get(tuple(values), ()))

    def sum_value(self, *values: str) -> float:
        with self._lock:
            return self._sums.get(tuple(values), 0.0)

    def bucket_counts(self, *values: str) -> dict[float, int]:
        """Cumulative count per upper bound (inf included), as exposed."""
        with self._lock:
            counts = self._counts.get(tuple(values),
                                      [0] * (len(self.buckets) + 1))
            out: dict[float, int] = {}
            running = 0
            for bound, c in zip(self.buckets, counts):
                running += c
                out[bound] = running
            out[float("inf")] = running + counts[-1]
            return out

    def value(self, *values: str) -> float:
        return float(self.count_value(*values))

    def collect(self) -> dict[tuple[str, ...], float]:
        with self._lock:
            return {k: float(sum(c)) for k, c in self._counts.items()}

    def exemplar(self, *values: str) -> dict[float, tuple[dict, float]]:
        """Bucket upper bound -> (labels, observed value) for the stored
        exemplars of one label set (tests assert on this)."""
        with self._lock:
            stored = self._exemplars.get(tuple(values), {})
            bounds = self.buckets + (float("inf"),)
            return {bounds[i]: (dict(lbl), v)
                    for i, (lbl, v) in stored.items()}

    @staticmethod
    def _exemplar_suffix(ex: Optional[tuple[dict, float]]) -> str:
        if not ex:
            return ""
        labels, v = ex
        inner = ",".join(f'{k}="{val}"' for k, val in sorted(labels.items()))
        return " # {%s} %g" % (inner, v)

    def sample_lines(self, openmetrics: bool = False) -> list[str]:
        lines = []
        with self._lock:
            items = sorted(self._counts.items())
            sums = dict(self._sums)
            exemplars = {k: dict(v) for k, v in self._exemplars.items()}
        for key, counts in items:
            ex = exemplars.get(key, {}) if openmetrics else {}
            running = 0
            for i, (bound, c) in enumerate(zip(self.buckets, counts)):
                running += c
                le = 'le="%g"' % bound
                lines.append(
                    f"{self.name}_bucket"
                    f"{self._label_str(key, le)} {running}"
                    f"{self._exemplar_suffix(ex.get(i))}")
            total = running + counts[-1]
            inf = 'le="+Inf"'
            lines.append(
                f"{self.name}_bucket"
                f"{self._label_str(key, inf)} {total}"
                f"{self._exemplar_suffix(ex.get(len(self.buckets)))}")
            lines.append(
                f"{self.name}_sum{self._label_str(key)} "
                f"{sums.get(key, 0.0):g}")
            lines.append(f"{self.name}_count{self._label_str(key)} {total}")
        return lines


class Registry:
    def __init__(self, max_label_sets: Optional[int] = None) -> None:
        # METRICS_MAX_LABEL_SETS: per-family cap inherited by every metric
        # registered without an explicit max_label_sets (0 disables)
        if max_label_sets is None:
            try:
                max_label_sets = int(os.environ.get(
                    "METRICS_MAX_LABEL_SETS", DEFAULT_MAX_LABEL_SETS))
            except ValueError:
                max_label_sets = DEFAULT_MAX_LABEL_SETS
        self.max_label_sets = max(0, max_label_sets)
        self._metrics: list[_Metric] = []
        self._by_name: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._by_name.get(metric.name)
            if existing is not None:
                identical = (
                    type(existing) is type(metric)
                    and existing.help == metric.help
                    and existing.label_names == metric.label_names
                    and getattr(existing, "buckets", None)
                    == getattr(metric, "buckets", None)
                )
                if identical:
                    return existing
                raise ValueError(
                    f"metric {metric.name!r} already registered as a "
                    f"{existing.kind()} with labels {existing.label_names}; "
                    "duplicate families render two HELP/TYPE blocks, which "
                    "Prometheus rejects")
            self._metrics.append(metric)
            self._by_name[metric.name] = metric
            return metric

    def _cap(self, max_label_sets: Optional[int]) -> int:
        return (self.max_label_sets if max_label_sets is None
                else max(0, max_label_sets))

    def counter(
        self, name: str, help_: str = "", labels: tuple[str, ...] = (),
        max_label_sets: Optional[int] = None,
    ) -> Counter:
        m = self._register(Counter(name, help_, tuple(labels),
                                   max_label_sets=self._cap(max_label_sets)))
        assert isinstance(m, Counter)
        return m

    def gauge(
        self, name: str, help_: str = "", labels: tuple[str, ...] = (),
        max_label_sets: Optional[int] = None,
    ) -> Gauge:
        m = self._register(Gauge(name, help_, tuple(labels),
                                 max_label_sets=self._cap(max_label_sets)))
        assert isinstance(m, Gauge)
        return m

    def histogram(
        self, name: str, help_: str = "", labels: tuple[str, ...] = (),
        buckets: Optional[tuple[float, ...]] = None,
        max_label_sets: Optional[int] = None,
    ) -> Histogram:
        m = self._register(Histogram(name, help_, tuple(labels),
                                     buckets=buckets,
                                     max_label_sets=self._cap(
                                         max_label_sets)))
        assert isinstance(m, Histogram)
        return m

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._by_name.get(name)

    def families(self) -> list[tuple[str, str]]:
        """(name, kind) per registered family, in registration order — the
        inventory ci/metrics_drift_check.sh diffs against its golden list."""
        with self._lock:
            return [(m.name, m.kind()) for m in self._metrics]

    def labelsets_dropped(self) -> dict[str, int]:
        """Family -> cumulative label sets folded into the reserved
        'other' series.  A plain dict (not an auto-registered family) so
        a combined scrape over several registries exports ONE
        metrics_labelsets_dropped_total counter fed from all of them."""
        with self._lock:
            metrics = list(self._metrics)
        return {m.name: m.labelsets_dropped for m in metrics
                if m.labelsets_dropped > 0}

    def render(self, openmetrics: bool = False) -> str:
        """Text exposition.  Default: Prometheus text format 0.0.4.  With
        `openmetrics=True`: OpenMetrics 1.0 — counter families declared
        without the `_total` sample suffix, histogram buckets annotated
        with their stored exemplars.  The `# EOF` terminator is the
        SERVING layer's job (one per exposition, and this registry may be
        only part of a combined scrape body)."""
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            family = m.name
            if openmetrics and m.kind() == "counter" and \
                    family.endswith("_total"):
                family = family[: -len("_total")]
            lines.append(f"# HELP {family} {m.help}")
            lines.append(f"# TYPE {family} {m.kind()}")
            lines.extend(m.sample_lines(openmetrics=openmetrics))
        return "\n".join(lines) + "\n"


def register_cardinality_metrics(registry: Registry) -> Counter:
    """The guard's visibility counter: label sets folded into 'other' by
    the per-family cap, by family.  Registered by NotebookMetrics (and fed
    there from every scraped registry's labelsets_dropped()); bounded by
    the number of families, so it needs no cap of its own."""
    return registry.counter(
        "metrics_labelsets_dropped_total",
        "Label sets folded into the reserved 'other' series by the "
        "per-family cardinality cap (METRICS_MAX_LABEL_SETS)",
        labels=("family",), max_label_sets=0)
