"""Programmable fault injection for the in-memory control plane.

The reference ships shift-left chaos CI (SURVEY.md §4.6,
chaos/knowledge/workbenches.yaml) whose premise is that level-triggered
reconcilers converge back to steady state under faults.  This module is the
injection surface that lets tests actually exercise that premise against the
in-memory ApiServer: a `FaultPlan` of `FaultRule`s installed via
`ApiServer.install_fault_plan` intercepts top-level API verbs and can

  - raise per-verb/per-kind API errors (409 Conflict, 500 internal,
    503 "etcd leader changed"),
  - add artificial latency (advances an attached FakeClock, so delays are
    deterministic and visible to the controller's backoff machinery),
  - serve stale reads (the previous version of the object, from the watch
    history),
  - drop watch connections and reset the resourceVersion history window,
    forcing resumable watchers through the 410 Gone → relist path.

Determinism: every probabilistic decision draws from the plan's seeded
`random.Random`, and every injected fault is appended to `plan.log` so a
test can assert exactly what was injected.  Rules carry match counts
(`max_matches`) so a plan always drains — after every rule is exhausted the
cluster is fault-free and reconcilers must converge.

Scoping: faults fire only at top-level verb entry (re-entrant ApiServer
internals — GC, patch retries, admission — and watch-event-driven
components such as the FakeCluster data plane run inside an outer verb and
are exempt).  That models client↔apiserver failures without breaking the
cluster's own invariants; use `ApiServer.fault_exempt()` to make test
harness calls immune too.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional

from ..utils import tracing
from .errors import ConflictError, ServerError

if TYPE_CHECKING:  # pragma: no cover - typing only
    from .store import ApiServer

# verbs the ApiServer gates (watch drops ride on any verb via drop_watch)
VERBS = ("get", "list", "create", "update", "patch", "delete")

_ERROR_FACTORIES = {
    "conflict": lambda: ConflictError(
        "injected: the object has been modified"),
    "server": lambda: ServerError("injected: internal error"),
    "unavailable": lambda: ServerError(
        "injected: etcd leader changed (503)"),
}

ERROR_KINDS = tuple(_ERROR_FACTORIES)


@dataclass
class FaultRule:
    """One injectable behavior.  Empty verb/kind tuples match everything.

    A rule fires on a matching call once `after` matches have been skipped,
    with probability `probability` per candidate call, at most `max_matches`
    times.  Actions: `error`, `latency_s`, `stale_read`, `drop_watch`
    (disconnect resumable watchers; they reconnect lazily and replay the
    gap), and `reset_watch_history` (etcd compaction: evict the resume
    window so a reconnect from a pre-reset resourceVersion gets
    410 Gone → relist).  drop_watch + reset_watch_history compose into the
    classic dead-resourceVersion scenario."""

    verbs: tuple[str, ...] = ()
    kinds: tuple[str, ...] = ()
    error: str = ""              # one of ERROR_KINDS, or ""
    latency_s: float = 0.0
    stale_read: bool = False
    drop_watch: bool = False
    reset_watch_history: bool = False
    probability: float = 1.0
    max_matches: int = 1
    after: int = 0
    name: str = ""

    def __post_init__(self) -> None:
        if self.error and self.error not in _ERROR_FACTORIES:
            raise ValueError(
                f"unknown error kind {self.error!r}; want one of "
                f"{sorted(_ERROR_FACTORIES)}")

    def action(self) -> str:
        parts = []
        if self.error:
            parts.append(f"error:{self.error}")
        if self.drop_watch:
            parts.append("drop_watch")
        if self.reset_watch_history:
            parts.append("reset_history")
        if self.stale_read:
            parts.append("stale_read")
        if self.latency_s:
            parts.append("latency")
        return "+".join(parts) or "noop"


@dataclass
class FaultRecord:
    """One injected fault, for post-hoc assertions.

    `trace_id`/`span_id` identify the live reconcile root span the fault
    hit — spans always record in-process (utils/tracing.py), so the ids are
    populated with or without an exporter installed and empty only when the
    fault fired outside any span.  `seq` is the fault's index in `plan.log`
    — the same value stamped on the span event, so a soak can pair every
    log entry with exactly one span event, and the flight recorder can
    attribute each fault to the attempt it hit."""

    rule: str
    action: str
    verb: str
    kind: str
    namespace: str
    name: str
    trace_id: str = ""
    span_id: str = ""
    seq: int = -1


class FaultPlan:
    """A seeded, countable set of FaultRules plus the injection log."""

    def __init__(self, rules: list[FaultRule], seed: int = 0,
                 clock=None) -> None:
        self.rules = list(rules)
        self.seed = seed
        self.clock = clock  # FakeClock: latency advances it deterministically
        self.rng = random.Random(seed)
        self.log: list[FaultRecord] = []
        self._seen: list[int] = [0] * len(self.rules)
        self._fired: list[int] = [0] * len(self.rules)

    # -- state ----------------------------------------------------------------
    def exhausted(self) -> bool:
        """True once no rule can fire again — the cluster is fault-free."""
        return all(f >= r.max_matches
                   for r, f in zip(self.rules, self._fired))

    def fired(self, rule_name: str = "") -> int:
        if not rule_name:
            return sum(self._fired)
        return sum(f for r, f in zip(self.rules, self._fired)
                   if r.name == rule_name)

    def summary(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for rec in self.log:
            out[rec.action] = out.get(rec.action, 0) + 1
        return out

    # -- the injection point (called by ApiServer._fault_scope) ---------------
    def intercept(self, api: "ApiServer", verb: str, kind: str,
                  namespace: str = "", name: str = "") -> Optional[dict]:
        """May raise an ApiError; returns directives for the verb body
        (currently {"stale": True} for stale reads) or None."""
        directives: Optional[dict] = None
        for i, rule in enumerate(self.rules):
            if self._fired[i] >= rule.max_matches:
                continue
            if rule.verbs and verb not in rule.verbs:
                continue
            if rule.kinds and kind not in rule.kinds:
                continue
            self._seen[i] += 1
            if self._seen[i] <= rule.after:
                continue
            if rule.probability < 1.0 and \
                    self.rng.random() >= rule.probability:
                continue
            self._fired[i] += 1
            # stamp the fault onto whichever reconcile attempt it hit: the
            # faulting ApiServer call may be running inside a controller
            # phase child span, so walk up to the root (the manager's
            # per-attempt reconcile span) — a chaos-soak trace then shows
            # exactly which 409/503/watch-drop landed on which attempt
            span = tracing.current_span()
            while span.parent is not None:
                span = span.parent
            rec = FaultRecord(
                rule=rule.name or f"rule{i}", action=rule.action(),
                verb=verb, kind=kind, namespace=namespace, name=name,
                trace_id=span.trace_id, span_id=span.span_id,
                seq=len(self.log))
            self.log.append(rec)
            span.add_event("fault.injected", {
                "fault.rule": rec.rule,
                "fault.action": rec.action,
                "fault.verb": verb,
                "fault.kind": kind,
                "fault.namespace": namespace,
                "fault.name": name,
                "fault.seq": rec.seq,
                "fault.plan_seed": self.seed,
            })
            if rule.latency_s > 0:
                self._inject_latency(rule.latency_s)
            if rule.reset_watch_history:
                api.reset_watch_history()
            if rule.drop_watch:
                api.drop_watch_connections()
            if rule.stale_read:
                directives = {"stale": True}
            if rule.error:
                raise _ERROR_FACTORIES[rule.error]()
        return directives

    def _inject_latency(self, seconds: float) -> None:
        advance = getattr(self.clock, "advance", None)
        if advance is not None:
            advance(seconds)
        # no real sleeping: against a wall clock latency is recorded only —
        # deterministic tests never block on injected delays


def random_fault_plan(seed: int, kinds: tuple[str, ...],
                      clock=None, max_rules: int = 4,
                      max_matches_per_rule: int = 3) -> FaultPlan:
    """A bounded random plan for soak tests: every rule has a finite match
    count, so the plan always drains and the post-fault steady state is
    reachable.  Drawn entirely from `seed` — the same seed reproduces the
    same plan AND the same per-call probability rolls."""
    rng = random.Random(seed)
    rules: list[FaultRule] = []
    n_rules = rng.randint(1, max_rules)
    for i in range(n_rules):
        roll = rng.random()
        verb_pool = ["get", "list", "create", "update", "delete", "patch"]
        verbs = tuple(rng.sample(verb_pool, rng.randint(1, 3)))
        rule_kinds = tuple(rng.sample(kinds, rng.randint(1, min(3, len(kinds)))))
        common = dict(
            verbs=verbs, kinds=rule_kinds,
            probability=rng.uniform(0.5, 1.0),
            max_matches=rng.randint(1, max_matches_per_rule),
            after=rng.randint(0, 2), name=f"soak-{seed}-{i}",
        )
        if roll < 0.55:
            rules.append(FaultRule(
                error=rng.choice(list(ERROR_KINDS)), **common))
        elif roll < 0.70:
            rules.append(FaultRule(
                latency_s=rng.uniform(0.001, 0.05), **common))
        elif roll < 0.85:
            common["verbs"] = ("get",)
            rules.append(FaultRule(stale_read=True, **common))
        else:
            rules.append(FaultRule(
                drop_watch=True,
                reset_watch_history=rng.random() < 0.5, **common))
    return FaultPlan(rules, seed=seed, clock=clock)


__all__ = [
    "ERROR_KINDS",
    "FaultPlan",
    "FaultRecord",
    "FaultRule",
    "VERBS",
    "random_fault_plan",
]
