"""Controller runtime: reconcilers, watch wiring, workqueue, manager.

Mirrors the controller-runtime model the reference is built on —
level-triggered reconcilers keyed by namespace/name, For/Owns/Watches source
wiring with predicates and request mappers
(notebook-controller/controllers/notebook_controller.go:777-826), and a
manager that runs every registered controller
(notebook-controller/main.go:58-148).  Execution is deterministic and
single-threaded by default (`run_until_idle`), which replaces envtest's
eventually-consistent goroutine loop with exact test semantics; a threaded
mode serves standalone operation.
"""

from __future__ import annotations

import logging
import threading
import traceback
from dataclasses import dataclass, field
from typing import Callable, Optional, Protocol

from ..utils.clock import Clock
from .meta import KubeObject
from .store import ApiServer, WatchEvent

logger = logging.getLogger("kubeflow_tpu.kube")


@dataclass(frozen=True)
class Request:
    namespace: str
    name: str


@dataclass
class Result:
    requeue: bool = False
    requeue_after: float = 0.0  # seconds


class Reconciler(Protocol):
    def reconcile(self, req: Request) -> Result: ...


Predicate = Callable[[WatchEvent], bool]
Mapper = Callable[[KubeObject], list[Request]]


@dataclass
class WatchSpec:
    kind: str
    mapper: Mapper
    predicate: Optional[Predicate] = None


@dataclass
class _Registration:
    name: str
    reconciler: Reconciler
    for_kind: str
    owns: list[str] = field(default_factory=list)
    watches: list[WatchSpec] = field(default_factory=list)
    max_retries: int = 5


@dataclass(order=True)
class _Delayed:
    due: float
    reg_name: str = field(compare=False)
    request: Request = field(compare=False)


class Manager:
    """Runs registered controllers against an ApiServer.

    Tests drive it with `run_until_idle()` (drains the workqueue, honoring
    requeue-after via the injected clock when `advance_clock=True`);
    standalone mode uses `start()` which spins a worker thread.
    """

    def __init__(self, api: ApiServer, clock: Optional[Clock] = None) -> None:
        self.api = api
        self.clock = clock or Clock()
        self._registrations: list[_Registration] = []
        self._lock = threading.Lock()
        self._queue: list[tuple[str, Request]] = []
        self._queued: set[tuple[str, Request]] = set()
        self._delayed: list[_Delayed] = []
        self._retries: dict[tuple[str, Request], int] = {}
        self._errors: list[tuple[str, Request, BaseException]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        api.watch(self._on_event)

    # -- registration ---------------------------------------------------------
    def register(
        self,
        name: str,
        reconciler: Reconciler,
        for_kind: str,
        owns: Optional[list[str]] = None,
        watches: Optional[list[WatchSpec]] = None,
        max_retries: int = 5,
    ) -> None:
        self._registrations.append(
            _Registration(
                name=name,
                reconciler=reconciler,
                for_kind=for_kind,
                owns=owns or [],
                watches=watches or [],
                max_retries=max_retries,
            )
        )

    def unregister(self, name: str) -> None:
        """Remove a controller and drop its queued/delayed work.  An
        in-flight reconcile for it finishes first (the worker holds no
        lock across reconciles, so the next _pop simply won't see it)."""
        with self._lock:
            self._registrations = [
                r for r in self._registrations if r.name != name]
            self._queue = [k for k in self._queue if k[0] != name]
            self._queued = {k for k in self._queued if k[0] != name}
            self._delayed = [d for d in self._delayed if d.reg_name != name]
            # retry budgets die with the controller — a later registration
            # under the same name starts fresh, not mid-backoff
            self._retries = {k: v for k, v in self._retries.items()
                             if k[0] != name}

    # -- event -> requests ----------------------------------------------------
    def _on_event(self, ev: WatchEvent) -> None:
        for reg in self._registrations:
            for req in self._requests_for(reg, ev):
                self._enqueue(reg.name, req)

    def _requests_for(self, reg: _Registration, ev: WatchEvent) -> list[Request]:
        obj = ev.obj
        if obj.kind == reg.for_kind:
            return [Request(obj.namespace, obj.name)]
        if obj.kind in reg.owns:
            ref = obj.metadata.controller_owner()
            if ref is not None and ref.kind == reg.for_kind:
                return [Request(obj.namespace, ref.name)]
            return []
        out: list[Request] = []
        for spec in reg.watches:
            if spec.kind != obj.kind:
                continue
            if spec.predicate is not None and not spec.predicate(ev):
                continue
            out.extend(spec.mapper(obj))
        return out

    def _enqueue(self, reg_name: str, req: Request) -> None:
        with self._lock:
            key = (reg_name, req)
            if key not in self._queued:
                self._queued.add(key)
                self._queue.append(key)

    def enqueue(self, reg_name: str, req: Request) -> None:
        """Manual enqueue (tests, resync ticks)."""
        self._enqueue(reg_name, req)

    def watched_kinds(self) -> list[str]:
        """Every kind any controller watches — the informer set a real-cluster
        backend must stream (controller-runtime derives the same from
        For/Owns/Watches wiring)."""
        kinds: set[str] = set()
        for reg in self._registrations:
            kinds.add(reg.for_kind)
            kinds.update(reg.owns)
            kinds.update(spec.kind for spec in reg.watches)
        return sorted(kinds)

    def enqueue_all(self, reg_name: Optional[str] = None) -> None:
        """Resync: enqueue every existing primary object (informer re-list)."""
        for reg in self._registrations:
            if reg_name is not None and reg.name != reg_name:
                continue
            for obj in self.api.list(reg.for_kind):
                self._enqueue(reg.name, Request(obj.namespace, obj.name))

    # -- execution ------------------------------------------------------------
    def _pop(self) -> Optional[tuple[str, Request]]:
        with self._lock:
            if not self._queue:
                return None
            key = self._queue.pop(0)
            self._queued.discard(key)
            return key

    def _promote_delayed(self) -> None:
        now = self.clock.now()
        with self._lock:
            due = [d for d in self._delayed if d.due <= now]
            self._delayed = [d for d in self._delayed if d.due > now]
        for d in due:
            self._enqueue(d.reg_name, d.request)

    def _process_one(self) -> bool:
        self._promote_delayed()
        item = self._pop()
        if item is None:
            return False
        reg_name, req = item
        reg = next((r for r in self._registrations if r.name == reg_name),
                   None)
        if reg is None:
            return True  # unregistered while queued: drop the item

        def alive() -> bool:
            # unregister() may run DURING the reconcile; its queue/retry
            # cleanup must not be undone by this reconcile's bookkeeping —
            # identity check, so a same-name re-registration stays clean
            with self._lock:
                return any(r is reg for r in self._registrations)

        try:
            result = reg.reconciler.reconcile(req) or Result()
            self._retries.pop(item, None)
            if not alive():
                return True
            if result.requeue_after > 0:
                with self._lock:
                    self._delayed.append(
                        _Delayed(self.clock.now() + result.requeue_after, reg_name, req)
                    )
            elif result.requeue:
                self._enqueue(reg_name, req)
        except Exception as err:  # controller-runtime: requeue with backoff
            if not alive():
                return True
            count = self._retries.get(item, 0) + 1
            self._retries[item] = count
            if count <= reg.max_retries:
                logger.warning(
                    "reconcile %s %s failed (attempt %d): %s",
                    reg_name, req, count, err,
                )
                self._enqueue(reg_name, req)
            else:
                logger.error(
                    "reconcile %s %s dropped after %d attempts:\n%s",
                    reg_name, req, count, traceback.format_exc(),
                )
                self._errors.append((reg_name, req, err))
                self._retries.pop(item, None)  # fresh budget for future events
        return True

    def run_until_idle(self, max_iterations: int = 10_000) -> int:
        """Drain the workqueue; returns number of reconciles executed.
        Does NOT wait for delayed (requeue_after) items — use
        `advance(seconds)` to move the fake clock and re-drain."""
        n = 0
        while self._process_one():
            n += 1
            if n >= max_iterations:
                raise RuntimeError("run_until_idle: reconcile loop did not settle")
        return n

    def advance(self, seconds: float) -> int:
        """Advance a FakeClock and drain newly-due delayed requeues."""
        adv = getattr(self.clock, "advance", None)
        if adv is None:
            raise TypeError("advance() requires a FakeClock")
        adv(seconds)
        return self.run_until_idle()

    def pending_delayed(self) -> list[tuple[str, Request, float]]:
        with self._lock:
            return [(d.reg_name, d.request, d.due) for d in self._delayed]

    @property
    def dropped_errors(self) -> list[tuple[str, Request, BaseException]]:
        return list(self._errors)

    # -- standalone threaded mode ---------------------------------------------
    def start(self, poll_interval_s: float = 0.05) -> None:
        def loop() -> None:
            while not self._stop.is_set():
                try:
                    busy = self._process_one()
                except Exception:  # noqa: BLE001 — the loop must survive
                    # anything escaping the per-reconcile handler (queue
                    # bookkeeping, clock, mapping bugs): a silently-dead
                    # manager thread turns into every controller stalling,
                    # indistinguishable from a hung cluster
                    logger.exception("manager loop error; continuing")
                    busy = False
                if not busy:
                    self._stop.wait(poll_interval_s)

        self._thread = threading.Thread(target=loop, daemon=True, name="kube-manager")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # a reconciler may request shutdown from the worker thread itself
        # (e.g. the TLS-profile watcher); joining the current thread would
        # raise, and the loop exits on the event anyway
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=5)
            self._thread = None

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()

    def wait_until_stopped(self, timeout: Optional[float] = None) -> bool:
        """Block until stop() is called (standalone main loop); True when
        the stop event fired."""
        return self._stop.wait(timeout)
