"""Control-plane aggregation of worker data-plane telemetry.

Workers publish rolling summaries (runtime/telemetry.py TelemetryAgent)
into their pod's `notebooks.kubeflow.org/telemetry` annotation; this
module is the watch-fed read side.  A `WorkerTelemetryAggregator`
registers an incremental aggregate on the InformerCache (the PR 8
`add_aggregate` pattern: O(changed) per watch event, O(series) per
read, zero API calls), rolls per-worker summaries into per-notebook and
fleet series, and exports them:

  - `notebook_dataplane_tokens_per_second{namespace,name}` — sum over
    the slice's workers;
  - `notebook_dataplane_mfu_ratio{namespace,name}` — mean worker MFU
    (SPMD workers run the same program; the mean is the slice MFU);
  - `notebook_dataplane_step_time_seconds{namespace,name}` — the MAX
    worker step time (a synced slice steps at its slowest worker);
  - `notebook_dataplane_straggler{namespace,name}` — 1 while straggler
    detection fires: the slowest worker exceeds `straggler_ratio` x the
    slice median step time (with at least `min_workers` reporting).
    Firing also emits ONE Warning event naming the worker and a
    `dataplane.straggler` span event — observability only, no healing
    action (healing remains the RecoveryEngine's job, and a slow-but-
    alive worker is not a disruption).
  - `notebook_dataplane_straggler_checks_total{result}` and
    `notebook_dataplane_mfu_checks_total{result}` — per-evaluation
    verdict counters the SLO engine's (knob-disabled) `straggler_rate`
    and `fleet_mfu` objectives burn against.

`evaluate()` runs at every metrics scrape (NotebookMetrics wires it, the
same contract as the SLO engine); without a cache it brute-forces over
`api.list("Pod")` — the degraded-backend fallback every census has.
"""

from __future__ import annotations

import json
from typing import Optional

from ..utils import tracing
from ..utils.metrics import Registry

# MUST match runtime.telemetry.TELEMETRY_ANNOTATION / SUMMARY_VERSION —
# duplicated literals because core must not import the runtime package
# (tests/test_telemetry.py asserts the pair stays in sync)
TELEMETRY_ANNOTATION = "notebooks.kubeflow.org/telemetry"
SUMMARY_VERSION = 1

NOTEBOOK_NAME_LABEL = "notebook-name"

EVENT_STRAGGLER = "DataPlaneStraggler"
EVENT_STRAGGLER_CLEARED = "DataPlaneStragglerCleared"

_TRACER = tracing.get_tracer("kubeflow_tpu.core.telemetry")

_SEP = "\x1f"
# per-worker stats carried through the aggregate, one group key each
_FIELDS = ("tokens_per_s", "step_time_s", "mfu")


def register_dataplane_metrics(registry: Registry) -> dict:
    """The data-plane rollup families (registered by NotebookMetrics so
    the inventory is stable whether or not an aggregator is attached;
    the aggregator re-registers identically and feeds the same
    objects)."""
    return {
        "tokens_per_second": registry.gauge(
            "notebook_dataplane_tokens_per_second",
            "Aggregate training/decode throughput reported by a "
            "notebook's workers",
            labels=("namespace", "name")),
        "mfu_ratio": registry.gauge(
            "notebook_dataplane_mfu_ratio",
            "Mean worker MFU (0-1, runtime.roofline definition) per "
            "notebook",
            labels=("namespace", "name")),
        "step_time_seconds": registry.gauge(
            "notebook_dataplane_step_time_seconds",
            "Slowest-worker rolling step time per notebook (a synced "
            "slice steps at its slowest worker)",
            labels=("namespace", "name")),
        "straggler": registry.gauge(
            "notebook_dataplane_straggler",
            "Whether straggler detection currently fires for the "
            "notebook (slowest worker beyond the ratio of the slice "
            "median)",
            labels=("namespace", "name")),
        "straggler_checks": registry.counter(
            "notebook_dataplane_straggler_checks_total",
            "Per-notebook straggler evaluations by verdict "
            "(ok | straggler)",
            labels=("result",)),
        "mfu_checks": registry.counter(
            "notebook_dataplane_mfu_checks_total",
            "Per-notebook fleet-MFU evaluations by verdict (ok | low; "
            "checked against DATAPLANE_MFU_TARGET when set)",
            labels=("result",)),
    }


def parse_pod_telemetry(pod) -> Optional[dict]:
    """(notebook, worker, summary) contribution of one pod, or None for
    pods without a well-formed telemetry annotation."""
    nb = pod.metadata.labels.get(NOTEBOOK_NAME_LABEL)
    if not nb:
        return None
    payload = pod.metadata.annotations.get(TELEMETRY_ANNOTATION)
    if not payload:
        return None
    try:
        summary = json.loads(payload)
    except (ValueError, TypeError):
        return None
    if not isinstance(summary, dict) or summary.get("v") != SUMMARY_VERSION:
        return None
    return {"notebook": nb, "worker": pod.name, "summary": summary}


class WorkerTelemetryAggregator:
    """Roll per-worker telemetry annotations into per-notebook series;
    see module docstring."""

    AGGREGATE = "dataplane-telemetry"

    def __init__(self, api, registry: Registry, clock,
                 cache=None, recorder=None,
                 straggler_ratio: float = 1.5,
                 min_workers: int = 2,
                 mfu_target: float = 0.0) -> None:
        self.api = api
        self.clock = clock
        self.cache = cache
        self.recorder = recorder  # kube.EventRecorder (None = no events)
        self.straggler_ratio = max(straggler_ratio, 1.0)
        self.min_workers = max(min_workers, 2)
        self.mfu_target = mfu_target
        m = register_dataplane_metrics(registry)
        self.tokens_gauge = m["tokens_per_second"]
        self.mfu_gauge = m["mfu_ratio"]
        self.step_gauge = m["step_time_seconds"]
        self.straggler_gauge = m["straggler"]
        self.straggler_checks = m["straggler_checks"]
        self.mfu_checks = m["mfu_checks"]
        # (ns, nb) -> straggling worker name, for fire/clear transitions
        self._stragglers: dict[tuple[str, str], str] = {}
        # series emitted by the last evaluation — a notebook whose
        # workers stopped reporting must read 0, not stale
        self._seen: set[tuple[str, str]] = set()
        self._last: dict = {"notebooks": {}, "stragglers": [], "fleet": {}}
        self.evaluations = 0
        if self.cache is not None:
            try:
                self.cache.add_aggregate("Pod", self.AGGREGATE,
                                         self._pod_contrib)
            except Exception:  # noqa: BLE001 — degraded backend: the
                self.cache = None  # list-scan fallback serves instead

    # -- cache aggregate ------------------------------------------------------
    @classmethod
    def _pod_contrib(cls, pod) -> dict:
        """Per-pod contribution: one group per (notebook, worker, field).
        A worker's key is unique to its pod, so the per-group 'sum' IS
        the worker's current value and updates replace it O(1)."""
        parsed = parse_pod_telemetry(pod)
        if parsed is None:
            return {}
        s = parsed["summary"]
        out = {}
        for fld in _FIELDS:
            v = s.get(fld)
            if isinstance(v, (int, float)):
                out[_SEP.join((pod.namespace, parsed["notebook"],
                               parsed["worker"], fld))] = float(v)
        return out

    def _worker_stats(self) -> dict[tuple[str, str], dict[str, dict]]:
        """(ns, notebook) -> worker -> {field: value}, from the cache's
        incremental sums or the pod-list fallback."""
        out: dict[tuple[str, str], dict[str, dict]] = {}
        if self.cache is not None:
            sums = self.cache.aggregate("Pod", self.AGGREGATE)
        else:
            sums = {}
            for pod in self.api.list("Pod"):
                sums.update(self._pod_contrib(pod))
        for key, v in sums.items():
            ns, nb, worker, fld = key.split(_SEP)
            out.setdefault((ns, nb), {}).setdefault(worker, {})[fld] = v
        return out

    # -- evaluation (scrape-time) ---------------------------------------------
    def evaluate(self) -> dict:
        """Recompute the rollup, update gauges/counters, and transition
        straggler state.  Deterministic under FakeClock; NotebookMetrics
        calls this from every scrape."""
        self.evaluations += 1
        stats = self._worker_stats()
        notebooks: dict[str, dict] = {}
        stragglers: list[dict] = []
        seen: set[tuple[str, str]] = set()
        for (ns, nb), workers in sorted(stats.items()):
            complete = {w: f for w, f in workers.items()
                        if all(k in f for k in _FIELDS)}
            if not complete:
                continue
            seen.add((ns, nb))
            tokens = sum(f["tokens_per_s"] for f in complete.values())
            mfu = (sum(f["mfu"] for f in complete.values())
                   / len(complete))
            steps = sorted((f["step_time_s"], w)
                           for w, f in complete.items())
            slowest_time, slowest_worker = steps[-1]
            # lower-middle median: for even worker counts the upper
            # middle could BE the straggler, hiding it from its own
            # baseline (the 2-worker degenerate case otherwise never
            # fires)
            median = steps[(len(steps) - 1) // 2][0]
            straggling = (
                len(complete) >= self.min_workers and median > 0
                and slowest_time > self.straggler_ratio * median)
            self.tokens_gauge.labels(ns, nb).set(tokens)
            self.mfu_gauge.labels(ns, nb).set(mfu)
            self.step_gauge.labels(ns, nb).set(slowest_time)
            self.straggler_gauge.labels(ns, nb).set(
                1.0 if straggling else 0.0)
            self.straggler_checks.labels(
                "straggler" if straggling else "ok").inc()
            if self.mfu_target > 0:
                self.mfu_checks.labels(
                    "low" if mfu < self.mfu_target else "ok").inc()
            else:
                self.mfu_checks.labels("ok").inc()
            entry = {
                "workers": {w: dict(f) for w, f in sorted(complete.items())},
                "tokens_per_s": tokens,
                "mfu": mfu,
                "step_time_s": slowest_time,
                "median_step_time_s": median,
                "straggler": slowest_worker if straggling else None,
            }
            notebooks[f"{ns}/{nb}"] = entry
            if straggling:
                stragglers.append({
                    "namespace": ns, "name": nb,
                    "worker": slowest_worker,
                    "step_time_s": slowest_time,
                    "median_step_time_s": median,
                    "ratio": slowest_time / median,
                })
            self._transition(ns, nb, straggling, slowest_worker,
                             slowest_time, median)
        # notebooks that vanished (or stopped reporting) read 0, and a
        # firing straggler clears rather than lingering
        for ns, nb in self._seen - seen:
            self.tokens_gauge.labels(ns, nb).set(0.0)
            self.mfu_gauge.labels(ns, nb).set(0.0)
            self.step_gauge.labels(ns, nb).set(0.0)
            self.straggler_gauge.labels(ns, nb).set(0.0)
            self._transition(ns, nb, False, "", 0.0, 0.0)
        self._seen = seen
        self._last = {
            "notebooks": notebooks,
            "stragglers": stragglers,
            "fleet": {
                "notebooks": len(notebooks),
                "tokens_per_s": sum(
                    e["tokens_per_s"] for e in notebooks.values()),
                "mfu_mean": (sum(e["mfu"] for e in notebooks.values())
                             / len(notebooks)) if notebooks else 0.0,
                "stragglers": len(stragglers),
            },
        }
        return self._last

    def _transition(self, ns: str, nb: str, straggling: bool,
                    worker: str, slowest: float, median: float) -> None:
        key = (ns, nb)
        prev = self._stragglers.get(key)
        if straggling and prev != worker:
            self._stragglers[key] = worker
            msg = (f"worker {worker} step time {slowest:.3f}s exceeds "
                   f"{self.straggler_ratio:g}x the slice median "
                   f"{median:.3f}s")
            self._emit_event(ns, nb, "Warning", EVENT_STRAGGLER, msg)
            with _TRACER.start_span("dataplane.straggler", attributes={
                    "namespace": ns, "notebook": nb,
                    "worker": worker}) as span:
                span.add_event("straggler.detected", {
                    "worker": worker, "step_time_s": slowest,
                    "median_step_time_s": median})
        elif not straggling and prev is not None:
            del self._stragglers[key]
            self._emit_event(
                ns, nb, "Normal", EVENT_STRAGGLER_CLEARED,
                f"worker {prev} rejoined the slice pace")

    def _emit_event(self, ns: str, nb: str, etype: str, reason: str,
                    message: str) -> None:
        if self.recorder is None:
            return
        getter = self.cache.get if self.cache is not None \
            else self.api.try_get
        try:
            notebook = getter("Notebook", ns, nb)
            if notebook is not None:
                self.recorder.event(notebook, etype, reason, message)
        except Exception:  # noqa: BLE001 — telemetry must never take
            pass           # down the scrape path over an event write

    # -- read side (/debug/fleet, ops.diagnose) -------------------------------
    def snapshot(self) -> dict:
        """The /debug/fleet `dataplane` section: a fresh evaluation's
        per-notebook rollup, active stragglers, and fleet totals (an
        operator hitting /debug/fleet between scrapes must see the
        current annotations, not the last scrape's)."""
        self.evaluate()
        out = dict(self._last)
        out["evaluations"] = self.evaluations
        out["straggler_ratio"] = self.straggler_ratio
        return out


__all__ = [
    "EVENT_STRAGGLER", "EVENT_STRAGGLER_CLEARED", "SUMMARY_VERSION",
    "TELEMETRY_ANNOTATION", "WorkerTelemetryAggregator",
    "parse_pod_telemetry", "register_dataplane_metrics",
]
