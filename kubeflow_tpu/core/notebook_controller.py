"""Core Notebook reconciler: CR -> StatefulSet(s)/Service(s)/status.

Port of NotebookReconciler
(components/notebook-controller/controllers/notebook_controller.go:79-826)
with the TPU workload path.  Event re-emission lives in its own controller
(the reference multiplexes Events through the same queue and wishes it
didn't — see the TODO at notebook_controller.go:98; splitting removes the
name-collision hazard)."""

from __future__ import annotations

import copy
import logging
from typing import Optional

from ..api.types import CONDITION_RECOVERY_EXHAUSTED, Notebook, notebook_status
from ..common import reconcilehelper as rh
from ..kube import (
    ApiServer,
    EventRecorder,
    KubeObject,
    Manager,
    NotFoundError,
    Request,
    Result,
    WatchSpec,
    retry_on_conflict,
    set_controller_reference,
)
from ..utils import tracing
from ..utils.clock import Clock
from ..utils.config import CoreConfig
from . import constants as C
from .metrics import NotebookMetrics
from .selfheal import RecoveryEngine, SliceRestartError

logger = logging.getLogger("kubeflow_tpu.core")

# phase child spans (render/apply/status) parent onto the manager's
# per-attempt reconcile root span via the shared context stack
_TRACER = tracing.get_tracer("kubeflow_tpu.core.notebook")


class NotebookReconciler:
    def __init__(
        self,
        api: ApiServer,
        cfg: CoreConfig,
        metrics: NotebookMetrics,
        recorder: Optional[EventRecorder] = None,
        clock: Optional[Clock] = None,
        cache=None,
        session=None,
    ):
        self.api = api
        self.cfg = cfg
        self.metrics = metrics
        self.recorder = recorder or EventRecorder(api, "notebook-controller")
        self.clock = clock or Clock()
        # indexed informer cache (kube.InformerCache): the hot-path read
        # surface — owned-StatefulSet lookup by owner uid, worker pods by
        # label index — replacing O(all-objects) api.list scans.  None
        # falls back to live reads (direct-construction unit tests).
        self.cache = cache
        # session-state store (core/sessionstate.py): when wired (an
        # explicit store, or CHECKPOINT_STORE_URI in config), the recovery
        # engine prefers checkpoint/migrate over bare slice restarts
        if session is None and cfg.checkpoint_store_uri:
            from .sessionstate import open_store

            session = open_store(cfg.checkpoint_store_uri, clock=self.clock)
        self.session = session
        # every fence rejection (a demoted primary still trying to write)
        # is a near-miss worth counting — the soak asserts the zombie was
        # actually stopped, not merely absent
        if session is not None and hasattr(session, "on_fenced_write"):
            session.on_fenced_write = (
                lambda ns, _name: metrics.replication_fenced_writes
                .labels(ns).inc())
        # slice-atomic self-healing: budgeted recovery of disrupted TPU
        # slices, bookkeeping persisted on the CR (core/selfheal.py)
        self.recovery = RecoveryEngine(api, cfg, metrics, self.recorder,
                                       clock=self.clock, cache=cache,
                                       session=session)
        # first-readiness tracking for the notebook_to_ready_seconds
        # histogram: first-seen clock time per live notebook (keyed by uid
        # so a delete+recreate measures afresh), dropped once observed
        self._first_seen: dict[tuple, float] = {}
        self._ready_observed: set[tuple] = set()  # per (uid-key, generation)
        self._ready_measured: set[tuple[str, str, str]] = set()  # per uid

    # -- main loop (reference Reconcile, notebook_controller.go:94-294) -------
    def reconcile(self, req: Request) -> Result:
        # primary read off the informer cache (controller-runtime's cached
        # client): the event that enqueued this request already updated it
        if self.cache is not None:
            obj = self.cache.get("Notebook", req.namespace, req.name)
        else:
            obj = self.api.try_get("Notebook", req.namespace, req.name)
        if obj is None:
            return Result()
        nb = Notebook(obj)
        # lifecycle ledger identity: the attempt's root span carries the
        # spec generation so stage attribution keys (ns, name, generation)
        # and a spec update opens a fresh ledger entry
        _TRACER.current_span().set_attribute(
            "generation", int(obj.metadata.generation or 1))
        # jupyter-web-app deletes with foreground policy: while terminating,
        # recreating owned objects would fight the API server (:138)
        if obj.metadata.deletion_timestamp is not None:
            return Result()

        # gang gate (core/scheduler.py): with the slice scheduler on, a TPU
        # notebook renders NO workload until the all-or-nothing placement
        # intent covers every slice — so a half-placed slice can never
        # exist, let alone wedge.  The scheduler's annotation write is a
        # non-status update and re-triggers this reconciler.
        if self.cfg.enable_slice_scheduler and nb.tpu is not None \
                and C.STOP_ANNOTATION not in nb.metadata.annotations:
            from .scheduler import placement_covers

            # replicated notebooks gang-gate on EVERY replica's gangs:
            # a follower without capacity is a follower that cannot
            # catch up, so nothing renders until the full set is placed
            rep = nb.replication
            total_gangs = nb.tpu.slices * (rep.replicas if rep else 1)
            if not placement_covers(nb, total_gangs):
                self._update_status(nb, [], scheduling=True)
                return Result()

        from .workload import (
            generate_headless_service,
            generate_service,
            generate_statefulsets,
            generate_virtual_service,
        )

        # StatefulSets (one per slice; one total for CPU notebooks)
        with _TRACER.start_span("render",
                                {"phase": "render"}) as render_span:
            desired_sets = generate_statefulsets(nb, self.cfg)
            render_span.set_attribute("statefulsets", len(desired_sets))
        if self.cache is not None:
            # owner-uid index: O(this notebook's StatefulSets) instead of a
            # live list scan over every StatefulSet in the namespace
            existing = self.cache.by_index(
                "StatefulSet", "owner-uid", obj.metadata.uid)
        else:
            existing = [
                s
                for s in self.api.list("StatefulSet", namespace=req.namespace)
                if (ref := s.metadata.controller_owner()) is not None
                and ref.kind == "Notebook"
                and ref.uid == obj.metadata.uid
            ]
        existing_by_name = {s.name: s for s in existing}

        def slice_of(sts: KubeObject) -> Optional[str]:
            # generate-name matching key: replicated notebooks repeat each
            # slice label once per replica, so the replica label joins the
            # key or follower STS would collide with the primary's
            labels = (
                sts.spec.get("template", {})
                .get("metadata", {})
                .get("labels", {})
            )
            s = labels.get(C.TPU_SLICE_LABEL)
            if s is None:
                return None
            r = labels.get(C.REPLICA_LABEL)
            return s if r is None else f"{r}/{s}"

        existing_by_slice = {slice_of(s): s for s in existing if slice_of(s)}
        live_names: list[str] = []  # ordered: slice 0 first
        matched_live: set[str] = set()
        # Slice-atomic under partial failure: every slice STS is ATTEMPTED
        # each pass even when an earlier one fails (a transient 500 on slice
        # 0 must not leave slices 1..N un-reconciled — that is how a cull or
        # scale-down strands a half-stopped TPU slice).  Errors aggregate
        # and re-raise so the manager's backoff retries the whole set; the
        # per-slice writes themselves are idempotent.
        errors: list[Exception] = []
        with _TRACER.start_span("apply", {"phase": "apply"}) as apply_span:
            self._apply_workload(
                nb, obj, req, desired_sets, existing, existing_by_name,
                existing_by_slice, slice_of, live_names, matched_live, errors)

            if errors:
                apply_span.set_attribute("error", True)
                apply_span.add_event("apply.errors", {
                    "count": len(errors),
                    "first": str(errors[0]),
                })
                # best-effort truthful status over EVERY existing STS,
                # matched or not (a half-stopped slice must read
                # Stopping/Degraded, never Stopped/Healthy), then fail the
                # reconcile so the manager's backoff retries it
                names = live_names + [
                    s.name for s in existing if s.name not in matched_live]
                try:
                    self._update_status(nb, names)
                except Exception:  # noqa: BLE001 — the slice error wins
                    pass
                raise errors[0]

            # Services (no-op detection against the informer cache: a
            # converged notebook costs zero Service API calls per pass)
            svc = generate_service(nb)
            set_controller_reference(obj, svc)
            rh.reconcile_object(self.api, svc, rh.copy_service_fields,
                                cache=self.cache)
            if nb.tpu is not None:
                headless = generate_headless_service(nb)
                set_controller_reference(obj, headless)
                rh.reconcile_object(self.api, headless,
                                    rh.copy_service_fields, cache=self.cache)

            if self.cfg.use_istio:
                vs = generate_virtual_service(nb, self.cfg)
                set_controller_reference(obj, vs)
                rh.reconcile_object(self.api, vs, rh.copy_spec,
                                    cache=self.cache)

        # status from live STS + pods
        self._update_status(nb, live_names)

        # restart annotation (notebook_controller.go:259-294); for TPU
        # notebooks restart is slice-atomic: delete every worker pod
        if self.cache is not None:
            fresh = self.cache.get("Notebook", req.namespace, req.name)
        else:
            fresh = self.api.try_get("Notebook", req.namespace, req.name)
        annotations = fresh.metadata.annotations if fresh is not None else {}
        if annotations.get(C.ANNOTATION_NOTEBOOK_RESTART) == "true":
            # _restart_pods raises after attempting the whole slice set if
            # any delete failed — the annotation then survives for the
            # retry, so a half-restarted slice is never reported restarted
            self._restart_pods(nb, live_names)
            def clear() -> None:
                live = self.api.get("Notebook", req.namespace, req.name)
                live.metadata.annotations.pop(C.ANNOTATION_NOTEBOOK_RESTART, None)
                self.api.update(live)
            retry_on_conflict(clear)

        # self-healing pass: disruption detection + budgeted slice-atomic
        # recovery.  Runs after the status pass (it keys off the freshly
        # written slice health and persists bookkeeping over it) and after
        # the manual restart annotation (an operator-requested restart is
        # not charged against the recovery budget).
        requeue_s = self.recovery.maybe_recover(
            nb, live_names,
            pods_of=lambda name: self._pods_of(nb, name),
            restart_slice=lambda name: self._restart_pods(nb, [name]),
            stamp_restore=lambda name, idx: self._stamp_restore(
                nb, name, idx),
        )
        if requeue_s > 0:
            return Result(requeue_after=requeue_s)
        return Result()

    def _apply_workload(self, nb, obj, req, desired_sets, existing,
                        existing_by_name, existing_by_slice, slice_of,
                        live_names, matched_live, errors) -> None:
        """The workload half of the 'apply' phase: per-slice StatefulSet
        create/update plus scale-in pruning; errors aggregate into `errors`
        for the caller's slice-atomic handling."""
        for idx, desired in enumerate(desired_sets):
            set_controller_reference(obj, desired)
            if desired.name:
                found = existing_by_name.get(desired.name)
            elif (s := slice_of(desired)) is not None:
                # generate-name (long CR name) TPU slices match by slice label
                found = existing_by_slice.get(s)
            else:
                found = existing[0] if existing else None
            try:
                if found is None:
                    self.metrics.creation.labels(req.namespace).inc()
                    try:
                        live = self.api.create(desired)
                    except Exception:
                        self.metrics.fail_creation.labels(req.namespace).inc()
                        raise
                else:
                    # cache reads are shared frozen snapshots: drift
                    # correction mutates a private copy, never the cache
                    candidate = found.deepcopy()
                    if rh.copy_statefulset_fields(desired, candidate):
                        candidate = self.api.update(candidate)
                    live = candidate
            except Exception as err:  # noqa: BLE001 — aggregated below
                errors.append(err)
                continue
            live_names.append(live.name)
            matched_live.add(live.name)

        # prune slices beyond spec.tpu.slices (scale-in of multi-slice);
        # same aggregation — one failed delete must not strand the rest.
        # Skipped entirely when a create/update above failed: an STS whose
        # update errored never joined matched_live, and "failed to match"
        # must not be mistaken for "extra slice to delete".
        if not errors:
            for s in existing:
                if s.name not in matched_live:
                    try:
                        self.api.delete("StatefulSet", req.namespace, s.name)
                    except NotFoundError:
                        pass
                    except Exception as err:  # noqa: BLE001
                        errors.append(err)

    # -- helpers ---------------------------------------------------------------
    def _pods_of(self, nb: Notebook, live_sts_name: str) -> list[KubeObject]:
        """Pods of a live StatefulSet, selected via its own selector — the
        pod labels carry the *rendered* statefulset name, which differs from
        the live object name when generateName kicked in (long CR names).
        With a cache the selector lookup is served by the Pod label index
        (setup_core_controllers registers it for the STS selector label)."""
        if self.cache is not None:
            sts = self.cache.get("StatefulSet", nb.namespace, live_sts_name)
        else:
            sts = self.api.try_get("StatefulSet", nb.namespace, live_sts_name)
        if sts is None:
            return []
        selector = sts.spec.get("selector", {}).get("matchLabels", {})
        if not selector:
            return []
        if self.cache is not None:
            return self.cache.select("Pod", nb.namespace, selector)
        return self.api.list("Pod", namespace=nb.namespace, label_selector=selector)

    def _restart_pods(self, nb: Notebook, live_names: list[str]) -> None:
        """Slice-atomic worker restart: delete EVERY pod of every named
        slice, aggregating errors — a transient delete failure mid-loop
        must not leave the slice partially restarted with the rest
        untried.  Raises SliceRestartError after the full sweep so the
        manager's backoff retries the whole set (the deletes are
        idempotent: an already-gone pod is a NotFound no-op)."""
        errors: list[Exception] = []
        attempted = 0
        for live_name in live_names:
            for pod in self._pods_of(nb, live_name):
                attempted += 1
                try:
                    self.api.delete("Pod", nb.namespace, pod.name)
                except NotFoundError:
                    pass
                except Exception as err:  # noqa: BLE001 — aggregated below
                    errors.append(err)
        if errors:
            raise SliceRestartError(errors, attempted)

    def _stamp_restore(self, nb: Notebook, live_name: str,
                       slice_idx: int) -> None:
        """Sync one live slice StatefulSet with the restore intent the
        recovery engine just wrote into status.sessionState: re-render the
        slice template (workload._render_checkpoint_contract injects
        CHECKPOINT_RESTORE_URI/_GENERATION from the LIVE status) and copy
        the owned fields onto the live object, so the pods the restart
        recreates boot with the restore env.  Reads the apiserver, not the
        cache — the write-ahead status update this stamps from may be
        younger than the informer stream."""
        from .workload import generate_statefulsets

        fresh = self.api.try_get("Notebook", nb.namespace, nb.name)
        if fresh is None:
            return
        desired_sets = generate_statefulsets(Notebook(fresh), self.cfg)
        if slice_idx >= len(desired_sets):
            return
        desired = desired_sets[slice_idx]
        set_controller_reference(fresh, desired)
        live = self.api.try_get("StatefulSet", nb.namespace, live_name)
        if live is None:
            return
        if rh.copy_statefulset_fields(desired, live):
            self.api.update(live)

    def _update_status(self, nb: Notebook, live_names: list[str],
                       scheduling: bool = False) -> None:
        with _TRACER.start_span("status", {"phase": "status"}) as span:
            self._compute_and_write_status(nb, live_names, span,
                                           scheduling=scheduling)

    def _compute_and_write_status(self, nb: Notebook, live_names: list[str],
                                  span, scheduling: bool = False) -> None:
        """Mirror pod conditions + container state into the CR
        (createNotebookStatus, notebook_controller.go:299-374); TPU
        notebooks additionally get per-worker states and slice health.
        Condition/phase transitions land as events on the 'status' span,
        and the first time a notebook reaches full readiness the
        notebook_to_ready_seconds histogram observes the latency."""
        ready = 0
        worker_states: list[dict] = []
        conditions: list[dict] = []
        container_state: dict = {}
        tpu = nb.tpu
        num_slices = tpu.slices if tpu else 1
        expected_hosts = (tpu.shape.num_hosts * num_slices) if tpu else 1

        # replication: readiness/health speak for the PRIMARY replica only
        # (followers are redundancy, not capacity — a degraded follower
        # must never flip a healthy primary's notebook to Degraded); all
        # replicas' pods still land in workerStates for observability.
        # live_names is gang-major (replica-major from the renderer), so
        # the primary's gangs sit at [primary*num_slices, (primary+1)*...)
        rep_spec = nb.replication
        live_rep = nb.status.get("replication") or {}
        primary_replica = int(live_rep.get("primary", 0)) \
            if rep_spec is not None else 0
        primary_lo = primary_replica * num_slices
        primary_hi = primary_lo + num_slices

        first_sts_idx = primary_lo if rep_spec is not None else 0
        first_sts_name = live_names[first_sts_idx] \
            if first_sts_idx < len(live_names) else (
                live_names[0] if live_names else nb.name)
        for idx, live_name in enumerate(live_names):
            if self.cache is not None:
                sts = self.cache.get("StatefulSet", nb.namespace, live_name)
            else:
                sts = self.api.try_get("StatefulSet", nb.namespace, live_name)
            if sts is not None and (rep_spec is None
                                    or primary_lo <= idx < primary_hi):
                ready += int(sts.status.get("readyReplicas", 0) or 0)
            if tpu is not None:
                for pod in sorted(self._pods_of(nb, live_name), key=lambda p: p.name):
                    phase = pod.body.get("status", {}).get("phase", "Unknown")
                    pod_ready = any(
                        c.get("type") == "Ready" and c.get("status") == "True"
                        for c in pod.body.get("status", {}).get("conditions", [])
                    )
                    worker_states.append(
                        {"pod": pod.name, "phase": phase, "ready": pod_ready}
                    )

        # conditions + containerState mirror worker 0 (the Jupyter server)
        if self.cache is not None:
            pod0 = self.cache.get("Pod", nb.namespace, f"{first_sts_name}-0")
        else:
            pod0 = self.api.try_get("Pod", nb.namespace, f"{first_sts_name}-0")
        if pod0 is not None and pod0.body.get("status"):
            pstatus = pod0.body["status"]
            now = self.clock.now_iso()
            # reuse previous timestamps for unchanged conditions so the
            # computed status is idempotent — otherwise every reconcile
            # would differ by the defaulted times and the status write
            # would requeue the reconciler forever (the reference defaults
            # with metav1.Now(), PodCondToNotebookCond :397-414, but only
            # rewrites status through the apiserver's semantic no-op check)
            prev = {
                c.get("type"): c
                for c in (nb.status.get("conditions") or [])
            }
            for podc in pstatus.get("conditions", []):
                cond = {
                    "type": podc.get("type", ""),
                    "status": podc.get("status", ""),
                }
                if podc.get("reason"):
                    cond["reason"] = podc["reason"]
                if podc.get("message"):
                    cond["message"] = podc["message"]
                old = prev.get(cond["type"])
                unchanged = old is not None and all(
                    old.get(k) == cond.get(k)
                    for k in ("status", "reason", "message")
                )
                cond["lastProbeTime"] = podc.get("lastProbeTime") or (
                    old["lastProbeTime"] if unchanged else now
                )
                cond["lastTransitionTime"] = podc.get("lastTransitionTime") or (
                    old["lastTransitionTime"] if unchanged else now
                )
                conditions.append(cond)
            # container with the same name as the CR (:336-356)
            for cs in pstatus.get("containerStatuses", []):
                if cs.get("name") == nb.name:
                    container_state = cs.get("state", {})
                    break

        # self-healing state rides the same status object: carry the
        # RecoveryExhausted condition and the sliceRecovery bookkeeping
        # forward — this writer rebuilds status from pod state, but the
        # restart budget must survive every rewrite (the CR is its
        # crash-safe store; core/selfheal.py owns the mutations)
        for cond in (nb.status.get("conditions") or []):
            if cond.get("type") == CONDITION_RECOVERY_EXHAUSTED:
                conditions.append(copy.deepcopy(cond))
        slice_recovery = copy.deepcopy(nb.status.get("sliceRecovery"))
        # the migrate verb's write-ahead restore intent rides along too —
        # losing it on a status rewrite would orphan an in-flight restore
        session_state = copy.deepcopy(nb.status.get("sessionState"))
        # the replication authority record (epoch, primary pointer, the
        # write-ahead promotion record) MUST survive every status rewrite:
        # dropping it would reset the epoch and un-fence a demoted primary.
        # Seeded here for replicated notebooks so the record exists before
        # the first promotion ever needs to CAS against it.
        replication = copy.deepcopy(nb.status.get("replication"))
        if rep_spec is not None and replication is None:
            replication = {"epoch": 1, "primary": 0}

        slice_health = None
        if tpu is not None:
            stopped = C.STOP_ANNOTATION in nb.metadata.annotations
            if stopped:
                # "Stopped" only once every worker is actually gone — a
                # partially failed cull (some slice STS still scaled up)
                # reads "Stopping", so nothing downstream treats a
                # half-culled slice as safely parked
                slice_health = "Stopped" if ready == 0 else "Stopping"
            elif scheduling and ready == 0:
                # gang-gated: waiting on the slice scheduler's placement
                # intent — distinct from Unhealthy (nothing failed yet).
                # A gang the admission gate parked behind quota/fair
                # share reads "Queued" (it is not even in line for
                # capacity yet; the queued annotation is the marker)
                if C.ANNOTATION_QUEUED in nb.metadata.annotations:
                    slice_health = "Queued"
                else:
                    slice_health = "Scheduling"
            elif ready == expected_hosts:
                slice_health = "Healthy"
            elif ready == 0:
                slice_health = "Unhealthy"
            else:
                # partial readiness is a degraded slice: collectives hang
                slice_health = "Degraded"

        status = notebook_status(
            ready_replicas=ready,
            conditions=conditions,
            container_state=container_state,
            worker_states=worker_states if tpu is not None else None,
            slice_health=slice_health,
            slice_recovery=slice_recovery,
            session_state=session_state,
            replication=replication,
        )

        # transitions as span events: the trace timeline shows WHEN a slice
        # degraded or a pod condition flipped, attempt-correlated
        prev_status = nb.status or {}
        prev_health = prev_status.get("sliceHealth")
        if tpu is not None and slice_health != prev_health:
            span.add_event("phase.transition", {
                "field": "sliceHealth",
                "from": prev_health or "",
                "to": slice_health or "",
            })
        prev_conds = {
            c.get("type"): c.get("status")
            for c in (prev_status.get("conditions") or [])
        }
        for cond in conditions:
            before = prev_conds.get(cond["type"])
            if before != cond["status"]:
                span.add_event("condition.transition", {
                    "type": cond["type"],
                    "from": before or "",
                    "to": cond["status"],
                })
        span.set_attribute("readyReplicas", ready)

        # first-readiness latency, measured on the injected clock from the
        # first reconcile that saw this notebook (uid-keyed: delete+recreate
        # measures afresh; no wall-clock reads, deterministic under FakeClock).
        # The ready span event fires once per GENERATION — a spec update
        # opens a fresh lifecycle ledger entry that must finalize on its
        # own rollout — while the histogram observes once per uid.
        key = (nb.namespace, nb.name, nb.obj.metadata.uid)
        genkey = (key, int(nb.obj.metadata.generation or 1))
        first_seen = self._first_seen.setdefault(genkey, self.clock.now())
        if ready >= expected_hosts and expected_hosts > 0 \
                and genkey not in self._ready_observed:
            # exemplar the readiness latency with the attempt's trace: the
            # scrape's fat readiness bucket points at the reconcile that
            # finally turned the notebook Ready
            tid = span.trace_id
            if key not in self._ready_measured:
                self.metrics.notebook_ready_seconds.labels(
                    nb.namespace).observe(
                        self.clock.now() - first_seen,
                        exemplar={"trace_id": tid} if tid else None)
                self._ready_measured.add(key)
            self._ready_observed.add(genkey)
            self._first_seen.pop(genkey, None)
            span.add_event("notebook.ready", {"seconds":
                                              self.clock.now() - first_seen})
        elif ready < expected_hosts and expected_hosts > 0 and \
                C.STOP_ANNOTATION not in nb.metadata.annotations:
            # what the notebook is waiting ON right now — the lifecycle
            # ledger classifies the idle gap after this attempt with it
            if scheduling:
                # quota_wait vs scheduling: the lifecycle ledger charges
                # admission-gate time to its own stage, not pod_schedule
                waiting_on = "quota_wait" \
                    if C.ANNOTATION_QUEUED in nb.metadata.annotations \
                    else "scheduling"
            else:
                pods_found = len(worker_states) if tpu is not None else \
                    (1 if pod0 is not None else 0)
                waiting_on = "pod_start" if pods_found >= expected_hosts \
                    else "pod_schedule"
            span.add_event("notebook.waiting", {
                "on": waiting_on, "ready": ready,
                "expected": expected_hosts})
        if len(self._ready_observed) > 8192:
            self._ready_observed.clear()
        if len(self._ready_measured) > 8192:
            self._ready_measured.clear()
        if len(self._first_seen) > 8192:
            self._first_seen.clear()

        # status dedup, cache-first: when the cached live object already
        # carries exactly this status, skip the read-modify-write entirely
        # — the converged steady state issues ZERO status API calls.  A
        # stale cache merely delays the write until the next event-driven
        # pass (level-triggered correctness).
        if self.cache is not None:
            cached = self.cache.get("Notebook", nb.namespace, nb.name)
            if cached is not None and cached.body.get("status") == status:
                return

        def write() -> None:
            live = self.api.get("Notebook", nb.namespace, nb.name)
            new_status = status
            # epoch-regression guard: a promotion (or a follower-freshness
            # pass) may have advanced status.replication between this
            # reconcile's read and now — clobbering it with the stale copy
            # would roll back the epoch and un-fence a demoted primary.
            # The freshest record (by epoch, ties to the live object, which
            # is at least as new) always wins.
            live_rep_now = (live.body.get("status") or {}).get("replication")
            if replication is not None and live_rep_now is not None and \
                    live_rep_now.get("epoch", 0) >= \
                    replication.get("epoch", 0):
                new_status = dict(status)
                new_status["replication"] = copy.deepcopy(live_rep_now)
            if live.body.get("status") == new_status:
                return
            live.status = new_status
            self.api.update_status(live)

        retry_on_conflict(write)


class EventReemitReconciler:
    """Re-emits Events from owned StatefulSets/Pods onto the Notebook CR so
    users see workload failures with `kubectl describe notebook`
    (notebook_controller.go:99-122, nbNameFromInvolvedObject :705)."""

    # dedup window: a long-lived controller must not grow an unbounded UID
    # set; Events past this window have long aged out of the queue (the
    # apiserver TTLs them at 1h), so re-seeing one is a full relist — and
    # re-emitting after a relist is level-triggered-correct, merely chatty
    MAX_EMITTED = 8192

    def __init__(self, api: ApiServer, recorder: EventRecorder):
        self.api = api
        self.recorder = recorder
        from collections import OrderedDict

        self._emitted: "OrderedDict[str, None]" = OrderedDict()

    def reconcile(self, req: Request) -> Result:
        ev = self.api.try_get("Event", req.namespace, req.name)
        if ev is None:
            return Result()
        if ev.metadata.uid in self._emitted:
            self._emitted.move_to_end(ev.metadata.uid)
            return Result()
        involved = ev.body.get("involvedObject", {})
        nb_name = self._notebook_for(req.namespace, involved)
        if nb_name is None:
            return Result()
        nb = self.api.try_get("Notebook", req.namespace, nb_name)
        if nb is None:
            return Result()
        self._emitted[ev.metadata.uid] = None
        while len(self._emitted) > self.MAX_EMITTED:
            self._emitted.popitem(last=False)
        self.recorder.event(
            nb,
            ev.body.get("type", "Normal"),
            ev.body.get("reason", ""),
            "Reissued from %s/%s: %s"
            % (involved.get("kind", "").lower(), involved.get("name", ""),
               ev.body.get("message", "")),
        )
        return Result()

    def _notebook_for(self, namespace: str, involved: dict) -> Optional[str]:
        kind, name = involved.get("kind"), involved.get("name")
        if not kind or not name:
            return None
        obj = self.api.try_get(kind, namespace, name)
        if obj is None:
            return None
        if kind == "Pod":
            return obj.metadata.labels.get(C.NOTEBOOK_NAME_LABEL)
        if kind == "StatefulSet":
            ref = obj.metadata.controller_owner()
            if ref is not None and ref.kind == "Notebook":
                return ref.name
            return obj.metadata.labels.get(C.NOTEBOOK_NAME_LABEL)
        return None


def setup_core_controllers(
    mgr: Manager,
    cfg: Optional[CoreConfig] = None,
    metrics: Optional[NotebookMetrics] = None,
    session=None,
    provisioner=None,
) -> NotebookReconciler:
    """Wire the core controllers into a manager (main.go:58-148 analog;
    culling registration is separate, gated on ENABLE_CULLING —
    main.go:111-123 — see core.culling_controller.setup_culling)."""
    cfg = cfg or CoreConfig.from_env()
    api = mgr.api
    from ..api.validation import install_notebook_schema
    from ..kube import default_rate_limiter, suppress_status_only

    install_notebook_schema(api)
    # workqueue rate limiting from config (WORKQUEUE_* env vars): per-item
    # exponential backoff + overall token bucket on the manager's clock
    mgr.set_rate_limiter(default_rate_limiter(
        mgr.clock,
        base_s=cfg.workqueue_base_delay_s,
        cap_s=cfg.workqueue_max_delay_s,
        qps=cfg.workqueue_qps,
        burst=cfg.workqueue_burst,
    ))
    # parallel reconcile workers (WORKQUEUE_WORKERS): only widen — an
    # explicit Manager(workers=N) stays authoritative over the default
    if cfg.workqueue_workers > mgr.workers:
        mgr.workers = cfg.workqueue_workers
    # hot-path read indexes (controller-runtime FieldIndexer analog):
    # owned StatefulSets by controller-owner uid, worker Pods by the STS
    # selector label, Notebook fleet sweeps by namespace
    cache = mgr.cache
    if cache is not None:
        cache.add_owner_uid_index("StatefulSet")
        cache.add_label_index("Pod", C.STATEFULSET_LABEL)
        cache.add_namespace_index("Notebook")
    metrics = metrics or NotebookMetrics(api, manager=mgr)
    if metrics.manager is None:
        metrics.attach_manager(mgr)
    recorder = EventRecorder(api, "notebook-controller")
    rec = NotebookReconciler(api, cfg, metrics, recorder, clock=mgr.clock,
                             cache=cache, session=session)

    def pod_to_request(pod: KubeObject) -> list[Request]:
        name = pod.metadata.labels.get(C.NOTEBOOK_NAME_LABEL)
        return [Request(pod.namespace, name)] if name else []

    def node_to_requests(node: KubeObject) -> list[Request]:
        # a node vanishing or flipping unready can strand any multi-host
        # slice whose workers it carried; re-evaluate every TPU notebook so
        # the self-healing engine sees node-driven disruption without
        # waiting for a pod event or resync (cheap: cached sweep, rare
        # event — and never an api.list scan when the cache is wired)
        notebooks = cache.list("Notebook") if cache is not None \
            else api.list("Notebook")
        return [
            Request(o.namespace, o.name)
            for o in notebooks
            if o.spec.get("tpu")
        ]

    mgr.register(
        "notebook",
        rec,
        for_kind="Notebook",
        owns=["StatefulSet", "Service", "VirtualService"],
        watches=[WatchSpec(kind="Pod", mapper=pod_to_request),
                 WatchSpec(kind="Node", mapper=node_to_requests)],
        # the notebook controller is the sole writer of Notebook status;
        # its own status writes must not re-trigger it (or the fleet never
        # reaches a zero-reconcile steady state)
        for_predicate=suppress_status_only,
    )
    reemit = EventReemitReconciler(api, recorder)
    mgr.register(
        "event-reemit",
        reemit,
        for_kind="Event",
        watches=[],
    )
    # topology-aware slice scheduler + warm-pool autoscaler (ENABLE_SLICE_
    # SCHEDULER): owns placement intent and warm-slice claims; the
    # `provisioner` hook (FakeCluster in standalone mode) turns capacity
    # up/down for it
    if cfg.enable_slice_scheduler:
        from .scheduler import setup_scheduler

        # the reconciler may have self-built a store off
        # CHECKPOINT_STORE_URI — share that one instance so the
        # preemption engine secures checkpoints through the same chain
        # the restore machinery reads
        setup_scheduler(mgr, cfg, metrics, provisioner=provisioner,
                        session=rec.session)
    return rec
