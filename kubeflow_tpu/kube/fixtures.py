"""Golden-fixture replay: apiserver-semantics transcripts over the wire.

The reference grounds its controllers against a REAL kube-apiserver via
envtest (notebook-controller/controllers/suite_test.go:50-110), so apiserver
semantics — optimistic concurrency, generation bumps, RFC 7386 merge
patches, finalizer-gated deletion, owner-ref GC, watch resume/410 — are
independently enforced.  This module replays declarative golden transcripts
(conformance/apiserver_fixtures/*.json), each step recording the behavior a
real kube-apiserver exhibits, against ANY server speaking the k8s REST
protocol:

  - this repo's wire server (tests/test_apiserver_fixtures.py) — a
    store-semantics bug shows up as a fixture diff, not a green self-test;
  - a real cluster (`python -m kubeflow_tpu.kube.fixtures --server URL`),
    which is how the transcripts themselves are validated.

Known divergences are fixtures too: a fixture with an `expected_divergence`
marker pins BOTH behaviors — each diverging step carries `expect` (this
implementation, asserted by default so regressions in the documented
behavior are caught) and `expect_real` (what a genuine kube-apiserver
answers, asserted under `--real` so the divergence is adjudicated the day
the replay runs against a real cluster).

Fixture format — a JSON object:
  {"name": ..., "kube_semantics": "<what real k8s does, with source>",
   "steps": [{"op": "POST|GET|PUT|PATCH|DELETE|WATCH",
              "path": "/apis/...",            # ${var} substituted
              "body": {...},                  # ${var} substituted, deep
              "content_type": "...",          # PATCH merge type
              "repeat": N,                    # ${i} = iteration index
              "capture": {"var": "dotted.path"},
              "expect": {"status": 201,
                         "equals": {"dotted.path": value},
                         "startswith": {"dotted.path": "prefix"},
                         "absent": ["dotted.path"],
                         "exists": ["dotted.path"],
                         "events": [{"type": "ADDED",
                                     "name": "..."}, ...]}}]}
"""

from __future__ import annotations

import json
import ssl
import urllib.error
import urllib.request
from pathlib import Path
from typing import Any, Optional


def dig(obj: Any, path: str) -> Any:
    """Dotted-path lookup; integer segments index lists.  Raises KeyError
    with the full path on a miss."""
    cur = obj
    for seg in path.split("."):
        try:
            if isinstance(cur, list):
                cur = cur[int(seg)]
            else:
                cur = cur[seg]
        except (KeyError, IndexError, TypeError, ValueError):
            raise KeyError(f"{path} (at segment {seg!r})") from None
    return cur


def substitute(value: Any, variables: dict[str, Any]) -> Any:
    """Deep ${var} substitution in strings; a string that is exactly one
    placeholder keeps the captured value's type."""
    if isinstance(value, str):
        if value.startswith("${") and value.endswith("}") and \
                value.count("${") == 1:
            return variables[value[2:-1]]
        out = value
        for k, v in variables.items():
            out = out.replace("${" + k + "}", str(v))
        return out
    if isinstance(value, dict):
        return {k: substitute(v, variables) for k, v in value.items()}
    if isinstance(value, list):
        return [substitute(v, variables) for v in value]
    return value


class FixtureFailure(AssertionError):
    pass


class FixtureRunner:
    """Replays one fixture against a server base URL."""

    def __init__(self, server: str, token: str = "",
                 ssl_context: Optional[ssl.SSLContext] = None,
                 timeout_s: float = 10.0, real: bool = False,
                 clock=None) -> None:
        self.server = server.rstrip("/")
        self.token = token
        self.ctx = ssl_context
        self.timeout_s = timeout_s
        # real=True: the target is a genuine apiserver — steps with an
        # `expect_real` block assert it instead of `expect`
        self.real = real
        # retry pacing (clock discipline: a FakeClock makes retry loops
        # instant in tests; a real Clock sleeps between attempts)
        if clock is None:
            from ..utils.clock import Clock
            clock = Clock()
        self.clock = clock

    # -- transport ------------------------------------------------------------
    def _request(self, method: str, path: str, body: Any = None,
                 content_type: str = "application/json") -> tuple[int, Any]:
        headers = {"Content-Type": content_type, "Accept": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        req = urllib.request.Request(
            self.server + path,
            data=json.dumps(body).encode() if body is not None else None,
            headers=headers, method=method)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s,
                                        context=self.ctx) as resp:
                raw = resp.read()
                return resp.status, json.loads(raw) if raw else {}
        except urllib.error.HTTPError as err:
            raw = err.read()
            try:
                return err.code, json.loads(raw) if raw else {}
            except json.JSONDecodeError:
                return err.code, {"raw": raw.decode(errors="replace")}

    def _watch(self, path: str, max_events: int,
               timeout_s: float = 5.0) -> tuple[int, Any]:
        """Open a watch stream, read up to max_events event lines."""
        headers = {"Accept": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        req = urllib.request.Request(self.server + path, headers=headers)
        try:
            resp = urllib.request.urlopen(req, timeout=timeout_s,
                                          context=self.ctx)
        except urllib.error.HTTPError as err:
            raw = err.read()
            try:
                return err.code, json.loads(raw) if raw else {}
            except json.JSONDecodeError:
                return err.code, {}
        events = []
        try:
            while len(events) < max_events:
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if line:
                    events.append(json.loads(line))
        except (TimeoutError, OSError):
            pass
        finally:
            resp.close()
        return 200, {"events": events}

    # -- replay ---------------------------------------------------------------
    def run(self, fixture: dict) -> None:
        """Raises FixtureFailure on the first divergence."""
        variables: dict[str, Any] = {}
        for idx, step in enumerate(fixture.get("steps", [])):
            repeat = int(step.get("repeat", 1))
            for i in range(repeat):
                variables["i"] = i
                self._run_step(fixture, idx, step, variables)

    def _run_step(self, fixture: dict, idx: int, step: dict,
                  variables: dict[str, Any]) -> None:
        """One step, with optional retry_s — real-cluster effects the
        in-memory store applies synchronously (GC cascades, finalizer
        completion) are asynchronous on a genuine apiserver."""
        deadline = self.clock.monotonic() + float(step.get("retry_s", 0))
        while True:
            try:
                self._attempt_step(fixture, idx, step, variables)
                return
            except FixtureFailure:
                if self.clock.monotonic() >= deadline:
                    raise
                self.clock.sleep(0.25)

    def _attempt_step(self, fixture: dict, idx: int, step: dict,
                      variables: dict[str, Any]) -> None:
        label = f"{fixture.get('name', '?')}#{idx} {step.get('op')} " \
                f"{step.get('path')}"
        op = step["op"].upper()
        path = substitute(step["path"], variables)
        body = substitute(step.get("body"), variables) \
            if "body" in step else None
        expect = step.get("expect", {})
        if self.real and "expect_real" in step:
            expect = step["expect_real"]
        if op == "WATCH":
            max_events = len(expect.get("events", [])) or 1
            status, payload = self._watch(
                path, max_events, timeout_s=float(step.get("timeout_s", 5.0)))
        else:
            status, payload = self._request(
                op, path, body,
                content_type=step.get("content_type", "application/json"))

        want_status = expect.get("status")
        if want_status is not None and status != want_status:
            raise FixtureFailure(
                f"{label}: status {status} != {want_status}; body={payload}")
        for path_expr, want in expect.get("equals", {}).items():
            want = substitute(want, variables)
            try:
                got = dig(payload, path_expr)
            except KeyError as err:
                raise FixtureFailure(f"{label}: missing {err}") from None
            if got != want:
                raise FixtureFailure(
                    f"{label}: {path_expr} = {got!r} != {want!r}")
        for path_expr, prefix in expect.get("startswith", {}).items():
            got = dig(payload, path_expr)
            if not str(got).startswith(substitute(prefix, variables)):
                raise FixtureFailure(
                    f"{label}: {path_expr} = {got!r} !startswith {prefix!r}")
        for path_expr in expect.get("exists", []):
            try:
                dig(payload, path_expr)
            except KeyError as err:
                raise FixtureFailure(f"{label}: missing {err}") from None
        for path_expr in expect.get("absent", []):
            try:
                got = dig(payload, path_expr)
            except KeyError:
                continue
            if got is not None:
                raise FixtureFailure(
                    f"{label}: {path_expr} present ({got!r}), expected absent")
        for ev_idx, want_ev in enumerate(expect.get("events", [])):
            events = payload.get("events", [])
            if ev_idx >= len(events):
                raise FixtureFailure(
                    f"{label}: only {len(events)} events, wanted "
                    f"{len(expect['events'])}")
            got_ev = events[ev_idx]
            if got_ev.get("type") != want_ev["type"]:
                raise FixtureFailure(
                    f"{label}: event[{ev_idx}].type {got_ev.get('type')} != "
                    f"{want_ev['type']}")
            want_name = substitute(want_ev.get("name", ""), variables)
            got_name = got_ev.get("object", {}).get("metadata", {}).get("name")
            if want_name and got_name != want_name:
                raise FixtureFailure(
                    f"{label}: event[{ev_idx}].name {got_name} != {want_name}")
        for var, path_expr in step.get("capture", {}).items():
            try:
                variables[var] = dig(payload, path_expr)
            except KeyError as err:
                raise FixtureFailure(
                    f"{label}: capture {var}: missing {err}") from None


FIXTURE_DIR = Path(__file__).resolve().parents[2] / "conformance" / \
    "apiserver_fixtures"


def load_fixtures(directory: Optional[Path] = None) -> list[dict]:
    directory = directory or FIXTURE_DIR
    out = []
    for f in sorted(directory.glob("*.json")):
        fixture = json.loads(f.read_text())
        fixture.setdefault("name", f.stem)
        out.append(fixture)
    return out


def main(argv: Optional[list] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="replay apiserver golden fixtures against a server")
    parser.add_argument("--server", required=True,
                        help="base URL (http[s]://host:port)")
    parser.add_argument("--token", default="")
    parser.add_argument("--insecure", action="store_true")
    parser.add_argument("--fixtures", default=str(FIXTURE_DIR))
    parser.add_argument("--real", action="store_true",
                        help="target is a genuine apiserver: skip fixtures "
                             "marked skip_on_real (deterministic history "
                             "aging needs the in-memory window)")
    args = parser.parse_args(argv)
    ctx = ssl._create_unverified_context() if args.insecure else None
    runner = FixtureRunner(args.server, token=args.token, ssl_context=ctx,
                           real=args.real)
    failures = 0
    for fixture in load_fixtures(Path(args.fixtures)):
        if args.real and fixture.get("skip_on_real"):
            print(f"SKIP {fixture['name']} (skip_on_real)")
            continue
        tag = " (expected_divergence: asserting real-apiserver side)" \
            if args.real and fixture.get("expected_divergence") else ""
        try:
            runner.run(fixture)
            print(f"PASS {fixture['name']}{tag}")
        except FixtureFailure as err:
            failures += 1
            print(f"FAIL {fixture['name']}{tag}: {err}")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
