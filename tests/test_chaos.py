"""Chaos-model validation + fault-injection drills + the seeded soak.

Three parts, mirroring the reference's shift-left chaos CI (SURVEY.md §4.6):
1. the knowledge model (chaos/knowledge/workbenches.yaml) must stay in sync
   with what the controllers actually create — a drift check;
2. the declared fault injections actually hold: kill/fail a worker, delete a
   route, and watch level-triggered reconciliation restore steady state;
3. a seeded randomized soak (TestChaosSoak): N rounds of random FaultPlans
   (kube/faults.py — API errors, latency, stale reads, watch drops with
   resourceVersion resets) against a TPU+auth notebook, asserting every
   steady-state predicate declared in workbenches.yaml is restored after
   each round's faults drain, and that no reconciler ever exhausts its
   retry budget.  Reproduce a failure with
   CHAOS_SOAK_SEED=<printed seed> pytest tests/test_chaos.py -k soak
   (ci/chaos_soak.sh wraps exactly that).
"""

import os
import random
from pathlib import Path

import pytest
import yaml

from kubeflow_tpu.api.types import Notebook, TPUSpec
from kubeflow_tpu.core.notebook_controller import setup_core_controllers
from kubeflow_tpu.kube import ApiServer, FakeCluster, Manager, random_fault_plan
from kubeflow_tpu.odh import constants as OC
from kubeflow_tpu.odh.controller import setup_odh_controllers
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.config import CoreConfig, OdhConfig

KNOWLEDGE = Path(__file__).parent.parent / "chaos" / "knowledge" / "workbenches.yaml"
CENTRAL_NS = "opendatahub"

SOAK_ROUNDS = int(os.environ.get("CHAOS_SOAK_ROUNDS", "20"))
SOAK_SEED = int(os.environ.get("CHAOS_SOAK_SEED", "20260804"))
SELFHEAL_SOAK_ROUNDS = int(os.environ.get("SELFHEAL_SOAK_ROUNDS", "12"))
MIGRATE_SOAK_ROUNDS = int(os.environ.get("MIGRATE_SOAK_ROUNDS", "10"))
PREEMPT_SOAK_ROUNDS = int(os.environ.get("PREEMPT_SOAK_ROUNDS", "6"))
FAILOVER_SOAK_ROUNDS = int(os.environ.get("FAILOVER_SOAK_ROUNDS", "50"))

# the kinds the workbench controllers actually traffic in — the fault
# plans draw their per-kind targeting from this pool
FAULT_KINDS = (
    "Notebook", "StatefulSet", "Pod", "Service", "HTTPRoute",
    "NetworkPolicy", "ConfigMap", "Secret", "ServiceAccount", "Event",
)


@pytest.fixture()
def env():
    api = ApiServer()
    cluster = FakeCluster(api)
    cluster.add_node("cpu-node", allocatable={"cpu": "64", "memory": "256Gi"})
    cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 4, 4)
    # roomy per-object history: the soaks audit per-key serialization over
    # every recorded attempt (WORKQUEUE_WORKERS from the env — the CI soak
    # runs the full suite with a parallel worker pool)
    from kubeflow_tpu.utils.flightrecorder import FlightRecorder

    mgr = Manager(api, clock=FakeClock(),
                  flight_recorder=FlightRecorder(capacity=16384,
                                                 per_object=4096))
    setup_core_controllers(mgr, CoreConfig())
    setup_odh_controllers(mgr, OdhConfig(controller_namespace=CENTRAL_NS))
    return api, cluster, mgr


def assert_no_concurrent_per_key_reconciles(mgr):
    """No two recorded attempts of one (controller, object) key may have
    overlapping real-time execution windows — the per-key serialization
    invariant the parallel worker pool must uphold."""
    overlaps = mgr.flight_recorder.overlapping_attempts()
    assert not overlaps, (
        f"{len(overlaps)} overlapping attempt pairs; first: "
        f"{overlaps[0][0].controller} {overlaps[0][0].object_key} "
        f"[{overlaps[0][0].mono_start:.6f},{overlaps[0][0].mono_end:.6f}] vs "
        f"[{overlaps[0][1].mono_start:.6f},{overlaps[0][1].mono_end:.6f}]")


def knowledge():
    return yaml.safe_load(KNOWLEDGE.read_text())


class TestKnowledgeModel:
    def test_model_parses_and_names_controllers(self):
        model = knowledge()
        names = {c["name"] for c in model["controllers"]}
        assert names == {
            "notebook-controller", "culling-controller", "odh-notebook-controller",
        }
        assert all(c["primary"] == "Notebook" for c in model["controllers"])

    def test_managed_kinds_match_reality(self, env):
        """Drift check: every kind the stack creates for a TPU+auth notebook
        is declared in the model, and vice versa for non-optional kinds."""
        api, _, mgr = env
        nb = Notebook.new(
            "drift", "user1", tpu=TPUSpec("v5e", "4x4"),
            annotations={OC.ANNOTATION_INJECT_AUTH: "true"},
        )
        api.create(nb.obj)
        mgr.run_until_idle()
        created_kinds = {
            kind
            for kind, objs in api.dump().items()
            if kind not in ("Notebook", "Node", "Pod", "Event")
            and any(
                o["metadata"].get("namespace") in ("user1", CENTRAL_NS, "")
                for o in objs
            )
        }
        model = knowledge()
        declared = {
            m["kind"]
            for c in model["controllers"]
            for m in c["manages"]
        }
        undeclared = created_kinds - declared
        assert not undeclared, f"created but not in chaos model: {undeclared}"

    def test_steady_state_timeout_declared(self):
        model = knowledge()
        assert all(s["timeout_seconds"] <= 60 for s in model["steady_state"])


class TestFaultInjection:
    def _healthy_tpu_nb(self, api, mgr, name="chaos-nb"):
        nb = Notebook.new(name, "user1", tpu=TPUSpec("v5e", "4x4"))
        api.create(nb.obj)
        mgr.run_until_idle()
        status = api.get("Notebook", "user1", name).body["status"]
        assert status["sliceHealth"] == "Healthy"
        return name

    def test_kill_worker_pod_recovers(self, env):
        api, cluster, mgr = env
        name = self._healthy_tpu_nb(api, mgr)
        api.delete("Pod", "user1", f"{name}-2")
        mgr.run_until_idle()
        status = api.get("Notebook", "user1", name).body["status"]
        assert status["sliceHealth"] == "Healthy"
        assert status["readyReplicas"] == 4

    def test_failed_worker_degrades_then_restart_recovers(self):
        # self-healing off: this drill pins the MANUAL recovery path (the
        # restart annotation) — with healing on, the engine slice-restarts
        # the failed worker before Degraded can be observed (that path is
        # tests/test_selfheal.py + TestSliceRecoverySoak)
        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 4, 4)
        mgr = Manager(api, clock=FakeClock())
        setup_core_controllers(mgr, CoreConfig(enable_self_healing=False))
        name = self._healthy_tpu_nb(api, mgr)
        cluster.fail_pod("user1", f"{name}-1")
        mgr.run_until_idle()
        status = api.get("Notebook", "user1", name).body["status"]
        assert status["sliceHealth"] == "Degraded"
        # slice-atomic restart via the restart annotation
        live = api.get("Notebook", "user1", name)
        live.metadata.annotations["notebooks.opendatahub.io/notebook-restart"] = "true"
        api.update(live)
        mgr.run_until_idle()
        status = api.get("Notebook", "user1", name).body["status"]
        assert status["sliceHealth"] == "Healthy"
        live = api.get("Notebook", "user1", name)
        assert "notebooks.opendatahub.io/notebook-restart" not in (
            live.metadata.annotations
        )

    def test_delete_route_recreated(self, env):
        api, _, mgr = env
        name = self._healthy_tpu_nb(api, mgr)
        route_name = f"nb-user1-{name}"
        api.delete("HTTPRoute", CENTRAL_NS, route_name)
        mgr.run_until_idle()
        assert api.try_get("HTTPRoute", CENTRAL_NS, route_name) is not None


def assert_steady_state(api, namespace: str, name: str,
                        expected_hosts: int) -> None:
    """Every steady-state predicate DECLARED in the knowledge model must
    hold — driven off the yaml so a predicate added to the model without a
    check here fails loudly instead of silently going untested."""
    status = api.get("Notebook", namespace, name).body.get("status", {})
    for pred in knowledge()["steady_state"]:
        if pred["name"] == "notebook-ready":
            assert status.get("readyReplicas") == expected_hosts, \
                (pred["name"], status)
        elif pred["name"] == "slice-health":
            assert status.get("sliceHealth") in ("Healthy", "Stopped"), \
                (pred["name"], status)
        elif pred["name"] == "route-exists":
            routes = api.list(
                "HTTPRoute", namespace=CENTRAL_NS,
                label_selector={OC.NOTEBOOK_NAME_LABEL: name,
                                OC.NOTEBOOK_NAMESPACE_LABEL: namespace})
            assert len(routes) == 1, (pred["name"], [r.name for r in routes])
        else:  # a new model predicate needs a matching assertion
            pytest.fail(f"steady-state predicate {pred['name']!r} declared "
                        "in workbenches.yaml but not checked by the soak")


class TestChaosSoak:
    """Seeded randomized fault soak (ci/chaos_soak.sh runs this at higher
    round counts).  Each round: install a random bounded FaultPlan, perturb
    the cluster, drive reconciliation to convergence while faults fire,
    then clear faults and assert the declared steady state is restored with
    zero retry-budget exhaustions in Manager._errors."""

    EXPECTED_HOSTS = 4  # v5e 4x4 single slice

    def _perturb(self, rng, api, cluster, name):
        """One random cluster perturbation, exempt from the fault plan (the
        perturbation is the experiment, not the traffic under test)."""
        kind = rng.choice(
            ["kill_pod", "fail_pod", "delete_route", "touch", "none"])
        with api.fault_exempt():
            if kind == "kill_pod":
                api.delete("Pod", "user1", f"{name}-{rng.randrange(4)}")
            elif kind == "fail_pod":
                cluster.fail_pod("user1", f"{name}-{rng.randrange(4)}")
            elif kind == "delete_route":
                api.delete("HTTPRoute", CENTRAL_NS, f"nb-user1-{name}")
            elif kind == "touch":
                nb = api.get("Notebook", "user1", name)
                nb.metadata.annotations["chaos/touch"] = str(rng.random())
                api.update(nb)
        if kind == "fail_pod":
            # a Failed pod needs the slice-atomic restart to recover
            from kubeflow_tpu.core import constants as CC

            with api.fault_exempt():
                nb = api.get("Notebook", "user1", name)
                nb.metadata.annotations[CC.ANNOTATION_NOTEBOOK_RESTART] \
                    = "true"
                api.update(nb)
        return kind

    def test_seeded_random_fault_soak(self, env):
        from kubeflow_tpu.core.metrics import metering_bucket, placement_chips
        from kubeflow_tpu.utils import tracing
        from kubeflow_tpu.utils.lifecycle import LifecycleLedger
        from kubeflow_tpu.utils.metering import TenantMeteringLedger

        api, cluster, mgr = env
        # lifecycle conservation audit: every attempt the soak runs —
        # including errored/retried ones — folds into the stage ledger,
        # and the partition must stay exact under fault injection
        # (registry=None: no histogram, pure bookkeeping)
        ledger = LifecycleLedger()
        mgr.lifecycle = ledger
        # tenant metering rides the same soak: every dispatch (retries
        # included) attributes to user1, and the chip-second meter — fed
        # each round from the live notebook's bucket — must conserve
        # through every chaos excursion
        metering = TenantMeteringLedger(mgr.clock)
        mgr.metering = metering
        tracing.set_clock(mgr.clock)
        try:
            nb = Notebook.new(
                "soak", "user1", tpu=TPUSpec("v5e", "4x4"),
                annotations={OC.ANNOTATION_INJECT_AUTH: "true"},
            )
            api.create(nb.obj)
            mgr.run_until_idle()
            assert_steady_state(api, "user1", "soak", self.EXPECTED_HOSTS)

            print(f"\nchaos soak: seed={SOAK_SEED} rounds={SOAK_ROUNDS} "
                  "(reproduce with CHAOS_SOAK_SEED/CHAOS_SOAK_ROUNDS)")
            rng = random.Random(SOAK_SEED)
            total_faults = 0
            for round_i in range(SOAK_ROUNDS):
                plan_seed = rng.randrange(2**31)
                plan = random_fault_plan(plan_seed, kinds=FAULT_KINDS,
                                         clock=mgr.clock)
                api.install_fault_plan(plan)
                perturbation = self._perturb(rng, api, cluster, "soak")
                with api.fault_exempt():
                    mgr.enqueue_all()
                # converge WHILE faults fire (plans are bounded, so they
                # drain)
                mgr.settle(max_seconds=7200.0)
                api.clear_fault_plan()
                # faults cleared: one more level-triggered pass restores
                # whatever the chaos window left behind
                with api.fault_exempt():
                    mgr.enqueue_all()
                mgr.settle(max_seconds=7200.0)

                total_faults += len(plan.log)
                assert not mgr.dropped_errors, (
                    f"round {round_i} (plan_seed={plan_seed}, "
                    f"perturb={perturbation}): retry budget exhausted: "
                    f"{mgr.dropped_errors}, injected={plan.summary()}")
                assert_steady_state(api, "user1", "soak",
                                    self.EXPECTED_HOSTS)
                live = api.get("Notebook", "user1", "soak")
                metering.sample({("user1", "soak"):
                                 (metering_bucket(live),
                                  placement_chips(live))})

            # the soak must actually have injected chaos to mean anything
            assert total_faults > SOAK_ROUNDS, total_faults
            # and in threaded mode (WORKQUEUE_WORKERS > 1) the worker pool
            # must never have run two reconciles of one key concurrently
            assert_no_concurrent_per_key_reconciles(mgr)
            # lifecycle conservation under chaos: the soak notebook's
            # event->ready window finalized, its attributed stage time
            # equals the measured wall time, and no retry double-counted
            cons = ledger.conservation()
            assert cons["finalized"] >= 1, cons
            assert cons["violations"] == 0, ledger.violations()[:3]
            # metering conservation under chaos: the accrued buckets of
            # the soak notebook's (still-live) meter sum to its measured
            # wall time, and every dispatch was attributed to its tenant
            mcons = metering.conservation()
            assert mcons["checked"] >= 1, mcons
            assert mcons["violations"] == 0, metering.violations()[:3]
            row = metering.tenant_table()["user1"]
            assert row["dispatches"] > 0 and row["chip_seconds_total"] > 0
        finally:
            tracing.set_clock(None)

    def test_trace_integrity_under_faults(self, env):
        """Observability acceptance: run soak rounds with a span exporter
        installed and assert (1) every injected fault from the FaultPlan log
        appears as a `fault.injected` event on exactly one reconcile span —
        the very attempt it hit, (2) no span is dropped or left unfinished
        even when reconciles error mid-phase, and (3) every non-root span's
        parent was exported too (no orphaned timelines)."""
        from kubeflow_tpu.utils import tracing
        from kubeflow_tpu.utils.tracing import InMemorySpanExporter

        api, cluster, mgr = env
        exporter = InMemorySpanExporter()
        tracing.set_exporter(exporter)
        tracing.set_clock(mgr.clock)
        try:
            nb = Notebook.new(
                "soak", "user1", tpu=TPUSpec("v5e", "4x4"),
                annotations={OC.ANNOTATION_INJECT_AUTH: "true"},
            )
            api.create(nb.obj)
            mgr.run_until_idle()

            rng = random.Random(SOAK_SEED + 1)
            injected: list[tuple[int, object]] = []  # (plan_seed, record)
            rounds = 0
            while len(injected) < 8 and rounds < 12:
                rounds += 1
                plan_seed = rng.randrange(2**31)
                plan = random_fault_plan(plan_seed, kinds=FAULT_KINDS,
                                         clock=mgr.clock)
                api.install_fault_plan(plan)
                self._perturb(rng, api, cluster, "soak")
                with api.fault_exempt():
                    mgr.enqueue_all()
                mgr.settle(max_seconds=7200.0)
                api.clear_fault_plan()
                with api.fault_exempt():
                    mgr.enqueue_all()
                mgr.settle(max_seconds=7200.0)
                injected.extend((plan.seed, rec) for rec in plan.log)
            assert injected, "soak injected no faults to trace"

            spans = exporter.spans
            by_id = {s.span_id: s for s in spans}
            # (2) every exported span finished; (3) parents exported
            for s in spans:
                assert s.end_time >= s.start_time > 0, \
                    f"unfinished span {s.name}"
                if s.parent is not None:
                    assert s.parent.span_id in by_id, \
                        f"orphaned span {s.name}"
            # (1) fault <-> span-event pairing is exact and 1:1
            fault_events = [
                (s, e) for s in spans for e in s.events
                if e.name == "fault.injected"
            ]
            assert len(fault_events) == len(injected), (
                "fault log and span events disagree: "
                f"{len(injected)} injected, {len(fault_events)} events")
            for plan_seed, rec in injected:
                assert rec.span_id, f"fault fired outside any span: {rec}"
                owners = [
                    s for s, e in fault_events
                    if e.attributes["fault.plan_seed"] == plan_seed
                    and e.attributes["fault.seq"] == rec.seq
                ]
                assert len(owners) == 1, (rec, [s.name for s in owners])
                span = owners[0]
                assert span.name == "reconcile", span.name
                assert span.span_id == rec.span_id
                assert span.trace_id == rec.trace_id
                assert span.parent is None  # faults stamp the attempt ROOT
                assert "controller" in span.attributes
        finally:
            api.clear_fault_plan()
            tracing.set_exporter(None)
            tracing.set_clock(None)

    def test_soak_is_reproducible_for_a_seed(self, env):
        """The same plan seed yields the same injections — the printed seed
        genuinely reproduces a failing round."""
        a = random_fault_plan(1234, kinds=FAULT_KINDS)
        b = random_fault_plan(1234, kinds=FAULT_KINDS)
        assert [(r.verbs, r.kinds, r.error, r.latency_s, r.stale_read,
                 r.drop_watch, r.reset_watch_history, r.probability,
                 r.max_matches, r.after)
                for r in a.rules] == \
               [(r.verbs, r.kinds, r.error, r.latency_s, r.stale_read,
                 r.drop_watch, r.reset_watch_history, r.probability,
                 r.max_matches, r.after)
                for r in b.rules]


class TestSliceRecoverySoak:
    """ISSUE-4 acceptance: seeded worker kills + API faults against a
    self-healing TPU notebook.  Every round must converge back to
    sliceHealth == Healthy with NO manual restart annotation — the
    recovery engine does the work — and with slice-atomic restarts only:
    the fake ApiServer audit log must show pod-delete attempts arriving
    exclusively in whole-slice groups.  Mid-soak the manager is replaced
    (leader failover) and the persisted budget must carry over; a
    permanently failing slice must land on RecoveryExhausted after
    exactly the configured attempt cap instead of churning forever."""

    HOSTS = 4  # v5e 4x4 single slice

    CFG = dict(
        recovery_backoff_base_s=1.0,
        recovery_backoff_max_s=30.0,
        recovery_max_attempts=4,
        recovery_window_s=120.0,
        recovery_pending_deadline_s=60.0,
    )

    def _env(self):
        from kubeflow_tpu.core.metrics import NotebookMetrics
        from kubeflow_tpu.utils.flightrecorder import FlightRecorder

        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("cpu-node",
                         allocatable={"cpu": "64", "memory": "256Gi"})
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 4, 4)
        clock = FakeClock()
        mgr = Manager(api, clock=clock,
                      flight_recorder=FlightRecorder(capacity=16384,
                                                     per_object=4096))
        cfg = CoreConfig(**self.CFG)
        metrics = NotebookMetrics(api)
        setup_core_controllers(mgr, cfg, metrics)
        return api, cluster, mgr, clock, cfg, metrics

    def _assert_slice_atomic(self, api, name):
        """Every audited worker-pod delete attempt belongs to a
        contiguous whole-slice group — a partial-slice restart would
        break the grouping."""
        recs = [r for r in api.audit_log(verb="delete", kind="Pod")
                if r.name.startswith(name + "-")]
        expected = {f"{name}-{i}" for i in range(self.HOSTS)}
        for i in range(0, len(recs), self.HOSTS):
            chunk = {r.name for r in recs[i:i + self.HOSTS]}
            assert chunk == expected, (
                "partial-slice pod deletion observed in the audit log",
                [(r.name, r.ok) for r in recs])
        return len(recs) // self.HOSTS

    def _exhausted_cond(self, api, ns, name):
        status = api.get("Notebook", ns, name).body.get("status", {})
        return next((c for c in status.get("conditions", [])
                     if c.get("type") == "RecoveryExhausted"), None)

    def test_recovery_soak_with_failover(self):
        from kubeflow_tpu.utils import tracing
        from kubeflow_tpu.utils.lifecycle import LifecycleLedger
        from kubeflow_tpu.utils.metering import TenantMeteringLedger

        api, cluster, mgr, clock, cfg, metrics = self._env()
        # ONE ledger across the failover (like a sharded fleet's shared
        # ledger): the replacement manager keeps folding attempts into
        # the same stage partition, and conservation must survive the
        # handover plus every recovery excursion the soak provokes
        ledger = LifecycleLedger()
        mgr.lifecycle = ledger
        # same deal for tenant metering: one ledger outlives the deposed
        # manager, so user1's usage attribution spans the handover
        metering = TenantMeteringLedger(clock)
        mgr.metering = metering
        tracing.set_clock(clock)
        try:
            self._recovery_soak_body(api, cluster, mgr, clock, ledger,
                                     metering)
        finally:
            tracing.set_clock(None)

    def _recovery_soak_body(self, api, cluster, mgr, clock, ledger,
                            metering):
        from kubeflow_tpu.core.metrics import metering_bucket, placement_chips

        nb = Notebook.new("healsoak", "user1", tpu=TPUSpec("v5e", "4x4"))
        api.create(nb.obj)
        mgr.run_until_idle()

        print(f"\nrecovery soak: seed={SOAK_SEED} "
              f"rounds={SELFHEAL_SOAK_ROUNDS} "
              "(reproduce with CHAOS_SOAK_SEED/SELFHEAL_SOAK_ROUNDS)")
        rng = random.Random(SOAK_SEED + 13)
        failover_round = SELFHEAL_SOAK_ROUNDS // 2
        for round_i in range(SELFHEAL_SOAK_ROUNDS):
            if round_i == failover_round:
                # leader failover mid-soak: a brand-new manager resumes
                # from the CR-persisted bookkeeping alone.  The deposed
                # manager stops being driven (its queue simply never
                # runs again, as a deposed leader stops reconciling).
                from kubeflow_tpu.core.metrics import NotebookMetrics

                mgr = Manager(api, clock=clock)
                setup_core_controllers(mgr, CoreConfig(**self.CFG),
                                       NotebookMetrics(api))
                mgr.lifecycle = ledger
                mgr.metering = metering
                with api.fault_exempt():
                    mgr.enqueue_all()

            plan_seed = rng.randrange(2**31)
            plan = random_fault_plan(plan_seed, kinds=FAULT_KINDS,
                                     clock=mgr.clock)
            api.install_fault_plan(plan)
            # disrupt 1-2 workers; the recovery engine must do the rest
            # (no restart annotation anywhere in this soak)
            kind = rng.choice(
                ["fail_one", "fail_two", "crashloop", "kill", "none"])
            with api.fault_exempt():
                if kind == "fail_one":
                    cluster.fail_pod(
                        "user1", f"healsoak-{rng.randrange(4)}")
                elif kind == "fail_two":
                    for i in rng.sample(range(4), 2):
                        cluster.fail_pod("user1", f"healsoak-{i}")
                elif kind == "crashloop":
                    cluster.crashloop_pod(
                        "user1", f"healsoak-{rng.randrange(4)}")
                elif kind == "kill":
                    api.delete("Pod", "user1",
                               f"healsoak-{rng.randrange(4)}")
                mgr.enqueue_all()
            mgr.settle(max_seconds=7200.0)
            api.clear_fault_plan()
            with api.fault_exempt():
                mgr.enqueue_all()
            mgr.settle(max_seconds=7200.0)

            assert not mgr.dropped_errors, (
                f"round {round_i} (plan_seed={plan_seed}, "
                f"perturb={kind}): {mgr.dropped_errors}")
            status = api.get("Notebook", "user1",
                             "healsoak").body["status"]
            assert status["sliceHealth"] == "Healthy", (round_i, kind)
            assert status["readyReplicas"] == self.HOSTS
            assert self._exhausted_cond(api, "user1", "healsoak") is None, \
                (round_i, kind, status.get("sliceRecovery"))
            self._assert_slice_atomic(api, "healsoak")
            live = api.get("Notebook", "user1", "healsoak")
            metering.sample({("user1", "healsoak"):
                             (metering_bucket(live),
                              placement_chips(live))})
            # age the sliding window out between rounds so each round
            # gets a fresh budget (the exhaustion path is tested below)
            mgr.advance(self.CFG["recovery_window_s"])

        groups = self._assert_slice_atomic(api, "healsoak")
        assert groups > 0, "soak never exercised a recovery restart"
        assert_no_concurrent_per_key_reconciles(mgr)
        # conservation across the failover: the notebook finalized once
        # (ready is a per-generation event), the partition stayed exact,
        # and every post-ready recovery round landed as an excursion
        # instead of polluting the finalized window
        cons = ledger.conservation()
        assert cons["finalized"] >= 1, cons
        assert cons["violations"] == 0, ledger.violations()[:3]
        # metering conservation across the failover: the (single) meter
        # accrued under both managers and its bucketed sum still equals
        # the measured wall time; attribution kept flowing after handover
        mcons = metering.conservation()
        assert mcons["checked"] >= 1, mcons
        assert mcons["violations"] == 0, metering.violations()[:3]
        row = metering.tenant_table()["user1"]
        assert row["dispatches"] > 0 and row["chip_seconds_total"] > 0

    def test_permanent_failure_exhausts_exactly_at_cap(self):
        api, cluster, mgr, clock, cfg, metrics = self._env()
        nb = Notebook.new("doomed", "user1", tpu=TPUSpec("v5e", "4x4"))
        api.create(nb.obj)
        mgr.run_until_idle()
        cluster.poison_statefulset("user1", "doomed")
        with api.fault_exempt():
            mgr.enqueue_all()
        mgr.settle(max_seconds=float(
            cfg.recovery_window_s + 10 * cfg.recovery_backoff_max_s))
        groups = self._assert_slice_atomic(api, "doomed")
        assert groups == cfg.recovery_max_attempts, groups
        cond = self._exhausted_cond(api, "user1", "doomed")
        assert cond is not None and cond["status"] == "True"
        # terminal: a long quiet period adds zero restarts
        mgr.advance(3600)
        assert self._assert_slice_atomic(api, "doomed") == \
            cfg.recovery_max_attempts
        assert not mgr.dropped_errors


class TestMigrationRecoverySoak:
    """ISSUE-6 acceptance: the seeded checkpoint/migrate drill.  With a
    fresh session checkpoint a disrupted slice recovers via the `migrate`
    verb — audit-verified snapshot -> whole-slice restart -> restore
    stamping — and the restored session is byte-equivalent (digest) to the
    pre-disruption snapshot; with a stale checkpoint it falls back to the
    bare restart, accounted separately in
    notebook_slice_restarts_total{reason}; and a manager failover
    mid-migration resumes from status.sessionState without
    double-restoring."""

    HOSTS = 4

    CFG = dict(
        recovery_backoff_base_s=1.0,
        recovery_backoff_max_s=30.0,
        recovery_max_attempts=4,
        recovery_window_s=120.0,
        recovery_pending_deadline_s=60.0,
        checkpoint_store_uri="mem://session-state",
        checkpoint_max_age_s=300.0,
    )

    # API faults for this soak target the control-plane verbs, not Pod
    # deletes: a delete that fails mid-sweep legitimately leaves workers
    # of the OLD session running until the next detection pass (covered by
    # TestSliceRecoverySoak), which would make byte-exact equivalence
    # assertions racy here.  The drill's subject is state fidelity.
    FAULT_KINDS = ("Notebook", "StatefulSet", "Service", "ConfigMap",
                   "Event")

    def _env(self):
        from kubeflow_tpu.core.metrics import NotebookMetrics
        from kubeflow_tpu.core.sessionstate import InMemorySessionStore
        from kubeflow_tpu.utils.flightrecorder import FlightRecorder

        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 8, 4)
        clock = FakeClock()
        mgr = Manager(api, clock=clock,
                      flight_recorder=FlightRecorder(capacity=16384,
                                                     per_object=4096))
        store = InMemorySessionStore(clock=clock)
        cluster.attach_session_store(store)
        cfg = CoreConfig(**self.CFG)
        metrics = NotebookMetrics(api)
        setup_core_controllers(mgr, cfg, metrics, session=store)
        return api, cluster, mgr, clock, cfg, metrics, store

    def _delete_groups(self, api, name):
        recs = [r for r in api.audit_log(verb="delete", kind="Pod")
                if r.name.startswith(name + "-")]
        expected = {f"{name}-{i}" for i in range(self.HOSTS)}
        for i in range(0, len(recs), self.HOSTS):
            chunk = {r.name for r in recs[i:i + self.HOSTS]}
            assert chunk == expected, (
                "partial-slice pod deletion observed",
                [(r.name, r.ok) for r in recs])
        return len(recs) // self.HOSTS

    def _restored_stamps(self, api, ns="user1"):
        from kubeflow_tpu.core import constants as CC

        return {
            p.name: (p.metadata.annotations.get(
                CC.ANNOTATION_RESTORED_GENERATION),
                p.metadata.annotations.get(CC.ANNOTATION_RESTORED_DIGEST))
            for p in api.list("Pod", namespace=ns)
        }

    def test_seeded_migration_drill_restores_state(self):
        """Seeded rounds of disrupt-with-checkpoint: fresh rounds must
        migrate and restore the exact pre-disruption snapshot; stale
        rounds must bare-restart with NO restore stamping — the two verbs'
        accounting kept separate and exact across the whole soak."""
        api, cluster, mgr, clock, cfg, metrics, store = self._env()
        nb = Notebook.new("migsoak", "user1", tpu=TPUSpec("v5e", "4x4"))
        api.create(nb.obj)
        mgr.run_until_idle()

        print(f"\nmigration soak: seed={SOAK_SEED} "
              f"rounds={MIGRATE_SOAK_ROUNDS} "
              "(reproduce with CHAOS_SOAK_SEED/MIGRATE_SOAK_ROUNDS)")
        rng = random.Random(SOAK_SEED + 29)
        expect_migrated = 0
        expect_bare = 0
        for round_i in range(MIGRATE_SOAK_ROUNDS):
            payload = b"kernel-%d-%d" % (round_i, rng.randrange(2**32))
            cluster.set_session_payload("user1", "migsoak", payload)
            (snap,) = cluster.snapshot_sessions("user1", "migsoak")
            # every third round runs with a stale checkpoint — a fixed
            # cadence (not seed-drawn) so ANY round count exercises both
            # verbs and the expected accounting stays exact
            stale = round_i % 3 == 1
            if stale:
                # age the checkpoint past CHECKPOINT_MAX_AGE_S (and the
                # sliding budget window, which is shorter) before the hit
                mgr.advance(cfg.checkpoint_max_age_s + 60)
            plan_seed = rng.randrange(2**31)
            plan = random_fault_plan(plan_seed, kinds=self.FAULT_KINDS,
                                     clock=mgr.clock)
            api.install_fault_plan(plan)
            kind = rng.choice(["fail_one", "fail_two", "crashloop"])
            with api.fault_exempt():
                if kind == "fail_one":
                    cluster.fail_pod("user1",
                                     f"migsoak-{rng.randrange(4)}")
                elif kind == "fail_two":
                    for i in rng.sample(range(4), 2):
                        cluster.fail_pod("user1", f"migsoak-{i}")
                else:
                    cluster.crashloop_pod("user1",
                                          f"migsoak-{rng.randrange(4)}")
                mgr.enqueue_all()
            mgr.settle(max_seconds=7200.0)
            api.clear_fault_plan()
            with api.fault_exempt():
                mgr.enqueue_all()
            mgr.settle(max_seconds=7200.0)

            assert not mgr.dropped_errors, (round_i, kind, plan_seed)
            status = api.get("Notebook", "user1",
                             "migsoak").body["status"]
            assert status["sliceHealth"] == "Healthy", (round_i, kind)
            stamps = self._restored_stamps(api)
            if stale:
                expect_bare += 1
                # bare restart: the recreated session started cold
                assert all(g is None for g, _ in stamps.values()), \
                    (round_i, stamps)
            else:
                expect_migrated += 1
                # restored-state equivalence: every worker restored the
                # pre-disruption session byte-for-byte (digest).  The
                # generation may legitimately advance past the periodic
                # snapshot when an injected fault forced the
                # migrate.incomplete path to re-flush (a `final` snapshot
                # of the same session), but it can never regress.
                entry = status["sessionState"]["0"]
                assert entry["phase"] == "restored", (round_i, entry)
                assert entry["restoreGeneration"] >= snap.generation
                assert entry["digest"] == snap.digest, (round_i, entry)
                for pod_name, (gen, digest) in stamps.items():
                    assert gen == str(entry["restoreGeneration"]), \
                        (round_i, pod_name, stamps)
                    assert digest == snap.digest, (round_i, pod_name)
            self._delete_groups(api, "migsoak")
            # age out the sliding window so each round has a fresh budget
            mgr.advance(self.CFG["recovery_window_s"])

        assert expect_migrated > 0 and expect_bare > 0, \
            "soak must exercise both verbs; tune the seed"
        # migrate vs bare-restart accounting: every fresh round migrated
        # (possibly more than once when a fault forced a re-migration),
        # every stale round bare-restarted under the disruption's own
        # reason — the migrate label never bleeds into bare restarts
        assert metrics.slice_restarts.value("user1", "migrate") >= \
            expect_migrated
        bare_total = sum(
            metrics.slice_restarts.value("user1", reason)
            for reason in ("pod-failed", "crash-loop"))
        assert bare_total == expect_bare
        assert metrics.migrations.value("failure", "migrated") >= \
            expect_migrated
        # ...but each migration chain finalizes exactly once
        assert metrics.migrations.value("failure", "restored") == \
            expect_migrated
        assert metrics.migrations.value("failure", "fallback-restart") == \
            expect_bare
        assert_no_concurrent_per_key_reconciles(mgr)

    def test_failover_mid_migration_resumes_without_double_restore(self):
        """Kill the manager between the migrate restart and the slice
        turning Healthy: the successor must finish the SAME migration from
        status.sessionState — no second slice restart, no second restore,
        the original snapshot generation stamped on every worker."""
        api, cluster, mgr_a, clock, cfg, metrics_a, store = self._env()
        nb = Notebook.new("failover", "user1", tpu=TPUSpec("v5e", "4x4"))
        api.create(nb.obj)
        mgr_a.run_until_idle()
        cluster.set_session_payload("user1", "failover", b"mid-migration")
        (snap,) = cluster.snapshot_sessions("user1", "failover")

        # freeze the data plane mid-recreate: the migrate verb fires (pods
        # deleted, restore stamped) but the new pods never turn Ready
        # under manager A
        cluster.auto_ready = False
        cluster.fail_pod("user1", "failover-1")
        mgr_a.run_until_idle()
        status = api.get("Notebook", "user1", "failover").body["status"]
        assert status["sessionState"]["0"]["phase"] == "migrating"
        assert self._delete_groups(api, "failover") == 1

        # leader failover: a brand-new manager resumes from the CR alone
        from kubeflow_tpu.core.metrics import NotebookMetrics

        mgr_b = Manager(api, clock=clock)
        metrics_b = NotebookMetrics(api)
        setup_core_controllers(mgr_b, CoreConfig(**self.CFG), metrics_b,
                               session=store)
        with api.fault_exempt():
            mgr_b.enqueue_all()
        mgr_b.run_until_idle()
        # the successor must NOT re-restart the recreating slice
        assert self._delete_groups(api, "failover") == 1

        # the data plane catches up; B observes Healthy and finalizes
        cluster.auto_ready = True
        for i in range(self.HOSTS):
            cluster.mark_running("user1", f"failover-{i}")
        mgr_b.run_until_idle()
        status = api.get("Notebook", "user1", "failover").body["status"]
        assert status["sliceHealth"] == "Healthy"
        entry = status["sessionState"]["0"]
        assert entry["phase"] == "restored"
        assert entry["restoreGeneration"] == snap.generation
        assert self._delete_groups(api, "failover") == 1  # exactly one
        for pod_name, (gen, digest) in self._restored_stamps(api).items():
            assert gen == str(snap.generation), pod_name
            assert digest == snap.digest, pod_name
        # finalization happened exactly once, on the successor
        assert metrics_b.migrations.value("failure", "restored") == 1

    def test_migrate_budget_shared_with_restart_exhausts_at_cap(self):
        """Migrate attempts and bare-restart attempts draw from ONE
        budget: a poisoned slice whose checkpoint goes stale mid-recovery
        migrates first, bare-restarts after, and lands on
        RecoveryExhausted at exactly the configured cap."""
        api, cluster, mgr, clock, cfg, metrics, store = self._env()
        nb = Notebook.new("doomed", "user1", tpu=TPUSpec("v5e", "4x4"))
        api.create(nb.obj)
        mgr.run_until_idle()
        cluster.snapshot_sessions("user1", "doomed")
        cluster.poison_statefulset("user1", "doomed")
        with api.fault_exempt():
            mgr.enqueue_all()
        mgr.settle(max_seconds=float(
            cfg.recovery_window_s + 10 * cfg.recovery_backoff_max_s))
        assert self._delete_groups(api, "doomed") == \
            cfg.recovery_max_attempts
        migrated = metrics.slice_restarts.value("user1", "migrate")
        bare = metrics.slice_restarts.value("user1", "pod-failed")
        assert migrated >= 1, "the fresh checkpoint must migrate first"
        assert migrated + bare == cfg.recovery_max_attempts
        cond = next(
            (c for c in api.get("Notebook", "user1", "doomed")
             .body["status"]["conditions"]
             if c.get("type") == "RecoveryExhausted"), None)
        assert cond is not None and cond["status"] == "True"
        mgr.advance(3600)
        assert self._delete_groups(api, "doomed") == \
            cfg.recovery_max_attempts
        assert not mgr.dropped_errors


class TestPreemptionSoak:
    """ISSUE-19 acceptance: checkpoint-then-preempt under seeded manager
    kills.  Each round a high-priority two-slice gang forces the eviction
    of two checkpointed low-priority victims, and the acting manager is
    killed at a seeded point of the write-ahead protocol — after the
    record commit but before any teardown, between the two victim
    teardowns, or after both teardowns but before the records fold
    terminal.  A fresh successor must RESUME (never repeat) the eviction:
    every victim's StatefulSet is client-deleted exactly once across both
    managers, always whole-slice (zero pod-level client deletes — pods
    cascade through the apiserver's owner-ref GC), every record reaches
    its terminal phase exactly once, the victims' secured checkpoints
    survive intact, and the beneficiary lands on the freed capacity."""

    HOSTS = 4          # per slice: v5e 4x4 = 4 hosts x 4 chips

    class _Killed(RuntimeError):
        """Stands in for the manager process dying mid-protocol."""

    def _env(self):
        from kubeflow_tpu.core.metrics import NotebookMetrics
        from kubeflow_tpu.core.sessionstate import InMemorySessionStore
        from kubeflow_tpu.utils.clock import FakeClock as _FakeClock

        api = ApiServer()
        cluster = FakeCluster(api)
        # two slices of capacity; cold provisioning effectively disabled
        # so the only road to placement for the beneficiary is eviction
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 8, 4)
        clock = _FakeClock()
        mgr = Manager(api, clock=clock)
        cfg = CoreConfig.from_env({
            "ENABLE_SLICE_SCHEDULER": "true",
            "WARMPOOL_SIZE": "0",
            "WARMPOOL_PROVISION_S": "3600",
        })
        store = InMemorySessionStore(clock=clock)
        cluster.attach_session_store(store)
        metrics = NotebookMetrics(api)
        setup_core_controllers(mgr, cfg, metrics, session=store,
                               provisioner=cluster)
        return api, cluster, mgr, clock, cfg, store

    def _sts_deletes(self, api, name):
        return [r for r in api.audit_log(verb="delete", kind="StatefulSet")
                if r.name == name and r.ok]

    def _pod_deletes(self, api, name):
        return [r for r in api.audit_log(verb="delete", kind="Pod")
                if r.name.startswith(name + "-")]

    def test_seeded_kill_points_resume_exactly_once(self):
        import json as _json

        from kubeflow_tpu.api.types import TPUSpec as _TPUSpec
        from kubeflow_tpu.core import constants as CC
        from kubeflow_tpu.core.metrics import NotebookMetrics
        from kubeflow_tpu.core.preemption import (
            PREEMPT_RESULT_EVICTED,
            PREEMPT_RESULT_RESUMED,
            pending_preemption,
        )

        class _Span:
            def add_event(self, *a, **k):
                pass

            def set_attribute(self, *a, **k):
                pass

        print(f"\npreemption soak: seed={SOAK_SEED} "
              f"rounds={PREEMPT_SOAK_ROUNDS} "
              "(reproduce with CHAOS_SOAK_SEED/PREEMPT_SOAK_ROUNDS)")
        rng = random.Random(SOAK_SEED + 53)
        victims = [("t-low-a", "v-a"), ("t-low-b", "v-b")]
        for round_i in range(PREEMPT_SOAK_ROUNDS):
            api, cluster, mgr_a, clock, cfg, store = self._env()
            snaps = {}
            for ns, name in victims:
                nb = Notebook.new(name, ns, tpu=TPUSpec("v5e", "4x4"))
                nb.obj.spec["priority"] = "low"
                api.create(nb.obj)
                mgr_a.run_until_idle()
                payload = b"%s-%d-%d" % (
                    name.encode(), round_i, rng.randrange(2**32))
                cluster.set_session_payload(ns, name, payload)
                (snaps[(ns, name)],) = cluster.snapshot_sessions(ns, name)
            ben_spec = _TPUSpec("v5e", "4x4", 2)
            ben = Notebook.new("ben", "t-hi", tpu=ben_spec)
            ben.obj.spec["priority"] = "high"
            api.create(ben.obj)

            # kill point: after j completed teardowns (j == len(victims)
            # kills between the teardowns and the terminal record fold) —
            # a fixed cadence so ANY round count exercises every point
            kill_after = round_i % (len(victims) + 1)
            engine = mgr_a.preemption_engine
            orig_teardown = engine._teardown_victim
            done = {"n": 0}

            def kill_teardown(victim_rec):
                if done["n"] >= kill_after:
                    raise self._Killed()
                out = orig_teardown(victim_rec)
                done["n"] += 1
                return out

            engine._teardown_victim = kill_teardown
            if kill_after >= len(victims):
                engine._finish_records = lambda plan, result: (
                    (_ for _ in ()).throw(self._Killed()))

            # manager A plans the eviction and dies mid-protocol.  The
            # engine is driven directly (as the scheduler's waiting
            # branch would) so the kill cannot leak into A's workqueue —
            # A is abandoned from here on, exactly like a dead process.
            with pytest.raises(self._Killed):
                engine.maybe_preempt(
                    Notebook(api.get("Notebook", "t-hi", "ben")),
                    ben_spec.shape, 2 * float(ben_spec.shape.chips),
                    _Span())
            for ns, name in victims:
                assert pending_preemption(api, ns, name), (
                    round_i, kill_after, ns, name,
                    "the write-ahead record must be down before ANY kill "
                    "point")

            # successor manager resumes from the record alone
            mgr_b = Manager(api, clock=clock)
            metrics_b = NotebookMetrics(api)
            setup_core_controllers(mgr_b, cfg, metrics_b, session=store,
                                   provisioner=cluster)
            mgr_b.enqueue_all()
            mgr_b.run_until_idle()
            for _ in range(3):
                mgr_b.advance(20.0)
            assert not mgr_b.dropped_errors, (round_i, kill_after)

            quota = api.get(CC.TENANTQUOTA_KIND, "", CC.TENANTQUOTA_NAME)
            st = quota.body.get("status") or {}
            assert not (st.get("preemptions") or {}), (
                round_i, kill_after, st)
            recents = st.get("recentPreemptions") or []
            for ns, name in victims:
                key = f"{ns}/{name}"
                mine = [r for r in recents if r.get("victim") == key]
                assert len(mine) == 1 \
                    and mine[0]["phase"] == CC.PREEMPTION_DONE, (
                    round_i, kill_after, recents)
                # exactly-once, whole-slice teardown across BOTH managers
                assert len(self._sts_deletes(api, name)) == 1, (
                    round_i, kill_after, name)
                assert self._pod_deletes(api, name) == [], (
                    round_i, kill_after, name)
                assert api.list("Pod", namespace=ns) == [], (
                    round_i, kill_after, ns)
                vobj = api.get("Notebook", ns, name)
                assert CC.ANNOTATION_PLACEMENT not in \
                    vobj.metadata.annotations, (round_i, kill_after, name)
                # the eviction stamps reason "preempted"; once the
                # beneficiary places the fence lifts and ordinary
                # re-admission may restamp the line reason — but the
                # victim re-queues at its OWN priority either way
                info = _json.loads(
                    vobj.metadata.annotations[CC.ANNOTATION_QUEUED])
                assert info.get("reason") in (
                    "preempted", "quota", "fair-share", "ordered"), info
                assert info.get("priority") == "low", info
                sess = (vobj.body.get("status") or {}) \
                    .get("sessionState") or {}
                snap = snaps[(ns, name)]
                assert sess.get("0", {}).get("digest") == snap.digest, (
                    round_i, kill_after, name, sess)
                assert sess.get("0", {}).get("trigger") == "preempt", sess
            # A died before folding anything: the successor RESUMES every
            # record — each counted exactly once, none double-evicted
            assert metrics_b.preemptions.value(
                PREEMPT_RESULT_RESUMED, "low") == len(victims), (
                round_i, kill_after)
            assert metrics_b.preemptions.value(
                PREEMPT_RESULT_EVICTED, "low") == 0, (round_i, kill_after)
            # the beneficiary lands on the freed capacity
            ben_obj = api.get("Notebook", "t-hi", "ben")
            assert CC.ANNOTATION_PLACEMENT in ben_obj.metadata.annotations
            assert ben_obj.body["status"]["sliceHealth"] == "Healthy", (
                round_i, kill_after)
            # a second sweep is a no-op: the resume ran exactly once
            mgr_b.enqueue_all()
            mgr_b.run_until_idle()
            assert metrics_b.preemptions.value(
                PREEMPT_RESULT_RESUMED, "low") == len(victims)
            for _, name in victims:
                assert len(self._sts_deletes(api, name)) == 1
            assert not mgr_b.dropped_errors, (round_i, kill_after)


class TestFailoverSoak:
    """Replicated-kernel tier acceptance: a seeded soak that kills the
    CURRENT primary gang every round under injected control-plane
    partitions, against a spec.replication notebook whose follower gang
    is kept warm from the checkpoint-delta stream.  Every round must
    promote the follower with ZERO kernel-state loss (the elected
    standby's stamped digest is the store's chain head, the materialized
    state survives bit-for-bit, and the demoted zombie's stale-epoch
    write is fenced), ZERO double-primaries (the epoch bumps EXACTLY once
    per kill), and sub-second promotions: the promotion p99 must beat the
    snapshot->restore baseline — a non-replicated notebook recovered via
    the migrate verb in the SAME soak under the same faults — by at least
    5x, and stay under the ci/fleet_budget.json failover ceiling."""

    HOSTS = 4
    REPLICAS = 2
    # modeled checkpoint-reload time: a pod recreated with a restore stamp
    # stays in RestoringCheckpoint this long (cluster.restore_hold) — the
    # cost snapshot->restore recovery pays and promotion does not.  Kept
    # under recovery_pending_deadline_s so the hold never reads as a stuck
    # restart.
    RESTORE_S = 45.0

    CFG = dict(
        # failover-tier pacing: resumed promotions retry on this requeue,
        # so the base backoff is what bounds a fault-interrupted
        # promotion's tail latency
        recovery_backoff_base_s=0.25,
        recovery_backoff_max_s=30.0,
        recovery_max_attempts=4,
        recovery_window_s=120.0,
        recovery_pending_deadline_s=60.0,
        checkpoint_store_uri="mem://session-state",
        checkpoint_max_age_s=300.0,
    )

    # control-plane verbs only (the "partition"): Pod-delete faults are
    # TestSliceRecoverySoak's subject and would make the byte-exact
    # state-equivalence assertions racy here
    FAULT_KINDS = ("Notebook", "StatefulSet", "Service", "ConfigMap",
                   "Event")

    def _env(self):
        from kubeflow_tpu.core.metrics import NotebookMetrics
        from kubeflow_tpu.core.sessionstate import InMemorySessionStore
        from kubeflow_tpu.utils.flightrecorder import FlightRecorder

        api = ApiServer()
        cluster = FakeCluster(api)
        # two gangs for the replicated notebook + one for the baseline
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 12, 4)
        clock = FakeClock()
        mgr = Manager(api, clock=clock,
                      flight_recorder=FlightRecorder(capacity=16384,
                                                     per_object=4096))
        store = InMemorySessionStore(clock=clock)
        cluster.attach_session_store(store)
        # snapshot->restore pays a real reload: restore-stamped pods park
        # in RestoringCheckpoint until release_restores() after RESTORE_S
        cluster.restore_hold = True
        cfg = CoreConfig(**self.CFG)
        metrics = NotebookMetrics(api)
        setup_core_controllers(mgr, cfg, metrics, session=store)
        return api, cluster, mgr, clock, cfg, metrics, store

    @staticmethod
    def _p99(hist, ns):
        """Upper-bound p99 estimate from the exposed cumulative buckets —
        the same arithmetic a recording rule would run on the scrape."""
        import math

        cum = hist.bucket_counts(ns)
        total = cum[float("inf")]
        assert total > 0, "no observations to estimate p99 from"
        want = math.ceil(0.99 * total)
        return next(bound for bound, c in cum.items() if c >= want)

    def _replication(self, api):
        status = api.get("Notebook", "user1", "fsoak").body.get(
            "status") or {}
        return status.get("replication") or {}

    def test_failover_soak_sub_second_promotions(self):
        from kubeflow_tpu.api.types import ReplicationSpec
        from kubeflow_tpu.core.sessionstate import (
            StaleWriterError,
            payload_digest,
        )

        api, cluster, mgr, clock, cfg, metrics, store = self._env()
        api.create(Notebook.new(
            "fsoak", "user1", tpu=TPUSpec("v5e", "4x4"),
            replication=ReplicationSpec(replicas=self.REPLICAS)).obj)
        # the snapshot->restore baseline: same store, same faults, no
        # standby — recovery pays the full migrate cycle
        api.create(Notebook.new("fbase", "base",
                                tpu=TPUSpec("v5e", "4x4")).obj)
        mgr.run_until_idle()

        print(f"\nfailover soak: seed={SOAK_SEED} "
              f"rounds={FAILOVER_SOAK_ROUNDS} "
              "(reproduce with CHAOS_SOAK_SEED/FAILOVER_SOAK_ROUNDS)")
        rng = random.Random(SOAK_SEED + 41)
        epoch, primary = 1, 0
        kills = 0
        for round_i in range(FAILOVER_SOAK_ROUNDS):
            payload = b"kernel-%d-%d" % (round_i, rng.randrange(2**32))
            deltas = [b"+cell-%d-%d" % (round_i, j)
                      for j in range(rng.randrange(1, 4))]
            with api.fault_exempt():
                cluster.set_session_payload("user1", "fsoak", payload)
                cluster.snapshot_sessions("user1", "fsoak")
                for d in deltas:
                    cluster.stream_session_delta("user1", "fsoak", d,
                                                 writer_epoch=epoch)
                cluster.sync_followers("user1", "fsoak")
                cluster.set_session_payload("base", "fbase", payload)
                cluster.snapshot_sessions("base", "fbase")
                mgr.enqueue_all()
            mgr.settle(max_seconds=7200.0)

            expected_state = payload + b"".join(deltas)
            head = store.chain_head("user1", "fsoak", 0)
            assert head[2] == payload_digest(expected_state)
            # the election's evidence: the standby is stamped AT the head
            standby = 1 - primary
            rep = self._replication(api)
            fresh = rep["followers"][str(standby)]["slices"]["0"]
            assert fresh["digest"] == head[2], (round_i, fresh)

            plan_seed = rng.randrange(2**31)
            plan = random_fault_plan(plan_seed, kinds=self.FAULT_KINDS,
                                     clock=mgr.clock)
            api.install_fault_plan(plan)
            primary_sts = "fsoak" if primary == 0 else f"fsoak-r{primary}"
            with api.fault_exempt():
                cluster.fail_pod(
                    "user1", f"{primary_sts}-{rng.randrange(self.HOSTS)}")
                cluster.fail_pod(
                    "base", f"fbase-{rng.randrange(self.HOSTS)}")
                mgr.enqueue_all()
            kills += 1
            # drive the promotion to its terminal record under the ACTIVE
            # partition in short resurrect/advance beats — one deep
            # workqueue backoff must not park the resume behind the reload
            # windows below and smear its latency into them (controllers
            # run a periodic resync in production; enqueue_all plays it)
            for _ in range(10):
                with api.fault_exempt():
                    rep = self._replication(api)
                if rep.get("epoch") == epoch + 1 and \
                        rep.get("promotion", {}).get("phase") == "promoted":
                    break
                with api.fault_exempt():
                    mgr.enqueue_all()
                mgr.advance(1.0)
            # bounded drive, NOT settle: the recreated pods sit in
            # RestoringCheckpoint for the whole reload window, and
            # promotion must complete without waiting on any of them
            mgr.advance(self.RESTORE_S)
            api.clear_fault_plan()
            # a partition can exponential-backoff the restart itself past
            # the first window; enqueue_all resurrects it, then each sweep
            # completes the reloads the previous window's restarts started
            released = 0
            for _ in range(3):
                with api.fault_exempt():
                    released += cluster.release_restores()
                    mgr.enqueue_all()
                mgr.advance(self.RESTORE_S)
            # the kill always forces at least the baseline's pod (and
            # usually the demoted gang's) through the reload path
            assert released >= 1, (round_i, released)
            mgr.settle(max_seconds=7200.0)
            with api.fault_exempt():
                if cluster.release_restores():
                    mgr.enqueue_all()
                    mgr.settle(max_seconds=7200.0)

            assert not mgr.dropped_errors, (round_i, plan_seed)
            # zero double-primary: EXACTLY one epoch bump per kill, the
            # authority flipped to the standby, the record is terminal
            rep = self._replication(api)
            assert rep["epoch"] == epoch + 1, (round_i, rep)
            assert rep["primary"] == standby, (round_i, rep)
            assert rep["promotion"]["phase"] == "promoted", (round_i, rep)
            assert store.fence_epoch("user1", "fsoak") == epoch + 1
            # zero state loss: the stream survived the failover untouched
            assert store.materialize("user1", "fsoak", 0) == \
                expected_state, round_i
            # ... and the demoted zombie cannot ack a write after the fact
            with pytest.raises(StaleWriterError):
                store.append_delta("user1", "fsoak", 0, b"+zombie",
                                   writer_epoch=epoch)
            assert store.materialize("user1", "fsoak", 0) == \
                expected_state, round_i
            epoch += 1
            primary = standby
            for ns, name in (("user1", "fsoak"), ("base", "fbase")):
                status = api.get("Notebook", ns, name).body["status"]
                assert status["sliceHealth"] == "Healthy", (round_i, ns)
                assert status["readyReplicas"] == self.HOSTS, (round_i, ns)
            # fresh budget each round: the soak's subject is failover
            # latency, not the sliding-window exhaustion path
            mgr.advance(self.CFG["recovery_window_s"])

        assert kills >= 50 or kills == FAILOVER_SOAK_ROUNDS
        assert metrics.promotions.value("user1", "promoted") >= kills
        assert metrics.promotions.value("user1", "no-candidate") == 0
        assert store.fenced_rejections[("user1", "fsoak")] >= kills
        assert_no_concurrent_per_key_reconciles(mgr)

        # the tier's reason to exist: promotion p99 at least 5x below the
        # snapshot->restore baseline from the same soak, and under the CI
        # fleet budget's failover ceiling
        import json as _json

        promo_p99 = self._p99(metrics.promotion_duration_seconds, "user1")
        baseline_p99 = self._p99(metrics.disruption_recovery_seconds,
                                 "base")
        print(f"failover soak: promotion p99<={promo_p99}s, "
              f"snapshot->restore baseline p99<={baseline_p99}s")
        assert promo_p99 * 5 <= baseline_p99, (promo_p99, baseline_p99)
        budget = _json.loads(
            (Path(__file__).parent.parent / "ci" /
             "fleet_budget.json").read_text())
        assert promo_p99 <= budget["failover"]["max_promotion_p99_s"], (
            promo_p99, budget["failover"])


class TestFlightRecorderDebugSoak:
    """PR-3 acceptance: drive a TPU notebook through injected faults under
    FakeClock, then recover the full history PURELY via the flight recorder
    and the /debug HTTP endpoints — every attempt's result and duration,
    the slowest attempt's trace with per-phase spans, every injected fault
    attributed to the attempt it hit — and prove the telemetry spine around
    it: OpenMetrics exemplar trace ids resolve to recorded traces, and tail
    sampling exports ALL errored/slow attempts while dropping the
    fast-success firehose."""

    def test_post_hoc_diagnosis_via_debug_endpoints(self):
        import json
        import re
        import urllib.error
        import urllib.request

        from kubeflow_tpu.core.metrics import NotebookMetrics
        from kubeflow_tpu.kube.faults import FaultPlan, FaultRule
        from kubeflow_tpu.main import serve_http
        from kubeflow_tpu.utils import tracing
        from kubeflow_tpu.utils.tracing import InMemorySpanExporter, TailSampler

        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("cpu-node",
                         allocatable={"cpu": "64", "memory": "256Gi"})
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 4, 4)
        clock = FakeClock()
        mgr = Manager(api, clock=clock)
        metrics = NotebookMetrics(api)
        setup_core_controllers(mgr, CoreConfig(), metrics)
        setup_odh_controllers(mgr, OdhConfig(controller_namespace=CENTRAL_NS))
        inner = InMemorySpanExporter()
        sampler = TailSampler(inner, slow_threshold_s=0.2, sample_rate=0.0,
                              seed=3)
        tracing.set_exporter(sampler)
        tracing.set_clock(clock)
        server = serve_http(0, mgr, metrics)
        port = server.server_address[1]

        def get(path, headers=None, ok=(200,)):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", headers=headers or {})
            try:
                with urllib.request.urlopen(req, timeout=5) as resp:
                    assert resp.status in ok
                    return resp.read().decode()
            except urllib.error.HTTPError as err:
                assert err.code in ok, (path, err.code)
                return err.read().decode()

        try:
            nb = Notebook.new("fr", "user1", tpu=TPUSpec("v5e", "4x4"))
            api.create(nb.obj)
            mgr.run_until_idle()

            # a converged fleet's reconciles are all-cache-reads (indexed
            # informer cache + no-op write suppression), so faults must
            # target a verb real drift provokes: delete the notebook's
            # Service and fault its re-creation.
            # phase A: two injected 503s on the Service create -> two
            # errored notebook attempts, then recovery
            plan_err = FaultPlan([FaultRule(
                verbs=("create",), kinds=("Service",),
                error="unavailable", max_matches=2, name="err")],
                clock=clock)
            with api.fault_exempt():
                api.delete("Service", "user1", "fr")
            api.install_fault_plan(plan_err)
            with api.fault_exempt():
                mgr.enqueue_all()
            mgr.settle(max_seconds=7200.0)
            api.clear_fault_plan()
            assert plan_err.exhausted() and len(plan_err.log) == 2

            # phase B: one 0.5s latency on the Service create -> one SLOW
            # (but successful) attempt, above the 0.2s tail threshold
            plan_lag = FaultPlan([FaultRule(
                verbs=("create",), kinds=("Service",),
                latency_s=0.5, max_matches=1, name="lag")], clock=clock)
            with api.fault_exempt():
                api.delete("Service", "user1", "fr")
            api.install_fault_plan(plan_lag)
            with api.fault_exempt():
                mgr.enqueue_all()
            mgr.settle(max_seconds=7200.0)
            api.clear_fault_plan()
            assert len(plan_lag.log) == 1
            assert not mgr.dropped_errors
            assert_steady_state(api, "user1", "fr", 4)

            # -- recover the history purely over the /debug surface -------
            snap = json.loads(get("/debug/reconciles?object=user1/fr"))
            attempts = snap["attempts"]
            assert attempts, "no recorded attempts for user1/fr"
            for a in attempts:  # every attempt: result + duration
                assert a["result"] in ("success", "error", "requeue",
                                       "requeue_after"), a
                assert a["duration_s"] >= 0.0 and a["trace_id"], a

            # every injected fault is attributed to EXACTLY the attempt
            # (root span) it hit, carrying the fault's seq
            everything = json.loads(get("/debug/reconciles"))
            all_attempts = everything["attempts"]
            for plan in (plan_err, plan_lag):
                for rec in plan.log:
                    owners = [a for a in all_attempts
                              if a["span_id"] == rec.span_id]
                    assert len(owners) == 1, rec
                    a = owners[0]
                    assert a["trace_id"] == rec.trace_id
                    assert any(f.get("fault.seq") == rec.seq
                               for f in a["faults"]), (rec, a)
                    if rec.action.startswith("error:"):
                        assert a["result"] == "error" and a["error"], a

            # the two 503s are the ONLY errored attempts, retained
            errored = everything["errored"]
            assert len(errored) == 2
            assert {a["span_id"] for a in errored} == \
                {rec.span_id for rec in plan_err.log}

            # slowest attempt = the latency-injected one; its trace has the
            # controller's per-phase spans
            slowest = everything["slowest"][0]
            assert slowest["duration_s"] >= 0.5
            assert slowest["span_id"] == plan_lag.log[0].span_id
            assert slowest["phases"], slowest
            trace = json.loads(get(f"/debug/traces/{slowest['trace_id']}"))
            tree = next(s for s in trace["spans"]
                        if s["span_id"] == slowest["span_id"])
            child_names = {c["name"] for c in tree["children"]}
            assert {"render", "apply", "status"} <= child_names, child_names

            # -- exemplars: the OpenMetrics scrape pivots to recorded
            # traces ------------------------------------------------------
            om = get("/metrics",
                     headers={"Accept": "application/openmetrics-text"})
            assert om.rstrip().endswith("# EOF")
            tids = set(re.findall(r'# \{trace_id="([0-9a-f]+)"\}', om))
            assert tids, "no exemplars in the OpenMetrics scrape"
            for tid in tids:
                resolved = json.loads(get(f"/debug/traces/{tid}"))
                assert resolved["spans"], tid

            # -- tail sampling: all errored + slow exported, fast-success
            # attempts dropped --------------------------------------------
            exported_roots = inner.find("reconcile")
            decisions = [s.attributes["sampling.decision"]
                         for s in exported_roots]
            assert sorted(decisions) == ["error", "error", "slow"]
            assert {s.span_id for s in exported_roots
                    if s.attributes["sampling.decision"] == "error"} == \
                {rec.span_id for rec in plan_err.log}
            slow_root = next(s for s in exported_roots
                             if s.attributes["sampling.decision"] == "slow")
            assert slow_root.span_id == plan_lag.log[0].span_id
            # exported attempts come with their phase children
            assert inner.find("render") and inner.find("status")
            # ...while the fast-success majority stayed in-process only
            recorded = everything["recorded_total"]
            assert recorded > len(exported_roots) * 3
            assert sampler.dropped_total > 0
        finally:
            api.clear_fault_plan()
            tracing.set_exporter(None)
            tracing.set_clock(None)
            server.shutdown()


class TestFleetSLOSoak:
    """ISSUE-10 acceptance: a seeded chaos soak with the SLO engine and
    the continuous profiler enabled must end with (1) every injected
    degradation window producing exactly one fired-then-resolved burn
    alert carrying a trace_id that resolves in the flight recorder,
    (2) ZERO firing alerts at soak end (outside the injected-fault
    windows), (3) /debug/fleet counts matching the apiserver's ground
    truth, (4) profiler self-overhead under 5% of wall time, and (5) an
    ops.diagnose bundle from which the soak's slowest attempt is fully
    reconstructable offline."""

    FLEET = 4
    WINDOWS = 3

    def test_fleet_slo_soak_end_to_end(self):
        import json

        from kubeflow_tpu.core.metrics import NotebookMetrics, fleet_state
        from kubeflow_tpu.kube.faults import FaultPlan, FaultRule
        from kubeflow_tpu.ops.diagnose import collect_local
        from kubeflow_tpu.utils import tracing
        from kubeflow_tpu.utils.flightrecorder import FlightRecorder
        from kubeflow_tpu.utils.profiler import ContinuousProfiler
        from kubeflow_tpu.utils.slo import SLOEngine, default_objectives

        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("cpu-node",
                         allocatable={"cpu": "64", "memory": "256Gi"})
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4",
                                    4 * self.FLEET, 4)
        clock = FakeClock()
        mgr = Manager(api, clock=clock,
                      flight_recorder=FlightRecorder(capacity=16384,
                                                     per_object=4096))
        metrics = NotebookMetrics(api, manager=mgr)
        cfg = CoreConfig()
        setup_core_controllers(mgr, cfg, metrics)
        setup_odh_controllers(mgr, OdhConfig(controller_namespace=CENTRAL_NS))
        engine = SLOEngine(
            default_objectives(cfg),
            registries=[metrics.registry, mgr.metrics_registry],
            clock=clock, recorder=mgr.flight_recorder, burn_threshold=2.0)
        mgr.slo_engine = engine
        metrics.attach_slo(engine)
        profiler = ContinuousProfiler(registry=metrics.registry,
                                      interval_s=0.002)
        mgr.profiler = profiler
        tracing.set_clock(clock)
        profiler.start()
        try:
            for i in range(self.FLEET):
                api.create(Notebook.new(f"slo-{i}", "user1",
                                        tpu=TPUSpec("v5e", "4x4")).obj)
            mgr.run_until_idle()
            metrics.scrape()  # baseline evaluation: nothing fires
            assert not engine.firing()

            def alerts_for(objective):
                return [a for a in engine.alert_history()
                        if a.objective == objective]

            # one latency fault early on: the soak's distinguished
            # slowest attempt, reconstructed from the bundle at the end
            plan_lag = FaultPlan([FaultRule(
                verbs=("create",), kinds=("Service",), latency_s=0.75,
                max_matches=1, name="lag")], clock=clock)
            with api.fault_exempt():
                api.delete("Service", "user1", "slo-0")
            api.install_fault_plan(plan_lag)
            with api.fault_exempt():
                mgr.enqueue_all()
            mgr.settle(max_seconds=7200.0)
            api.clear_fault_plan()
            assert len(plan_lag.log) == 1

            # injected degradation windows: each faults Service creates
            # hard enough that the reconcile-error budget burns in both
            # windows, then recovers and drains the short window
            for w in range(self.WINDOWS):
                before = len(alerts_for("reconcile_errors"))
                plan = FaultPlan([FaultRule(
                    verbs=("create",), kinds=("Service",),
                    error="unavailable", max_matches=4,
                    name=f"win-{w}")], clock=clock)
                with api.fault_exempt():
                    api.delete("Service", "user1", f"slo-{w % self.FLEET}")
                api.install_fault_plan(plan)
                with api.fault_exempt():
                    mgr.enqueue_all()
                mgr.settle(max_seconds=7200.0)
                api.clear_fault_plan()
                assert len(plan.log) == 4

                metrics.scrape()  # scrape-driven evaluation mid-window
                firing = engine.firing()
                assert [a.objective for a in firing] == \
                    ["reconcile_errors"], (w, firing)
                assert len(alerts_for("reconcile_errors")) == before + 1

                # recovery: restore steady state, then drain the short
                # window with idle scrapes — the alert must resolve
                with api.fault_exempt():
                    mgr.enqueue_all()
                mgr.settle(max_seconds=7200.0)
                for _ in range(3):
                    clock.advance(150)
                    metrics.scrape()
                assert not engine.firing(), f"window {w} never resolved"
                for nb_i in range(self.FLEET):
                    assert_steady_state(api, "user1", f"slo-{nb_i}", 4)

            # (1)+(2): exactly one fired-then-resolved alert per window,
            # zero firing at soak end, each with a resolvable trace id
            history = alerts_for("reconcile_errors")
            assert len(history) == self.WINDOWS
            assert not engine.firing()
            for alert in history:
                assert alert.state == "resolved"
                assert alert.resolved_at > alert.fired_at
                assert alert.trace_id, alert
                trace = mgr.flight_recorder.trace(alert.trace_id)
                assert trace is not None and trace["spans"], alert
            # the firing gauge reads 0 in the final exposition
            final = metrics.scrape()
            assert 'notebook_slo_alert_firing{objective='\
                '"reconcile_errors"} 0' in final

            # (3) /debug/fleet counts == apiserver ground truth
            snap = metrics.fleet_snapshot()
            truth = {}
            for nb in api.list("Notebook"):
                s = fleet_state(nb)
                truth[s] = truth.get(s, 0) + 1
            assert {k: v for k, v in snap["totals"].items() if v} == truth
            assert snap["notebooks"] == self.FLEET
            assert snap["namespaces"]["user1"]["ready"] == self.FLEET

            # (4) profiler stayed cheap while always-on
            profiler.stop()
            assert profiler.passes > 0 and profiler.samples_total > 0
            overhead = profiler.overhead_ratio()
            assert overhead < 0.05, f"profiler overhead {overhead:.3f}"
            gauge = metrics.registry.get("notebook_profiler_overhead_ratio")
            assert gauge.collect()[()] == profiler.overhead_ratio()

            # (5) the diagnose bundle reconstructs the slowest attempt
            # offline: summary -> trace id -> span tree, no live objects
            bundle = collect_local(mgr, metrics)
            blob = json.dumps(bundle, default=str)  # self-contained JSON
            offline = json.loads(blob)
            slowest = offline["reconciles"]["slowest"][0]
            assert slowest["duration_s"] >= 0.75  # the injected lag
            tree = offline["traces"][slowest["trace_id"]]
            roots = [s for s in tree["spans"]
                     if s["span_id"] == slowest["span_id"]]
            assert len(roots) == 1
            assert {"render", "apply", "status"} <= {
                c["name"] for c in roots[0]["children"]}
            assert any(f.get("fault.rule") == "lag"
                       for f in slowest["faults"]), slowest
            # alert history and fleet rollup ride in the same artifact
            assert len(offline["alerts"]["history"]) >= self.WINDOWS
            assert offline["fleet"]["totals"]["ready"] == self.FLEET
            assert offline["profile"]["samples_total"] == \
                profiler.samples_total
        finally:
            api.clear_fault_plan()
            profiler.stop()
            tracing.set_clock(None)
            mgr.stop()


class TestStragglerSoak:
    """ISSUE-11 acceptance: an injected slow worker must be attributed to
    the right (notebook, worker) via the fleet rollup AND the diagnose
    bundle — exactly one straggler gauge + Warning event — must clear
    when healed, and the straggler SLO objective must never false-fire
    on healthy slices."""

    FLEET = 2
    WORKERS = 4

    def test_straggler_soak_attribution_and_clear(self):
        import json

        from kubeflow_tpu.core.metrics import NotebookMetrics
        from kubeflow_tpu.core.telemetry import (
            EVENT_STRAGGLER,
            EVENT_STRAGGLER_CLEARED,
            WorkerTelemetryAggregator,
        )
        from kubeflow_tpu.kube import EventRecorder
        from kubeflow_tpu.models.configs import LLAMA2_350M
        from kubeflow_tpu.ops.diagnose import collect_local
        from kubeflow_tpu.utils.flightrecorder import FlightRecorder
        from kubeflow_tpu.utils.slo import SLOEngine, default_objectives

        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("cpu-node",
                         allocatable={"cpu": "64", "memory": "256Gi"})
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4",
                                    self.WORKERS * self.FLEET, 4)
        clock = FakeClock()
        mgr = Manager(api, clock=clock, flight_recorder=FlightRecorder())
        metrics = NotebookMetrics(api, manager=mgr)
        # straggler SLO objective armed (knob-disabled by default): the
        # soak proves it stays silent on healthy slices
        cfg = CoreConfig(slo_straggler_rate=0.30)
        setup_core_controllers(mgr, cfg, metrics)
        aggregator = WorkerTelemetryAggregator(
            api, metrics.registry, clock, cache=mgr.cache,
            recorder=EventRecorder(api, "dataplane-telemetry"),
            straggler_ratio=cfg.dataplane_straggler_ratio,
            min_workers=cfg.dataplane_straggler_min_workers)
        metrics.attach_dataplane(aggregator)
        mgr.telemetry_aggregator = aggregator
        engine = SLOEngine(
            default_objectives(cfg),
            registries=[metrics.registry, mgr.metrics_registry],
            clock=clock, recorder=mgr.flight_recorder)
        metrics.attach_slo(engine)
        mgr.slo_engine = engine
        try:
            for i in range(self.FLEET):
                api.create(Notebook.new(f"tele-{i}", "user1",
                                        tpu=TPUSpec("v5e", "4x4")).obj)
            mgr.run_until_idle()
            for i in range(self.FLEET):
                assert api.get("Notebook", "user1", f"tele-{i}").body[
                    "status"]["sliceHealth"] == "Healthy"

            def stamp(slow=None):
                for i in range(self.FLEET):
                    cluster.stamp_worker_telemetry(
                        "user1", f"tele-{i}", step_time_s=0.5,
                        config=LLAMA2_350M, batch=8, seq_len=2048,
                        num_chips=4, slow_worker=(
                            slow if i == 0 else None),
                        slow_factor=4.0, now=clock.now())

            def straggler_events(nb, reason=EVENT_STRAGGLER):
                return [e for e in api.list("Event", namespace="user1")
                        if e.body.get("reason") == reason
                        and e.body["involvedObject"]["name"] == nb]

            gauge = metrics.registry.get("notebook_dataplane_straggler")

            # phase 1 — healthy fleet: scrapes see telemetry, zero
            # straggler firings, SLO objective silent
            stamp()
            for _ in range(4):
                clock.advance(60)
                metrics.scrape()
            snap = metrics.fleet_snapshot()["dataplane"]
            assert snap["fleet"]["notebooks"] == self.FLEET
            assert snap["stragglers"] == []
            for i in range(self.FLEET):
                assert gauge.collect()[("user1", f"tele-{i}")] == 0.0
                assert straggler_events(f"tele-{i}") == []
            assert not engine.firing()

            # phase 2 — inject one deliberately slow worker on tele-0
            stamp(slow=2)  # ordinal 2 -> pod tele-0-2
            clock.advance(60)
            metrics.scrape()
            snap = metrics.fleet_snapshot()["dataplane"]
            assert [(s["namespace"], s["name"], s["worker"])
                    for s in snap["stragglers"]] == \
                [("user1", "tele-0", "tele-0-2")]
            assert snap["notebooks"]["user1/tele-0"]["straggler"] == \
                "tele-0-2"
            assert gauge.collect()[("user1", "tele-0")] == 1.0
            assert gauge.collect()[("user1", "tele-1")] == 0.0
            # exactly ONE Warning event, naming the worker, even across
            # repeated scrapes while the breach persists
            for _ in range(3):
                clock.advance(60)
                metrics.scrape()
            events = straggler_events("tele-0")
            assert len(events) == 1
            assert "tele-0-2" in events[0].body["message"]
            assert straggler_events("tele-1") == []

            # the diagnose bundle attributes the straggler offline
            bundle = json.loads(json.dumps(
                collect_local(mgr, metrics), default=str))
            assert [s["worker"] for s in
                    bundle["telemetry"]["stragglers"]] == ["tele-0-2"]
            assert bundle["fleet"]["dataplane"]["notebooks"][
                "user1/tele-0"]["straggler"] == "tele-0-2"
            assert 'notebook_dataplane_straggler{namespace="user1",' \
                'name="tele-0"} 1' in bundle["metrics"]

            # phase 3 — heal: the worker rejoins the pace
            stamp()
            clock.advance(60)
            metrics.scrape()
            snap = metrics.fleet_snapshot()["dataplane"]
            assert snap["stragglers"] == []
            assert gauge.collect()[("user1", "tele-0")] == 0.0
            assert len(straggler_events("tele-0")) == 1  # no re-fire
            assert len(straggler_events(
                "tele-0", EVENT_STRAGGLER_CLEARED)) == 1

            # phase 4 — healthy soak tail: the straggler SLO objective
            # drains and must not be firing at soak end, and tele-1
            # stayed clean the whole run
            for _ in range(6):
                clock.advance(120)
                metrics.scrape()
            assert not engine.firing()
            assert straggler_events("tele-1") == []
            assert straggler_events("tele-1", EVENT_STRAGGLER_CLEARED) \
                == []
            # verdict counters saw both phases: mostly-ok, some straggler
            checks = metrics.registry.get(
                "notebook_dataplane_straggler_checks_total").collect()
            assert checks[("straggler",)] >= 1
            assert checks[("ok",)] > checks[("straggler",)]
        finally:
            mgr.stop()


class TestShardKillRejoinSoak:
    """Active-active acceptance (kube/shard.py): a 3-replica sharded
    fleet survives seeded rounds of kill / zombie-write / rejoin /
    notebook churn with

      1. every notebook converged (StatefulSet present, status stamped)
         after each round,
      2. ZERO cross-process double-reconciles over the MERGED
         flight-recorder histories of all replicas — the single-owner
         proof, swept by the same `sweep_overlaps` that backs
         `ops.diagnose --merge`,
      3. every zombie write REJECTED with a stale epoch and counted in
         the shard snapshot (fenced_rejections),
      4. the map epoch strictly monotonic across membership changes, and
      5. one diagnose bundle per replica, merged offline, agreeing with
         the in-process sweep (0 overlapping pairs).
    """

    REPLICAS = 3
    NOTEBOOKS = 12
    ROUNDS = int(os.environ.get("SHARD_SOAK_ROUNDS", "8"))

    def _expire_dead(self, fleet, clock, steps=3, step=8):
        # sub-lease steps: survivors renew every settle pass, so only
        # the dead member's lease ages past the 15s duration
        for _ in range(steps):
            clock.advance(step)
            fleet.settle()

    def test_kill_rejoin_soak(self):
        from kubeflow_tpu.kube.leader import StaleEpochError
        from kubeflow_tpu.main import build_sharded_fleet
        from kubeflow_tpu.ops.diagnose import (collect_local,
                                               merge_overlaps,
                                               merge_records)

        clock = FakeClock()
        fleet, api, cluster, metrics = build_sharded_fleet(
            core_cfg=CoreConfig(), count=self.REPLICAS, clock=clock)
        cluster.add_node("cpu-node",
                         allocatable={"cpu": "64", "memory": "256Gi"})
        keys = [(f"user{i % 4}", f"soak-{i}")
                for i in range(self.NOTEBOOKS)]
        for ns, name in keys:
            api.create(Notebook.new(name, ns).obj)
        fleet.settle()

        def assert_converged(round_i):
            for ns, name in keys:
                assert api.try_get("StatefulSet", ns, name) is not None, \
                    (round_i, ns, name, "statefulset missing")
                nb = api.get("Notebook", ns, name)
                assert nb.body.get("status", {}).get("conditions"), \
                    (round_i, ns, name, "status never stamped")

        assert_converged(-1)
        print(f"\nshard soak: seed={SOAK_SEED} rounds={self.ROUNDS} "
              "(reproduce with CHAOS_SOAK_SEED/SHARD_SOAK_ROUNDS)")
        rng = random.Random(SOAK_SEED ^ 0x5AAD)
        epochs = [fleet.shard_snapshot()["epoch"]]
        zombie_attempts = 0
        for round_i in range(self.ROUNDS):
            alive = sorted(r.shard_id for r in fleet.alive_replicas())
            dead = sorted(set(fleet.replicas) - set(alive))
            # choose: kill a replica (keep >= 1 alive), or rejoin one
            if dead and (len(alive) <= 1 or rng.random() < 0.5):
                fleet.rejoin(rng.choice(dead))
                fleet.settle()
            else:
                victim_id = rng.choice(alive)
                victim = fleet.replicas[victim_id]
                fleet.kill(victim_id)
                self._expire_dead(fleet, clock)
                # the zombie still holds its (stale) token: every write
                # it attempts must fence, not land
                ns, name = rng.choice(keys)
                with api.fault_exempt():
                    nb = api.get("Notebook", ns, name)
                nb.metadata.annotations["chaos/zombie"] = str(round_i)
                try:
                    victim.fenced.update(nb)
                    raise AssertionError(
                        f"round {round_i}: zombie {victim_id} write "
                        "landed after eviction")
                except StaleEpochError:
                    zombie_attempts += 1
            # churn: touch a few notebooks, let the survivors reconcile
            for ns, name in rng.sample(keys, 3):
                with api.fault_exempt():
                    nb = api.get("Notebook", ns, name)
                    nb.metadata.annotations["chaos/touch"] = \
                        f"{round_i}.{rng.random()}"
                    api.update(nb)
            for r in fleet.alive_replicas():
                r.manager.enqueue_all()
            fleet.settle()

            snap = fleet.shard_snapshot()
            assert snap["members"] == sorted(
                r.shard_id for r in fleet.alive_replicas()), round_i
            assert snap["handoff"] is None, round_i
            assert snap["epoch"] > epochs[-1], (
                f"round {round_i}: epoch must move on every membership "
                f"change ({epochs[-1]} -> {snap['epoch']})")
            epochs.append(snap["epoch"])
            assert_converged(round_i)

        # (2) the single-owner proof: merged histories, zero overlaps
        assert fleet.merged_records(), "soak recorded no attempts"
        overlaps = fleet.cross_process_overlaps()
        assert not overlaps, (
            f"{len(overlaps)} cross-process double-reconciles; first: "
            f"{overlaps[0][0].controller} {overlaps[0][0].object_key}")
        # (3) every zombie write was rejected AND counted
        assert zombie_attempts > 0, "soak never exercised a zombie"
        rejected = sum(s["fenced_rejections"] for s in
                       fleet.shard_snapshot()["replicas"].values())
        assert rejected >= zombie_attempts, (rejected, zombie_attempts)
        # (5) offline agreement: one bundle per replica, merged
        bundles = [collect_local(r.manager, env={})
                   for r in fleet.replicas.values()]
        merged = merge_records(bundles)
        assert merged, "bundles carried no attempts"
        assert merge_overlaps(bundles) == []


class TestNoisyNeighborSoak:
    """ISSUE-17 acceptance: a multi-tenant soak where one tenant floods
    the control plane WHILE bounded API faults fire.  The metering
    ledger must attribute the flood to exactly that tenant (exactly one
    deduped Warning event naming it), keep the victims' event->reconcile
    p99 measurement honest (it shows the degradation, bounded by the CI
    budget ceiling), conserve chip-seconds through the chaos, clear the
    flag once traffic rebalances — and the whole verdict must
    reconstruct offline from an ops.diagnose bundle."""

    TENANTS = 4
    PER_TENANT = 2
    NOISY = 1  # tenant index that floods

    def test_noisy_neighbor_soak_attribution_and_clear(self):
        import json as _json

        from kubeflow_tpu.core import constants as CC
        from kubeflow_tpu.core.metrics import NotebookMetrics
        from kubeflow_tpu.kube import EventRecorder
        from kubeflow_tpu.kube import retry_on_conflict
        from kubeflow_tpu.ops.diagnose import collect_local
        from kubeflow_tpu.utils import tracing
        from kubeflow_tpu.utils.metering import (REASON_NOISY,
                                                 TenantMeteringLedger)

        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("cpu-node",
                         allocatable={"cpu": "64", "memory": "256Gi"})
        clock = FakeClock()
        mgr = Manager(api, clock=clock)
        metrics = NotebookMetrics(api, manager=mgr)
        setup_core_controllers(mgr, CoreConfig(), metrics)
        tracing.set_clock(clock)
        try:
            namespaces = [f"tenant-{i}" for i in range(self.TENANTS)]
            noisy_ns = namespaces[self.NOISY]
            for ns in namespaces:
                for i in range(self.PER_TENANT):
                    # placement-annotated from birth: the census meters
                    # every tenant's wall time for the whole soak
                    api.create(Notebook.new(
                        f"nb-{i}", ns,
                        annotations={CC.ANNOTATION_PLACEMENT:
                                     _json.dumps({"pool": "p0"})}).obj)
            mgr.run_until_idle()

            # attach metering only after convergence so the fairness
            # baselines latch from benign traffic (production managers
            # boot into an already-converged fleet all the time)
            metering = TenantMeteringLedger(
                clock, registry=metrics.registry,
                recorder=EventRecorder(api, "tenant-metering"))
            mgr.metering = metering
            metrics.attach_metering(metering)

            touch_seq = [0]

            def touch(ns):
                for i in range(self.PER_TENANT):
                    # strictly increasing stamp: an unchanged annotation
                    # would be a no-op update and produce no watch event
                    touch_seq[0] += 1

                    def _bump(i=i, stamp=touch_seq[0]):
                        nb = api.get("Notebook", ns, f"nb-{i}")
                        nb.metadata.annotations["chaos/touch"] = str(stamp)
                        api.update(nb)

                    retry_on_conflict(_bump)

            # benign phase: balanced traffic latches every tenant's
            # baseline p99 (FakeClock + immediate settles => ~0s e2r)
            for _ in range(20):
                for ns in namespaces:
                    touch(ns)
                mgr.settle(max_seconds=60.0)
                clock.advance(10.0)
                metrics.scrape()
            assert metering.flagged() == [], metering.tenant_table()

            # flood phase UNDER FAULTS: the noisy tenant hammers the
            # control plane while every round's bounded fault plan
            # injects API errors/latency — attribution must stay exact
            rng = random.Random(SOAK_SEED + 17)
            for _ in range(6):
                plan = random_fault_plan(rng.randrange(2**31),
                                         kinds=FAULT_KINDS, clock=clock)
                api.install_fault_plan(plan)
                with api.fault_exempt():
                    for ns in namespaces:
                        if ns != noisy_ns:
                            touch(ns)
                clock.advance(2.5)   # victims wait behind the flood
                mgr.settle(max_seconds=600.0)
                with api.fault_exempt():
                    for _ in range(80):
                        touch(noisy_ns)
                        mgr.settle(max_seconds=600.0)
                api.clear_fault_plan()
                mgr.settle(max_seconds=600.0)
                metrics.scrape()
            assert metering.flagged() == [noisy_ns], \
                metering.tenant_table()

            # exactly one deduped Warning names exactly the noisy tenant
            warnings = [e for e in api.list("Event")
                        if e.body.get("reason") == REASON_NOISY]
            assert len(warnings) == 1, [e.body for e in warnings]
            assert warnings[0].body["involvedObject"]["name"] == noisy_ns
            assert metering.tenant_table()[noisy_ns]["fired_total"] == 1

            # the victims' measured degradation stays under the same
            # ceiling ci/fleet_budget.json gates the loadtest with
            for ns in namespaces:
                if ns == noisy_ns:
                    continue
                row = metering.tenant_table()[ns]
                assert 0.0 < row["e2r_p99_recent_s"] <= 4.0, (ns, row)

            # recovery: balanced traffic rolls the flood out of the
            # window and the flag clears without operator action
            for _ in range(metering.window_evals + 4):
                for ns in namespaces:
                    touch(ns)
                mgr.settle(max_seconds=60.0)
                clock.advance(10.0)
                metrics.scrape()
            assert metering.flagged() == [], metering.tenant_table()

            # chip-second conservation held through faults + flood for
            # every metered notebook, and the verdict reconstructs
            # offline from a diagnose bundle
            cons = metering.conservation()
            assert cons["checked"] >= self.TENANTS * self.PER_TENANT
            assert cons["violations"] == 0, metering.violations()[:3]
            bundle = collect_local(mgr, metrics, env={})
            tn = bundle["tenants"]
            assert tn["tenants"][noisy_ns]["fired_total"] == 1, tn
            assert tn["fairness"]["flagged"] == [], tn["fairness"]
            assert tn["conservation"]["violations"] == 0
            assert _json.dumps(tn)  # the bundle section serializes
        finally:
            api.clear_fault_plan()
            tracing.set_clock(None)


class TestDiagnosisSoak:
    """ISSUE-18 acceptance: a seeded soak with THREE disjoint injected
    degradation windows of different kinds — an API fault plan, a slow
    data-plane worker, and killed replication primaries.  The causal
    diagnosis engine must (1) name the true injected cause as the
    top-ranked explanation for EVERY affected notebook, (2) fire the
    change-point detector inside each window and NEVER on the quiet
    baseline segments between them (zero false positives), (3) attach a
    non-empty one-line diagnosis to the firing burn alert, and (4) have
    both verdicts reconstruct offline from an ops.diagnose bundle."""

    FAULT_A = 3   # API-fault batch
    SLOW_B = 2    # telemetry batch (index 0 gets the slow worker)
    REPL_C = 3    # replicated batch (all primaries killed at once)
    SCRAPE_S = 60.0

    CFG = dict(
        checkpoint_store_uri="mem://session-state",
        recovery_backoff_base_s=0.25,
        recovery_backoff_max_s=30.0,
    )

    def _env(self):
        from kubeflow_tpu.core.metrics import NotebookMetrics
        from kubeflow_tpu.core.sessionstate import InMemorySessionStore
        from kubeflow_tpu.core.telemetry import WorkerTelemetryAggregator
        from kubeflow_tpu.kube import EventRecorder
        from kubeflow_tpu.utils.diagnosis import DiagnosisEngine
        from kubeflow_tpu.utils.flightrecorder import FlightRecorder
        from kubeflow_tpu.utils.lifecycle import LifecycleLedger
        from kubeflow_tpu.utils.slo import SLOEngine, default_objectives
        from kubeflow_tpu.utils.tsdb import TimeSeriesStore

        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("cpu-node",
                         allocatable={"cpu": "64", "memory": "256Gi"})
        # fault batch + telemetry batch + two gangs per replicated nb,
        # 4 hosts per gang
        gangs = self.FAULT_A + self.SLOW_B + 2 * self.REPL_C + 1
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4",
                                    4 * gangs, 4)
        clock = FakeClock()
        mgr = Manager(api, clock=clock,
                      flight_recorder=FlightRecorder(capacity=16384,
                                                     per_object=4096))
        store = InMemorySessionStore(clock=clock)
        cluster.attach_session_store(store)
        cfg = CoreConfig(**self.CFG)
        metrics = NotebookMetrics(api, manager=mgr)
        setup_core_controllers(mgr, cfg, metrics, session=store)
        ledger = LifecycleLedger(metrics.registry)
        mgr.lifecycle = ledger
        metrics.attach_lifecycle(ledger)
        tsdb = TimeSeriesStore()
        mgr.tsdb = tsdb
        metrics.attach_tsdb(tsdb, clock=clock)
        slo = SLOEngine(
            default_objectives(cfg),
            registries=[metrics.registry, mgr.metrics_registry],
            clock=clock, recorder=mgr.flight_recorder,
            burn_threshold=2.0)
        mgr.slo_engine = slo
        metrics.attach_slo(slo)
        aggregator = WorkerTelemetryAggregator(
            api, metrics.registry, clock, cache=mgr.cache,
            recorder=EventRecorder(api, "dataplane-telemetry"),
            straggler_ratio=cfg.dataplane_straggler_ratio,
            min_workers=cfg.dataplane_straggler_min_workers)
        metrics.attach_dataplane(aggregator)
        mgr.telemetry_aggregator = aggregator
        diag = DiagnosisEngine(
            clock, registry=metrics.registry,
            recorder=mgr.flight_recorder, lifecycle=ledger,
            slo_engine=slo, tsdb=tsdb, dataplane=aggregator, api=api)
        mgr.diagnosis = diag
        metrics.attach_diagnosis(diag)
        return api, cluster, mgr, clock, metrics, diag, slo

    def test_three_window_soak_names_every_injected_cause(self):
        import json as _json

        from kubeflow_tpu.api.types import ReplicationSpec
        from kubeflow_tpu.kube.faults import FaultPlan, FaultRule
        from kubeflow_tpu.models.configs import LLAMA2_350M
        from kubeflow_tpu.ops.diagnose import collect_local
        from kubeflow_tpu.utils import tracing
        from kubeflow_tpu.utils.diagnosis import (
            CAUSE_FAULT_INJECTION,
            CAUSE_PRIMARY_FAILOVER,
            CAUSE_SLOW_WORKER,
            changepoints_from_bundle,
        )

        api, cluster, mgr, clock, metrics, diag, slo = self._env()
        tracing.set_clock(clock)

        def stamp(slow=None):
            """Fresh telemetry for the SLOW batch every scrape beat so
            the straggler gauge is level, not flapping on staleness."""
            for i in range(self.SLOW_B):
                cluster.stamp_worker_telemetry(
                    "user1", f"slow-b-{i}", step_time_s=0.5,
                    config=LLAMA2_350M, batch=8, seq_len=2048,
                    num_chips=4,
                    slow_worker=(slow if i == 0 else None),
                    slow_factor=4.0, now=clock.now())

        def beat(slow=None, n=1):
            for _ in range(n):
                clock.advance(self.SCRAPE_S)
                stamp(slow=slow)
                metrics.scrape()

        try:
            for i in range(self.FAULT_A):
                api.create(Notebook.new(f"fault-a-{i}", "user1",
                                        tpu=TPUSpec("v5e", "4x4")).obj)
            for i in range(self.SLOW_B):
                api.create(Notebook.new(f"slow-b-{i}", "user1",
                                        tpu=TPUSpec("v5e", "4x4")).obj)
            for i in range(self.REPL_C):
                api.create(Notebook.new(
                    f"repl-c-{i}", "user1", tpu=TPUSpec("v5e", "4x4"),
                    replication=ReplicationSpec(replicas=2)).obj)
            mgr.run_until_idle()

            # quiet baseline: latch every series level; nothing may fire
            beat(n=8)
            assert diag.findings() == [], diag.findings()

            # -- window A: API fault plan ------------------------------
            wa0 = clock.now()
            for r in range(6):
                plan = FaultPlan([FaultRule(
                    verbs=("create",), kinds=("Service",),
                    error="unavailable", max_matches=3,
                    name=f"diag-api-{r}")], clock=clock)
                with api.fault_exempt():
                    api.delete("Service", "user1",
                               f"fault-a-{r % self.FAULT_A}")
                api.install_fault_plan(plan)
                with api.fault_exempt():
                    mgr.enqueue_all()
                mgr.settle(max_seconds=7200.0)
                api.clear_fault_plan()
                assert len(plan.log) == 3, (r, plan.log)
                beat()
            # mid-window: the burn alert fires AND carries a one-line
            # diagnosis naming the fault plan (the /debug/alerts field)
            firing = [a.objective for a in slo.firing()]
            assert "reconcile_errors" in firing, firing
            ann = diag.annotate_alerts(slo.snapshot())
            lines = [a["diagnosis"] for a in ann["firing"]
                     if a["objective"] == "reconcile_errors"]
            assert lines and all(line for line in lines), ann["firing"]
            assert any("fault plan" in line for line in lines), lines
            # settle-back margin: the recovery edge of the same injected
            # window (the errors-rate step back to zero) detects here
            beat(n=4)
            wa1 = clock.now()

            # quiet segment 1: drain the alert, freeze every series
            n_quiet1 = len(diag.findings())
            beat(n=2)
            for _ in range(8):
                clock.advance(150.0)
                stamp()
                metrics.scrape()
            assert not slo.firing()
            quiet1_end = clock.now()
            assert len(diag.findings()) == n_quiet1, diag.findings()

            # -- window B: slow data-plane worker ----------------------
            wb0 = clock.now()
            beat(slow=1, n=8)
            # the straggler verdict is live: the explainer must name the
            # slow worker for the afflicted notebook, and ONLY for it
            assert diag.explain("user1", "slow-b-0")["cause"] == \
                CAUSE_SLOW_WORKER
            assert diag.explain("user1", "slow-b-1")["cause"] != \
                CAUSE_SLOW_WORKER
            wb1 = clock.now()

            # quiet segment 2: the worker stays slow (constant level —
            # a held degradation is not a new change point)
            n_quiet2 = len(diag.findings())
            beat(slow=1, n=10)
            quiet2_end = clock.now()
            assert len(diag.findings()) == n_quiet2, diag.findings()

            # -- window C: kill every replication primary --------------
            wc0 = clock.now()
            for i in range(self.REPL_C):
                cluster.set_session_payload("user1", f"repl-c-{i}",
                                            b"kernel-%d" % i)
                cluster.snapshot_sessions("user1", f"repl-c-{i}")
                cluster.sync_followers("user1", f"repl-c-{i}")
            mgr.enqueue_all()
            mgr.settle(max_seconds=7200.0)
            for i in range(self.REPL_C):
                cluster.fail_pod("user1", f"repl-c-{i}-0")
            mgr.enqueue_all()

            def promoted(i):
                st = api.get("Notebook", "user1",
                             f"repl-c-{i}").body.get("status") or {}
                rep = st.get("replication") or {}
                return rep.get("promotion", {}).get("phase") == "promoted"

            for _ in range(12):
                if all(promoted(i) for i in range(self.REPL_C)):
                    break
                mgr.enqueue_all()
                mgr.advance(1.0)
            assert all(promoted(i) for i in range(self.REPL_C))
            mgr.settle(max_seconds=7200.0)
            # window C runs to soak end: the promotion-rate pulse and its
            # settle-back edge both belong to this injected degradation
            beat(slow=1, n=9)

            # -- verdicts ---------------------------------------------
            # (1) the explainer names the true injected cause for every
            # affected notebook, per batch
            for i in range(self.FAULT_A):
                out = diag.explain("user1", f"fault-a-{i}")
                assert out["cause"] == CAUSE_FAULT_INJECTION, (i, out)
                assert out["verdict"], out
            assert diag.explain("user1", "slow-b-0")["cause"] == \
                CAUSE_SLOW_WORKER
            for i in range(self.REPL_C):
                out = diag.explain("user1", f"repl-c-{i}")
                assert out["cause"] == CAUSE_PRIMARY_FAILOVER, (i, out)

            # (2) the detector fired inside each window, with the right
            # correlated event kind...
            findings = diag.findings()
            windows = [(wa0, wa1), (wb0, wb1), (wc0, clock.now())]

            def in_window(f, w):
                return w[0] <= f["t_end"] <= w[1]

            assert any(f["series"] == "reconcile_errors_delta"
                       and f["matched"] == "fault"
                       and in_window(f, windows[0]) for f in findings), \
                findings
            assert any(f["series"] == "dataplane_stragglers"
                       and f["matched"] == "slow_worker"
                       and in_window(f, windows[1]) for f in findings), \
                findings
            assert any(f["series"] == "promotions_delta"
                       and f["matched"] == "promotion"
                       and in_window(f, windows[2]) for f in findings), \
                findings
            # ... and NEVER on the quiet baseline segments: every finding
            # triggered inside one of the three injected windows
            for f in findings:
                assert any(in_window(f, w) for w in windows), f
            assert quiet1_end <= wb0 and quiet2_end <= wc0

            # the bounded counter carries the same verdicts
            counts = metrics.registry.get(
                "notebook_changepoints_total").collect()
            assert counts.get(("reconcile_errors_delta", "fault"))
            assert counts.get(("dataplane_stragglers", "slow_worker"))
            assert counts.get(("promotions_delta", "promotion"))

            # (4) both verdicts reconstruct OFFLINE from the bundle
            clock.advance(self.SCRAPE_S)
            stamp(slow=1)
            bundle = _json.loads(_json.dumps(
                collect_local(mgr, metrics), default=str))
            ex = bundle["diagnosis"]["explanations"]
            for i in range(self.FAULT_A):
                assert ex[f"user1/fault-a-{i}"]["cause"] == \
                    CAUSE_FAULT_INJECTION
            assert ex["user1/slow-b-0"]["cause"] == CAUSE_SLOW_WORKER
            for i in range(self.REPL_C):
                assert ex[f"user1/repl-c-{i}"]["cause"] == \
                    CAUSE_PRIMARY_FAILOVER
            offline = changepoints_from_bundle(bundle)
            live = {(f["series"], f["t_start"], f["direction"])
                    for f in bundle["diagnosis"]["changepoints"]}
            recon = {(f["series"], f["t_start"], f["direction"])
                     for f in offline}
            assert live == recon, (live ^ recon)
            kinds = {e["kind"] for e in bundle["diagnosis"]["timeline"]}
            assert {"fault", "slow_worker", "promotion"} <= kinds, kinds

            assert not mgr.dropped_errors
            assert_no_concurrent_per_key_reconciles(mgr)
        finally:
            api.clear_fault_plan()
            tracing.set_clock(None)
            mgr.stop()
