"""MNIST MLP — the BASELINE "v5e-1 single chip" smoke workload.

Small on purpose: it validates the `google.com/tpu` request path end-to-end
(`jax.devices()` sees the chip, a jitted step runs) rather than performance.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import optax


class MLP(nn.Module):
    features: Sequence[int] = (512, 256, 10)

    @nn.compact
    def __call__(self, x):
        x = x.reshape(x.shape[0], -1)
        for i, feat in enumerate(self.features):
            x = nn.Dense(feat, name=f"dense_{i}")(x)
            if i < len(self.features) - 1:
                x = nn.relu(x)
        return x


def train_mnist_steps(
    num_steps: int = 20, batch: int = 128, rng: int = 0
) -> dict:
    """Self-contained training sanity loop on synthetic MNIST-shaped data;
    returns first/last loss so callers can assert learning happened."""
    key = jax.random.PRNGKey(rng)
    model = MLP()
    kx, kp = jax.random.split(key)
    x = jax.random.normal(kx, (batch, 28, 28, 1))
    y = jax.random.randint(kp, (batch,), 0, 10)
    params = model.init(kp, x)
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            logits = model.apply(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    first = None
    for _ in range(num_steps):
        params, opt_state, loss = step(params, opt_state)
        if first is None:
            first = float(loss)
    return {"first_loss": first, "last_loss": float(loss)}
