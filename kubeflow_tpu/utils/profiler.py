"""Continuous sampling profiler: always-on CPU attribution per
(controller, phase).

PR 8's only CPU-attribution tool is the post-mortem cProfile artifact the
fleet loadtest writes on a budget failure — useless for "what is the
manager burning CPU on RIGHT NOW", and cProfile's tracing overhead is far
too high to leave on in production.  Podracer (arXiv:2104.06272) makes
the case that sharded-worker throughput claims are only trustworthy when
per-worker utilization is measured continuously, not sampled after the
fact.  This module is the standing equivalent:

  - a sampling thread wakes every `interval_s` of real time, grabs every
    thread's current Python frame (`sys._current_frames()`), and
    collapses it into a flamegraph-style stack string;
  - each sample is attributed to the `(controller, phase)` the sampled
    thread was inside, read from the live span-stack mirror
    (`tracing.live_span_stacks()`) — the same contextvar spine the
    flight recorder rides, so profile buckets line up with trace phases;
  - aggregation is a bounded collapsed-stack store (overflow counts are
    kept, never silently dropped), served at loopback `/debug/profile`
    as JSON or flamegraph-ready collapsed text (`?format=collapsed`);
  - the profiler measures ITSELF: time spent inside sampling passes over
    elapsed wall time is exported as
    `notebook_profiler_overhead_ratio`, so "can this stay always-on" is
    a gauge, not a guess (the fleet soak gates it under 5%).

Wall-clock sampling is deliberately REAL time (allowlisted in
ci/analyzers): a FakeClock stands still while reconciles execute, so
logical-time sampling would never fire; tier-1 tests keep the sampler
off (ENABLE_CONTINUOUS_PROFILER defaults false) and drive `sample_once`
/ `_record` directly for determinism.
"""

from __future__ import annotations

import os.path
import sys
import threading
import time
from typing import Optional

from . import tracing
from .metrics import Registry

# attribution labels for samples taken outside any live span (the HTTP
# serving thread, the watch fan-out, the sampler's idle peers)
UNATTRIBUTED = "-"


def register_profiler_metrics(registry: Registry) -> tuple:
    """The profiler metric families (registered by NotebookMetrics so
    the inventory is stable even with the sampler off; a started
    profiler re-registers identically and feeds the same objects)."""
    overhead = registry.gauge(
        "notebook_profiler_overhead_ratio",
        "Fraction of wall time the continuous profiler spent sampling "
        "(0 while disabled)")
    if registry.get("notebook_profiler_samples_total") is None:
        # first registration: pin the disabled-state samples so the
        # series exists in every scrape (0 until a profiler starts)
        overhead.set(0.0)
    samples = registry.counter(
        "notebook_profiler_samples_total",
        "Thread stack samples taken by the continuous profiler")
    return overhead, samples


def collapse_frame(frame, max_depth: int = 64) -> str:
    """Flamegraph collapsed-stack rendering of one thread's live frame:
    root-first `file:func` segments joined by `;`."""
    parts: list[str] = []
    f = frame
    while f is not None and len(parts) < max_depth:
        code = f.f_code
        parts.append(f"{os.path.basename(code.co_filename)}:{code.co_name}")
        f = f.f_back
    return ";".join(reversed(parts))


def attribute(spans) -> tuple[str, str]:
    """(controller, phase) attribution from a live span stack: the
    innermost span carrying each attribute wins (a `render` phase span
    inside a `reconcile` root yields ("notebook", "render"); a root with
    no phase child open yet attributes to the controller's own time)."""
    controller = phase = ""
    for span in reversed(spans):
        if not phase and "phase" in span.attributes:
            phase = str(span.attributes["phase"])
        if not controller and "controller" in span.attributes:
            controller = str(span.attributes["controller"])
        if controller and phase:
            break
    if controller and not phase:
        phase = "reconcile"
    return controller or UNATTRIBUTED, phase or UNATTRIBUTED


class ContinuousProfiler:
    """Sampling wall-clock profiler thread; see module docstring.

    Bounds: at most `max_stacks` distinct (controller, phase, stack)
    keys; samples past the bound are counted in `overflow_samples` (and
    reported by /debug/profile) instead of growing memory."""

    def __init__(self, registry: Optional[Registry] = None,
                 interval_s: float = 0.01, max_stacks: int = 2048,
                 max_depth: int = 64) -> None:
        self.interval_s = max(interval_s, 0.001)
        self.max_stacks = max_stacks
        self.max_depth = max_depth
        self._lock = threading.Lock()
        # (controller, phase, collapsed stack) -> sample count
        self._stacks: dict[tuple[str, str, str], int] = {}
        self.samples_total = 0
        self.overflow_samples = 0
        self.passes = 0
        self._busy_s = 0.0
        self._started_mono = 0.0
        self._stopped_mono = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.overhead_gauge = None
        self.samples_counter = None
        if registry is not None:
            self.overhead_gauge, self.samples_counter = \
                register_profiler_metrics(registry)
            self.overhead_gauge.set_function(self.overhead_ratio)

    # -- lifecycle ------------------------------------------------------------
    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        if self.running:
            return
        self._stop.clear()
        self._started_mono = time.monotonic()
        self._stopped_mono = 0.0
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="continuous-profiler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        self._thread = None
        if self._started_mono > 0.0 and self._stopped_mono == 0.0:
            # freeze the overhead denominator: a stopped profiler's ratio
            # must read stable, not decay toward zero as wall time passes
            self._stopped_mono = time.monotonic()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — the profiler must never
                pass           # take down the process it observes

    # -- sampling -------------------------------------------------------------
    def sample_once(self) -> int:
        """One sampling pass over every thread but the sampler itself;
        returns the number of stacks recorded.  Public so tests can
        drive sampling deterministically with the thread off."""
        t0 = time.monotonic()
        me = threading.get_ident()
        frames = sys._current_frames()
        stacks = tracing.live_span_stacks()
        n = 0
        for tid, frame in frames.items():
            if tid == me:
                continue
            controller, phase = attribute(stacks.get(tid, ()))
            self._record(controller, phase,
                         collapse_frame(frame, self.max_depth))
            n += 1
        if self.samples_counter is not None and n:
            self.samples_counter.inc(n)
        self._busy_s += time.monotonic() - t0
        self.passes += 1
        return n

    def _record(self, controller: str, phase: str, stack: str) -> None:
        key = (controller, phase, stack)
        with self._lock:
            self.samples_total += 1
            if key in self._stacks:
                self._stacks[key] += 1
            elif len(self._stacks) < self.max_stacks:
                self._stacks[key] = 1
            else:
                self.overflow_samples += 1

    # -- self-measurement -----------------------------------------------------
    def overhead_ratio(self) -> float:
        """Sampling time over elapsed wall time since start() (0 before
        the first start) — the always-on budget gauge."""
        if self._started_mono <= 0.0:
            return 0.0
        end = self._stopped_mono or time.monotonic()
        elapsed = end - self._started_mono
        if elapsed <= 0.0:
            return 0.0
        return min(self._busy_s / elapsed, 1.0)

    # -- read side (/debug/profile) -------------------------------------------
    def snapshot(self, top: int = 0) -> dict:
        """JSON body for /debug/profile: aggregated stacks (count-desc),
        per-(controller, phase) rollups, bounds, and self-overhead."""
        with self._lock:
            items = sorted(self._stacks.items(), key=lambda kv: -kv[1])
            overflow = self.overflow_samples
            total = self.samples_total
        if top:
            items = items[:top]
        by_phase: dict[str, int] = {}
        for (controller, phase, _stack), count in items:
            k = f"{controller}/{phase}"
            by_phase[k] = by_phase.get(k, 0) + count
        return {
            "enabled": self.running,
            "interval_s": self.interval_s,
            "samples_total": total,
            "passes": self.passes,
            "distinct_stacks": len(items),
            "overflow_samples": overflow,
            "overhead_ratio": round(self.overhead_ratio(), 6),
            "by_controller_phase": dict(
                sorted(by_phase.items(), key=lambda kv: -kv[1])),
            "stacks": [
                {"controller": c, "phase": p, "stack": s, "count": n}
                for (c, p, s), n in items
            ],
        }

    def collapsed(self) -> str:
        """Flamegraph collapsed-stack text: `controller;phase;frames N`
        per line — feed straight to flamegraph.pl / speedscope."""
        with self._lock:
            items = sorted(self._stacks.items())
        return "\n".join(
            f"{c};{p};{s} {n}" for (c, p, s), n in items) + ("\n" if items
                                                             else "")

    def clear(self) -> None:
        with self._lock:
            self._stacks.clear()
            self.samples_total = 0
            self.overflow_samples = 0


__all__ = ["ContinuousProfiler", "attribute", "collapse_frame",
           "register_profiler_metrics", "UNATTRIBUTED"]
