"""Benchmark: flagship decoder training MFU on the local TPU chip.

Prints ONE JSON line:
  {"metric": "train_mfu_v5e", "value": <mfu>, "unit": "fraction",
   "vs_baseline": <mfu / 0.35>}

`python bench.py --decode [steps]` instead measures KV-cache decode
throughput (models/generate.py): aggregate sampled tokens/s at batch 16,
reported against the HBM roofline.  The roofline counts the traffic a
decode step actually incurs: every bf16 weight streamed once PLUS the full
static-shape KV cache read once (2 * B * max_seq * kv_heads * head_dim *
2B * layers — the cache is read to max_seq_len regardless of fill), so the
ceiling is hbm_gbps / (param_bytes + kv_bytes) steps/s and `vs_baseline`
is the fraction of that roofline achieved.  Round 4 unrolled the decode
layer stack (see models/generate.py:decode_config) — 6.5k tok/s, 0.66 of
roofline, vs round 3's 3.6k/0.26-of-weights-only.

The reference publishes no perf numbers (BASELINE.md); the baseline is this
framework's own headline target — >=35% MFU on the MaxText-style Llama
workload (BASELINE.json), so vs_baseline = mfu / 0.35.  Single-chip proxy:
BENCH_CHIP (models/configs.py), the same decoder family at ~0.47B params,
bf16 compute + fp32 master weights, remat + scanned layers, Pallas flash
attention with 1024x512 tiles, chunked cross-entropy (loss_chunks=32) and
bf16 Adam first-moment — the round-5 sweep winner (ci/mfu_sweep_r5.py):
batch 40 x 2048 in 16 GiB HBM, 0.475 MFU sustained-median (34k tok/s,
5 agreeing windows) vs 0.39 round-3 / 0.236 round-2 — 1.36x the 0.35
headline target under the CONSERVATIVE estimator (now the default;
--best-of keeps the old best-window mode).
"""

from __future__ import annotations

import json
import sys

import jax
import jax.numpy as jnp

from kubeflow_tpu.models.configs import BENCH_CHIP
from kubeflow_tpu.models.train import (
    default_optimizer,
    mfu,
    setup_training,
    timed_steps,
)
from kubeflow_tpu.parallel.mesh import MeshConfig, make_mesh

MFU_TARGET = 0.35  # BASELINE.md headline: MaxText Llama-2-7B on v5e-16


def main_decode(num_steps: int) -> None:
    import time

    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.configs import BENCH_CHIP, TINY
    from kubeflow_tpu.models.generate import decode_config, generate
    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.tpu.topology import (
        ACCELERATORS,
        accelerator_from_device_kind,
    )

    backend = jax.default_backend()
    devices = jax.devices()
    accel = (accelerator_from_device_kind(devices[0].device_kind)
             if backend == "tpu" else "v5e")
    int8 = "--int8" in sys.argv
    int4 = "--int4" in sys.argv
    config, batch, prompt_len, new_tokens = BENCH_CHIP, 16, 128, 256
    if backend == "cpu":  # CI smoke
        config, batch, prompt_len, new_tokens = TINY, 2, 8, 16
        int4 = False  # TINY's contract dims (64) are below the int4
        # kernel's 2*INT4_GROUP granularity; keep the smoke line honest
    config = decode_config(config).with_(max_seq_len=prompt_len + new_tokens)

    model = Transformer(config)
    rng = jax.random.PRNGKey(0)
    prompt = jax.random.randint(rng, (batch, prompt_len), 0,
                                config.vocab_size)
    params = jax.jit(model.init)(rng, prompt)["params"]
    # decode is weight-bandwidth bound: stream bf16 weights, not fp32
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
    if int8:
        # opt-in int8 weight streaming (models.quant): halves the weight
        # bytes each step streams; the roofline recomputes accordingly
        from kubeflow_tpu.models.quant import quantize_params

        params = quantize_params(params)
        config = config.with_(weight_dtype="int8")
    elif int4:
        # int4: nibble-packed, group scales — quarter the weight bytes
        from kubeflow_tpu.models.quant import quantize_params_int4

        params = quantize_params_int4(params)
        config = config.with_(weight_dtype="int4")

    import numpy as np

    run = jax.jit(lambda p, t: generate(config, p, t, new_tokens))
    np.asarray(run(params, prompt))  # compile + warmup; a VALUE transfer —
    # block_until_ready alone does not block through the remote relay, and
    # identical inputs can be served from its result cache, so each timed
    # iteration also uses a fresh prompt
    best = 0.0
    for i in range(max(1, num_steps // 4) if backend != "cpu" else 1):
        p = jax.random.randint(jax.random.PRNGKey(1000 + i),
                               (batch, prompt_len), 0, config.vocab_size)
        np.asarray(p)
        t0 = time.perf_counter()
        np.asarray(run(params, p))
        dt = time.perf_counter() - t0
        best = max(best, batch * new_tokens / dt)
    from kubeflow_tpu.models.quant import quantized_bytes
    from kubeflow_tpu.runtime.roofline import decode_estimate

    # Streamed bytes per step: every matmul weight once.  The embedding
    # table (vocab*d) is a per-token row lookup and does NOT stream —
    # counting it understated the roofline ~10% at this scale (round-4
    # advisor finding) — EXCEPT for tied configs, where the table is the
    # LM-head matmul weight (transformer.py head()) and streams fully.
    # The floor itself is runtime.roofline's decode_estimate, fed the
    # measured byte count off the real (possibly quantized) tree.
    exclude = () if config.tie_embeddings else ("embed",)
    param_bytes = quantized_bytes(params, exclude=exclude)
    est = decode_estimate(config, batch, accelerator=accel,
                          param_bytes=param_bytes)
    kv_bytes = est.hbm_bytes - param_bytes
    roofline_tok_s = batch / est.memory_floor_s
    print(json.dumps({
        "metric": f"decode_tok_s_{accel}" + (
            "_int8" if int8 else "_int4" if int4 else ""),
        "value": round(best, 1),
        "unit": "tokens/s",
        "vs_baseline": round(best / roofline_tok_s, 4),
        "roofline_fraction": round(best / est.tokens_per_s_ceiling, 4),
        "bound": est.bound,
        "detail": {
            "model": "bench-chip-470m" if backend != "cpu" else "tiny-cpu",
            "batch": batch, "prompt_len": prompt_len,
            "new_tokens": new_tokens,
            "hbm_roofline_tok_s": round(roofline_tok_s, 1),
            "roofline_weight_mb": round(param_bytes / 1e6, 1),
            "roofline_kv_mb": round(kv_bytes / 1e6, 1),
            "backend": backend,
        },
    }))


def main_vit(num_steps: int) -> None:
    """ViT-B/16 fine-tune MFU — the BASELINE matrix's "v5e-8 single host"
    workload measured on one chip (encoder family grounding next to the
    decoder headline)."""
    import time

    import numpy as np
    import optax

    from kubeflow_tpu.models.vit import (
        VIT_B16,
        VIT_TINY,
        ViT,
        vit_flops_per_image,
    )
    from kubeflow_tpu.tpu.topology import (
        ACCELERATORS,
        accelerator_from_device_kind,
    )

    backend = jax.default_backend()
    accel = (accelerator_from_device_kind(jax.devices()[0].device_kind)
             if backend == "tpu" else "v5e")
    cfg, batch = (VIT_B16, 256) if backend != "cpu" else (VIT_TINY, 4)
    model = ViT(cfg)
    rng = jax.random.PRNGKey(0)
    images = jax.random.normal(
        rng, (batch, cfg.image_size, cfg.image_size, 3), jnp.bfloat16)
    labels = jax.random.randint(rng, (batch,), 0, cfg.num_classes)
    params = jax.jit(model.init)(rng, images)["params"]
    tx = optax.adamw(1e-4)
    opt_state = jax.jit(tx.init)(params)

    @jax.jit
    def step(params, opt_state, images, labels):
        def loss_fn(p):
            logits = model.apply({"params": p}, images)
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.mean(
                jnp.take_along_axis(logp, labels[:, None], axis=1))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # warmup + best-of-3 windows (relay interference rejection, as main())
    params, opt_state, _ = step(params, opt_state, images, labels)
    jax.block_until_ready(jax.tree_util.tree_leaves(params)[0])
    best = 0.0
    loss = 0.0
    for _ in range(3 if backend != "cpu" else 1):
        t0 = time.perf_counter()
        for _ in range(num_steps):
            params, opt_state, loss_t = step(params, opt_state, images,
                                             labels)
        loss = float(np.asarray(loss_t))  # value transfer closes the window
        dt = time.perf_counter() - t0
        best = max(best, batch * num_steps / dt)
    flops = vit_flops_per_image(cfg) * best
    peak = ACCELERATORS[accel].bf16_peak_tflops * 1e12
    achieved = flops / peak
    # no HBM traffic model for the encoder family yet: the compute
    # roofline IS the peak, so roofline_fraction == MFU and the workload
    # reads compute-bound by construction
    print(json.dumps({
        "metric": "train_mfu_v5e_vit_b16",
        "value": round(achieved, 4),
        "unit": "fraction",
        "vs_baseline": round(achieved / MFU_TARGET, 4),
        "roofline_fraction": round(achieved, 4),
        "bound": "compute",
        "detail": {
            "model": "vit-b16" if backend != "cpu" else "vit-tiny-cpu",
            "images_per_s": round(best, 1),
            "batch": batch,
            "final_loss": round(loss, 4),
            "backend": backend,
        },
    }))


def main(long_context: bool = False, moe: bool = False) -> None:
    numeric = [a for a in sys.argv[1:] if a.isdigit()]
    num_steps = int(numeric[0]) if numeric else 10
    backend = jax.default_backend()
    devices = jax.devices()
    from kubeflow_tpu.tpu.topology import accelerator_from_device_kind

    accel = accelerator_from_device_kind(devices[0].device_kind)

    config = BENCH_CHIP
    batch, seq = 40, 2048  # round-5 sweep (ci/sweep_r5_results.jsonl):
    # batch 48 OOMs at 256x512/512x512 tiles (512x256 fits but measures
    # ~0.34); batch 40 with the 1024x512 tiles sustains 34.0k tok/s =
    # 0.475 MFU across 5 agreeing windows
    if moe:
        # MoE config (configs.BENCH_MOE): 4 experts, top-2, ~0.76B total /
        # ~0.48B activated.  batch 16 is the largest 16-GiB fit (the
        # GShard dense-dispatch buffers [E, B, C, D] plus one-hot
        # dispatch/combine tensors take the headroom; 24 OOMs).  MFU uses
        # activated FLOPs, so the dispatch einsums are honest overhead.
        from kubeflow_tpu.models.configs import BENCH_MOE

        config, batch = BENCH_MOE, 16
    if long_context == 8192:
        # seq-8192: batch 8 is the largest fit (12 OOMs); block_k 1024
        # edges out 512 at this kv length (ci/longctx probes)
        batch, seq = 8, 8192
        config = config.with_(max_seq_len=8192,
                              flash_block_q=512, flash_block_k=1024)
    elif long_context:
        # seq-4096 config: the round-4 sweep winner (ci/longctx_sweep.py,
        # ci/longctx_results.jsonl) — the causal-attention FLOP share
        # doubles at 4k and the flash tile optimum moves from 256x256 to
        # 512x512; batch 20 is the largest that fits (24 OOMs 16 GiB)
        batch, seq = 20, 4096
        config = config.with_(flash_block_q=512, flash_block_k=512)
    optimizer = default_optimizer(mu_dtype="bfloat16")
    if backend == "cpu":  # CI smoke: tiny shapes, still one honest JSON line
        from kubeflow_tpu.models.configs import TINY

        config, batch, seq = TINY, 4, 128
        long_context = moe = False  # keep the metric name honest: this
        # measures the tiny smoke config, not the seq-4096/MoE workloads

    mesh = make_mesh(MeshConfig(data=len(devices)), devices=devices)
    setup = setup_training(config, mesh, optimizer=optimizer,
                           batch_shape=(batch, seq))
    key = jax.random.PRNGKey(0)
    data = {
        "inputs": jax.random.randint(key, (batch, seq), 0, config.vocab_size),
    }
    data["targets"] = jnp.roll(data["inputs"], -1, axis=1)

    # the chip is reached through a shared relay with intermittent
    # interference (whole measurement windows run at exactly half speed,
    # then recover).  DEFAULT estimator (round 5): sustained-median — the
    # MEDIAN of 5 post-warmup windows on the SAME compiled step (first
    # window discarded as dispatch-pipeline warmup), the conservative
    # choice where interference windows count AGAINST the number.
    # --best-of reports the best window instead (the round-3/4 estimator,
    # kept for continuity); per-window rates stay in detail either way.
    best_of = "--best-of" in sys.argv
    sustained = not best_of
    n_windows = 1 if backend == "cpu" else (3 if best_of else 6)
    windows = []
    for w in range(n_windows):
        windows.append(
            timed_steps(setup, data, num_steps=num_steps,
                        warmup=2 if w == 0 else 0)
        )
    if sustained and backend != "cpu":
        ranked = sorted(windows[1:], key=lambda r: r["tokens_per_s"])
        result = ranked[len(ranked) // 2]
    else:
        result = max(windows, key=lambda r: r["tokens_per_s"])
    achieved_mfu = mfu(
        result["tokens_per_s"], config, seq, num_chips=len(devices), accelerator=accel
    )
    # roofline attribution (runtime/roofline.py — the ONE MFU/floor
    # definition the TelemetryAgent publishes too): which resource the
    # analytic model says binds this workload, and how close the measured
    # step ran to the floor.  Emitted on every result, CPU smoke included
    # (ci/bench_trajectory_check.py requires the fields on all paths).
    from kubeflow_tpu.runtime.roofline import train_estimate

    est = train_estimate(config, batch, seq, num_chips=len(devices),
                         accelerator=accel)
    print(
        json.dumps(
            {
                "metric": (f"train_mfu_v5e_seq{seq}" if long_context
                           else "train_mfu_v5e_moe" if moe
                           else "train_mfu_v5e"),
                "value": round(achieved_mfu, 4),
                "unit": "fraction",
                "vs_baseline": round(achieved_mfu / MFU_TARGET, 4),
                "roofline_fraction": round(
                    est.roofline_fraction(result["step_time_s"]), 4),
                "bound": est.bound,
                "detail": {
                    "model": ("tiny-cpu" if backend == "cpu"
                              else "bench-moe-760m" if moe
                              else "bench-chip-470m"),
                    "tokens_per_s": round(result["tokens_per_s"], 1),
                    "step_time_s": round(result["step_time_s"], 4),
                    "final_loss": round(result["loss"], 4),
                    "chips": len(devices),
                    "backend": backend,
                    "estimator": ("sustained-median"
                                  if sustained and backend != "cpu"
                                  else "best-of-windows"),
                    "best_of_windows_tokens_per_s": round(
                        max(w["tokens_per_s"] for w in windows), 1),
                    "window_tokens_per_s": [
                        round(w["tokens_per_s"], 1) for w in windows
                    ],
                },
            }
        )
    )


def _ensure_backend() -> bool:
    """Probe JAX backend init BEFORE any benchmark work.  A TPU-built jax
    on a host without a TPU raises at first device use (rc 1, raw
    traceback, unparseable BENCH_*.json).  Fall back to CPU when possible;
    otherwise emit a parseable {"skipped": true} record and exit 0 so
    CI's bench collection keeps working on CPU-only hosts."""
    try:
        jax.default_backend()
        return True
    except Exception as err:  # noqa: BLE001 — jaxlib raises RuntimeError
        # subclasses (XlaRuntimeError) but wrappers vary by version
        first_error = err
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        # jax memoizes backend init failure per-platform set; with
        # JAX_PLATFORMS overridden a fresh lookup may still succeed
        jax.extend.backend.clear_backends()  # type: ignore[attr-defined]
    except Exception:  # noqa: BLE001 — older jax: no clear API; fall through
        pass
    try:
        jax.default_backend()
        return True
    except Exception:  # noqa: BLE001
        print(json.dumps({
            "metric": "train_mfu_v5e",
            "skipped": True,
            "reason": f"no usable JAX backend: {str(first_error)[:300]}",
        }))
        return False


if __name__ == "__main__":
    if not _ensure_backend():
        raise SystemExit(0)
    if "--decode" in sys.argv:
        args = [a for a in sys.argv[1:] if a.isdigit()]
        main_decode(int(args[0]) if args else 12)
    elif any(a.startswith("--long-context") for a in sys.argv):
        arg = next(a for a in sys.argv if a.startswith("--long-context"))
        sys.argv.remove(arg)
        main(long_context=int(arg.split("=", 1)[1]) if "=" in arg else 4096)
    elif "--moe" in sys.argv:
        sys.argv.remove("--moe")
        main(moe=True)
    elif "--vit" in sys.argv:
        args = [a for a in sys.argv[1:] if a.isdigit()]
        main_vit(int(args[0]) if args else 10)
    else:
        main()
