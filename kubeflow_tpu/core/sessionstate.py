"""Session-state tier: the per-notebook slice checkpoint inventory.

Self-healing (core/selfheal.py) restores slice *membership* but not the
user's in-memory kernel/JAX session — the one thing notebook users care
about.  ElasticNotebook (arXiv:2309.11083) shows notebook state can be
snapshotted and live-migrated; NotebookOS (arXiv:2503.20591) replicates
kernel state for exactly this failure mode.  This module is the contract
between the two planes:

- the **data plane** (runtime/checkpoint.py sidecar hooks inside the
  worker pods) writes periodic / pre-stop / final snapshots of the
  session payload into a `SessionStateStore`;
- the **control plane** (RecoveryEngine's `migrate` verb) reads snapshot
  freshness + generation to decide whether a disrupted slice can be
  migrated (snapshot -> whole-slice restart -> restore) instead of
  bare-restarted, and mirrors the restore intent into
  `status.sessionState` (write-ahead, crash/failover-safe like
  `status.sliceRecovery`).

The store itself is an object-store *stub* in the same spirit as the
fake ApiServer: an in-memory backend for unit tests and a dir-backed
backend whose writes are torn-write-safe (payload first, fsync, then an
atomically renamed metadata commit marker) so a killed sidecar never
leaves a snapshot that restores garbage.  `request_final_snapshot` is
the control plane's "flush now if you still can" RPC; the registered
handler (the in-pod sidecar in production, FakeCluster in tests) returns
the fresh SnapshotInfo or None when the slice is unreachable.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Optional

from ..utils.clock import Clock

# snapshot triggers — a bounded set (they label
# notebook_checkpoint_snapshots_total{trigger})
TRIGGER_PERIODIC = "periodic"
TRIGGER_PRE_STOP = "pre-stop"
TRIGGER_FINAL = "final"
TRIGGER_CULL = "cull"

DEFAULT_MAX_TO_KEEP = 5

FinalSnapshotHandler = Callable[[str, str, int], Optional["SnapshotInfo"]]


@dataclass(frozen=True)
class SnapshotInfo:
    """Metadata of one stored slice checkpoint.  `digest` fingerprints the
    payload — restored-state equivalence drills compare it across the
    snapshot/restore boundary."""

    namespace: str
    notebook: str
    slice_id: int
    generation: int
    saved_at: float
    digest: str
    trigger: str
    uri: str
    size: int


def payload_digest(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()[:16]


class SessionStateStore:
    """Backend-agnostic snapshot inventory keyed by
    (namespace, notebook, slice_id), generations monotonic per key."""

    def __init__(self, clock: Optional[Clock] = None,
                 max_to_keep: int = DEFAULT_MAX_TO_KEEP) -> None:
        self.clock = clock or Clock()
        self.max_to_keep = max_to_keep
        self._lock = threading.RLock()
        self._final_handler: Optional[FinalSnapshotHandler] = None

    # -- identity --------------------------------------------------------------
    @property
    def uri(self) -> str:
        raise NotImplementedError

    def snapshot_uri(self, namespace: str, notebook: str, slice_id: int,
                     generation: int) -> str:
        return (f"{self.uri}/{namespace}/{notebook}/slice-{slice_id}/"
                f"gen-{generation}")

    # -- writes ----------------------------------------------------------------
    def put(self, namespace: str, notebook: str, slice_id: int,
            payload: bytes, trigger: str = TRIGGER_PERIODIC) -> SnapshotInfo:
        with self._lock:
            latest = self.latest(namespace, notebook, slice_id)
            generation = (latest.generation + 1) if latest else 1
            info = SnapshotInfo(
                namespace=namespace,
                notebook=notebook,
                slice_id=slice_id,
                generation=generation,
                saved_at=self.clock.now(),
                digest=payload_digest(payload),
                trigger=trigger,
                uri=self.snapshot_uri(namespace, notebook, slice_id,
                                      generation),
                size=len(payload),
            )
            self._store(info, payload)
            self._prune(namespace, notebook, slice_id)
            return info

    # -- reads -----------------------------------------------------------------
    def snapshots(self, namespace: str, notebook: str,
                  slice_id: int) -> list[SnapshotInfo]:
        raise NotImplementedError

    def latest(self, namespace: str, notebook: str,
               slice_id: int) -> Optional[SnapshotInfo]:
        snaps = self.snapshots(namespace, notebook, slice_id)
        return snaps[-1] if snaps else None

    def info(self, namespace: str, notebook: str, slice_id: int,
             generation: int) -> Optional[SnapshotInfo]:
        return next((s for s in self.snapshots(namespace, notebook, slice_id)
                     if s.generation == generation), None)

    def payload(self, namespace: str, notebook: str, slice_id: int,
                generation: Optional[int] = None) -> Optional[bytes]:
        raise NotImplementedError

    # -- the control-plane "flush now" hook ------------------------------------
    def set_final_snapshot_handler(
            self, handler: Optional[FinalSnapshotHandler]) -> None:
        """Register the data-plane responder (the in-pod sidecar; in tests,
        FakeCluster).  One handler — the store is per-fleet, the handler
        fans out to the addressed slice itself."""
        self._final_handler = handler

    def request_final_snapshot(self, namespace: str, notebook: str,
                               slice_id: int) -> Optional[SnapshotInfo]:
        """Ask the slice to snapshot RIGHT NOW (pre-migration flush).
        Returns the fresh SnapshotInfo, or None when no handler is wired
        or the slice is unreachable/failed to snapshot."""
        handler = self._final_handler
        if handler is None:
            return None
        try:
            return handler(namespace, notebook, slice_id)
        except Exception:  # noqa: BLE001 — an unreachable slice is a
            return None    # normal outcome, not an engine error

    # -- backend hooks ---------------------------------------------------------
    def _store(self, info: SnapshotInfo, payload: bytes) -> None:
        raise NotImplementedError

    def _prune(self, namespace: str, notebook: str, slice_id: int) -> None:
        raise NotImplementedError


class InMemorySessionStore(SessionStateStore):
    """Dict-backed store for unit tests and single-process drills."""

    def __init__(self, clock: Optional[Clock] = None,
                 max_to_keep: int = DEFAULT_MAX_TO_KEEP) -> None:
        super().__init__(clock=clock, max_to_keep=max_to_keep)
        self._data: dict[tuple[str, str, int],
                         list[tuple[SnapshotInfo, bytes]]] = {}

    @property
    def uri(self) -> str:
        return "mem://session-state"

    def snapshots(self, namespace: str, notebook: str,
                  slice_id: int) -> list[SnapshotInfo]:
        with self._lock:
            return [info for info, _ in
                    self._data.get((namespace, notebook, slice_id), [])]

    def payload(self, namespace: str, notebook: str, slice_id: int,
                generation: Optional[int] = None) -> Optional[bytes]:
        with self._lock:
            entries = self._data.get((namespace, notebook, slice_id), [])
            if not entries:
                return None
            if generation is None:
                return entries[-1][1]
            return next((p for info, p in entries
                         if info.generation == generation), None)

    def _store(self, info: SnapshotInfo, payload: bytes) -> None:
        key = (info.namespace, info.notebook, info.slice_id)
        self._data.setdefault(key, []).append((info, bytes(payload)))

    def _prune(self, namespace: str, notebook: str, slice_id: int) -> None:
        key = (namespace, notebook, slice_id)
        entries = self._data.get(key, [])
        if len(entries) > self.max_to_keep:
            self._data[key] = entries[-self.max_to_keep:]


class DirSessionStore(SessionStateStore):
    """Directory-backed store with torn-write safety.

    Layout: `<root>/<ns>/<notebook>/slice-<id>/gen-<G>.bin` (payload) +
    `gen-<G>.json` (metadata).  A snapshot COMMITS when its metadata file
    lands, and the metadata is written tmp-file -> fsync -> atomic rename
    AFTER the fsync'd payload — a sidecar killed mid-save leaves a stray
    `.bin`/`.tmp-` orphan that reads as "no snapshot", never as a
    half-written generation.  Orphans are GC'd on the next scan."""

    def __init__(self, root: str, clock: Optional[Clock] = None,
                 max_to_keep: int = DEFAULT_MAX_TO_KEEP) -> None:
        super().__init__(clock=clock, max_to_keep=max_to_keep)
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    @property
    def uri(self) -> str:
        return f"file://{self.root}"

    def _slice_dir(self, namespace: str, notebook: str,
                   slice_id: int) -> Path:
        return self.root / namespace / notebook / f"slice-{slice_id}"

    def snapshots(self, namespace: str, notebook: str,
                  slice_id: int) -> list[SnapshotInfo]:
        d = self._slice_dir(namespace, notebook, slice_id)
        if not d.is_dir():
            return []
        with self._lock:
            out = []
            for meta_path in sorted(d.glob("gen-*.json")):
                info = self._load_meta(meta_path)
                if info is not None:
                    out.append(info)
            self._gc_orphans(d, {s.generation for s in out})
            return sorted(out, key=lambda s: s.generation)

    def _load_meta(self, meta_path: Path) -> Optional[SnapshotInfo]:
        try:
            meta = json.loads(meta_path.read_text())
            info = SnapshotInfo(**meta)
        except (OSError, ValueError, TypeError):
            # torn/corrupt commit marker: GC both halves
            meta_path.unlink(missing_ok=True)
            meta_path.with_suffix(".bin").unlink(missing_ok=True)
            return None
        if not meta_path.with_suffix(".bin").exists():
            meta_path.unlink(missing_ok=True)
            return None
        return info

    def _gc_orphans(self, d: Path, committed: set[int]) -> None:
        """Drop payloads that never got their commit marker (a save killed
        between the payload write and the metadata rename) and any stray
        tmp files from interrupted writers."""
        for tmp in d.glob(".tmp-*"):
            tmp.unlink(missing_ok=True)
        for bin_path in d.glob("gen-*.bin"):
            try:
                gen = int(bin_path.stem.split("-", 1)[1])
            except ValueError:
                bin_path.unlink(missing_ok=True)
                continue
            if gen not in committed:
                bin_path.unlink(missing_ok=True)

    def payload(self, namespace: str, notebook: str, slice_id: int,
                generation: Optional[int] = None) -> Optional[bytes]:
        with self._lock:
            if generation is None:
                latest = self.latest(namespace, notebook, slice_id)
                if latest is None:
                    return None
                generation = latest.generation
            p = self._slice_dir(namespace, notebook,
                                slice_id) / f"gen-{generation}.bin"
            try:
                return p.read_bytes()
            except OSError:
                return None

    def _store(self, info: SnapshotInfo, payload: bytes) -> None:
        d = self._slice_dir(info.namespace, info.notebook, info.slice_id)
        d.mkdir(parents=True, exist_ok=True)
        bin_final = d / f"gen-{info.generation}.bin"
        _atomic_write(bin_final, payload)
        meta = {
            "namespace": info.namespace,
            "notebook": info.notebook,
            "slice_id": info.slice_id,
            "generation": info.generation,
            "saved_at": info.saved_at,
            "digest": info.digest,
            "trigger": info.trigger,
            "uri": info.uri,
            "size": info.size,
        }
        # the commit marker lands LAST: its atomic rename is the point of
        # no return, and everything before it is invisible to readers
        _atomic_write(d / f"gen-{info.generation}.json",
                      json.dumps(meta).encode())

    def _prune(self, namespace: str, notebook: str, slice_id: int) -> None:
        snaps = self.snapshots(namespace, notebook, slice_id)
        for stale in snaps[:-self.max_to_keep] if self.max_to_keep else []:
            d = self._slice_dir(namespace, notebook, slice_id)
            (d / f"gen-{stale.generation}.json").unlink(missing_ok=True)
            (d / f"gen-{stale.generation}.bin").unlink(missing_ok=True)


def _atomic_write(final: Path, data: bytes) -> None:
    """tmp file in the target dir -> write -> fsync -> atomic rename ->
    fsync(dir): a crash at any point leaves either the old state or the
    new state, never a torn file under the final name."""
    tmp = final.parent / f".tmp-{final.name}-{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)
    dirfd = os.open(final.parent, os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)


def open_store(uri: str, clock: Optional[Clock] = None,
               max_to_keep: int = DEFAULT_MAX_TO_KEEP) -> SessionStateStore:
    """URI -> store: `mem://...` (fresh in-memory instance), `file://<path>`
    or a bare filesystem path (dir-backed)."""
    if uri.startswith("mem://"):
        return InMemorySessionStore(clock=clock, max_to_keep=max_to_keep)
    if uri.startswith("file://"):
        uri = uri[len("file://"):]
    return DirSessionStore(uri, clock=clock, max_to_keep=max_to_keep)


__all__ = [
    "DirSessionStore",
    "InMemorySessionStore",
    "SessionStateStore",
    "SnapshotInfo",
    "TRIGGER_CULL",
    "TRIGGER_FINAL",
    "TRIGGER_PERIODIC",
    "TRIGGER_PRE_STOP",
    "open_store",
    "payload_digest",
]
