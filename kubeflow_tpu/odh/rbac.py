"""Pipeline RBAC: Elyra RoleBindings.

Port of notebook_rbac.go: under SET_PIPELINE_RBAC, bind the notebook's SA to
the `ds-pipeline-user-access-dspa` Role via RoleBinding
`elyra-pipelines-{name}`, skipping quietly when the Role doesn't exist
(notebook_rbac.go:36-154).
"""

from __future__ import annotations

from ..api.types import Notebook
from ..kube import ApiServer, KubeObject, ObjectMeta, set_controller_reference
from . import constants as C


def new_role_binding(
    nb: Notebook, binding_name: str, role_ref_kind: str, role_ref_name: str
) -> KubeObject:
    """NewRoleBinding (notebook_rbac.go:36-58)."""
    return KubeObject(
        api_version="rbac.authorization.k8s.io/v1",
        kind="RoleBinding",
        metadata=ObjectMeta(
            name=binding_name,
            namespace=nb.namespace,
            labels={C.NOTEBOOK_NAME_LABEL: nb.name},
        ),
        body={
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": role_ref_kind,
                "name": role_ref_name,
            },
            "subjects": [
                {
                    "kind": "ServiceAccount",
                    "name": nb.name,
                    "namespace": nb.namespace,
                }
            ],
        },
    )


def check_role_exists(
    api: ApiServer, role_ref_kind: str, role_ref_name: str, namespace: str
) -> bool:
    """checkRoleExists (notebook_rbac.go:61-86)."""
    if role_ref_kind == "ClusterRole":
        return api.try_get("ClusterRole", "", role_ref_name) is not None
    return api.try_get("Role", namespace, role_ref_name) is not None


def reconcile_role_bindings(api: ApiServer, nb: Notebook) -> None:
    """ReconcileRoleBindings (notebook_rbac.go:144-154): the Elyra pipelines
    binding, created only when the target Role exists."""
    if not check_role_exists(api, "Role", C.PIPELINE_ROLE_NAME, nb.namespace):
        return
    desired = new_role_binding(
        nb, C.PIPELINE_ROLEBINDING_PREFIX + nb.name, "Role", C.PIPELINE_ROLE_NAME
    )
    set_controller_reference(nb.obj, desired)
    found = api.try_get("RoleBinding", nb.namespace, desired.name)
    if found is None:
        api.create(desired)
        return
    # RoleRef is immutable; only subjects/labels drift is corrected
    # (notebook_rbac.go:174-185)
    if found.body.get("subjects") != desired.body.get("subjects") or (
        found.metadata.labels != desired.metadata.labels
    ):
        found.body["subjects"] = desired.body.get("subjects")
        found.metadata.labels = dict(desired.metadata.labels)
        api.update(found)
