"""Pipeline parallelism: GPipe over a "pipeline" mesh axis.

TPU-first design: the decoder's stacked layer parameters (leading "layers"
axis from `nn.scan`) are sharded across pipeline stages — rule
("layers", "pipeline"), see parallel.sharding.rules_for_mesh — and the
schedule runs under a PARTIALLY-manual `jax.shard_map`: only the pipeline
axis is manual (explicit `lax.ppermute` moves activations stage->stage over
ICI neighbors), while data/fsdp/sequence/tensor stay automatic so the
layers' internal logical sharding constraints keep composing.  pp therefore
stacks with dp/fsdp/sp/tp in one jitted step.

Schedule: classic GPipe.  The global batch splits into M microbatches; for
T = M + S - 1 ticks every stage applies its L/S layers to the activation it
holds and rotates the result to the next stage.  Stage s computes microbatch
m at tick t = s + m; ticks outside that window are bubbles (computed but
masked — uniform control flow keeps the collective schedule identical on
every shard, as ring attention does).  The backward schedule is whatever AD
produces for the scan (activations for all T ticks are live unless
`remat_layer` wraps the layer), so this is throughput-optimal in FLOPs but
not 1F1B-optimal in memory — the standard GPipe trade.

The reference has no analog (single-pod notebooks, SURVEY.md §2.5); this is
part of the in-notebook compute plane the TPU build adds.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

PIPELINE_AXIS = "pipeline"


def num_stages(mesh: Mesh, axis_name: str = PIPELINE_AXIS) -> int:
    return int(mesh.shape.get(axis_name, 1))


def match_vma(value, ref):
    """Give `value` the same varying-manual-axes (VMA) type as `ref` so the
    two can share a loop carry inside a shard_map region with
    check_vma=True; a no-op outside manual regions."""
    vma = tuple(getattr(jax.typeof(ref), "vma", ()))
    return jax.lax.pcast(value, vma, to="varying") if vma else value


def _scan_layers(layer_fn, params, x_in, layer_has_aux: bool):
    """Scan `layer_fn` over stacked layer params, accumulating the
    per-layer aux into the carry — shared by gpipe's single-stage fallback,
    each gpipe stage, and the 1F1B stage body."""
    def body(carry, layer_params):
        x, aux = carry
        if layer_has_aux:
            x, layer_aux = layer_fn(layer_params, x)
            return (x, aux + layer_aux), None
        return (layer_fn(layer_params, x), aux), None

    aux0 = match_vma(jnp.float32(0.0), x_in)
    (out, aux), _ = jax.lax.scan(body, (x_in, aux0), params)
    return out, aux


def gpipe(
    apply_layer: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = PIPELINE_AXIS,
    remat_layer: bool = False,
    remat_policy=None,
    layer_has_aux: bool = False,
) -> jax.Array:
    """Run a layer stack as a GPipe pipeline.

    apply_layer(layer_params, x) applies ONE layer (params without the
    leading stack axis) to activations x of shape [mb, ...]; the engine
    scans it over each stage's local layers.  stacked_params is the full
    pytree with leading axis L (L % stages == 0), sharded over `axis_name`.
    x: [B, ...] with B % num_microbatches == 0.  Returns [B, ...] outputs,
    replicated over the pipeline axis; with layer_has_aux=True,
    apply_layer returns (x, aux_scalar) per layer (MoE load-balance loss)
    and gpipe returns (out, aux) where aux is the microbatch-mean total —
    per-stage aux is accumulated only over VALID ticks (bubbles compute
    masked garbage) and averaged over microbatches.  Note the estimator
    choice: the load-balance statistic is computed PER MICROBATCH and
    averaged (mean of per-group f·P products), not over the global batch
    (product of global means) — the same per-group convention
    GShard/Mesh-TF use for per-shard batches; both estimators share the
    uniform-routing minimizer.

    Composition constraint: if the stage body itself shards the batch
    dimension (ring attention's shard_map over data/fsdp does), the
    per-microbatch batch B/num_microbatches must remain divisible by that
    sharding group — pick num_microbatches accordingly (e.g.
    B // (data*fsdp)).
    """
    stages = num_stages(mesh, axis_name)
    if stages <= 1:
        out, aux = _scan_layers(apply_layer, stacked_params, x, layer_has_aux)
        return (out, aux) if layer_has_aux else out

    layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if layers % stages != 0:
        raise ValueError(f"{layers} layers not divisible by {stages} stages")
    batch = x.shape[0]
    if batch % num_microbatches != 0:
        raise ValueError(
            f"batch {batch} not divisible by {num_microbatches} microbatches")

    one_layer = apply_layer
    if remat_layer:
        one_layer = jax.checkpoint(apply_layer, policy=remat_policy)

    m_shape = (num_microbatches, batch // num_microbatches) + x.shape[1:]

    def body(stage_params, x_all):
        # stage_params: this stage's [L/stages, ...] slice; x_all: [M, mb, ...]
        s = jax.lax.axis_index(axis_name)
        microbatches = x_all.shape[0]
        ticks = microbatches + stages - 1
        perm = [(i, (i + 1) % stages) for i in range(stages)]

        def apply_stage(x_in):
            return _scan_layers(one_layer, stage_params, x_in, layer_has_aux)

        # pcast to='varying': the zero inits join a carry whose other leg
        # (y, rotated activations) varies over the pipeline axis —
        # consistent VMA types let check_vma=True verify the collective
        # placement statically (the safeguard that caught the
        # ring-under-pipeline gradient bug)
        buf = jax.lax.pcast(jnp.zeros_like(x_all[0]), (axis_name,),
                            to="varying")
        out = jax.lax.pcast(jnp.zeros_like(x_all), (axis_name,),
                            to="varying")
        aux_acc = jax.lax.pcast(jnp.float32(0.0), (axis_name,),
                                to="varying")

        def tick(carry, t):
            buf, out, aux_acc = carry
            inject = x_all[jnp.clip(t, 0, microbatches - 1)]
            x_in = jnp.where(s == 0, inject, buf)
            y, aux_t = apply_stage(x_in)
            # this stage works on microbatch m = t - s; bubbles (invalid m)
            # compute masked garbage whose aux must not accumulate
            valid = (t >= s) & (t < s + microbatches)
            aux_acc = aux_acc + jnp.where(valid, aux_t, 0.0)
            m = t - (stages - 1)
            write = out.at[jnp.clip(m, 0, microbatches - 1)].set(y)
            out = jnp.where((s == stages - 1) & (m >= 0), write, out)
            buf = jax.lax.ppermute(y, axis_name, perm)
            return (buf, out, aux_acc), None

        (buf, out, aux_acc), _ = jax.lax.scan(
            tick, (buf, out, aux_acc), jnp.arange(ticks))
        # results live on the last stage; zero-elsewhere + psum replicates
        # them across the pipeline (the head/loss runs on every stage)
        out = jnp.where(s == stages - 1, out, jnp.zeros_like(out))
        out = jax.lax.psum(out, axis_name)
        # total aux: every stage contributed its layers' aux for every
        # microbatch exactly once; batch-mean = sum / microbatches
        aux = jax.lax.psum(aux_acc, axis_name) / microbatches
        return out, aux

    run = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=(P(), P()),
        axis_names={axis_name},
        # the static VMA check holds for the pipeline engine itself; it must
        # stay off only when ring attention's shard_map NESTS inside the
        # stage body (mesh sequence axis > 1): jax 0.9's sdy export then
        # hoists/splits the nested region and propagates inconsistent
        # shardings onto the pieces (MLIR manual_computation verifier
        # failure regardless of user-code structure).  The gradient-bug
        # class check_vma guarded there is closed a different way: ring
        # attention's VJP is self-contained (custom_vjp, both directions
        # their own check_vma=True regions), so JAX never transposes
        # through the nested shard_map, and the parameter-update allclose
        # gates (tests/test_pipeline.py, dryrun_multichip) pin the
        # numerics dynamically.  RETESTED on jax 0.9.0 (round 5): with
        # check_vma=True the pp x sp TINY program did not finish
        # compiling in 20+ minutes (vs ~4 with the guard) — the
        # pathological path persists; retest again on the next jax
        # upgrade.
        check_vma=int(mesh.shape.get("sequence", 1)) <= 1,
    )
    out, aux = run(stacked_params, x.reshape(m_shape))
    out = out.reshape(x.shape)
    return (out, aux) if layer_has_aux else out


def pipeline_1f1b(
    apply_layer: Callable[[Any, jax.Array], Any],
    stacked_params: Any,
    head_loss: Callable[[Any, jax.Array, jax.Array], jax.Array],
    head_params: Any,
    x: jax.Array,
    targets: jax.Array,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = PIPELINE_AXIS,
    remat_layer: bool = False,
    remat_policy=None,
    layer_has_aux: bool = False,
    aux_weight: float = 0.0,
):
    """1F1B pipeline TRAINING engine: returns (loss, aux, dstacked, dhead, dx).

    Unlike `gpipe` (a forward pass differentiated by outer AD, which keeps
    every tick's activations live through the backward), this engine owns
    the whole schedule and computes gradients itself, so backward work for
    microbatch m starts as soon as its forward leaves the last stage — the
    activation stash is capped at `stages` microbatch inputs per stage
    instead of all `M` ticks.  That requires the per-microbatch loss INSIDE
    the schedule: `head_loss(head_params, y_mb, targets_mb)` must map the
    last stage's output microbatch to its MEAN loss (final norm + LM head +
    CE in the decoder case); its gradient is what enters the backward ring.

    Schedule (non-interleaved 1F1B / PipeDream-flush): with S stages and M
    microbatches, stage s runs the forward of microbatch m at tick
    `s + 2m` and its backward at tick `2S-1-s + 2m`.  The two tick sets
    have opposite parities, so every stage does exactly one op per tick —
    one `jax.vjp` whose forward recompute doubles as the F op (the vjp
    runs on every stage every tick; masks select which result is real:
    SPMD uniform control flow, same as gpipe's bubbles).  Total ticks:
    2(M + S - 1).  Cotangents ride the reverse ring one stage per tick.

    FLOPs trade vs gpipe: ~4/3x (each tick pays forward + transpose, and
    there are 2(M+S-1) ticks vs gpipe's 3 fwd-equivalents over M+S-1) —
    bought memory: stash is min(S, M)/(M+S-1) of gpipe's live set, which
    is what makes pp usable at the 7B/v5p scale BASELINE.md names.

    Gradient outputs: dstacked matches stacked_params (stage-sharded),
    dhead matches head_params (nonzero contributions only from the last
    stage, psum-replicated), dx matches x (cotangent of the embedded
    input, for the embedding's outer vjp).  loss/aux are batch means.
    MoE: with layer_has_aux, apply_layer returns (x, aux_mb) and
    `aux_weight * mean(aux)` joins the optimized loss inside the engine.

    Known jax-0.9 limit: a PER-SHARD microbatch batch of 1 — i.e.
    batch / num_microbatches / (data*fsdp) == 1 — combined with a
    populated sequence axis (ring attention inside the stage) aborts in
    XLA's SPMD partitioner (spmd_partitioner_util.cc:495 check failure);
    keep the per-shard microbatch batch >= 2 on such meshes
    (dryrun_multichip picks its microbatch count accordingly).
    """
    stages = num_stages(mesh, axis_name)
    batch = x.shape[0]
    if batch % num_microbatches != 0:
        raise ValueError(
            f"batch {batch} not divisible by {num_microbatches} microbatches")
    if stages <= 1:
        raise ValueError("pipeline_1f1b requires a populated pipeline axis")
    layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if layers % stages != 0:
        raise ValueError(f"{layers} layers not divisible by {stages} stages")

    one_layer = apply_layer
    if remat_layer:
        one_layer = jax.checkpoint(apply_layer, policy=remat_policy)

    M = num_microbatches
    mb = batch // M
    m_shape = (M, mb) + x.shape[1:]
    t_shape = (M, mb) + targets.shape[1:]

    def body(stage_params, hparams, x_all, t_all):
        s = jax.lax.axis_index(axis_name)
        S = stages
        is_last = s == S - 1
        ticks = 2 * (M + S - 1)
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [(i, (i - 1) % S) for i in range(S)]

        def stage_fn(sp, hp, x_in, t_mb):
            y, aux = _scan_layers(one_layer, sp, x_in, layer_has_aux)
            loss_mb = head_loss(hp, y, t_mb)
            return y, aux, loss_mb

        def vary(v):
            return jax.lax.pcast(v, (axis_name,), to="varying")

        def zeros_g(tree):
            return jax.tree.map(
                lambda l: vary(jnp.zeros(l.shape, l.dtype)), tree)

        stash = vary(jnp.zeros((S, mb) + x.shape[1:], x.dtype))
        fwd_buf = vary(jnp.zeros((mb,) + x.shape[1:], x.dtype))
        bwd_buf = vary(jnp.zeros((mb,) + x.shape[1:], jnp.float32))
        dstack = zeros_g(stage_params)
        dhead = zeros_g(hparams)
        # the embed cotangent is inherently batch-sized (gpipe materializes
        # the same buffer transiently in its backward); keep it in the
        # activation dtype so it doesn't dominate the carry
        dx_out = vary(jnp.zeros(m_shape, x.dtype))
        loss_acc = vary(jnp.float32(0.0))
        aux_acc = vary(jnp.float32(0.0))

        def tick(carry, t):
            stash, fwd_buf, bwd_buf, dstack, dhead, dx_out, loss_acc, aux_acc = carry
            f_off = t - s
            m_f = f_off // 2
            do_f = (f_off >= 0) & (f_off % 2 == 0) & (m_f < M)
            b_off = t - (2 * S - 1 - s)
            m_b = b_off // 2
            do_b = (b_off >= 0) & (b_off % 2 == 0) & (m_b < M)

            m_f_c = jnp.clip(m_f, 0, M - 1)
            m_b_c = jnp.clip(m_b, 0, M - 1)
            x_inject = jnp.where(s == 0, x_all[m_f_c], fwd_buf)
            x_sel = jnp.where(do_b, stash[m_b_c % S], x_inject)
            t_sel = t_all[m_b_c]

            (y, aux, loss_mb), vjp_fn = jax.vjp(
                stage_fn, stage_params, hparams, x_sel, t_sel)

            inv_m = jnp.float32(1.0 / M)
            cot_y = jnp.where(is_last, 0.0, bwd_buf).astype(y.dtype)
            cot_aux = jnp.where(do_b, jnp.float32(aux_weight) * inv_m, 0.0)
            cot_loss = jnp.where(do_b & is_last, inv_m, 0.0)
            dsp, dhp, dx_in, _ = vjp_fn((cot_y, cot_aux, cot_loss))

            mask_b = do_b
            dstack = jax.tree.map(
                lambda a, g: a + jnp.where(mask_b, g, 0.0).astype(a.dtype),
                dstack, dsp)
            dhead = jax.tree.map(
                lambda a, g: a + jnp.where(mask_b, g, 0.0).astype(a.dtype),
                dhead, dhp)
            loss_acc = loss_acc + jnp.where(mask_b & is_last,
                                            loss_mb * inv_m, 0.0)
            aux_acc = aux_acc + jnp.where(mask_b, aux * inv_m, 0.0)
            dx_out = jnp.where(
                mask_b & (s == 0),
                dx_out.at[m_b_c].set(dx_in.astype(dx_out.dtype)),
                dx_out)
            stash = jnp.where(do_f, stash.at[m_f_c % S].set(x_sel), stash)

            fwd_buf = jax.lax.ppermute(
                jnp.where(do_f, y, jnp.zeros_like(y)), axis_name, fwd_perm)
            bwd_buf = jax.lax.ppermute(
                jnp.where(do_b, dx_in.astype(jnp.float32),
                          jnp.zeros_like(bwd_buf)),
                axis_name, bwd_perm)
            return (stash, fwd_buf, bwd_buf, dstack, dhead, dx_out,
                    loss_acc, aux_acc), None

        carry = (stash, fwd_buf, bwd_buf, dstack, dhead, dx_out,
                 loss_acc, aux_acc)
        (stash, fwd_buf, bwd_buf, dstack, dhead, dx_out,
         loss_acc, aux_acc), _ = jax.lax.scan(
            tick, carry, jnp.arange(ticks))

        # loss/aux/dhead/dx live on specific stages (masked zeros elsewhere)
        loss = jax.lax.psum(loss_acc, axis_name)
        aux_total = jax.lax.psum(aux_acc, axis_name)
        dhead = jax.tree.map(lambda g: jax.lax.psum(g, axis_name), dhead)
        dx_out = jax.lax.psum(dx_out, axis_name)
        return loss, aux_total, dstack, dhead, dx_out

    run = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis_name), P(), P(), P()),
        out_specs=(P(), P(), P(axis_name), P(), P()),
        axis_names={axis_name},
        check_vma=int(mesh.shape.get("sequence", 1)) <= 1,  # see gpipe note
    )
    loss, aux_total, dstack, dhead, dx = run(
        stacked_params, head_params, x.reshape(m_shape),
        targets.reshape(t_shape))
    return loss, aux_total, dstack, dhead, dx.reshape(x.shape).astype(x.dtype)


__all__ = ["gpipe", "pipeline_1f1b", "num_stages", "PIPELINE_AXIS"]
