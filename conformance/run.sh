#!/usr/bin/env bash
# Notebook conformance profile — an EXTERNAL contract, not a re-run of the
# implementation's own tests (reference analog: conformance/1.7/Makefile).
# Three independent artifact sets certify an implementation:
#   1. rendered-object goldens (conformance/goldens/) — the exact object
#      set a conformant controller renders for canonical workbenches;
#   2. apiserver wire-protocol fixtures (conformance/apiserver_fixtures/) —
#      golden transcripts of real kube-apiserver semantics, replayed over
#      real sockets;
#   3. the black-box behavioral runner (conformance/behavior.py) — drives
#      any server over HTTP only: CRD lifecycle, the stop/restart
#      annotation protocol, TPU topology + slice-atomic semantics.
# Sets 2 and 3 run against ANY implementation: point them at a kubeconfig'd
# cluster running an alternative controller via --server/--token.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== 1/3 rendered-object goldens =="
python conformance/check_goldens.py

echo "== 2+3 booting the shipped manager standalone with a wire apiserver =="
OUT=$(mktemp)
# no --run-seconds cap: the trap below owns the manager's lifetime (a cap
# could expire mid-suite on a slow machine and turn into opaque
# connection-refused failures)
# --fake-tpu-nodes 4: the in-memory analog of the kind lane's fake device
# plugin — the TPU gang actually schedules, so the behavioral runner can
# assert node binding (--expect-scheduled) here too
# USE_ISTIO=true (exact string, reference parity notebook_controller.go:238):
# the istio profile only ADDS a VirtualService per notebook, so the same
# manager serves the base contract and the --istio leg
USE_ISTIO=true python -m kubeflow_tpu.main --serve-api 0 --metrics-addr 0 --fake-tpu-nodes 4 >"$OUT" 2>&1 &
MGR=$!
trap 'kill $MGR 2>/dev/null || true; rm -f "$OUT"' EXIT
URL=""
for _ in $(seq 1 100); do
  URL=$(sed -n 's/^WIRE_API=//p' "$OUT" | head -1)
  [ -n "$URL" ] && break
  sleep 0.2
done
[ -n "$URL" ] || { echo "manager did not publish WIRE_API"; cat "$OUT"; exit 1; }

echo "== 2/3 apiserver wire-protocol fixtures ($URL) =="
python -m kubeflow_tpu.kube.fixtures --server "$URL"

echo "== 3/3 black-box behavioral contract =="
python conformance/behavior.py --server "$URL" --expect-scheduled --istio

echo "notebook conformance: PASS"
