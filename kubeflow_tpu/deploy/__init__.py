"""Deployment manifests as code (the reference's kustomize plane)."""

from .manifests import PROFILES, render_profile, render_yaml, validate_docs

__all__ = ["PROFILES", "render_profile", "render_yaml", "validate_docs"]
