"""Unit suite for the bounded downsampling time-series store.

The TSDB is the "a loadtest is a curve, not a point" half of the
observability work: fed once per NotebookMetrics.scrape(), queried at
/debug/timeline, and captured wholesale into the ops/diagnose bundle.
These tests pin the fold-at-append bucket math, every capacity bound,
the never-raise read side, and the dump/snapshot shapes the smoke
script and bundle consumers assert against.
"""

import math

import pytest

from kubeflow_tpu.utils.tsdb import TIERS, TimeSeriesStore


class TestFolding:
    def test_raw_points_preserved_in_order(self):
        store = TimeSeriesStore()
        for i in range(5):
            store.sample(float(i), {"q": float(i * 10)})
        q = store.query("q", tier="raw")
        assert q["points"] == [[0.0, 0.0], [1.0, 10.0], [2.0, 20.0],
                               [3.0, 30.0], [4.0, 40.0]]
        assert "error" not in q

    def test_tier_bucket_keys_floor_to_width(self):
        store = TimeSeriesStore()
        # 3.0 and 9.9 share the [0,10) bucket; 10.0 opens the next one.
        store.sample(3.0, {"q": 1.0})
        store.sample(9.9, {"q": 2.0})
        store.sample(10.0, {"q": 3.0})
        ten = store.query("q", tier="10s")["points"]
        assert [b["t"] for b in ten] == [0.0, 10.0]
        # all three fold into one 60s bucket
        sixty = store.query("q", tier="60s")["points"]
        assert [b["t"] for b in sixty] == [0.0]
        assert sixty[0]["count"] == 3

    def test_bucket_aggregates(self):
        store = TimeSeriesStore()
        for v in (4.0, 1.0, 7.0):
            store.sample(12.0, {"q": v})
        (b,) = store.query("q", tier="10s")["points"]
        assert b["count"] == 3
        assert b["sum"] == 12.0
        assert b["min"] == 1.0
        assert b["max"] == 7.0
        assert b["last"] == 7.0
        assert b["mean"] == pytest.approx(4.0)

    def test_mean_is_derived_not_stored(self):
        store = TimeSeriesStore()
        store.sample(0.0, {"q": 2.0})
        store.sample(1.0, {"q": 4.0})
        # dump() returns the stored bucket (no mean); query() derives it
        raw_bucket = store.dump()["series"]["q"]["10s"][0]
        assert "mean" not in raw_bucket
        assert store.query("q", tier="10s")["points"][0]["mean"] == 3.0

    def test_multiple_series_fold_independently(self):
        store = TimeSeriesStore()
        store.sample(0.0, {"a": 1.0, "b": 100.0})
        store.sample(5.0, {"a": 3.0})
        assert store.series_names() == ["a", "b"]
        assert store.query("a", tier="10s")["points"][0]["count"] == 2
        assert store.query("b", tier="10s")["points"][0]["count"] == 1


class TestBounds:
    def test_raw_ring_is_bounded_but_tiers_keep_folding(self):
        store = TimeSeriesStore(raw_capacity=4)
        for i in range(10):
            store.sample(float(i), {"q": float(i)})
        q = store.query("q", tier="raw")
        # only the newest raw_capacity points survive ...
        assert q["points"] == [[6.0, 6.0], [7.0, 7.0], [8.0, 8.0],
                               [9.0, 9.0]]
        # ... but every sample still reached the downsampled tiers
        (b,) = store.query("q", tier="10s")["points"]
        assert b["count"] == 10 and b["sum"] == 45.0
        assert store.samples_total == 10

    def test_tier_rings_are_bounded(self):
        store = TimeSeriesStore(tier10_capacity=3)
        for i in range(6):  # six distinct 10s buckets
            store.sample(i * 10.0, {"q": 1.0})
        ten = store.query("q", tier="10s")["points"]
        assert [b["t"] for b in ten] == [30.0, 40.0, 50.0]

    def test_max_series_cap_counts_drops(self):
        store = TimeSeriesStore(max_series=2)
        store.sample(0.0, {"a": 1.0, "b": 2.0, "c": 3.0, "d": 4.0})
        assert store.series_names() == ["a", "b"]
        assert store.dropped_series_total == 2
        # existing series keep accepting samples under the cap
        store.sample(1.0, {"a": 5.0, "c": 6.0})
        assert len(store.query("a", tier="raw")["points"]) == 2
        assert store.dropped_series_total == 3

    def test_non_finite_and_non_numeric_skipped(self):
        store = TimeSeriesStore()
        store.sample(0.0, {"q": float("nan"), "r": float("inf"),
                           "s": float("-inf"), "t": "not-a-number",
                           "u": None, "ok": "2.5"})
        # only the coercible finite value landed; nothing else created
        # a series or counted as a sample
        assert store.series_names() == ["ok"]
        assert store.query("ok", tier="raw")["points"] == [[0.0, 2.5]]
        assert store.samples_total == 1


class TestReadSide:
    def test_unknown_series_yields_error_dict(self):
        store = TimeSeriesStore()
        q = store.query("missing", tier="raw")
        assert q == {"series": "missing", "tier": "raw", "points": [],
                     "error": "unknown series"}

    def test_unknown_tier_yields_error_dict(self):
        store = TimeSeriesStore()
        store.sample(0.0, {"q": 1.0})
        q = store.query("q", tier="5m")
        assert q["points"] == [] and "unknown tier" in q["error"]

    def test_dump_shape(self):
        store = TimeSeriesStore(raw_capacity=8, tier10_capacity=9,
                                tier60_capacity=10, max_series=11)
        store.sample(0.0, {"q": 1.0})
        d = store.dump()
        assert d["samples_total"] == 1
        assert d["dropped_series_total"] == 0
        assert d["bounds"] == {"raw_capacity": 8, "tier10_capacity": 9,
                               "tier60_capacity": 10, "max_series": 11}
        assert set(d["series"]["q"]) == {"raw", "10s", "60s"}
        assert d["series"]["q"]["raw"] == [[0.0, 1.0]]

    def test_dump_is_a_copy(self):
        store = TimeSeriesStore()
        store.sample(0.0, {"q": 1.0})
        d = store.dump()
        d["series"]["q"]["10s"][0]["sum"] = math.pi
        assert store.query("q", tier="10s")["points"][0]["sum"] == 1.0

    def test_snapshot_inventory(self):
        store = TimeSeriesStore()
        for i in range(3):
            store.sample(i * 60.0, {"q": 1.0})
        snap = store.snapshot()
        assert snap["tiers"] == ["raw", "10s", "60s"]
        assert list(TIERS) == snap["tiers"]
        assert snap["samples_total"] == 3
        assert snap["series"]["q"] == {"raw_points": 3, "10s_buckets": 3,
                                       "60s_buckets": 3}

    def test_clear_resets_everything(self):
        store = TimeSeriesStore(max_series=1)
        store.sample(0.0, {"a": 1.0, "b": 2.0})
        assert store.dropped_series_total == 1
        store.clear()
        assert store.series_names() == []
        assert store.samples_total == 0
        assert store.dropped_series_total == 0
        # the name space is reusable after clear
        store.sample(0.0, {"b": 2.0})
        assert store.series_names() == ["b"]
