#!/usr/bin/env bash
# Metric-inventory drift gate: boot the standalone manager, scrape
# /metrics, and diff the metric-family inventory (name + type, from the
# `# TYPE` exposition lines) against the committed golden list
# (ci/metrics_families.golden).  A rename, removal, or type change of any
# family fails CI here instead of silently breaking dashboards and
# recording rules downstream.
#
# Intentional changes: update the golden with
#   ci/metrics_drift_check.sh --update
set -euo pipefail
cd "$(dirname "$0")/.."

PORT="${METRICS_DRIFT_PORT:-18478}"
GOLDEN="ci/metrics_families.golden"
SCRAPE="$(mktemp)"
FAMILIES="$(mktemp)"

python -m kubeflow_tpu.main --metrics-addr "$PORT" --webhook-port -1 \
  --run-seconds 30 >/dev/null 2>&1 &
MGR_PID=$!
cleanup() {
  kill "$MGR_PID" 2>/dev/null || true
  rm -f "$SCRAPE" "$FAMILIES"
}
trap cleanup EXIT

# poll until the manager serves a scrape (stdlib only — no curl dependency)
python - "$PORT" "$SCRAPE" <<'EOF'
import sys, time, urllib.request

port, out = sys.argv[1], sys.argv[2]
deadline = time.time() + 20
while True:
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=2).read().decode()
        if "# TYPE" in body:
            break
    except Exception:
        if time.time() > deadline:
            raise SystemExit("manager never served /metrics")
        time.sleep(0.25)
with open(out, "w") as f:
    f.write(body)
EOF

# control-plane families from the live scrape + data-plane families from
# the runtime StepTimer registry (register_step_metrics imports without
# jax, so this inventory is cheap and runs everywhere)
{
  grep '^# TYPE ' "$SCRAPE" | awk '{print $3" "$4}'
  python - <<'EOF'
from kubeflow_tpu.runtime.metrics import register_step_metrics
from kubeflow_tpu.utils.metrics import Registry

reg = Registry()
register_step_metrics(reg)
for name, kind in reg.families():
    print(name, kind)
EOF
} | sort > "$FAMILIES"

if [[ "${1:-}" == "--update" ]]; then
  cp "$FAMILIES" "$GOLDEN"
  echo "updated $GOLDEN ($(wc -l < "$GOLDEN") families)"
  exit 0
fi

if [[ ! -f "$GOLDEN" ]]; then
  echo "missing $GOLDEN — bootstrap with: ci/metrics_drift_check.sh --update" >&2
  exit 1
fi

if ! diff -u "$GOLDEN" "$FAMILIES"; then
  echo >&2
  echo "metric-family inventory drifted from $GOLDEN." >&2
  echo "If intentional, refresh it: ci/metrics_drift_check.sh --update" >&2
  exit 1
fi
echo "metrics drift check OK ($(wc -l < "$GOLDEN") families)"
