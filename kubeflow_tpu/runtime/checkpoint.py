"""Checkpoint/restore hooks with cull-signal integration.

The reference has no in-process checkpointing — all state is CR annotations
(SURVEY.md §5 "Checkpoint/resume").  A TPU notebook does real training, so
the runtime pairs Orbax with the culling controller's checkpoint-before-cull
protocol (core/constants.py ANNOTATION_CHECKPOINT_REQUESTED/_COMPLETE):

  controller sets  checkpoint-requested  ->  (downward-API file appears)
  runtime saves + acks via the signal file ->  controller proceeds to cull

The signal transport is a file because annotations are projected into pods
via the downward API; tests drive the same path with a tmp file.
"""

from __future__ import annotations

import os
import threading
import time
from pathlib import Path
from typing import Any, Callable, Optional

DEFAULT_SIGNAL_DIR = "/etc/podinfo"
REQUEST_FILE = "checkpoint-requested"
ACK_FILE = "checkpoint-complete"


class CheckpointManager:
    """Thin Orbax wrapper: sharded async-capable save/restore keyed by step.

    Multi-host safe: orbax coordinates the distributed write itself; every
    process must call save/restore collectively.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = Path(directory)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        self.manager.save(step, args=self._ocp.args.StandardSave(state))
        if wait:
            self.manager.wait_until_finished()

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        step = step if step is not None else self.manager.latest_step()
        if step is None:
            return None
        return self.manager.restore(
            step, args=self._ocp.args.StandardRestore(state_like)
        )

    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()

    def close(self) -> None:
        self.manager.wait_until_finished()
        self.manager.close()


class CullSignalWatcher:
    """Watches for the controller's checkpoint-before-cull request.

    `check()` is cheap enough for a per-step call; `acknowledge()` writes the
    completion marker the culling controller's checkpoint gate polls for
    (core/culling_controller.py)."""

    def __init__(self, signal_dir: str = DEFAULT_SIGNAL_DIR):
        self.signal_dir = Path(signal_dir)

    def check(self) -> bool:
        req = self.signal_dir / REQUEST_FILE
        try:
            return req.exists() and req.read_text().strip() not in ("", "false")
        except OSError:
            return False

    def acknowledge(self) -> None:
        self.signal_dir.mkdir(parents=True, exist_ok=True)
        (self.signal_dir / ACK_FILE).write_text(str(time.time()))


def checkpoint_on_cull(
    manager: CheckpointManager,
    watcher: Optional[CullSignalWatcher] = None,
) -> Callable[[int, Any], bool]:
    """Returns a per-step hook: `hook(step, state)` saves synchronously and
    acknowledges when a cull is pending; returns True when it fired so the
    training loop can drain/exit cleanly."""
    watcher = watcher or CullSignalWatcher()
    fired = threading.Event()

    def hook(step: int, state: Any) -> bool:
        if fired.is_set() or not watcher.check():
            return False
        manager.save(step, state, wait=True)
        watcher.acknowledge()
        fired.set()
        return True

    return hook
