"""Elyra pipeline runtime-images ConfigMap.

Port of notebook_runtime.go: scan controller-namespace ImageStreams labeled
`opendatahub.io/runtime-image`, build a per-user-namespace ConfigMap
`pipeline-runtime-images` (key = sanitized display_name + ".json", value =
tag metadata JSON with the image reference injected as `image_name`), and
mount it at /opt/app-root/pipeline-runtimes into every container
(notebook_runtime.go:43-285).
"""

from __future__ import annotations

import json
import re
from typing import Optional

from ..api.types import Notebook
from ..kube import ApiServer, KubeObject, ObjectMeta
from ..tpu.env import upsert_by_name
from . import constants as C

_INVALID_CHARS = re.compile(r"[^a-z0-9-]")
_MULTI_DASH = re.compile(r"-+")


def format_key_name(display_name: str) -> str:
    """Sanitize a display name into a ConfigMap key
    (formatKeyName, notebook_runtime.go:174-183)."""
    s = _INVALID_CHARS.sub("-", display_name.lower())
    s = _MULTI_DASH.sub("-", s).strip("-")
    return f"{s}.json" if s else ""


def parse_runtime_image_metadata(raw_json: str, image_url: str) -> str:
    """First object of the metadata array with image_name injected; "{}" on
    any parse failure (parseRuntimeImageMetadata,
    notebook_runtime.go:185-208)."""
    try:
        array = json.loads(raw_json)
    except ValueError:
        return "{}"
    if not isinstance(array, list) or not array or not isinstance(array[0], dict):
        return "{}"
    entry = array[0]
    if isinstance(entry.get("metadata"), dict):
        entry["metadata"]["image_name"] = image_url
    try:
        return json.dumps(entry, sort_keys=True)
    except (TypeError, ValueError):
        return "{}"


def _extract_display_name(metadata_json: str) -> str:
    try:
        parsed = json.loads(metadata_json)
    except ValueError:
        return ""
    name = parsed.get("display_name")
    return name if isinstance(name, str) else ""


def build_runtime_images_data(api: ApiServer, controller_namespace: str) -> dict:
    """ImageStreams -> ConfigMap data (notebook_runtime.go:47-95)."""
    data: dict[str, str] = {}
    for stream in api.list("ImageStream", namespace=controller_namespace):
        if stream.metadata.labels.get(C.LABEL_RUNTIME_IMAGE) != "true":
            continue
        for tag in stream.spec.get("tags") or []:
            raw = (tag.get("annotations") or {}).get(
                C.ANNOTATION_RUNTIME_IMAGE_METADATA, ""
            ) or "[]"
            image_url = (tag.get("from") or {}).get("name", "")
            if not image_url:
                continue
            parsed = parse_runtime_image_metadata(raw, image_url)
            display_name = _extract_display_name(parsed)
            if not display_name:
                continue
            key = format_key_name(display_name)
            if key:
                data[key] = parsed
    return data


def sync_runtime_images_configmap(
    api: ApiServer, notebook_namespace: str, controller_namespace: str
) -> Optional[KubeObject]:
    """Create/update `pipeline-runtime-images` in the user namespace; empty
    scan results never create (and never clobber) the ConfigMap
    (SyncRuntimeImagesConfigMap, notebook_runtime.go:43-152)."""
    data = build_runtime_images_data(api, controller_namespace)
    found = api.try_get("ConfigMap", notebook_namespace, C.RUNTIME_IMAGES_CONFIGMAP)
    if not data:
        return found
    if found is None:
        return api.create(
            KubeObject(
                api_version="v1",
                kind="ConfigMap",
                metadata=ObjectMeta(
                    name=C.RUNTIME_IMAGES_CONFIGMAP,
                    namespace=notebook_namespace,
                    labels={"opendatahub.io/managed-by": "workbenches"},
                ),
                body={"data": data},
            )
        )
    if found.body.get("data") != data:
        found.body["data"] = data
        return api.update(found)
    return found


def mount_pipeline_runtime_images(nb: Notebook) -> None:
    """Webhook-side mutation: optional ConfigMap volume mounted into ALL
    containers (MountPipelineRuntimeImages, notebook_runtime.go:216-285)."""
    spec = nb.pod_spec
    upsert_by_name(
        spec.setdefault("volumes", []),
        {
            "name": C.RUNTIME_IMAGES_VOLUME,
            "configMap": {"name": C.RUNTIME_IMAGES_CONFIGMAP, "optional": True},
        },
    )
    mount = {
        "name": C.RUNTIME_IMAGES_VOLUME,
        "mountPath": C.RUNTIME_IMAGES_MOUNT_PATH,
    }
    for container in spec.get("containers") or []:
        upsert_by_name(container.setdefault("volumeMounts", []), mount)
