"""Pallas TPU kernel: matmul against nibble-packed int4 weights.

The XLA formulation of int4 decode (models/quant.py Int4DenseGeneral)
cannot keep the dequantized weights out of HBM — the group-scale multiply
defeats operand fusion, measured at 5.9k tok/s vs int8's 10.4k on the
470M bench (BASELINE.md).  This kernel is the fix: each [block_k/2,
block_n] packed-int8 tile is DMA'd to VMEM, sign-extended with shifts,
scaled by its group scales, and fed straight to the MXU — the bf16
weights exist only tile-at-a-time in VMEM, so HBM sees exactly the int4
bytes.

Packing layout matches models/quant.py: byte i of the packed [K/2, N]
buffer holds contract rows 2i (low nibble) and 2i+1 (high nibble), scales
[K/G, N] with G = INT4_GROUP rows per scale.  The kernel avoids in-VMEM
interleaving the same way the XLA path does:
    x @ W == x_even @ lo + x_odd @ hi
with x pre-split OUTSIDE the kernel (two [M, K/2] operands — cheap, they
are activations, not weights).

Grid: (M/bm, N/bn, K/bk) with K innermost; fp32 accumulator scratch in
VMEM, written to the output on the last K step.
"""

from __future__ import annotations

import functools

import jax
import jax.experimental.pallas as pl
import jax.numpy as jnp


def _kernel(xe_ref, xo_ref, w_ref, s_ref, o_ref, acc_ref, *, n_k: int,
            group: int):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Mosaic has no int8 vector shifts (arith.shli on i8 fails to
    # legalize); unpack in int32 — lo sign-extends via <<28 then
    # arithmetic >>28, hi is the sign-extended byte arithmetic >>4.
    # (An output-side-scaling variant with per-group batched dots — which
    # would cut the per-weight VPU work — fails Mosaic layout inference
    # ("unsupported shape cast" on the [M, G, half] transpose), so the
    # scale applies weight-side.)
    wp = w_ref[:].astype(jnp.int32)      # [bk/2, bn] packed pairs
    lo = jax.lax.shift_right_arithmetic(
        jax.lax.shift_left(wp, jnp.int32(28)), jnp.int32(28))
    hi = jax.lax.shift_right_arithmetic(wp, jnp.int32(4))
    sc = s_ref[:]                        # [bk/group, bn] f32
    half = group // 2
    bk2, bn = wp.shape

    def dequant(part):  # -> bf16 MXU operand, built entirely in VMEM
        g = part.astype(jnp.float32).reshape(bk2 // half, half, bn)
        return (g * sc[:, None, :]).reshape(bk2, bn).astype(jnp.bfloat16)

    acc_ref[:] += (
        jnp.dot(xe_ref[:], dequant(lo),
                preferred_element_type=jnp.float32)
        + jnp.dot(xo_ref[:], dequant(hi),
                  preferred_element_type=jnp.float32)
    )

    @pl.when(k == n_k - 1)
    def _write():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def _pick_block(dim: int, candidates=(512, 256, 128)) -> int:
    for c in candidates:
        if dim % c == 0:
            return c
    return 0


def supported(m: int, k: int, n: int, group: int) -> bool:
    bm = _pick_block(m, (128, 64, 32, 16))
    bk = _pick_block(k)
    bn = _pick_block(n)
    return bool(bm and bk and bn) and bk % (2 * group) == 0


@functools.partial(jax.jit, static_argnames=("group", "out_dtype"))
def int4_matmul(x, packed, scales, *, group: int = 64,
                out_dtype=jnp.bfloat16):
    """x [M, K] @ int4-packed W -> [M, N].

    packed: [K/2, N] int8 (models/quant.py layout); scales: [K/group, N]
    (any float dtype).  Caller guarantees `supported(M, K, N, group)`."""
    from jax.experimental.pallas import tpu as pltpu

    m, k_dim = x.shape
    n = packed.shape[1]
    bm = _pick_block(m, (128, 64, 32, 16))
    bk = _pick_block(k_dim)
    bn = _pick_block(n)
    n_k = k_dim // bk

    x = x.astype(jnp.bfloat16)
    xe = x[:, 0::2]
    xo = x[:, 1::2]
    # models/quant.py stores scales [K/G, 1, N]; the kernel wants 2-D
    scales = scales.reshape(scales.shape[0], scales.shape[-1]) \
        .astype(jnp.float32)

    grid = (m // bm, n // bn, n_k)
    kernel = functools.partial(_kernel, n_k=n_k, group=group)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk // 2), lambda i, j, kk: (i, kk),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, bk // 2), lambda i, j, kk: (i, kk),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bk // 2, bn), lambda i, j, kk: (kk, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bk // group, bn), lambda i, j, kk: (kk, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
    )(xe, xo, packed, scales)


__all__ = ["int4_matmul", "supported"]
