"""Real-cluster client: the Kubernetes REST API over HTTP(S).

The drop-in counterpart of the in-memory ApiServer (kube/store.py): exposes
the same read/write surface (get/list/create/update/update_status/
merge_patch/delete) plus reflector-style informers feeding the Manager's
watch callbacks, so `Manager(KubeClient(...))` reconciles a *real* cluster
with the controllers unchanged.  Mirrors the client-go stack the reference
sits on: rest.Config + kubeconfig/in-cluster loading
(notebook-controller/main.go:87-89), client-side qps/burst throttling
(main.go:71-72,80-85), and a list-then-watch reflector with 410-Gone relist
(controller-runtime's informer cache).  Dependency-free: stdlib http.client,
ssl, and PyYAML for kubeconfig.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import socket
import ssl
import tempfile
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional
from urllib.parse import urlencode, urlsplit

import http.client

from .errors import (
    AlreadyExistsError,
    ApiError,
    ConflictError,
    ForbiddenError,
    GoneError,
    InvalidError,
    NotFoundError,
    ServerError,
)
from .meta import KubeObject
from .resources import DEFAULT_SCHEME, Scheme
from .store import AdmissionHook, EventType, WatchEvent

logger = logging.getLogger("kubeflow_tpu.kube.client")

SA_MOUNT = "/var/run/secrets/kubernetes.io/serviceaccount"

_ERR_BY_REASON = {
    "NotFound": NotFoundError,
    "AlreadyExists": AlreadyExistsError,
    "Conflict": ConflictError,
    "Invalid": InvalidError,
    "Forbidden": ForbiddenError,
    "Expired": GoneError,
}
_ERR_BY_CODE = {
    404: NotFoundError, 409: ConflictError, 422: InvalidError,
    401: ForbiddenError, 403: ForbiddenError, 410: GoneError,
}


# canonical namespace detection lives in utils.config (odh main.go:127-139)
from ..utils.clock import Clock  # noqa: E402
from ..utils.config import detect_namespace  # noqa: E402  (re-export)


@dataclass
class RestConfig:
    """Where the apiserver is and how to authenticate — rest.Config."""

    server: str
    token: str = ""
    ca_file: str = ""
    client_cert_file: str = ""
    client_key_file: str = ""
    insecure_skip_verify: bool = False
    namespace: str = "default"
    qps: float = 0.0   # 0 = unlimited (client-go default left to the lib)
    burst: int = 0

    @classmethod
    def from_kubeconfig(cls, path: str, context: Optional[str] = None) -> "RestConfig":
        import yaml

        with open(path) as f:
            kc = yaml.safe_load(f) or {}
        ctx_name = context or kc.get("current-context", "")
        ctx = next((c["context"] for c in kc.get("contexts", [])
                    if c.get("name") == ctx_name), {})
        cluster = next((c["cluster"] for c in kc.get("clusters", [])
                        if c.get("name") == ctx.get("cluster")), {})
        user = next((u["user"] for u in kc.get("users", [])
                     if u.get("name") == ctx.get("user")), {})

        def materialize(data_key: str, file_key: str) -> str:
            # *-data keys are base64-inline; write to a temp file for ssl
            if user.get(data_key) or cluster.get(data_key):
                raw = base64.b64decode(user.get(data_key) or cluster.get(data_key))
                tf = tempfile.NamedTemporaryFile(delete=False, suffix=".pem")
                tf.write(raw)
                tf.close()
                return tf.name
            return user.get(file_key) or cluster.get(file_key) or ""

        return cls(
            server=cluster.get("server", ""),
            token=user.get("token", ""),
            ca_file=materialize("certificate-authority-data", "certificate-authority"),
            client_cert_file=materialize("client-certificate-data", "client-certificate"),
            client_key_file=materialize("client-key-data", "client-key"),
            insecure_skip_verify=bool(cluster.get("insecure-skip-tls-verify", False)),
            namespace=ctx.get("namespace", "default"),
        )

    @classmethod
    def in_cluster(cls) -> "RestConfig":
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        if not host:
            raise RuntimeError("not running in-cluster "
                               "(KUBERNETES_SERVICE_HOST unset)")
        with open(os.path.join(SA_MOUNT, "token")) as f:
            token = f.read().strip()
        return cls(
            server=f"https://{host}:{port}",
            token=token,
            ca_file=os.path.join(SA_MOUNT, "ca.crt"),
            namespace=detect_namespace(),
        )


class RateLimiter:
    """Token bucket — client-go's flowcontrol.NewTokenBucketRateLimiter.
    Time flows through the injected Clock (clock discipline): a real Clock
    sleeps; a FakeClock advances, so tests never block."""

    def __init__(self, qps: float, burst: int,
                 clock: Optional[Clock] = None) -> None:
        self.qps = qps
        self.burst = max(burst, 1)
        self.clock = clock or Clock()
        self._tokens = float(self.burst)
        self._last = self.clock.monotonic()
        self._lock = threading.Lock()

    def acquire(self) -> None:
        if self.qps <= 0:
            return
        while True:
            with self._lock:
                now = self.clock.monotonic()
                self._tokens = min(self.burst,
                                   self._tokens + (now - self._last) * self.qps)
                self._last = now
                if self._tokens >= 1:
                    self._tokens -= 1
                    return
                wait = (1 - self._tokens) / self.qps
            self.clock.sleep(wait)


@dataclass
class _Informer:
    kind: str
    thread: threading.Thread
    stop: threading.Event = field(default_factory=threading.Event)
    conn: Optional[http.client.HTTPConnection] = None  # live watch stream
    namespace: Optional[str] = None  # None = cluster-wide
    # last-known objects, mutated only by this informer's thread — used to
    # synthesize DELETED events for objects that vanished while the watch
    # was down (client-go's DeletedFinalStateUnknown)
    known: dict[tuple[str, str], KubeObject] = field(default_factory=dict)
    # set once the initial list completed (client-go HasSynced): readiness
    # gates on every informer reaching this point
    synced: threading.Event = field(default_factory=threading.Event)


class KubeClient:
    """ApiServer-compatible facade over a real apiserver."""

    def __init__(self, config: RestConfig, scheme: Optional[Scheme] = None,
                 watch_timeout_s: float = 300.0) -> None:
        self.config = config
        self.scheme_registry = scheme or DEFAULT_SCHEME
        self.limiter = RateLimiter(config.qps, config.burst)
        self.watch_timeout_s = watch_timeout_s
        self._watchers: list[Callable[[WatchEvent], None]] = []
        self._watchers_lock = threading.Lock()
        self._informers: dict[str, _Informer] = {}
        self._admission: list[AdmissionHook] = []
        split = urlsplit(config.server)
        self._host = split.hostname or "127.0.0.1"
        self._port = split.port or (443 if split.scheme == "https" else 80)
        self._tls = split.scheme == "https"
        self._ssl_ctx = self._build_ssl() if self._tls else None

    def _build_ssl(self) -> ssl.SSLContext:
        """Verification is dropped ONLY on explicit opt-in
        (insecure-skip-tls-verify), as in client-go; a kubeconfig without
        certificate-authority data falls back to the system trust roots and
        fails the handshake loudly rather than silently accepting any cert
        while still sending the bearer token."""
        ctx = ssl.create_default_context(cafile=self.config.ca_file or None)
        if self.config.insecure_skip_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        if self.config.client_cert_file:
            ctx.load_cert_chain(self.config.client_cert_file,
                                self.config.client_key_file or None)
        return ctx

    # -- transport ------------------------------------------------------------
    def _connect(self, timeout: float) -> http.client.HTTPConnection:
        if self._tls:
            return http.client.HTTPSConnection(
                self._host, self._port, timeout=timeout, context=self._ssl_ctx)
        return http.client.HTTPConnection(self._host, self._port, timeout=timeout)

    def _headers(self, content_type: str = "application/json") -> dict:
        h = {"Content-Type": content_type, "Accept": "application/json"}
        if self.config.token:
            h["Authorization"] = f"Bearer {self.config.token}"
        return h

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 content_type: str = "application/json",
                 timeout: float = 30.0) -> dict:
        self.limiter.acquire()
        conn = self._connect(timeout)
        try:
            payload = json.dumps(body).encode() if body is not None else None
            conn.request(method, path, body=payload,
                         headers=self._headers(content_type))
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status >= 400:
                self._raise_status(resp.status, raw)
            return json.loads(raw) if raw else {}
        except (OSError, http.client.HTTPException) as err:
            raise ServerError(f"{method} {path}: {err}") from err
        finally:
            conn.close()

    @staticmethod
    def _raise_status(code: int, raw: bytes) -> None:
        reason, message = "", ""
        try:
            status = json.loads(raw)
            reason = status.get("reason", "")
            message = status.get("message", "")
        except (ValueError, AttributeError):
            message = raw.decode(errors="replace")[:500]
        err_cls = _ERR_BY_REASON.get(reason) or _ERR_BY_CODE.get(code) or ServerError
        raise err_cls(message or f"HTTP {code}")

    # -- ApiServer-compatible surface -----------------------------------------
    def get(self, kind: str, namespace: str, name: str) -> KubeObject:
        info = self.scheme_registry.by_kind(kind)
        d = self._request("GET", info.object_path(namespace, name))
        return KubeObject.from_dict(d)

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[KubeObject]:
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[dict[str, str]] = None,
             field_selector: Optional[str] = None) -> list[KubeObject]:
        """`field_selector` is the raw fieldSelector string
        ("metadata.name=wb,involvedObject.kind=Notebook") — server-side
        filtering on dotted field paths."""
        info = self.scheme_registry.by_kind(kind)
        path = info.collection_path(namespace)
        q: dict[str, str] = {}
        if label_selector:
            q["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(label_selector.items()))
        if field_selector:
            q["fieldSelector"] = field_selector
        if q:
            path += "?" + urlencode(q)
        d = self._request("GET", path)
        return sorted(
            (KubeObject.from_dict(i) for i in d.get("items", [])),
            key=lambda o: (o.namespace, o.name),
        )

    def create(self, obj: KubeObject) -> KubeObject:
        info = self.scheme_registry.by_kind(obj.kind)
        d = self._request("POST", info.collection_path(obj.namespace or None),
                          body=obj.to_dict())
        return KubeObject.from_dict(d)

    def update(self, obj: KubeObject, subresource: str = "") -> KubeObject:
        info = self.scheme_registry.by_kind(obj.kind)
        path = info.object_path(obj.namespace or None, obj.name)
        if subresource:
            path += f"/{subresource}"
        d = self._request("PUT", path, body=obj.to_dict())
        return KubeObject.from_dict(d)

    def update_status(self, obj: KubeObject) -> KubeObject:
        return self.update(obj, subresource="status")

    def merge_patch(self, kind: str, namespace: str, name: str,
                    patch: dict) -> KubeObject:
        info = self.scheme_registry.by_kind(kind)
        d = self._request("PATCH", info.object_path(namespace or None, name),
                          body=patch,
                          content_type="application/merge-patch+json")
        return KubeObject.from_dict(d)

    def strategic_merge_patch(self, kind: str, namespace: str, name: str,
                              patch: dict) -> KubeObject:
        """client-go types.StrategicMergePatchType: keyed-list merge on the
        server (containers by name, volumeMounts by mountPath, ...)."""
        info = self.scheme_registry.by_kind(kind)
        d = self._request("PATCH", info.object_path(namespace or None, name),
                          body=patch,
                          content_type="application/strategic-merge-patch+json")
        return KubeObject.from_dict(d)

    def apply(self, obj: KubeObject, field_manager: str,
              force: bool = False) -> KubeObject:
        """Server-side apply (client-go types.ApplyPatchType): declarative
        upsert with managedFields ownership arbitration on the server."""
        info = self.scheme_registry.by_kind(obj.kind)
        path = info.object_path(obj.namespace or None, obj.name)
        path += "?" + urlencode({"fieldManager": field_manager,
                                 "force": "true" if force else "false"})
        d = self._request("PATCH", path, body=obj.to_dict(),
                          content_type="application/apply-patch+yaml")
        return KubeObject.from_dict(d)

    def json_patch(self, kind: str, namespace: str, name: str,
                   ops: list) -> KubeObject:
        """RFC 6902 patch (client-go types.JSONPatchType); `test` ops carry
        preconditions the server answers 422 for on mismatch."""
        info = self.scheme_registry.by_kind(kind)
        d = self._request("PATCH", info.object_path(namespace or None, name),
                          body=ops,
                          content_type="application/json-patch+json")
        return KubeObject.from_dict(d)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        info = self.scheme_registry.by_kind(kind)
        self._request("DELETE", info.object_path(namespace or None, name))

    # -- admission: collected here, served by the webhook HTTPS server --------
    def register_admission(self, hook: AdmissionHook) -> None:
        """On a real cluster admission runs in the apiserver write path via
        webhook callout (odh main.go:285-311); the client only collects the
        hooks for odh.webhook_server.AdmissionReviewServer to serve."""
        self._admission.append(hook)

    @property
    def admission_hooks(self) -> list[AdmissionHook]:
        return list(self._admission)

    # -- informers ------------------------------------------------------------
    def watch(self, fn: Callable[[WatchEvent], None]) -> None:
        with self._watchers_lock:
            self._watchers.append(fn)

    def _dispatch(self, ev: WatchEvent) -> None:
        with self._watchers_lock:
            fns = list(self._watchers)
        for fn in fns:
            try:
                fn(ev)
            except Exception:  # watcher bugs must not kill the informer
                logger.exception("watch callback failed for %s", ev.obj.key())

    def start_informers(self, kinds: list[str],
                        namespace: Optional[str] = None) -> None:
        """List-and-watch reflectors.  `namespace` scopes every informer to
        one namespace (client-go cache.Options.DefaultNamespaces) — a
        single-tenant deployment should not list/watch the whole cluster."""
        for kind in kinds:
            if kind in self._informers:
                continue
            info = self.scheme_registry.by_kind(kind)
            ns = namespace if info.namespaced else None
            inf = _Informer(kind, thread=None,  # type: ignore[arg-type]
                            namespace=ns)
            inf.thread = threading.Thread(
                target=self._informer_loop, args=(inf,),
                daemon=True, name=f"informer-{kind.lower()}")
            self._informers[kind] = inf
            inf.thread.start()

    def informers_synced(self) -> bool:
        """True once every started informer finished its initial list
        (cache.WaitForCacheSync); False with no informers running — a
        manager that never started its event sources is not ready."""
        if not self._informers:
            return False
        return all(inf.synced.is_set() for inf in self._informers.values())

    def stop_informers(self) -> None:
        for inf in self._informers.values():
            inf.stop.set()
            # shutdown() the live watch socket to unblock the reader thread;
            # conn.close() would deadlock on the response-buffer lock the
            # blocked readline() holds, and without either, every join waits
            # out a read timeout
            conn = inf.conn
            sock = getattr(conn, "sock", None)
            if sock is not None:
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
        for inf in self._informers.values():
            inf.thread.join(timeout=2)
        self._informers.clear()

    def _informer_loop(self, inf: _Informer) -> None:
        """List-then-watch reflector.  A clean stream end (idle timeout,
        server close) resumes the watch from the newest resourceVersion seen
        on the stream, as client-go does; a full relist — which re-dispatches
        ADDED for every object — happens only on 410 Gone or a transport
        error, so controllers are not re-reconciling the whole cluster every
        watch_timeout_s."""
        info = self.scheme_registry.by_kind(inf.kind)
        path = info.collection_path(inf.namespace)
        while not inf.stop.is_set():
            try:
                # paginated relist (client-go's pager, 500/page): a large
                # cluster must not be materialized in one response
                rv = 0
                fresh: dict[tuple[str, str], KubeObject] = {}
                params: dict[str, str] = {"limit": "500"}
                while True:
                    listing = self._request(
                        "GET", f"{path}?{urlencode(params)}")
                    meta = listing.get("metadata", {})
                    rv = int(meta.get("resourceVersion", 0) or 0)
                    for item in listing.get("items", []):
                        obj = KubeObject.from_dict(item)
                        fresh[(obj.namespace, obj.name)] = obj
                        self._dispatch(WatchEvent(EventType.ADDED, obj))
                    if not meta.get("continue"):
                        break
                    params["continue"] = meta["continue"]
                # objects that vanished while the watch was down get a
                # synthetic DELETED with their last-known state
                for key, gone in inf.known.items():
                    if key not in fresh:
                        self._dispatch(WatchEvent(EventType.DELETED, gone))
                inf.known = fresh
                inf.synced.set()
                while not inf.stop.is_set():
                    rv = self._watch_stream(info, rv, inf)
            except GoneError:
                continue  # history window lost: relist
            except ApiError as err:
                logger.warning("informer %s: %s; backing off", inf.kind, err)
                inf.stop.wait(1.0)
            except Exception:
                if inf.stop.is_set():
                    return  # socket torn down by stop_informers
                logger.exception("informer %s crashed; restarting", inf.kind)
                inf.stop.wait(1.0)

    def _watch_stream(self, info, rv: int, inf: _Informer) -> int:
        """Stream watch events from `rv`; returns the newest resourceVersion
        seen so the caller can resume without a relist."""
        qs = urlencode({"watch": "true", "resourceVersion": str(rv),
                        "allowWatchBookmarks": "true"})
        path = f"{info.collection_path(inf.namespace)}?{qs}"
        self.limiter.acquire()
        conn = self._connect(timeout=self.watch_timeout_s)
        inf.conn = conn
        try:
            conn.request("GET", path, headers=self._headers())
            resp = conn.getresponse()
            if resp.status >= 400:
                self._raise_status(resp.status, resp.read())
            while not inf.stop.is_set():
                try:
                    line = resp.readline()
                except (TimeoutError, OSError, http.client.HTTPException):
                    return rv  # idle timeout or teardown: resume from rv
                if not line:
                    return rv  # server closed the stream: resume from rv
                line = line.strip()
                if not line:
                    continue
                ev = json.loads(line)
                if ev["type"] == "BOOKMARK":
                    # progress notify: advance the resume RV, dispatch nothing
                    try:
                        rv = max(rv, int(ev["object"].get("metadata", {})
                                         .get("resourceVersion", 0)))
                    except (TypeError, ValueError):
                        pass
                    continue
                if ev["type"] == "ERROR":
                    # mid-stream Status event: the apiserver compacted our
                    # resourceVersion away (410 Gone / Expired).  The resume
                    # RV is dead — raise GoneError so the informer loop
                    # RELISTS instead of resuming from it (client-go
                    # reflector does exactly this on watch.Error + Expired)
                    status = ev.get("object") or {}
                    if status.get("code") == 410 or \
                            status.get("reason") == "Expired":
                        raise GoneError(
                            status.get("message", "watch expired"))
                    raise ServerError(
                        status.get("message", "watch stream error"))
                etype = EventType(ev["type"])
                obj = KubeObject.from_dict(ev["object"])
                try:
                    rv = max(rv, int(obj.metadata.resource_version or 0))
                except ValueError:
                    pass  # opaque RV (a real apiserver may send one): keep last
                if etype is EventType.DELETED:
                    inf.known.pop((obj.namespace, obj.name), None)
                    self._dispatch(WatchEvent(etype, obj))
                else:
                    # last-known state rides along as `prev` (the in-memory
                    # watch cache provides the same), so event predicates
                    # like suppress_status_only work on a real cluster too
                    prev = inf.known.get((obj.namespace, obj.name))
                    inf.known[(obj.namespace, obj.name)] = obj
                    self._dispatch(WatchEvent(
                        etype, obj,
                        prev=prev if etype is EventType.MODIFIED else None))
            return rv
        finally:
            inf.conn = None
            conn.close()


__all__ = ["KubeClient", "RestConfig", "RateLimiter", "detect_namespace"]
