"""Autoregressive generation with a static-shape KV cache.

TPU-first decode: the cache is a fixed [B, max_seq_len] ring per layer
(flax "cache" collection, stacked over the scanned layer axis), written
with `dynamic_update_slice` — no growing shapes, so the whole decode loop
is ONE compiled `lax.scan` program.  Prefill runs the prompt through the
same decode path in a single call (filling the cache), then the loop feeds
one token per step with its global position; rope is applied with global
positions before caching, so cached keys never need re-rotation.

Sampling: greedy (temperature=0) or temperature + top-k.  The reference
ships no inference path (it is a notebook controller); this is part of the
in-notebook compute plane the TPU build adds, and what a workbench uses to
serve/inspect a model it just trained.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .configs import TransformerConfig
from .transformer import Transformer


def decode_config(cfg: TransformerConfig,
                  unroll_layers: bool = True) -> TransformerConfig:
    """Training config -> decode config: remat off (nothing to rematerialize
    and the cache mutation must not be replayed), XLA attention (single-token
    queries never fit the flash kernel's tiling), and UNROLLED layers.

    scan_layers=False matters for bandwidth: under nn.scan the per-layer KV
    cache is a scanned variable, so every token step re-stacks the whole
    [layers, B, max_seq, kv_heads, head_dim] cache as fresh scan outputs —
    ~2x the step's HBM traffic in pure copies.  Unrolled, each layer's cache
    is a separate carry leaf of the token scan and the dynamic_update_slice
    aliases in place.  Measured on v5e (ci/decode_profile.py): 6.5k vs 3.6k
    tok/s at batch 16.  `unroll_layers=False` keeps the scanned stack (the
    profiler's A/B baseline).  Params from a scan_layers=True training run
    are converted by `generate` (see `unroll_params`).
    """
    return cfg.with_(remat=False, attention_impl="xla",
                     scan_layers=not unroll_layers)


def unroll_params(params, num_layers: int):
    """Stacked training params ('layers' subtree with a leading layer axis,
    the scan_layers=True layout) -> the unrolled 'layer_i' layout the
    decode config's param tree uses.  Leaves boxes behind (nn.unbox): the
    stacked partition metadata names a 'layers' axis that does not exist on
    the per-layer slices."""
    import flax.linen as nn

    if "layers" not in params:
        return params
    stacked = nn.unbox(params["layers"])
    rest = {k: v for k, v in params.items() if k != "layers"}
    for i in range(num_layers):
        rest[f"layer_{i}"] = jax.tree.map(lambda a: a[i], stacked)
    return rest


def sample_token(
    logits: jax.Array,
    rng: Optional[jax.Array],
    temperature: float,
    top_k: int = 0,
) -> jax.Array:
    """[B, V] logits -> [B] token ids."""
    if temperature <= 0.0 or rng is None:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def generate(
    cfg: TransformerConfig,
    params,
    prompt: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    rng: Optional[jax.Array] = None,
    mesh=None,
    unroll_layers: bool = True,
) -> jax.Array:
    """prompt [B, P] int32 -> [B, P + max_new_tokens] completions.

    Prompts are assumed unpadded and equal-length (the notebook batch
    case); P + max_new_tokens must fit cfg.max_seq_len.  Accepts params in
    either layout: a scan_layers=True training run's stacked 'layers'
    subtree is converted to the decode layout on the fly (a trace-time
    reshuffle, free after jit).
    """
    cfg = decode_config(cfg, unroll_layers=unroll_layers)
    if not cfg.scan_layers:
        params = unroll_params(params, cfg.num_layers)
    batch, prompt_len = prompt.shape
    total = prompt_len + max_new_tokens
    if total > cfg.max_seq_len:
        raise ValueError(
            f"prompt({prompt_len}) + new({max_new_tokens}) exceeds "
            f"max_seq_len {cfg.max_seq_len}")
    model = Transformer(cfg, mesh)
    if rng is None and temperature > 0.0:
        rng = jax.random.PRNGKey(0)

    # prefill: one full-prompt pass fills the cache and yields the first
    # sampled token from the last prompt position
    (logits, _aux), cache_vars = model.apply(
        {"params": params}, prompt, return_aux=True, decode=True,
        mutable=["cache"])
    step_rng = rng
    if step_rng is not None:
        step_rng, sub = jax.random.split(step_rng)
    else:
        sub = None
    next_tok = sample_token(logits[:, -1, :], sub, temperature, top_k)

    # thread the cache through the scan carry; every step is the same
    # static-shape program
    def scan_step(carry, _):
        cache, tok, pos, rng_ = carry
        positions = jnp.broadcast_to(pos, (batch, 1))
        (logits, _), new_cache = model.apply(
            {"params": params, **cache}, tok[:, None], return_aux=True,
            decode=True, positions=positions, mutable=["cache"])
        if rng_ is not None:
            rng_, sub = jax.random.split(rng_)
        else:
            sub = None
        nxt = sample_token(logits[:, -1, :], sub, temperature, top_k)
        return (new_cache, nxt, pos + 1, rng_), tok

    if max_new_tokens == 1:
        return jnp.concatenate([prompt, next_tok[:, None]], axis=1)

    carry = (cache_vars, next_tok, jnp.int32(prompt_len), step_rng)
    (_, last_tok, _, _), toks = jax.lax.scan(
        scan_step, carry, None, length=max_new_tokens - 1)
    # toks[i] is the token fed at step i (= sampled at step i-1); append the
    # final sample to complete the sequence
    generated = jnp.concatenate(
        [jnp.moveaxis(toks, 0, 1), last_tok[:, None]], axis=1)
    return jnp.concatenate([prompt, generated], axis=1)


__all__ = ["generate", "decode_config", "sample_token", "unroll_params"]
