"""TLS security-profile negotiation tests (odh main.go:178-214,324-340)."""

from kubeflow_tpu.kube import ApiServer, KubeObject, Manager, ObjectMeta
from kubeflow_tpu.odh.tls_profile import (
    HARDENED_FALLBACK,
    INTERMEDIATE_CIPHERS,
    SecurityProfileWatcher,
    fetch_apiserver_tls_profile,
)
from kubeflow_tpu.utils.clock import FakeClock


def apiserver_cr(profile: dict) -> KubeObject:
    return KubeObject(
        api_version="config.openshift.io/v1",
        kind="APIServer",
        metadata=ObjectMeta(name="cluster"),
        body={"spec": {"tlsSecurityProfile": profile}},
    )


class TestFetch:
    def test_fallback_without_cr(self):
        profile = fetch_apiserver_tls_profile(ApiServer())
        assert profile == HARDENED_FALLBACK
        assert profile.min_version == "VersionTLS12"
        assert profile.ciphers == INTERMEDIATE_CIPHERS

    def test_named_profiles(self):
        api = ApiServer()
        api.create(apiserver_cr({"type": "Modern"}))
        profile = fetch_apiserver_tls_profile(api)
        assert profile.min_version == "VersionTLS13"
        assert profile.source == "apiserver"

    def test_custom_profile(self):
        api = ApiServer()
        api.create(apiserver_cr({
            "type": "Custom",
            "custom": {
                "minTLSVersion": "VersionTLS13",
                "ciphers": ["TLS_AES_256_GCM_SHA384"],
            },
        }))
        profile = fetch_apiserver_tls_profile(api)
        assert profile.ciphers == ("TLS_AES_256_GCM_SHA384",)


class TestWatcher:
    def test_profile_change_fires_restart(self):
        api = ApiServer()
        api.create(apiserver_cr({"type": "Intermediate"}))
        mgr = Manager(api, clock=FakeClock())
        initial = fetch_apiserver_tls_profile(api)
        changes = []
        watcher = SecurityProfileWatcher(
            api, initial, lambda old, new: changes.append((old, new))
        )
        watcher.setup(mgr)
        mgr.run_until_idle()
        assert not changes  # unchanged profile -> no restart

        cr = api.get("APIServer", "", "cluster")
        cr.spec["tlsSecurityProfile"] = {"type": "Modern"}
        api.update(cr)
        mgr.run_until_idle()
        assert len(changes) == 1
        old, new = changes[0]
        assert old.min_version == "VersionTLS12"
        assert new.min_version == "VersionTLS13"
        # fires once (the process restarts; no repeat notifications)
        cr = api.get("APIServer", "", "cluster")
        cr.spec["tlsSecurityProfile"] = {"type": "Old"}
        api.update(cr)
        mgr.run_until_idle()
        assert len(changes) == 1
